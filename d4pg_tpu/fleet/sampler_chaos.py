"""Sample-on-ingest chaos: the dealer plane vs the host-sample plane.

One run stands up the full fleet ingest rig (``fleet/harness.py`` — N
chaos-wrapped sender lanes over real TCP into a sharded
``ReplayService``) over a **prioritized** buffer, and bolts a consumer
lane onto it that trains the way a learner replica would:

  - ``sample_path='host'``: the PR-10 path — ``weight_base`` +
    ``sample_chunk`` + ``update_priorities`` per block, every call an
    acquisition of the service's buffer lock (counted per call as
    ``sample_path_buffer_acqs``).
  - ``sample_path='dealer'``: the sample-on-ingest path
    (``replay/sampler.py``) — a ``SampleDealer`` rides the commit
    thread, the consumer pops ready-to-train blocks from its
    ``DealtBlockRing`` and feeds priorities back through
    ``queue_writeback``. ZERO buffer-lock acquisitions on the consume
    path, by construction — the counter stays 0 because no call on the
    path can take that lock, not because we remembered not to.
  - ``sample_path='device'``: the device-dealt variant
    (``replay/device_sampler.py``) — the service's buffer is a
    gen-tracked ``FusedDeviceReplay`` and the descent runs ON DEVICE
    fused behind the commit dispatch; blocks arrive device-resident in
    a ``DeviceDealtBlockRing`` whose clear-on-kill eagerly deletes the
    dropped device buffers. Same zero-buffer-lock consume contract and
    the same audit oracles; single ingest shard by construction (the
    gen-tracked ring pre-assigns slots under one commit thread).

Fault set on top of the harness's seeded sender chaos:

  - **learner kill** — the consumer thread is stopped mid-stream and
    respawned; in dealer mode its ring is cleared at the kill instant
    (blocks dealt to the corpse must not train), and the dealer keeps
    dealing to the successor.
  - **shed pressure** — a small ingest ring + low watermark forces
    oldest-batch sheds under load; shed tickets are marked dead.
  - **stale-generation frames** — raw frames stamped with a
    pre-restart generation are injected straight into ``add_payload``;
    they must fence at admission and never reach the dealer.

Oracles gating the run (the acceptance bar the bench ``sampler`` block
pins): 0 deadlocks, 0 lock-hierarchy violations, 0 trace orphans
(every dealt block's ``deal`` span hangs off a committed frame), and
``dealt_dead_tickets == 0`` — the dealer, running in audit mode, never
dealt a row whose source ticket was shed, tombstoned or fenced.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from d4pg_tpu.distributed import transport
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.fleet.chaos import ChaosConfig
from d4pg_tpu.fleet.harness import FleetConfig, FleetHarness
from d4pg_tpu.fleet.sender import synthetic_block
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.draw_ledger import LEDGER
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.trace import RECORDER as TRACE
from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.sampler import SampleDealer
from d4pg_tpu.replay.schedule import SharedBetaSchedule
from d4pg_tpu.replay.staging import DealtBlockRing


@dataclasses.dataclass(frozen=True)
class SamplerChaosConfig:
    """One sampler-chaos run. ``(config, seed)`` replays the same fault
    script (harness sender chaos + seeded consumer kills + fixed stale
    injection instants)."""

    sample_path: str = "dealer"  # 'dealer' | 'host' | 'device'
    n_actors: int = 16
    duration_s: float = 6.0
    rows_per_sec: float = 40.0
    block_rows: int = 16
    obs_dim: int = 24
    act_dim: int = 6
    capacity: int = 4096
    ingest_capacity: int = 24
    shed_watermark: float = 0.75
    ingest_shards: int = 2
    k: int = 4
    batch_size: int = 32
    alpha: float = 0.6
    beta0: float = 0.4
    beta_steps: int = 100_000
    consume_hz: float = 200.0
    learner_kills: int = 0
    stale_frames: int = 0
    seed: int = 0

    def __post_init__(self):
        if self.sample_path not in ("dealer", "host", "device"):
            raise ValueError(f"unknown sample_path {self.sample_path!r}")
        if self.sample_path == "device" and self.ingest_shards != 1:
            # the gen-tracked ring pre-assigns slots under ONE commit
            # thread; coerce rather than raise so the sweep's A/B loop
            # can run the same config across all three arms (the shard
            # count difference is structural, not a knob)
            object.__setattr__(self, "ingest_shards", 1)

    def kill_schedule(self) -> list[float]:
        """Seeded consumer-kill offsets (s): even across the middle 80%
        of the run, each jittered +-25% of its slot."""
        if self.learner_kills <= 0:
            return []
        rng = LEDGER.wrap("schedule.sampler_kill", np.random.default_rng(
            np.random.SeedSequence(self.seed, spawn_key=(0xD4B0,))))
        span = 0.8 * self.duration_s
        slot = span / self.learner_kills
        return sorted(0.1 * self.duration_s + (i + 0.5) * slot
                      + float(rng.uniform(-0.25, 0.25)) * slot
                      for i in range(self.learner_kills))

    def stale_schedule(self) -> list[float]:
        """Fixed injection instants for the stale-generation frames,
        even across the middle 60% of the run."""
        if self.stale_frames <= 0:
            return []
        return [0.2 * self.duration_s
                + (i + 0.5) * 0.6 * self.duration_s / self.stale_frames
                for i in range(self.stale_frames)]


class _SamplerHarness(FleetHarness):
    """The fleet ingest rig over a PER buffer, with the dealer (dealer
    mode) attached inside ``_make_service`` and the learner consumer
    supervised for seeded kills inside ``_start_consumer``."""

    def __init__(self, config: FleetConfig, scfg: SamplerChaosConfig):
        super().__init__(config)
        self.scfg = scfg
        self._dealer: SampleDealer | None = None
        self._ring: DealtBlockRing | None = None
        self._beta = SharedBetaSchedule(beta0=scfg.beta0,
                                        beta_steps=scfg.beta_steps)
        self._service_stats: dict = {}
        self.cstats = {
            "blocks_consumed": 0,
            "steps_consumed": 0,
            "sample_path_buffer_acqs": 0,
            "consumer_kills": 0,
            "blocks_cleared_on_kill": 0,
            "stale_frames_injected": 0,
            "sample_errors": 0,
        }

    # -- service over a PER buffer, dealer attached in dealer mode ----------
    def _make_service(self, obs_dim=None, act_dim=None,
                      generation: int = 0) -> ReplayService:
        cfg, scfg = self.config, self.scfg
        # generation floor 1: injected frames stamped with generation 0
        # are "pre-restart" retries and must fence at admission (lanes
        # send generation-less frames — they admit as always)
        if scfg.sample_path == "device":
            from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

            buffer = FusedDeviceReplay(
                cfg.capacity, cfg.obs_dim, cfg.act_dim, alpha=scfg.alpha,
                prioritized=True, ingest_shards=1, gen_tracked=True)
        else:
            buffer = PrioritizedReplayBuffer(
                cfg.capacity, cfg.obs_dim, cfg.act_dim,
                alpha=scfg.alpha, seed=scfg.seed)
        service = ReplayService(
            buffer,
            ingest_capacity=cfg.ingest_capacity,
            heartbeat_timeout=cfg.heartbeat_timeout,
            shed_watermark=cfg.shed_watermark,
            num_ingest_shards=cfg.ingest_shards,
            generation=max(1, generation),
        )
        if scfg.sample_path == "dealer":
            self._ring = DealtBlockRing(4)
            self._dealer = SampleDealer(
                cfg.capacity, [self._ring],
                n_shards=cfg.ingest_shards, k=scfg.k,
                batch_size=scfg.batch_size, alpha=scfg.alpha,
                beta_schedule=self._beta,
                min_size=max(1, scfg.batch_size), seed=scfg.seed,
                audit=True)
            service.attach_dealer(self._dealer)
        elif scfg.sample_path == "device":
            from d4pg_tpu.replay.device_sampler import DeviceSampleDealer
            from d4pg_tpu.replay.staging import DeviceDealtBlockRing

            self._ring = DeviceDealtBlockRing(4)
            self._dealer = DeviceSampleDealer(
                cfg.capacity, [self._ring], k=scfg.k,
                batch_size=scfg.batch_size, alpha=scfg.alpha,
                beta_schedule=self._beta,
                min_size=max(1, scfg.batch_size), seed=scfg.seed,
                audit=True)
            service.attach_dealer(self._dealer)
        return service

    # -- the supervised learner consumer ------------------------------------
    def _start_consumer(self, service_ref,
                        stop: threading.Event) -> threading.Thread | None:
        t = threading.Thread(target=self._consume_supervise,
                             args=(service_ref, stop), daemon=True,
                             name="sampler-consumer-supervisor")
        t.start()
        return t

    def _consume_supervise(self, service_ref, stop: threading.Event) -> None:
        """Run the consumer thread, killing + respawning it on the seeded
        schedule, and inject the stale-generation frames."""
        try:
            self._supervise_consumers(service_ref, stop)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.sampler_supervisor", e)

    def _supervise_consumers(self, service_ref,
                             stop: threading.Event) -> None:
        scfg = self.scfg
        kills = scfg.kill_schedule()
        stales = scfg.stale_schedule()
        stale_block = synthetic_block(
            self.config.block_rows, self.config.obs_dim,
            self.config.act_dim, seed=scfg.seed + 7919)
        t0 = time.monotonic()
        inner_stop = threading.Event()
        worker = self._spawn_consumer(service_ref, stop, inner_stop)
        while not stop.is_set():
            now = time.monotonic() - t0
            if kills and now >= kills[0]:
                kills.pop(0)
                inner_stop.set()
                worker.join(timeout=5.0)
                self.cstats["consumer_kills"] += 1
                if self._ring is not None:
                    # the corpse's undelivered blocks must not train
                    self.cstats["blocks_cleared_on_kill"] += \
                        self._ring.clear()
                record_event("sampler_consumer_kill",
                             kills=self.cstats["consumer_kills"])
                inner_stop = threading.Event()
                worker = self._spawn_consumer(service_ref, stop, inner_stop)
            if stales and now >= stales[0]:
                stales.pop(0)
                i = self.cstats["stale_frames_injected"]
                # encode_raw returns length-prefixed wire bytes; admission
                # takes the bare payload the receiver would hand it
                frame = transport.encode_raw(
                    f"stale-{i}", stale_block, True, generation=0)
                service_ref().add_payload(
                    frame[transport._HEADER.size:],
                    shard=i % self.config.ingest_shards, codec="raw")
                self.cstats["stale_frames_injected"] += 1
            stop.wait(0.01)
        inner_stop.set()
        worker.join(timeout=5.0)

    def _spawn_consumer(self, service_ref, stop: threading.Event,
                        inner_stop: threading.Event) -> threading.Thread:
        # 'device' blocks ride the same dealt consume lane — the lane is
        # arm-agnostic (pop + write-back), only the block residency
        # differs (queue_writeback materializes idx/gen on the host)
        target = (self._consume_host if self.scfg.sample_path == "host"
                  else self._consume_dealt)
        t = threading.Thread(target=target,
                             args=(service_ref, stop, inner_stop),
                             daemon=True, name="sampler-consumer")
        t.start()
        return t

    def _consume_dealt(self, service_ref, stop, inner_stop) -> None:
        """The dealt lane: ring pop -> (stand-in) grad -> write-back.
        NOTHING on this path can acquire the buffer lock — pop waits on
        the ``ring`` leaf tier, ``queue_writeback`` enqueues under the
        ``sampler`` tier. Paced at ``consume_hz`` like the host lane so
        the A/B arms model the SAME per-block grad time — what differs
        is only how the block is obtained (an unpaced pop loop would
        compare a zero-grad-time learner against a 200 Hz one)."""
        try:
            self._consume_dealt_loop(service_ref, stop, inner_stop)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.sampler_dealt", e)

    def _consume_dealt_loop(self, service_ref, stop, inner_stop) -> None:
        scfg = self.scfg
        rng = np.random.default_rng(np.random.SeedSequence(
            scfg.seed, spawn_key=(0xD4B1, self.cstats["consumer_kills"])))
        ring = self._ring
        period = 1.0 / max(1.0, scfg.consume_hz)
        while not (stop.is_set() or inner_stop.is_set()):
            block = ring.pop(timeout=0.1)
            if block is None:
                if ring.closed:
                    return
                continue
            # stand-in TD magnitudes: the priority write-back machinery
            # is the system under test, not SGD
            td = rng.uniform(0.1, 2.0, size=block.idx.shape)
            service_ref().queue_writeback(block.idx, td, block.gen)
            TRACE.mark_grad()
            self.cstats["blocks_consumed"] += 1
            self.cstats["steps_consumed"] += int(block.idx.shape[0])
            inner_stop.wait(period)

    def _consume_host(self, service_ref, stop, inner_stop) -> None:
        """The PR-10 lane: every consumed block is weight_base +
        sample_chunk + update_priorities — three buffer-lock
        acquisitions, counted."""
        try:
            self._consume_host_loop(service_ref, stop, inner_stop)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.sampler_host", e)

    def _consume_host_loop(self, service_ref, stop, inner_stop) -> None:
        scfg = self.scfg
        rng = np.random.default_rng(np.random.SeedSequence(
            scfg.seed, spawn_key=(0xD4B2, self.cstats["consumer_kills"])))
        period = 1.0 / max(1.0, scfg.consume_hz)
        while not (stop.is_set() or inner_stop.is_set()):
            svc = service_ref()
            if len(svc) >= scfg.batch_size:
                beta = self._beta.beta_at(self._beta.current_step())
                try:
                    _b, _w, idx, gen = svc.sample_chunk(
                        scfg.k, scfg.batch_size, beta=beta,
                        weight_base=svc.weight_base())
                    td = rng.uniform(0.1, 2.0, size=idx.shape)
                    svc.update_priorities(idx, td, generation=gen)
                except (ValueError, RuntimeError):
                    self.cstats["sample_errors"] += 1
                    continue
                self.cstats["sample_path_buffer_acqs"] += 3
                self._beta.advance(scfg.k)
                TRACE.mark_grad()
                self.cstats["blocks_consumed"] += 1
                self.cstats["steps_consumed"] += scfg.k
            inner_stop.wait(period)

    def _report(self, **kwargs) -> dict:
        self._service_stats = dict(kwargs.get("service_stats") or {})
        return super()._report(**kwargs)


def run_sampler_chaos(cfg: SamplerChaosConfig | None = None,
                      chaos: ChaosConfig | None = None,
                      **overrides) -> dict:
    """Execute one sampler-chaos run and return the artifact block."""
    cfg = dataclasses.replace(cfg or SamplerChaosConfig(), **overrides)
    fleet_cfg = FleetConfig(
        n_actors=cfg.n_actors, duration_s=cfg.duration_s,
        rows_per_sec=cfg.rows_per_sec, block_rows=cfg.block_rows,
        obs_dim=cfg.obs_dim, act_dim=cfg.act_dim, capacity=cfg.capacity,
        ingest_capacity=cfg.ingest_capacity,
        shed_watermark=cfg.shed_watermark,
        ingest_shards=cfg.ingest_shards, codec="raw",
        trace_sample=1.0, consume_hz=cfg.consume_hz,
        chaos=chaos if chaos is not None else ChaosConfig(seed=cfg.seed))
    harness = _SamplerHarness(fleet_cfg, cfg)
    result = harness.run()
    result.pop("chaos_log", None)
    locks = result.get("locks")
    lat = result.get("latency") or {}
    stages = lat.get("stages") or {}
    dealer = harness._dealer
    report = {
        "metric": "sampler_chaos",
        "schema": 1,
        "sample_path": cfg.sample_path,
        "n_actors": cfg.n_actors,
        "ingest_shards": cfg.ingest_shards,
        "duration_s": result["duration_s"],
        "rows_inserted": result["rows_inserted"],
        "sheds": result["drops"]["shed_batches"],
        "shed_rows": result["drops"]["shed_rows"],
        "fenced_frames": harness._service_stats.get("fenced_frames", 0),
        "fenced_rows": harness._service_stats.get("fenced_rows", 0),
        "wire_to_grad_p95_ms": (stages.get("wire_to_grad") or {}).get("p95"),
        "commit_to_deal_p95_ms": (stages.get("commit_to_deal")
                                  or {}).get("p95"),
        "deal_to_grad_p95_ms": (stages.get("deal_to_grad") or {}).get("p95"),
        "consumer": dict(harness.cstats),
        "sampler": dealer.sampler_stats() if dealer is not None else None,
        "deadlocks": result["deadlocks"],
        "hierarchy_violations": (locks["hierarchy_violations"]
                                 if locks else None),
        "trace_orphans": lat.get("orphans"),
        # schedule_digest is config-deterministic: two arms at the same
        # seed/config must report the same value (the A/B equal-load pin)
        "draw_ledger": result["draw_ledger"],
        "seed": cfg.seed,
    }
    return report
