"""Socket-vs-collective aggregation A/B: the ``mesh_learners`` block.

Both arms run the SAME offered load — N replicas with identical nets
from decorrelated seeds, identically-filled fused device rings, R
timed rounds of S fused grad steps per replica at the same (k, batch)
— and differ ONLY in how a round's updates become the next round's
basis:

- **socket** arm: the PR-10 host-thread plane (``--agg_transport
  socket``). Each replica thread trains through the legacy
  ``FusedLoop`` and then pays the full host round trip per round:
  device→host pull of all four param subtrees (``params_of``), the
  aggregator's host-numpy merge math, and the host→device push when it
  adopts the next basis (``adopt_params``).
- **collective** arm: ``MeshReplicaGroup`` (``--agg_transport
  collective``). Replica states are [N, ...]-stacked along the
  ``replica`` mesh axis by partition rule, the SAME pure fused chunk
  runs under ``shard_map``, and the merge + basis adoption is one
  on-device computation — the params never visit the host.

Per-round aggregation latency (p50/p95 across timed rounds) is the
attribution headline: grad work is identical by construction, so the
arms differ exactly by the transport the tentpole replaces. One warmup
round per arm absorbs jit compilation before timing starts.

On CPU the collective arm runs over virtual devices
(``xla_force_host_platform_device_count``), which prices dispatch
structure and collective count honestly but NOT real ICI bandwidth —
the artifact labels the backend for that reason.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.registry import percentile_summary


@dataclasses.dataclass(frozen=True)
class MeshABConfig:
    """One socket-vs-collective pair at ``n_replicas``. ``(config,
    seed)`` fixes the fills, the initial states and the sampling
    streams, so the two arms train on identical work."""

    n_replicas: int = 2
    rounds: int = 6          # timed rounds (one extra warmup round each)
    steps_per_round: int = 8
    k: int = 4
    batch_size: int = 32
    n_rows: int = 512
    obs_dim: int = 8
    act_dim: int = 2
    hidden: tuple = (32, 32)
    mode: str = "async"
    clip: float = 8.0
    seed: int = 0


def _learner_config(cfg: MeshABConfig):
    from d4pg_tpu.learner import D4PGConfig

    return D4PGConfig(obs_dim=cfg.obs_dim, act_dim=cfg.act_dim,
                      v_min=-10.0, v_max=10.0, n_atoms=51,
                      hidden=tuple(cfg.hidden))


def _fill(cfg: MeshABConfig):
    """A deterministically-filled fused device ring (one per replica
    per arm — the fused engine's ring is single-consumer)."""
    from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay
    from d4pg_tpu.replay.uniform import TransitionBatch

    rng = np.random.default_rng(cfg.seed)
    n = cfg.n_rows
    batch = TransitionBatch(
        obs=rng.standard_normal((n, cfg.obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, cfg.act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, cfg.obs_dim)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32))
    buf = FusedDeviceReplay(n, cfg.obs_dim, cfg.act_dim, alpha=0.6)
    buf.add(batch)
    buf.drain()
    return buf


def _replica_states(config, n: int):
    """train.py's replica construction: identical nets, decorrelated
    keys, per-replica leaf copies (updates donate their inputs)."""
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.learner import init_state

    base = init_state(config, jax.random.key(0))
    states = []
    for i in range(n):
        rstate = jax.tree_util.tree_map(jnp.copy, base)
        if i:
            rstate = rstate._replace(key=jax.random.fold_in(rstate.key, i))
        states.append(rstate)
    return states


def _run_socket_arm(cfg: MeshABConfig, config) -> dict:
    """N host-thread replicas through the in-process ``Aggregator`` —
    train.py's socket-transport wiring, minus the TCP hop (which only
    exists cross-host; within a host the D2H/H2D crossings and the
    host merge math ARE the transport cost)."""
    import jax

    from d4pg_tpu.distributed.weights import WeightStore
    from d4pg_tpu.learner.aggregator import Aggregator
    from d4pg_tpu.learner.loop import FusedLoop
    from d4pg_tpu.learner.replica import adopt_params, params_of

    n = cfg.n_replicas
    agg = Aggregator(WeightStore(), mode=cfg.mode, clip=cfg.clip)
    states = _replica_states(config, n)
    loops = [FusedLoop(config, _fill(cfg), k=cfg.k,
                       batch_size=cfg.batch_size) for _ in range(n)]
    epochs = [agg.register(i) for i in range(n)]
    bvs = [0] * n  # each replica's last-pulled basis version
    agg_lat: list[float] = []

    def _fanout(fn) -> None:
        def _runner(i: int) -> None:
            try:
                fn(i)
            except Exception as e:  # noqa: BLE001 — top frame of the lane
                contained_crash("mesh_ab.replica", e)

        threads = [threading.Thread(target=_runner, args=(i,), daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def round_once(timed: bool) -> None:
        # grad phase: every replica trains S steps on its own thread
        def grads(i: int) -> None:
            states[i], _ = loops[i].run(states[i], cfg.steps_per_round)

        _fanout(grads)
        # aggregation phase — the transport under test. Sub-phase 1:
        # D2H pull + host merge math (concurrent submits; sync mode is
        # an N-way barrier). Sub-phase 2: every replica pulls the
        # round's merged basis and adopts it (H2D) — the same
        # round-synchronous order train.py's thread replicas follow.
        t0 = time.perf_counter()

        def submit(i: int) -> None:
            tree = params_of(states[i])           # device → host
            agg.submit(i, epochs[i], tree, bvs[i],
                       step=cfg.steps_per_round)  # host merge math

        _fanout(submit)

        def adopt(i: int) -> None:
            bvs[i], basis = agg.basis(i)
            if basis is not None:
                states[i] = adopt_params(
                    states[i], jax.device_put(basis))  # host → device

        _fanout(adopt)
        jax.block_until_ready([states[i].actor_params for i in range(n)])
        if timed:
            agg_lat.append(time.perf_counter() - t0)

    round_once(timed=False)  # warmup: compile the fused chunk
    t_start = time.perf_counter()
    for _ in range(cfg.rounds):
        round_once(timed=True)
    wall = time.perf_counter() - t_start
    agg.close()
    updates = n * cfg.rounds * cfg.steps_per_round
    return {
        "updates_per_sec": round(updates / wall, 1),
        "wall_s": round(wall, 4),
        "agg_latency_s": percentile_summary(agg_lat),
    }


def _run_collective_arm(cfg: MeshABConfig, config) -> dict:
    """The same load through ``MeshReplicaGroup``: one shard_map'd
    dispatch per chunk, the merge an on-device collective."""
    from d4pg_tpu.learner.mesh_replicas import MeshReplicaGroup

    group = MeshReplicaGroup(
        config, _replica_states(config, cfg.n_replicas), k=cfg.k,
        batch_size=cfg.batch_size, mode=cfg.mode, clip=cfg.clip)
    group.load(_fill(cfg))
    group.run_round(cfg.steps_per_round)  # warmup: compile chunk + merge
    merge_lat: list[float] = []
    t_start = time.perf_counter()
    for _ in range(cfg.rounds):
        group._fused_steps(cfg.steps_per_round)
        group.merge()  # blocks until the merged tree is ready
        merge_lat.append(group.last_merge_s)
    wall = time.perf_counter() - t_start
    group.close()
    updates = cfg.n_replicas * cfg.rounds * cfg.steps_per_round
    return {
        "updates_per_sec": round(updates / wall, 1),
        "wall_s": round(wall, 4),
        "agg_latency_s": percentile_summary(merge_lat),
    }


def run_mesh_ab(cfg: MeshABConfig | None = None, **overrides) -> dict:
    """One A/B pair at ``cfg.n_replicas``: the socket and collective
    arms over identical offered load, plus the attribution ratios."""
    import jax

    cfg = dataclasses.replace(cfg or MeshABConfig(), **overrides)
    if cfg.n_replicas > len(jax.devices()):
        raise ValueError(
            f"n_replicas={cfg.n_replicas} exceeds visible devices "
            f"({len(jax.devices())}) — the collective arm shards one "
            "replica per device")
    config = _learner_config(cfg)
    socket = _run_socket_arm(cfg, config)
    collective = _run_collective_arm(cfg, config)
    p50_s, p50_c = (socket["agg_latency_s"]["p50"],
                    collective["agg_latency_s"]["p50"])
    return {
        "metric": "mesh_learners_ab",
        "schema": 1,
        "n_replicas": cfg.n_replicas,
        "mode": cfg.mode,
        "clip": cfg.clip,
        "backend": jax.default_backend(),
        "load": {
            "rounds": cfg.rounds,
            "steps_per_round": cfg.steps_per_round,
            "k": cfg.k,
            "batch_size": cfg.batch_size,
            "obs_dim": cfg.obs_dim,
            "act_dim": cfg.act_dim,
            "hidden": list(cfg.hidden),
        },
        "socket": socket,
        "collective": collective,
        "speedup_updates_per_sec": round(
            collective["updates_per_sec"] / socket["updates_per_sec"], 3)
        if socket["updates_per_sec"] else None,
        "agg_latency_ratio_p50": round(p50_s / p50_c, 3)
        if p50_s and p50_c else None,
        "seed": cfg.seed,
    }
