"""Seeded fault injection for the fleet plane.

The BASELINE fleet is 256 actors; at that fan-out transient failures are
the steady state, not the exception (Adamski et al., arXiv:1801.02852:
stragglers and restarts dominate wall-clock once fleets are wide). The
stress harness therefore injects faults ON PURPOSE, from a seeded policy,
so every degradation path in the transport/ingest stack is exercised
deterministically:

  - ``drop``  — a block vanishes at the transport boundary (lossy DCN);
  - ``delay`` — a block is delivered late with uniform jitter (straggler);
  - ``crash`` — the actor dies abruptly (no flush, no goodbye) and
    restarts after a fixed downtime (preemption / OOM kill);
  - receiver stalls — the learner-side ingest callback freezes for a
    window (GC pause, checkpoint write, learner restart).

Determinism contract: decision ``i`` of actor ``k`` depends ONLY on
``(ChaosConfig.seed, k, i)`` — never on wall clock or thread interleaving
— so a seeded fleet run replays the same fault script bit-for-bit at the
harness level (the acceptance bar for reproducible chaos runs). Each
decision consumes exactly ``DRAWS_PER_EVENT`` uniforms from a
``SeedSequence``-derived per-actor stream, which keeps the event index
aligned with the RNG state no matter which faults fire.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import NamedTuple

import numpy as np

from d4pg_tpu.obs.draw_ledger import LEDGER


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Fault probabilities are PER DECISION POINT (one sender block)."""

    drop_prob: float = 0.0
    delay_prob: float = 0.0
    delay_min_s: float = 0.0
    delay_max_s: float = 0.05
    crash_prob: float = 0.0
    restart_delay_s: float = 0.5
    # Receiver stalls run on a fixed schedule rather than a probability:
    # every ``stall_every_s`` of harness time the ingest callback freezes
    # for ``receiver_stall_s`` (0 for either disables stalls).
    receiver_stall_s: float = 0.0
    stall_every_s: float = 0.0
    # Learner-kill chaos (the crash-recovery plane's fault): the harness
    # SIGKILL-equivalently tears down the WHOLE replay service (receiver
    # + ingest + buffer) ``service_kill_count`` times, roughly every
    # ``service_kill_every_s`` of harness time (seeded jitter spreads the
    # kill instants so they never phase-lock with the stall script), and
    # a supervisor restarts it from the last durable snapshot — bounded
    # by ``service_restart_max`` attempts with ``service_restart_backoff_s``
    # exponential backoff between them.
    service_kill_every_s: float = 0.0
    service_kill_count: int = 0
    service_restart_max: int = 3
    service_restart_backoff_s: float = 0.25
    # Snapshot cadence for the supervisor (the "checkpoint interval"):
    # rows committed after the latest snapshot die with the service —
    # the declared crash loss the recovery report accounts for.
    service_snapshot_every_s: float = 1.0
    seed: int = 0

    def __post_init__(self):
        for name in ("drop_prob", "delay_prob", "crash_prob"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name}={p} outside [0, 1]")
        if self.delay_max_s < self.delay_min_s:
            raise ValueError("delay_max_s < delay_min_s")
        if self.service_kill_count < 0:
            raise ValueError("service_kill_count must be >= 0")
        if self.service_kill_count > 0 and self.service_kill_every_s <= 0:
            raise ValueError(
                "service_kill_count > 0 needs service_kill_every_s > 0")

    def enabled(self) -> bool:
        return (self.drop_prob > 0 or self.delay_prob > 0
                or self.crash_prob > 0
                or (self.receiver_stall_s > 0 and self.stall_every_s > 0)
                or self.service_chaos_enabled())

    def service_chaos_enabled(self) -> bool:
        return self.service_kill_count > 0 and self.service_kill_every_s > 0


class ChaosEvent(NamedTuple):
    actor_id: str
    index: int
    kind: str  # 'ok' | 'drop' | 'delay' | 'crash'
    arg: float  # delay seconds / restart downtime; 0.0 otherwise


# Uniforms consumed per decision: (crash, drop, delay, delay-jitter). A
# FIXED draw count per event keeps actor streams index-stable: event i is
# the same regardless of which faults fired before it.
DRAWS_PER_EVENT = 4


class ActorChaos:
    """One actor's deterministic fault stream (+ its event log)."""

    def __init__(self, config: ChaosConfig, actor_index: int, actor_id: str):
        self.config = config
        self.actor_id = actor_id
        # ledger-wrapped so every chaos run reports per-actor draw
        # counts (obs.draw_ledger; runtime twin of jaxlint family 24)
        self._rng = LEDGER.wrap(f"chaos.{actor_id}", np.random.default_rng(
            np.random.SeedSequence(config.seed, spawn_key=(actor_index,))))
        self.log: list[ChaosEvent] = []
        self._i = 0

    def next(self) -> ChaosEvent:
        u_crash, u_drop, u_delay, u_jit = self._rng.random(DRAWS_PER_EVENT)
        cfg = self.config
        if u_crash < cfg.crash_prob:
            kind, arg = "crash", cfg.restart_delay_s
        elif u_drop < cfg.drop_prob:
            kind, arg = "drop", 0.0
        elif u_delay < cfg.delay_prob:
            kind = "delay"
            arg = cfg.delay_min_s + u_jit * (cfg.delay_max_s - cfg.delay_min_s)
        else:
            kind, arg = "ok", 0.0
        ev = ChaosEvent(self.actor_id, self._i, kind, float(arg))
        self._i += 1
        self.log.append(ev)
        return ev


class ChaosPolicy:
    """Factory for per-actor fault streams and the receiver-stall script."""

    def __init__(self, config: ChaosConfig):
        self.config = config

    def actor_stream(self, actor_index: int, actor_id: str) -> ActorChaos:
        return ActorChaos(self.config, actor_index, actor_id)

    def stall_schedule(self, horizon_s: float) -> list[tuple[float, float]]:
        """Deterministic ``(start_offset_s, duration_s)`` receiver stalls
        within ``horizon_s`` of harness time."""
        cfg = self.config
        if cfg.stall_every_s <= 0 or cfg.receiver_stall_s <= 0:
            return []
        out, t = [], cfg.stall_every_s
        while t < horizon_s:
            out.append((t, cfg.receiver_stall_s))
            t += cfg.stall_every_s + cfg.receiver_stall_s
        return out

    def service_kill_schedule(self, horizon_s: float) -> list[float]:
        """Seeded kill instants (offsets into harness time) for the
        learner-kill supervisor: ``service_kill_count`` kills, nominally
        ``service_kill_every_s`` apart, each jittered by a seeded uniform
        in ±25% of the interval so kills never phase-lock with the stall
        script (a kill landing INSIDE a stall is a legal — and nastier —
        schedule, it just should not be the only one a seed can produce).
        Deterministic from ``ChaosConfig.seed`` alone, like every other
        fault stream; kills past ``horizon_s`` are clipped."""
        cfg = self.config
        if not cfg.service_chaos_enabled():
            return []
        rng = LEDGER.wrap("schedule.service_kill", np.random.default_rng(
            np.random.SeedSequence(cfg.seed, spawn_key=(0x5E11,))))
        out = []
        for i in range(cfg.service_kill_count):
            base = (i + 1) * cfg.service_kill_every_s
            jit = (rng.random() - 0.5) * 0.5 * cfg.service_kill_every_s
            t = max(0.1, base + jit)
            if t < horizon_s:
                out.append(round(float(t), 3))
        return out


class StallGate:
    """The receiver-stall injection point: the ingest callback passes
    through ``wait()``; the stall controller closes/opens the gate. Waits
    are BOUNDED so a stall can never be mistaken for a receiver deadlock
    — a gated callback resumes the moment the gate opens or the bound
    elapses."""

    def __init__(self):
        self._open = threading.Event()
        self._open.set()
        self.stalls = 0

    def stall(self) -> None:
        self.stalls += 1
        self._open.clear()

    def resume(self) -> None:
        self._open.set()

    def wait(self, timeout: float = 30.0) -> bool:
        return self._open.wait(timeout)
