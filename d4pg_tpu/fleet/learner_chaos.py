"""Learner-replica chaos: the multi-learner plane under fire.

The ingest harness kills learners, the weight harness kills relays;
this drill kills **learner replicas** mid-update and proves the
aggregation plane degrades correctly. One run stands up a REAL
``Aggregator`` over a real ``WeightStore`` behind a real
``AggregatorServer`` TCP endpoint, with N synthetic replica lanes
(numpy param mutations — the merge/fence/transport machinery is the
system under test, not SGD) submitting version-stamped updates at
``submit_hz`` through real ``UpdateClient`` sockets.

Fault set:

  - **replica kill mid-update** — a lane is stopped, its id fenced
    (``Aggregator.fence_replica``), and its LAST WIRE FRAME — the bytes
    that were genuinely in flight — is replayed verbatim against the
    server. The frame must bounce off the zero-decode header check
    (status ``fenced``, payload never merged). The replica then
    respawns at the next epoch and resumes submitting.
  - **torn payloads** — a submission's payload bytes are corrupted
    without fixing the crc; the server must detect (status ``torn``)
    and shed, never merge.

Oracles gating the run (the acceptance bar the bench ``learners``
block pins):

  1. **ledger**: the aggregator's published (generation, version)
     stream never rewinds — generation monotone, version strictly
     increasing within a generation — across every kill/respawn.
  2. **fencing**: every replayed in-flight frame from a killed epoch
     was rejected; 0 dead-epoch updates merged.
  3. **locks**: the run executes under lock-hierarchy record mode —
     0 new violations across the replica/agg/wstore tiers.
  4. **trace**: with the recorder at sample 1.0, every submitted frame
     terminates (commit on merge, shed on fence/tear) — 0 orphans.

The staleness histogram and correction-clip rate come straight from
the aggregator's obs provider counters — the same numbers a production
export would show.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from d4pg_tpu.core import locking
from d4pg_tpu.distributed.update_plane import AggregatorServer, UpdateClient
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.learner.aggregator import Aggregator
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import percentile_summary
from d4pg_tpu.obs.trace import RECORDER as TRACE


@dataclasses.dataclass(frozen=True)
class LearnerChaosConfig:
    """One learner-chaos run. ``(config, seed)`` replays the same fault
    script (seeded kill instants, seeded torn choices)."""

    n_replicas: int = 4
    duration_s: float = 6.0
    submit_hz: float = 30.0
    replica_kills: int = 2
    torn_prob: float = 0.03
    mode: str = "async"
    clip: float = 8.0
    param_dim: int = 32
    seed: int = 0

    def kill_schedule(self, kills: int, lane: int) -> list[float]:
        """Seeded kill offsets (s): nominally even across the middle
        80% of the run, each jittered +-25% of its slot."""
        if kills <= 0:
            return []
        rng = np.random.default_rng(
            np.random.SeedSequence(self.seed, spawn_key=(0xD4AB, lane)))
        span = 0.8 * self.duration_s
        slot = span / kills
        return sorted(0.1 * self.duration_s + (i + 0.5) * slot
                      + float(rng.uniform(-0.25, 0.25)) * slot
                      for i in range(kills))


class _ReplicaLane:
    """One synthetic replica: adopts the aggregator's basis, perturbs it
    (a stand-in gradient step), submits over a real socket. Basis pulls
    and registration go in-process (replicas and aggregator share the
    train process; only submissions ride the wire — mirroring
    ``train.py``'s wiring)."""

    def __init__(self, replica_id: int, agg: Aggregator, port: int,
                 cfg: LearnerChaosConfig, epoch: int, params: dict):
        self.replica_id = replica_id
        self.epoch = epoch
        self._agg = agg
        self._cfg = cfg
        self._params = params
        self._rng = np.random.default_rng(np.random.SeedSequence(
            cfg.seed, spawn_key=(0xD4AC, replica_id, epoch)))
        self.client = UpdateClient("127.0.0.1", port)
        self.results: dict[str, int] = {}
        self.lags: list[int] = []
        self.torn_injected = 0
        self.torn_detected = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit_once(self) -> None:
        basis_version, basis = self._agg.basis(self.replica_id)
        if basis is not None:
            self._params = {k: np.array(v) for k, v in basis.items()}
        for v in self._params.values():
            v += self._rng.normal(scale=0.01, size=v.shape).astype(v.dtype)
        torn = self._rng.random() < self._cfg.torn_prob
        try:
            if torn:
                from d4pg_tpu.distributed.update_plane import encode_update
                frame = bytearray(encode_update(
                    self._params, replica_id=self.replica_id,
                    epoch=self.epoch,
                    generation=self._agg._store.generation,
                    basis_version=basis_version))
                frame[-1] ^= 0xFF  # corrupt payload, leave crc claiming truth
                self.torn_injected += 1
                res = self.client.submit_frame(bytes(frame))
            else:
                res = self.client.submit(
                    self.replica_id, self.epoch, self._params,
                    basis_version,
                    generation=self._agg._store.generation)
        except (ConnectionError, OSError) as exc:
            self.errors += 1
            record_event("learner_lane_error", replica=self.replica_id,
                         error=type(exc).__name__)
            return
        status = res["status"]
        self.results[status] = self.results.get(status, 0) + 1
        if status == "torn":
            self.torn_detected += 1
        if status == "applied" and res["lag"] is not None:
            self.lags.append(res["lag"])

    def _run(self) -> None:
        try:
            interval = 1.0 / self._cfg.submit_hz
            while not self._stop.is_set():
                self.submit_once()
                self._stop.wait(interval)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.learner_lane", e)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self.client.close()


def _merge_counts(total: dict, lane: _ReplicaLane) -> None:
    for k, v in lane.results.items():
        total[k] = total.get(k, 0) + v


def run_learner_chaos(cfg: LearnerChaosConfig | None = None, **overrides
                      ) -> dict:
    """Execute one learner-chaos run and return the artifact block."""
    cfg = dataclasses.replace(cfg or LearnerChaosConfig(), **overrides)
    violations_before = locking.violation_count()
    locking.enable_debug(raise_on_violation=False)
    TRACE.reset()
    TRACE.enable(sample_rate=1.0)

    store = WeightStore()
    agg = Aggregator(store, mode=cfg.mode, clip=cfg.clip)
    server = AggregatorServer(agg)
    rng = np.random.default_rng(
        np.random.SeedSequence(cfg.seed, spawn_key=(0xD4AD,)))
    init = {"w0": rng.normal(size=(cfg.param_dim, cfg.param_dim)
                             ).astype(np.float32),
            "b0": rng.normal(size=(cfg.param_dim,)).astype(np.float32)}

    lanes: dict[int, _ReplicaLane] = {}
    for i in range(cfg.n_replicas):
        epoch = agg.register(i, params={k: v.copy() for k, v in init.items()})
        lanes[i] = _ReplicaLane(i, agg, server.port, cfg, epoch,
                                {k: v.copy() for k, v in init.items()})

    retired: dict[str, int] = {}
    retired_lags: list[int] = []
    retired_torn = 0
    retired_errors = 0
    kill_times = cfg.kill_schedule(cfg.replica_kills, lane=1)
    kills = 0
    replay_attempts = 0
    replay_fenced = 0

    start = time.monotonic()
    while True:
        now = time.monotonic() - start
        if now >= cfg.duration_s:
            break
        if kill_times and now >= kill_times[0]:
            kill_times.pop(0)
            victim_id = int(rng.integers(0, cfg.n_replicas))
            lane = lanes[victim_id]
            lane.stop()  # the kill: thread gone, socket dropped
            _merge_counts(retired, lane)
            retired_lags.extend(lane.lags)
            retired_torn += lane.torn_injected
            retired_errors += lane.errors
            agg.fence_replica(victim_id)
            version_before = agg.version
            # replay the corpse's genuinely in-flight frame bytes: the
            # aggregator MUST bounce them off the dead epoch
            if lane.client.last_frame is not None:
                replay_attempts += 1
                probe = UpdateClient("127.0.0.1", server.port)
                res = probe.submit_frame(lane.client.last_frame)
                probe.close()
                if (res["status"] in ("fenced", "torn")
                        and agg.version == version_before):
                    replay_fenced += 1
            # respawn at the next epoch, resuming from the corpse's params
            epoch = agg.register(victim_id)
            lanes[victim_id] = _ReplicaLane(
                victim_id, agg, server.port, cfg, epoch,
                {k: np.array(v) for k, v in lane._params.items()})
            kills += 1
            record_event("learner_chaos_kill", replica=victim_id,
                         new_epoch=epoch)
        time.sleep(0.01)
    duration = time.monotonic() - start

    for lane in lanes.values():
        lane.stop()
        _merge_counts(retired, lane)
        retired_lags.extend(lane.lags)
    server.close()
    time.sleep(0.3)  # serve threads notice teardown, shed in-flight traces

    counters = agg.counters()
    snapshot = agg._snapshot()
    trace_block = TRACE.latency_block()
    TRACE.disable()
    report = {
        "metric": "learner_chaos",
        "schema": 1,
        "n_replicas": cfg.n_replicas,
        "mode": cfg.mode,
        "clip": cfg.clip,
        "duration_s": round(duration, 3),
        "submits": dict(retired),
        "server": server.stats(),
        "replica_kills": kills,
        "replayed_inflight": replay_attempts,
        "replayed_fenced": replay_fenced,
        "updates_applied": counters["applied"],
        "updates_fenced": counters["fenced"],
        "updates_per_sec": round(counters["applied"] / duration, 1),
        "final_version": agg.version,
        "staleness": percentile_summary([float(v) for v in retired_lags]),
        "clip_rate": snapshot["clip_rate"],
        "torn": {
            "injected": retired_torn
            + sum(l.torn_injected for l in lanes.values()),
            "detected": server.torn,
        },
        "lane_errors": retired_errors
        + sum(l.errors for l in lanes.values()),
        "ledger": {
            "published": counters["published"],
            "monotone": agg.ledger_monotone(),
        },
        "hierarchy_violations":
            locking.violation_count() - violations_before,
        "trace": {
            "orphans": trace_block["orphans"],
            "n_traces": trace_block["n_traces"],
            "completed": trace_block["completed"],
            "shed": trace_block["shed"],
            "overflow": trace_block["overflow"],
        },
        "seed": cfg.seed,
    }
    agg.close()
    TRACE.reset()
    return report
