"""Fleet fan-out sweep: rows/s vs N actors, chaos on, one receiver.

The BASELINE-closing measurement (ROADMAP "Fan-out above 8 actors"):
N ∈ {8, 32, 64, 128, 256} throttled lanes at fixed per-lane demand, so
the sweep walks the plane from idle (8 × 20 = 160 rows/s) through the
priced ~5,200 rows/s/core ceiling (256 × 20 = 5,120 rows/s) with the
default chaos mix injecting drops, stragglers, crashes and receiver
stalls the whole way. Run it:

    python -m d4pg_tpu.fleet.sweep --ns 8 32 64 128 256 --seconds 10
    python bench.py --fleet           # same sweep, persisted artifact

Per-N rows of the artifact are ``FleetHarness._report`` dicts minus the
raw chaos log (the log is deterministic from the seed — regenerate it by
re-running; the artifact carries the seed).
"""

from __future__ import annotations

import argparse
import dataclasses
import json

from d4pg_tpu.fleet.chaos import ChaosConfig
from d4pg_tpu.fleet.harness import FleetConfig, FleetHarness

SWEEP_NS = (8, 32, 64, 128, 256)


def default_chaos(seed: int = 0) -> ChaosConfig:
    """The sweep's standard fault mix: ~2% dropped blocks, ~5% stragglers
    (5-50 ms), ~0.4%/tick crashes with a 4 s outage (long enough to cross
    the 3 s heartbeat timeout, so every crash exercises eviction AND
    re-admission), and a 0.5 s receiver stall every 3 s."""
    return ChaosConfig(
        drop_prob=0.02,
        delay_prob=0.05, delay_min_s=0.005, delay_max_s=0.05,
        crash_prob=0.004, restart_delay_s=4.0,
        receiver_stall_s=0.5, stall_every_s=3.0,
        seed=seed,
    )


def default_service_chaos(seed: int = 0,
                          duration_s: float = 10.0) -> ChaosConfig:
    """The recovery run's fault mix: the FULL standard set (drops,
    stragglers, actor crashes, receiver stalls) PLUS the learner-kill
    script — two service kills inside ``duration_s`` (the acceptance
    bar: the service dies >= 2x mid-run), a 1 s snapshot cadence and a
    bounded-backoff supervisor."""
    import dataclasses as _dc

    return _dc.replace(
        default_chaos(seed),
        service_kill_every_s=duration_s / 3.5,
        service_kill_count=2,
        service_snapshot_every_s=1.0,
        service_restart_max=3,
        service_restart_backoff_s=0.25,
    )


def run_sweep(
    ns=SWEEP_NS,
    duration_s: float = 10.0,
    chaos: ChaosConfig | None = None,
    **overrides,
) -> dict:
    """Run the fleet harness at each N; returns the bench_fleet artifact."""
    chaos = default_chaos() if chaos is None else chaos
    rows = []
    for n in ns:
        cfg = FleetConfig(n_actors=int(n), duration_s=duration_s,
                          chaos=chaos, **overrides)
        result = FleetHarness(cfg).run()
        result.pop("chaos_log", None)  # deterministic from the seed
        rows.append(result)
    base = FleetConfig(chaos=chaos, **overrides)
    return {
        "metric": "fleet_rows_per_sec",
        "unit": "rows/sec",
        "schema": 1,
        "sweep": rows,
        "config": {
            "rows_per_sec_per_actor": base.rows_per_sec,
            "block_rows": base.block_rows,
            "obs_dim": base.obs_dim,
            "act_dim": base.act_dim,
            "ingest_capacity": base.ingest_capacity,
            "shed_watermark": base.shed_watermark,
            "heartbeat_timeout": base.heartbeat_timeout,
            "send_timeout": base.send_timeout,
            "max_retries": base.max_retries,
            "mode": base.mode,
            "ingest_shards": base.ingest_shards,
            "codec": base.resolved_codec(),
            "chaos": dataclasses.asdict(chaos),
        },
    }


def shard_sweep(
    ks=(1, 2, 4),
    n_actors: int = 256,
    duration_s: float = 10.0,
    rows_per_sec: float = 60.0,
    chaos: ChaosConfig | None = None,
    trace_sample: float | None = None,
    **overrides,
) -> dict:
    """The multi-core receiver sweep: FIXED N, ingest shards K ∈ ``ks``.

    Offered load is raised (default 60 rows/s/lane = 15,360 rows/s at
    N=256) so the RECEIVER is the saturated stage — at PR 3's 20 rows/s
    the sweep was offered-load-limited above ~5,120 and no receiver
    change could show. ``codec='auto'``: the K=1 row runs the legacy npz
    plane exactly as PR 3 shipped it (the ~5,200 rows/s/core baseline);
    K≥2 rows run the sharded plane end to end (v2 raw frames, zero-decode
    admission, shard-worker decode, ordered merge commit). Each row
    reports ``rows_per_sec_per_shard``; the summary adds scaling
    efficiency vs K=1 and vs the priced single-core ceiling.

    ``trace_sample`` (default ``obs.trace.DEFAULT_SAMPLE``) arms
    wire-to-grad tracing on the K≥2 rows, so the scaling table carries
    per-stage latency attribution NEXT TO ``lock_wait_ms`` — flat
    scaling now names its stage, not just its lock. The K=1 legacy-npz
    row is deliberately untraced (npz frames carry no extension; that
    row must measure the plane exactly as PR 3 shipped it)."""
    from d4pg_tpu.obs.trace import DEFAULT_SAMPLE

    if trace_sample is None:
        trace_sample = DEFAULT_SAMPLE
    chaos = default_chaos() if chaos is None else chaos
    rows = []
    for k in ks:
        cfg = FleetConfig(n_actors=int(n_actors), duration_s=duration_s,
                          rows_per_sec=rows_per_sec, ingest_shards=int(k),
                          chaos=chaos,
                          trace_sample=(trace_sample if int(k) > 1 else 0.0),
                          **overrides)
        result = FleetHarness(cfg).run()
        result.pop("chaos_log", None)
        rows.append(result)
    base = rows[0]["rows_per_sec"] if rows else 0.0
    return {
        "n_actors": int(n_actors),
        "rows_per_sec_per_actor": rows_per_sec,
        "offered_rows_per_sec": round(n_actors * rows_per_sec, 1),
        "single_core_ceiling_rows_per_sec": 5200.0,  # PR 2's priced value
        "sweep": rows,
        "scaling": [
            {
                "ingest_shards": r["ingest_shards"],
                "rows_per_sec": r["rows_per_sec"],
                "rows_per_sec_per_shard": r["rows_per_sec_per_shard"],
                "speedup_vs_k1": (round(r["rows_per_sec"] / base, 2)
                                  if base else None),
                "efficiency": (round(r["rows_per_sec"]
                                     / (base * r["ingest_shards"]), 2)
                               if base else None),
                "vs_ceiling": round(r["rows_per_sec"] / 5200.0, 2),
                # per-K lock-wait attribution (core/locking.py sentinels):
                # on a multi-core receiver host, flat rows/s with rising
                # lock_wait_ms fingers contention, not CPU, as the limit
                "lock_wait_ms": _lock_wait_ms(r),
                "hierarchy_violations": (
                    r["locks"]["hierarchy_violations"]
                    if r.get("locks") else None),
                # per-K STAGE attribution (obs/trace spans): where a
                # frame's time goes between socket write and commit —
                # the column that turns "K didn't scale" into "decode
                # saturated" vs "the merge floor stalled". None on the
                # untraced K=1 legacy row.
                "stage_ms": _stage_attribution(r),
            }
            for r in rows
        ],
    }


def recovery_probe(seed: int = 0, blocks: int = 48, block_rows: int = 32,
                   obs_dim: int = 12, act_dim: int = 3,
                   cut: int = 24, lost: int = 4) -> dict:
    """The post-restore bitwise oracle: kill-and-restore must equal an
    uninterrupted run, modulo the declared losses.

    Deterministic, no sockets: incarnation A ingests blocks ``[0, cut)``,
    snapshots, ingests ``lost`` more (the in-flight rows a real crash
    forgets) and is SIGKILL-equivalently torn down. Incarnation B is
    built at the next generation, restores the snapshot, and ingests the
    remainder ``[cut+lost, blocks)``. The oracle C ingests exactly the
    surviving blocks in one uninterrupted life. B's buffer must equal
    C's BITWISE (columns, PER tree, write head) — recovery is
    exactly-once w.r.t. committed rows, with the ``lost`` blocks
    appearing ONLY in the declared-loss ledger."""
    import numpy as np

    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.fleet.sender import synthetic_block
    from d4pg_tpu.replay.uniform import ReplayBuffer

    capacity = blocks * block_rows  # no wraparound: the cut stays legible

    def mk(generation: int = 0) -> ReplayService:
        return ReplayService(ReplayBuffer(capacity, obs_dim, act_dim),
                             generation=generation)

    def block(i: int):
        return synthetic_block(block_rows, obs_dim, act_dim,
                               seed=seed * 100_003 + i)

    a = mk()
    for i in range(cut):
        a.add(block(i), actor_id="probe")
    a.flush(timeout=10.0)
    snap = a.snapshot()
    for i in range(cut, cut + lost):
        a.add(block(i), actor_id="probe")
    a.flush(timeout=10.0)
    rows_lost = a.env_steps - int(snap["env_steps"])
    a.kill()  # abrupt: the post-snapshot rows die undeclared-nowhere-else

    b = mk(generation=int(snap["generation"]) + 1)
    b.restore(snap)
    survivors = list(range(cut)) + list(range(cut + lost, blocks))
    for i in range(cut + lost, blocks):
        b.add(block(i), actor_id="probe")
    b.flush(timeout=10.0)
    b_state = b.replay_state()
    b_rows = b.env_steps
    b.close()

    c = mk()
    for i in survivors:
        c.add(block(i), actor_id="probe")
    c.flush(timeout=10.0)
    c_state = c.replay_state()
    c.close()

    def bitwise(x, y) -> bool:
        if isinstance(x, dict):
            return (isinstance(y, dict) and x.keys() == y.keys()
                    and all(bitwise(x[k], y[k]) for k in x))
        if isinstance(x, (list, tuple)):
            return (isinstance(y, (list, tuple)) and len(x) == len(y)
                    and all(bitwise(a_, b_) for a_, b_ in zip(x, y)))
        xa, ya = np.asarray(x), np.asarray(y)
        return xa.dtype == ya.dtype and bool(np.array_equal(xa, ya))

    return {
        "oracle_bitwise_equal": bitwise(b_state, c_state),
        "rows_lost_declared": int(rows_lost),
        "rows_compared": int(b_rows),
        "blocks": int(blocks),
        "blocks_lost": int(lost),
        "seed": int(seed),
    }


def run_recovery(
    n_actors: int = 64,
    duration_s: float = 10.0,
    ingest_shards: int = 2,
    rows_per_sec: float = 30.0,
    seed: int = 0,
    chaos: ChaosConfig | None = None,
    **overrides,
) -> dict:
    """The bench_fleet recovery block: one service_chaos run (full fault
    set + the seeded learner-kill script) flattened to the recovery
    headline numbers, plus the deterministic bitwise oracle probe."""
    chaos = (default_service_chaos(seed, duration_s) if chaos is None
             else chaos)
    cfg = FleetConfig(n_actors=int(n_actors), duration_s=duration_s,
                      ingest_shards=int(ingest_shards),
                      rows_per_sec=rows_per_sec, codec="raw", chaos=chaos,
                      **overrides)
    result = FleetHarness(cfg).run()
    result.pop("chaos_log", None)
    sc = result.get("service_chaos") or {}
    locks = result.get("locks")
    return {
        "metric": "fleet_recovery",
        "schema": 1,
        "n_actors": int(n_actors),
        "ingest_shards": int(ingest_shards),
        "duration_s": result["duration_s"],
        "kills": sc.get("kills", 0),
        "restarts": sc.get("restarts", 0),
        "failed_restarts": sc.get("failed_restarts", 0),
        "mttr_s": sc.get("mttr_s"),
        "snapshots": sc.get("snapshots", 0),
        "rows_fenced": sc.get("rows_fenced", 0),
        "frames_fenced": sc.get("frames_fenced", 0),
        "rows_lost_to_crash": sc.get("rows_lost_to_crash", 0),
        "final_generation": sc.get("final_generation"),
        "reconnect_storm": sc.get("reconnect_storm"),
        "rows_inserted": result["rows_inserted"],
        "deadlocks": result["deadlocks"],
        "hierarchy_violations": (locks["hierarchy_violations"]
                                 if locks else None),
        "oracle": recovery_probe(seed=seed),
        "chaos": dataclasses.asdict(chaos),
        "seed": int(seed),
    }


def run_weights(
    n_pullers: int = 64,
    relay_depth: int = 2,
    duration_s: float = 8.0,
    seed: int = 0,
    learner_kills: int = 1,
    **overrides,
) -> dict:
    """The bench_fleet weights block: one weight-chaos run
    (``fleet/weight_chaos.py`` — N pullers across a relay tree, torn/
    stale injection, relay crash, learner kill at generation+1) reported
    as the broadcast headline numbers + the three run-gating oracles
    (ledger / trace orphans / lock hierarchy)."""
    from d4pg_tpu.fleet.weight_chaos import WeightChaosConfig, run_weight_chaos

    return run_weight_chaos(WeightChaosConfig(
        n_pullers=int(n_pullers), relay_depth=int(relay_depth),
        duration_s=float(duration_s), learner_kills=int(learner_kills),
        seed=int(seed), **overrides))


def run_learners(
    ns=(1, 2, 4),
    duration_s: float = 4.0,
    seed: int = 0,
    replica_kills: int = 2,
    mode: str = "async",
    **overrides,
) -> dict:
    """The bench_fleet learners block (``fleet/learner_chaos.py``):
    updates/s vs replica count from kill-free rows (the scaling story —
    staleness percentiles and correction-clip rate per N), then ONE
    chaos row at N=max(ns) with seeded replica kills — in-flight-frame
    fencing, ledger monotonicity, trace orphans and the lock hierarchy
    are its run-gating oracles."""
    from d4pg_tpu.fleet.learner_chaos import (
        LearnerChaosConfig,
        run_learner_chaos,
    )

    sweep = []
    for n in ns:
        r = run_learner_chaos(LearnerChaosConfig(
            n_replicas=int(n), duration_s=float(duration_s),
            replica_kills=0, torn_prob=0.0, mode=mode, seed=int(seed),
            **overrides))
        sweep.append({
            "n_replicas": int(n),
            "updates_per_sec": r["updates_per_sec"],
            "staleness": r["staleness"],
            "clip_rate": r["clip_rate"],
            "ledger_monotone": r["ledger"]["monotone"],
            "trace_orphans": r["trace"]["orphans"],
            "hierarchy_violations": r["hierarchy_violations"],
        })
    chaos_row = run_learner_chaos(LearnerChaosConfig(
        n_replicas=int(max(ns)), duration_s=float(duration_s),
        replica_kills=int(replica_kills), mode=mode, seed=int(seed),
        **overrides))
    return {"metric": "fleet_learners", "schema": 1, "mode": mode,
            "sweep": sweep, "chaos": chaos_row, "seed": int(seed)}


def run_mesh_learners(
    ns=(1, 2, 4),
    rounds: int = 6,
    steps_per_round: int = 8,
    mode: str = "async",
    seed: int = 0,
    **overrides,
) -> dict:
    """The bench_fleet mesh_learners block (``fleet/mesh_ab.py``): the
    socket-vs-collective aggregation A/B at equal offered load per
    replica count — updates/s on each arm plus per-round aggregation
    latency p50/p95, the measurement that attributes the mesh-native
    transport's win to the transport (grad work is identical by
    construction). Needs a JAX backend with >= max(ns) devices;
    bench.py runs it in a virtual-device child process so the rest of
    the fleet suite stays accelerator-free."""
    import jax

    from d4pg_tpu.fleet.mesh_ab import run_mesh_ab

    sweep = []
    for n in ns:
        if int(n) > len(jax.devices()):
            continue  # the collective arm shards one replica per device
        sweep.append(run_mesh_ab(
            n_replicas=int(n), rounds=int(rounds),
            steps_per_round=int(steps_per_round), mode=mode,
            seed=int(seed), **overrides))
    return {"metric": "fleet_mesh_learners", "schema": 1, "mode": mode,
            "backend": jax.default_backend(), "sweep": sweep,
            "seed": int(seed)}


def run_sampler(
    n_actors: int = 64,
    duration_s: float = 6.0,
    seed: int = 0,
    learner_kills: int = 2,
    stale_frames: int = 8,
    **overrides,
) -> dict:
    """The bench_fleet sampler block (``fleet/sampler_chaos.py``):

    - **ab**: a fault-free three-arm sweep — host vs dealer vs device
      (the PR-17 on-device descent) — under the SAME offered load and
      seed: wire_to_grad / deal_to_grad p95 on each arm, buffer-lock
      acquisitions on the consume path (the dealer and device arms'
      must be 0 by construction), blocks/s dealt.
    - **chaos**: one dealer-mode run at ``n_actors`` with the full
      fault set — seeded sender chaos, consumer kills + ring clears,
      shed pressure, stale-generation frame injection — gated by the
      run oracles (0 deadlocks / violations / orphans / dealt dead
      tickets).
    """
    from d4pg_tpu.fleet.sampler_chaos import (
        SamplerChaosConfig,
        run_sampler_chaos,
    )

    ab = {}
    for path in ("host", "dealer", "device"):
        r = run_sampler_chaos(
            SamplerChaosConfig(
                sample_path=path, n_actors=int(n_actors),
                duration_s=float(duration_s), learner_kills=0,
                stale_frames=0, seed=int(seed), **overrides),
            chaos=ChaosConfig(seed=int(seed)))
        ab[path] = {
            "wire_to_grad_p95_ms": r["wire_to_grad_p95_ms"],
            "deal_to_grad_p95_ms": r["deal_to_grad_p95_ms"],
            "sample_path_buffer_acqs":
                r["consumer"]["sample_path_buffer_acqs"],
            "blocks_consumed": r["consumer"]["blocks_consumed"],
            "rows_inserted": r["rows_inserted"],
            "deadlocks": r["deadlocks"],
            "hierarchy_violations": r["hierarchy_violations"],
            "trace_orphans": r["trace_orphans"],
            "sampler": r["sampler"],
        }
    h = ab["host"]["wire_to_grad_p95_ms"]
    for path in ("dealer", "device"):
        d = ab[path]["wire_to_grad_p95_ms"]
        ab[path]["wire_to_grad_p95_delta_ms"] = (
            round(d - h, 3) if d is not None and h is not None else None)
    # legacy top-level delta (dealer vs host) kept for old readers
    ab["wire_to_grad_p95_delta_ms"] = ab["dealer"]["wire_to_grad_p95_delta_ms"]
    chaos_row = run_sampler_chaos(SamplerChaosConfig(
        sample_path="dealer", n_actors=int(n_actors),
        duration_s=float(duration_s), learner_kills=int(learner_kills),
        stale_frames=int(stale_frames), seed=int(seed), **overrides),
        chaos=default_chaos(int(seed)))
    return {"metric": "fleet_sampler", "schema": 1, "n_actors": int(n_actors),
            "ab": ab, "chaos": chaos_row, "seed": int(seed)}


def run_serving(
    lane_counts=(1, 2, 4),
    envs_per_lane: int = 4,
    duration_s: float = 3.0,
    seed: int = 0,
    server_kills: int = 1,
    torn_prob: float = 0.05,
    pair_lanes: int | None = None,
    **overrides,
) -> dict:
    """The bench_fleet serving block (``fleet/serving_chaos.py``):
    actions/s vs lane count from fault-free rows (batch occupancy and
    request latency percentiles per row), ONE batched-vs-unbatched pair
    at ``pair_lanes`` (default ``max(lane_counts)`` floored at 16 —
    continuous batching is a concurrency claim, and at a handful of
    closed-loop single-row lanes the amortization margin sits inside
    one-core scheduling noise) with single-row requests — the continuous-
    batching claim measured on the same wire, BOTH arms at zero window
    so exactly one thing differs: the batched arm coalesces every
    pending request into one dispatch (``max_batch_rows`` default)
    while the unbatched arm pops one request per dispatch
    (``max_batch_rows=1``), i.e. N independent single-row dispatches.
    Zero window is the greedy continuous-batching configuration —
    requests that arrive while a dispatch is in flight coalesce into
    the next one — and is what isolates dispatch amortization from the
    window's latency tax (the nonzero default window only pays off for
    multi-row requests; the sweep rows above measure that default).
    Also one chaos row (seeded server kills + torn responses) with its
    MTTR and run-gating oracles. One-core caveat: lanes, server and
    publisher share the host, so absolute actions/s is conservative;
    the batched/unbatched ratio is the honest headline."""
    from d4pg_tpu.fleet.serving_chaos import run_serving_chaos

    sweep = []
    for n in lane_counts:
        r = run_serving_chaos(
            n_lanes=int(n), envs_per_lane=int(envs_per_lane),
            duration_s=float(duration_s), server_kills=0, torn_prob=0.0,
            seed=int(seed), **overrides)
        sweep.append({
            "n_lanes": int(n),
            "actions_per_sec": r["actions_per_sec"],
            "requests": r["requests"],
            "served": r["served"],
            "fallbacks": r["fallbacks"],
            "batch_occupancy": r["batch_occupancy"],
            "latency_ms": r["latency_ms"],
            "trace_orphans": r["trace"]["orphans"],
            "hierarchy_violations": r["hierarchy_violations"],
        })

    # the batching claim: same lanes, same wire, single-row requests,
    # both arms at zero window; only the coalescing cap differs
    n_pair = int(pair_lanes if pair_lanes is not None
                 else max(max(lane_counts), 16))
    batched = run_serving_chaos(
        n_lanes=n_pair, envs_per_lane=1, duration_s=float(duration_s),
        server_kills=0, torn_prob=0.0, seed=int(seed) + 1,
        batch_window_s=0.0, **overrides)
    unbatched = run_serving_chaos(
        n_lanes=n_pair, envs_per_lane=1, duration_s=float(duration_s),
        server_kills=0, torn_prob=0.0, seed=int(seed) + 1,
        batch_window_s=0.0, max_batch_rows=1, **overrides)
    pair = {
        "n_lanes": n_pair,
        "batched_actions_per_sec": batched["actions_per_sec"],
        "unbatched_actions_per_sec": unbatched["actions_per_sec"],
        "speedup": (round(batched["actions_per_sec"]
                          / unbatched["actions_per_sec"], 3)
                    if unbatched["actions_per_sec"] else None),
        "batched_latency_ms": batched["latency_ms"],
        "unbatched_latency_ms": unbatched["latency_ms"],
        "batched_occupancy": batched["batch_occupancy"],
    }

    chaos_row = run_serving_chaos(
        n_lanes=int(max(lane_counts)), envs_per_lane=int(envs_per_lane),
        duration_s=float(duration_s), server_kills=int(server_kills),
        torn_prob=float(torn_prob), seed=int(seed), **overrides)
    return {"metric": "fleet_serving", "schema": 1, "sweep": sweep,
            "batching": pair, "chaos": chaos_row, "seed": int(seed)}


def run_elastic(seed: int = 0, **overrides) -> dict:
    """The bench_fleet elastic block (``fleet/elastic_chaos.py``): the
    flash-crowd A/B drill — identical seeded offered load (the traffic
    model's schedule is a pure recurrence over each lane's model clock)
    through a static arm and an autoscaler arm — plus the offered-load
    determinism probe (two models from the same config must emit the
    bit-identical fleet curve). The drill's ``ab_gate`` must pass in
    every committed artifact: strictly fewer serving SLO breaches AND
    strictly fewer ingest shed rows in the autoscaler arm, with the
    scaling ledger replaying bit-identically from its recorded
    signals."""
    import numpy as np

    from d4pg_tpu.elastic.traffic import TrafficModel
    from d4pg_tpu.fleet.elastic_chaos import (
        ElasticChaosConfig,
        run_elastic_chaos,
    )

    drill = run_elastic_chaos(seed=int(seed), **overrides)
    cfg = ElasticChaosConfig(seed=int(seed))
    tcfg = cfg.serving_traffic()
    dt = cfg.model_horizon_s / 48.0
    offered = TrafficModel(tcfg).fleet_trace(cfg.model_horizon_s, dt)
    replayed = TrafficModel(tcfg).fleet_trace(cfg.model_horizon_s, dt)
    return {
        "metric": "fleet_elastic",
        "schema": 1,
        "offered_rows_per_s": [round(float(x), 2) for x in offered],
        "offered_deterministic": bool(np.array_equal(offered, replayed)),
        "drill": drill,
        "seed": int(seed),
    }


def _lock_wait_ms(row: dict) -> float | None:
    """Total contended-acquisition wait across every tiered lock."""
    locks = row.get("locks")
    if not locks:
        return None
    return round(sum(per["wait_ns"]
                     for per in locks["per_lock"].values()) / 1e6, 3)


# The stage pairs the scaling table surfaces (p95 of each, ms) — the
# full histograms stay in the row's ``latency`` block.
_STAGE_COLUMNS = ("wire_to_admission", "admission_to_decode",
                  "decode_to_stage", "stage_to_merge", "merge_to_commit",
                  "wire_to_commit", "wire_to_grad")


def _stage_attribution(row: dict) -> dict | None:
    """p95 per pipeline stage from the row's trace-span latency block."""
    lat = row.get("latency")
    if not lat or not lat.get("stages"):
        return None
    return {name: lat["stages"][name]["p95"]
            for name in _STAGE_COLUMNS if name in lat["stages"]}


def main(argv=None):
    ap = argparse.ArgumentParser(prog="d4pg_tpu.fleet.sweep")
    ap.add_argument("--ns", type=int, nargs="+", default=list(SWEEP_NS))
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--rows_per_sec", type=float, default=20.0)
    ap.add_argument("--block_rows", type=int, default=16)
    ap.add_argument("--mode", choices=("thread", "process", "actor"),
                    default="thread")
    ap.add_argument("--ingest_shards", type=int, default=1,
                    help="receiver-side ingest shards K (SO_REUSEPORT "
                         "listeners + K decode workers + ordered merge)")
    ap.add_argument("--codec", choices=("auto", "npz", "raw"),
                    default="auto")
    ap.add_argument("--shards_sweep", type=int, nargs="+", default=None,
                    metavar="K",
                    help="run the fixed-N shard sweep over these K values "
                         "instead of the N sweep (e.g. --shards_sweep 1 2 4)")
    ap.add_argument("--trace_sample", type=float, default=None,
                    help="wire-to-grad trace sampling rate (raw codec "
                         "only; shard sweep default: obs.trace."
                         "DEFAULT_SAMPLE on K>=2 rows, N sweep default: "
                         "off)")
    ap.add_argument("--weights", action="store_true",
                    help="run the weight-chaos harness (broadcast plane: "
                         "N pullers over a relay tree, torn/stale/kill "
                         "faults) instead of the ingest sweep")
    ap.add_argument("--relay_depth", type=int, default=2)
    ap.add_argument("--learners", type=int, nargs="+", default=None,
                    help="run the multi-learner block instead: updates/s "
                         "vs these replica counts + one replica-kill "
                         "chaos row (fleet/learner_chaos.py)")
    ap.add_argument("--sampler", action="store_true",
                    help="run the sample-on-ingest block instead: a "
                         "dealer-vs-host A/B pair + one dealer chaos row "
                         "(consumer kills, shed pressure, stale-gen "
                         "injection — fleet/sampler_chaos.py)")
    ap.add_argument("--serving", type=int, nargs="+", default=None,
                    metavar="LANES",
                    help="run the serving block instead: actions/s vs "
                         "these lane counts, a batched-vs-unbatched pair "
                         "and one server-kill chaos row "
                         "(fleet/serving_chaos.py)")
    ap.add_argument("--elastic", action="store_true",
                    help="run the elastic block instead: the flash-crowd "
                         "autoscaler-on/off A/B drill at equal seeded "
                         "offered load (fleet/elastic_chaos.py)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no_chaos", action="store_true",
                    help="clean-plane control run (all fault probs 0)")
    ap.add_argument("--out", default=None,
                    help="also write the artifact JSON to this path")
    ns = ap.parse_args(argv)
    chaos = (ChaosConfig(seed=ns.seed) if ns.no_chaos
             else default_chaos(ns.seed))
    if ns.elastic:
        artifact = run_elastic(seed=ns.seed)
    elif ns.sampler:
        artifact = run_sampler(
            n_actors=max(ns.ns), duration_s=ns.seconds, seed=ns.seed,
            **({"learner_kills": 0, "stale_frames": 0}
               if ns.no_chaos else {}))
    elif ns.serving:
        artifact = run_serving(
            lane_counts=tuple(ns.serving), duration_s=ns.seconds,
            seed=ns.seed,
            **({"server_kills": 0, "torn_prob": 0.0}
               if ns.no_chaos else {}))
    elif ns.learners:
        artifact = run_learners(
            ns=tuple(ns.learners), duration_s=ns.seconds, seed=ns.seed,
            **({"replica_kills": 0, "torn_prob": 0.0}
               if ns.no_chaos else {}))
    elif ns.weights:
        artifact = run_weights(
            n_pullers=max(ns.ns), relay_depth=ns.relay_depth,
            duration_s=ns.seconds, seed=ns.seed,
            **({"torn_prob": 0.0, "stale_prob": 0.0, "learner_kills": 0,
                "relay_kills": 0} if ns.no_chaos else {}))
    elif ns.shards_sweep:
        artifact = shard_sweep(ks=tuple(ns.shards_sweep),
                               n_actors=max(ns.ns), duration_s=ns.seconds,
                               rows_per_sec=ns.rows_per_sec, chaos=chaos,
                               block_rows=ns.block_rows, codec=ns.codec,
                               trace_sample=ns.trace_sample)
    else:
        artifact = run_sweep(ns=tuple(ns.ns), duration_s=ns.seconds,
                             chaos=chaos, rows_per_sec=ns.rows_per_sec,
                             block_rows=ns.block_rows, mode=ns.mode,
                             ingest_shards=ns.ingest_shards, codec=ns.codec,
                             trace_sample=ns.trace_sample or 0.0)
    if ns.out:
        with open(ns.out, "w") as f:
            json.dump(artifact, f, indent=2)
    print(json.dumps(artifact))


if __name__ == "__main__":
    main()
