"""meshgraph — whole-program sharding & collective static analysis.

Fourth member of the whole-program family (lockgraph: tiers/cycles,
wiregraph: frame-registry symmetry, failgraph: exception flow).  This one
models the *mesh* surface: where ``shard_map`` binds axis names, which
collectives consume them, how sharding specs flow from the partition-rule
core into ``jit``/``device_put`` consumers, and which jitted callables
donate buffers that a caller might still be holding.

Three families over a call-graph-aware index of every jit/``shard_map``/
collective site:

- ``collective-axis-unbound`` (19): every ``psum``/``pmean``/
  ``all_gather``/``axis_index``-style use of an ``axis_name`` must be
  reachable only from a ``shard_map`` (or mesh-context) site binding that
  axis, and the axis identity must be one of the axes declared in
  ``parallel/mesh.py`` — spelled as the declared CONSTANT, never as a raw
  string (a hand-spelled ``'data'`` silently desynchronizes from a mesh
  rename).  Helpers called under a binder established elsewhere may
  declare ``# jaxlint: axis-bound-by=<caller>`` on the def line; the
  declaration is audited like failgraph's ``contained-by`` (the named
  caller must itself resolve to a bound frame).
- ``sharding-spec-drift`` (20): extends family 15 from constructor sites
  to DATAFLOW — an ``in_shardings``/``out_shardings``/``device_put``
  sharding argument must resolve (through local aliases, self-attributes
  and helper returns) to a ``parallel/partition.py`` factory; resolving
  to a raw ``NamedSharding``/``PartitionSpec`` construction reached
  through an alias is flagged, and a tree placed under one rule-resolved
  factory but later re-placed under a different one is an implicit
  reshard.  Device-placement calls (``device_put(x, device)``) resolve to
  a parameter or opaque handle and are deliberately not flagged.
- ``donation-alias`` (21): a call into a ``donate_argnums`` signature
  whose donated argument textually aliases another argument, or is a
  captured reference (``self._x`` / ``obj.attr``) that the call's
  assignment neither rebinds nor hands back to its owner — the PR-10
  replica deep-copy defect shape, caught statically.  Donation
  signatures resolve through module jit bindings, function-local
  ``fn = jax.jit(...)`` aliases, ``self._fn = jax.jit(...)`` /
  ``self._fn = self._make_fn()`` attributes, jit-decorated defs, and
  factory returns (same- and cross-module).

The declared-axis table is MIRRORED from ``parallel/mesh.py`` (and the
factory list from ``parallel/partition.py.__all__``), not imported: the
lint package is stdlib-only by contract.  tests/test_meshgraph.py pins
the mirrors against the real modules.

Pure stdlib (ast) — same contract as the rest of the package.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from d4pg_tpu.lint.context import (
    FunctionNode,
    ModuleContext,
    _int_tuple,
    dotted_name,
    last_part,
)
from d4pg_tpu.lint.findings import Finding

MESH_RULES = (
    "collective-axis-unbound",
    "sharding-spec-drift",
    "donation-alias",
)

_AXIS_BOUND_BY = re.compile(r"#\s*jaxlint:\s*axis-bound-by=([\w\.\-,]+)")

# Mirrored, not imported: the lint package is stdlib-only by contract.
# tests/test_meshgraph.py pins this table against parallel/mesh.py —
# any axis added, renamed or removed there fails the pin with the exact
# constant named.
_DECLARED_AXES = {
    "DATA_AXIS": "data",
    "MODEL_AXIS": "model",
    "REPLICA_AXIS": "replica",
}
_AXIS_VALUES = set(_DECLARED_AXES.values())

# Sharding-producing names of parallel/partition.py — the sanctioned
# resolution targets of family 20.  Mirrored (subset of
# partition.__all__; pinned by tests/test_meshgraph.py).
_FACTORIES = {
    "spec", "sharding", "replicated", "batch_sharding", "stacked_sharding",
    "replica_sharding", "replicated_spec", "batch_spec", "data_spec",
    "stacked_spec", "replica_spec", "shardings_for", "state_specs",
    "state_shardings", "replica_stack_shardings", "match_partition_rules",
}

# Raw sharding constructors — reaching one of these through an alias is
# exactly the drift family 15 cannot see (it only flags the ctor SITE).
_SHARDING_CTORS = {
    "NamedSharding", "PartitionSpec", "PS", "P", "PositionalSharding",
    "GSPMDSharding", "SingleDeviceSharding",
}

# Collective op -> positional index of its axis-name operand (the
# ``axis_name=`` kwarg always wins).  ``fold_in`` is excluded: its second
# operand is DATA (usually an ``axis_index`` value, which is itself a
# family-19 site).
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "pbroadcast": 1,
    "axis_index": 0, "axis_size": 0,
}

_JIT_NAMES = {"jit", "pjit"}

_MAX_DEPTH = 6


def _short(path: str) -> str:
    return path.rsplit("/d4pg_tpu/", 1)[-1] if "/d4pg_tpu/" in path else path


def _is_partition_module(path: str) -> bool:
    return path.replace("\\", "/").endswith("parallel/partition.py")


def _unwrap_partial(call: ast.Call) -> ast.expr | None:
    if last_part(dotted_name(call.func)) == "partial" and call.args:
        return call.args[0]
    return None


def _jit_call(node: ast.expr) -> ast.Call | None:
    """The ``jax.jit(...)``/``pjit(...)`` call denoted by ``node`` (through
    one ``partial`` wrapper), else None."""
    if not isinstance(node, ast.Call):
        return None
    inner = _unwrap_partial(node)
    if inner is not None and isinstance(inner, ast.Call):
        return _jit_call(inner)
    if inner is not None:
        return None
    if last_part(dotted_name(node.func)) in _JIT_NAMES:
        return node
    return None


def _decorator_jit_kwargs(node: ast.AST) -> dict[str, ast.expr]:
    """kwargs of a ``@partial(jax.jit, donate_argnums=...)``-style
    decorator on a def (bare ``@jax.jit`` carries none)."""
    out: dict[str, ast.expr] = {}
    for dec in getattr(node, "decorator_list", ()):
        if isinstance(dec, ast.Call):
            target = _unwrap_partial(dec)
            name = last_part(dotted_name(
                target if target is not None else dec.func))
            if name in _JIT_NAMES:
                out.update({k.arg: k.value for k in dec.keywords if k.arg})
    return out


def _bound_lines(source: str) -> dict[int, tuple[str, ...]]:
    out: dict[int, tuple[str, ...]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _AXIS_BOUND_BY.search(text)
        if m:
            out[i] = tuple(h.strip() for h in m.group(1).split(",")
                           if h.strip())
    return out


# --------------------------------------------------------------------------
# Program index
# --------------------------------------------------------------------------

@dataclass
class _ShardMapSite:
    path: str
    line: int
    col: int
    body_src: str
    axes: frozenset[str]
    bodies: list[ast.AST] = field(default_factory=list)


@dataclass
class _CollectiveSite:
    path: str
    line: int
    col: int
    op: str
    axis_expr: ast.expr | None
    fn_stack: tuple[ast.AST, ...]     # innermost first; () at module scope
    scopes: tuple[ast.AST, ...]       # name-resolution chain, innermost first


@dataclass
class _ShardingSite:
    path: str
    line: int
    col: int
    kind: str                         # in_shardings | out_shardings | ...
    expr: ast.expr
    scopes: tuple[ast.AST, ...]
    cls: str | None


@dataclass
class _CallSite:
    path: str
    call: ast.Call
    stmt: ast.stmt | None
    fn: ast.AST | None                # enclosing function (stmt list owner)
    scopes: tuple[ast.AST, ...]
    cls: str | None


@dataclass
class _Mod:
    ctx: ModuleContext
    # scope node (module tree or function node) -> {name: [value exprs]}
    envs: dict[int, dict[str, list[ast.expr]]]
    # class name -> attr -> [value exprs] (``self.attr = ...`` anywhere)
    self_attrs: dict[str, dict[str, list[ast.expr]]]
    # def node id -> parameter-name set
    params: dict[int, set[str]]
    by_bare: dict[str, list[ast.AST]]
    qual_of: dict[int, str]
    shard_maps: list[_ShardMapSite]
    collectives: list[_CollectiveSite]
    shardings: list[_ShardingSite]
    calls: list[_CallSite]
    bound_ann: dict[int, tuple[str, ...]]   # def lineno -> declared binders


@dataclass
class _Program:
    mods: list[_Mod]
    by_bare: dict[str, list[tuple[_Mod, ast.AST]]]
    by_qual: dict[str, list[tuple[_Mod, ast.AST]]]
    # binding fixpoint: id(def node) -> bound axis set
    bound: dict[int, frozenset[str]] = field(default_factory=dict)


def _mesh_axes(mod: _Mod, scopes: tuple[ast.AST, ...],
               expr: ast.expr | None, depth: int = 0) -> frozenset[str]:
    """Axes a shard_map's ``mesh=`` operand binds.  ``make_mesh`` ->
    (data, model); ``replica_mesh`` -> all three; anything opaque (a
    parameter, ``self.mesh``) conservatively binds every declared axis —
    family 19's teeth are the NO-binder case, not axis-set mismatches on
    handles the AST cannot see."""
    if expr is None or depth > _MAX_DEPTH:
        return frozenset(_AXIS_VALUES)
    if isinstance(expr, ast.Call):
        name = last_part(dotted_name(expr.func))
        if name == "make_mesh":
            return frozenset({"data", "model"})
        if name == "replica_mesh":
            return frozenset(_AXIS_VALUES)
        return frozenset(_AXIS_VALUES)
    if isinstance(expr, ast.Name):
        for val in _lookup(mod, scopes, expr.id):
            return _mesh_axes(mod, scopes, val, depth + 1)
    return frozenset(_AXIS_VALUES)


def _lookup(mod: _Mod, scopes: tuple[ast.AST, ...],
            name: str) -> list[ast.expr]:
    for scope in scopes:
        vals = mod.envs.get(id(scope), {}).get(name)
        if vals:
            return vals
    return []


def _index_module(ctx: ModuleContext) -> _Mod:
    mod = _Mod(ctx=ctx, envs={}, self_attrs={}, params={}, by_bare={},
               qual_of={}, shard_maps=[], collectives=[], shardings=[],
               calls=[], bound_ann=_bound_lines(ctx.source))

    def record_assign(scope: ast.AST, target: ast.expr, value: ast.expr,
                      cls: str | None) -> None:
        if isinstance(target, ast.Name):
            mod.envs.setdefault(id(scope), {}).setdefault(
                target.id, []).append(value)
        elif (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id == "self" and cls):
            mod.self_attrs.setdefault(cls, {}).setdefault(
                target.attr, []).append(value)

    def visit(node: ast.AST, scopes: tuple[ast.AST, ...],
              fn_stack: tuple[ast.AST, ...], cls: str | None,
              stmt: ast.stmt | None, qual: str) -> None:
        for child in ast.iter_child_nodes(node):
            c_scopes, c_stack, c_cls, c_stmt, c_qual = (
                scopes, fn_stack, cls, stmt, qual)
            if isinstance(child, ast.stmt):
                c_stmt = child
            if isinstance(child, ast.ClassDef):
                c_cls = child.name
                c_qual = f"{qual}{child.name}."
            elif isinstance(child, FunctionNode):
                c_scopes = (child, *scopes)
                c_stack = (child, *fn_stack)
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    name = child.name
                    mod.by_bare.setdefault(name, []).append(child)
                    mod.qual_of[id(child)] = f"{qual}{name}"
                    c_qual = f"{qual}{name}."
                args = child.args
                mod.params[id(child)] = {
                    a.arg for a in (args.posonlyargs + args.args
                                    + args.kwonlyargs)}
                if args.vararg:
                    mod.params[id(child)].add(args.vararg.arg)
                if args.kwarg:
                    mod.params[id(child)].add(args.kwarg.arg)
            elif isinstance(child, ast.Assign):
                for t in child.targets:
                    targets = t.elts if isinstance(
                        t, (ast.Tuple, ast.List)) else [t]
                    for one in targets:
                        record_assign(scopes[0], one, child.value, cls)
            elif isinstance(child, ast.AnnAssign) and child.value is not None:
                record_assign(scopes[0], child.target, child.value, cls)
            elif isinstance(child, ast.Call):
                _index_call(mod, child, scopes, fn_stack, cls, stmt)
            visit(child, c_scopes, c_stack, c_cls, c_stmt, c_qual)

    visit(ctx.tree, (ctx.tree,), (), None, None, "")
    return mod


def _index_call(mod: _Mod, call: ast.Call, scopes: tuple[ast.AST, ...],
                fn_stack: tuple[ast.AST, ...], cls: str | None,
                stmt: ast.stmt | None) -> None:
    path = mod.ctx.path
    name = last_part(dotted_name(call.func))
    kwargs = {k.arg: k.value for k in call.keywords if k.arg}

    if name == "shard_map":
        bodies: list[ast.AST] = []
        body_expr = call.args[0] if call.args else kwargs.get("f")
        if body_expr is not None:
            bodies.extend(_body_fns(mod, scopes, body_expr))
        site = _ShardMapSite(
            path=path, line=call.lineno, col=call.col_offset,
            body_src=ast.unparse(body_expr) if body_expr is not None
            else "?",
            axes=_mesh_axes(mod, scopes, kwargs.get("mesh")),
            bodies=bodies)
        mod.shard_maps.append(site)

    if name in _COLLECTIVES:
        pos = _COLLECTIVES[name]
        axis_expr = kwargs.get("axis_name")
        if axis_expr is None and len(call.args) > pos:
            axis_expr = call.args[pos]
        mod.collectives.append(_CollectiveSite(
            path=path, line=call.lineno, col=call.col_offset, op=name,
            axis_expr=axis_expr, fn_stack=fn_stack, scopes=scopes))

    jit = _jit_call(call)
    if jit is not None:
        jkw = {k.arg: k.value for k in jit.keywords if k.arg}
        for kind in ("in_shardings", "out_shardings"):
            if kind in jkw:
                mod.shardings.append(_ShardingSite(
                    path=path, line=call.lineno, col=call.col_offset,
                    kind=kind, expr=jkw[kind], scopes=scopes, cls=cls))
    if name == "device_put":
        spec = call.args[1] if len(call.args) > 1 else kwargs.get("device")
        if spec is not None:
            mod.shardings.append(_ShardingSite(
                path=path, line=call.lineno, col=call.col_offset,
                kind="device_put", expr=spec, scopes=scopes, cls=cls))
    if name == "make_array_from_process_local_data":
        spec = call.args[0] if call.args else kwargs.get("sharding")
        if spec is not None:
            mod.shardings.append(_ShardingSite(
                path=path, line=call.lineno, col=call.col_offset,
                kind="process_local", expr=spec, scopes=scopes, cls=cls))

    if isinstance(call.func, (ast.Name, ast.Attribute, ast.Call)):
        mod.calls.append(_CallSite(
            path=path, call=call, stmt=stmt,
            fn=fn_stack[0] if fn_stack else None, scopes=scopes, cls=cls))


def _body_fns(mod: _Mod, scopes: tuple[ast.AST, ...],
              expr: ast.expr) -> list[ast.AST]:
    """Function nodes a shard_map body expression can denote: a bare name
    (every same-module def so named — mark-all keeps the pass biased
    toward bound), a lambda (plus the defs its body references), or a
    ``partial(f, ...)`` wrapper."""
    if isinstance(expr, ast.Call):
        inner = _unwrap_partial(expr)
        return _body_fns(mod, scopes, inner) if inner is not None else []
    if isinstance(expr, ast.Lambda):
        out: list[ast.AST] = [expr]
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                out.extend(mod.by_bare.get(node.id, ()))
        return out
    if isinstance(expr, ast.Name):
        return list(mod.by_bare.get(expr.id, ()))
    return []


def build_program(ctxs: list[ModuleContext]) -> _Program:
    mods = [_index_module(ctx) for ctx in ctxs]
    by_bare: dict[str, list[tuple[_Mod, ast.AST]]] = {}
    by_qual: dict[str, list[tuple[_Mod, ast.AST]]] = {}
    for mod in mods:
        for name, nodes in mod.by_bare.items():
            for node in nodes:
                by_bare.setdefault(name, []).append((mod, node))
        for name, nodes in mod.by_bare.items():
            for node in nodes:
                qual = mod.qual_of.get(id(node), name)
                by_qual.setdefault(qual, []).append((mod, node))
    prog = _Program(mods=mods, by_bare=by_bare, by_qual=by_qual)
    _propagate_bindings(prog)
    return prog


def _propagate_bindings(prog: _Program) -> None:
    """Fixpoint: a function passed to shard_map is bound with that site's
    axes; everything lexically nested in OR referenced by bare name from
    a bound function inherits the axes (mark-all-candidates across
    modules — conservative toward bound, family 19 only fires when no
    binder is reachable at all)."""
    work: list[tuple[ast.AST, frozenset[str]]] = []
    for mod in prog.mods:
        for site in mod.shard_maps:
            for body in site.bodies:
                work.append((body, site.axes))

    mod_of: dict[int, _Mod] = {}
    for mod in prog.mods:
        for nodes in mod.by_bare.values():
            for node in nodes:
                mod_of[id(node)] = mod
        for sm in mod.shard_maps:
            for body in sm.bodies:
                mod_of.setdefault(id(body), mod)

    while work:
        node, axes = work.pop()
        have = prog.bound.get(id(node), frozenset())
        if axes <= have:
            continue
        axes = axes | have
        prog.bound[id(node)] = axes
        mod = mod_of.get(id(node))
        for child in ast.walk(node):
            if isinstance(child, FunctionNode) and child is not node:
                mod_of.setdefault(id(child), mod)
                work.append((child, axes))
            if (isinstance(child, ast.Name)
                    and isinstance(child.ctx, ast.Load)):
                if mod is not None:
                    for cand in mod.by_bare.get(child.id, ()):
                        work.append((cand, axes))
                else:
                    for cmod, cand in prog.by_bare.get(child.id, ()):
                        work.append((cand, axes))


# --------------------------------------------------------------------------
# Family 19 — collective-axis-unbound
# --------------------------------------------------------------------------

def _resolve_axis(mod: _Mod, site: _CollectiveSite,
                  expr: ast.expr | None, depth: int = 0
                  ) -> tuple[str, str]:
    """(axis value or '?', status): 'pinned' (declared constant),
    'literal' (hand-spelled string equal to a declared axis), 'unknown'
    (string naming no declared axis), 'opaque' (parameter / handle)."""
    if expr is None or depth > _MAX_DEPTH:
        return "?", "opaque"
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        if expr.value in _AXIS_VALUES:
            return expr.value, "literal"
        return expr.value, "unknown"
    name = last_part(dotted_name(expr))
    if name in _DECLARED_AXES:
        return _DECLARED_AXES[name], "pinned"
    if isinstance(expr, ast.Name):
        for val in _lookup(mod, site.scopes, expr.id):
            return _resolve_axis(mod, site, val, depth + 1)
    if isinstance(expr, (ast.Tuple, ast.List)) and expr.elts:
        # multi-axis collective: report the first non-opaque element
        for elt in expr.elts:
            axis, status = _resolve_axis(mod, site, elt, depth + 1)
            if status != "opaque":
                return axis, status
        return "?", "opaque"
    return "?", "opaque"


def _resolve_binder(prog: _Program, spec: str) -> list[tuple[_Mod, ast.AST]]:
    cands = prog.by_qual.get(spec, [])
    if not cands:
        cands = prog.by_bare.get(spec.rsplit(".", 1)[-1], [])
    return cands


def _check_collectives(prog: _Program, graph: "MeshGraph", emit) -> None:
    for mod in prog.mods:
        for site in mod.collectives:
            where = f"{_short(site.path)}:{site.line}"
            axis, axis_status = _resolve_axis(mod, site, site.axis_expr)

            if axis_status == "literal":
                emit("collective-axis-unbound", site.path, site.line,
                     site.col,
                     f"{site.op} axis {axis!r} is hand-spelled — use the "
                     f"declared constant from parallel/mesh.py "
                     f"({_axis_const(axis)}) so a mesh rename cannot "
                     f"silently desynchronize the collective")
            elif axis_status == "unknown":
                emit("collective-axis-unbound", site.path, site.line,
                     site.col,
                     f"{site.op} names axis {axis!r}, which is not a "
                     f"declared mesh axis (parallel/mesh.py declares "
                     f"{sorted(_AXIS_VALUES)})")

            binder = None
            for fn in site.fn_stack:
                axes = prog.bound.get(id(fn))
                if axes is None:
                    continue
                if axis_status == "opaque" or axis in axes:
                    binder = fn
                    break
            if binder is not None:
                qual = mod.qual_of.get(id(binder), "<lambda>")
                graph.collectives.append(
                    (where, site.op, axis, f"shard_map:{qual}", "bound"))
                continue

            # no reachable binder: an audited axis-bound-by declaration
            # on the innermost enclosing def is the only way out
            declared = ()
            for fn in site.fn_stack:
                declared = mod.bound_ann.get(fn.lineno, ())
                if declared:
                    break
            if declared:
                status = "declared"
                for spec in declared:
                    cands = _resolve_binder(prog, spec)
                    if not cands:
                        graph.handlers[spec] = "unresolved"
                        status = "declared!"
                        emit("collective-axis-unbound", site.path,
                             site.line, site.col,
                             f"axis-bound-by={spec}: declared binder does "
                             f"not resolve to a known function — the "
                             f"binding declaration is unauditable")
                    elif not any(id(n) in prog.bound for _m, n in cands):
                        graph.handlers[spec] = "weak"
                        status = "declared!"
                        emit("collective-axis-unbound", site.path,
                             site.line, site.col,
                             f"axis-bound-by={spec}: declared binder is "
                             f"not itself under any shard_map axis "
                             f"binding — same bar as a direct binding")
                    else:
                        graph.handlers.setdefault(spec, "ok")
                graph.collectives.append(
                    (where, site.op, axis,
                     "axis-bound-by=" + ",".join(declared), status))
                continue

            graph.collectives.append((where, site.op, axis, "-", "unbound"))
            emit("collective-axis-unbound", site.path, site.line, site.col,
                 f"{site.op}({axis!r}) is not reachable from any shard_map "
                 f"site binding that axis — outside a binder the collective "
                 f"is an unbound-axis trace error at best and a silent "
                 f"cross-replica leak at worst; move it under the binding "
                 f"shard_map or declare `# jaxlint: axis-bound-by=<caller>`")


def _axis_const(value: str) -> str:
    for const, v in _DECLARED_AXES.items():
        if v == value:
            return const
    return "?"


# --------------------------------------------------------------------------
# Family 20 — sharding-spec-drift
# --------------------------------------------------------------------------

def _resolve_spec(prog: _Program, mod: _Mod, site: _ShardingSite,
                  expr: ast.expr, depth: int = 0) -> tuple[str, str]:
    """(status, label).  status: 'factory' (partition.py), 'ctor' (raw
    sharding constructor reached through dataflow — the drift), 'param',
    'opaque', 'tree' (composite whose elements all resolved clean)."""
    if depth > _MAX_DEPTH:
        return "opaque", "..."
    if isinstance(expr, ast.Constant):
        return "opaque", repr(expr.value)
    if isinstance(expr, ast.Call):
        name = last_part(dotted_name(expr.func))
        if name in _FACTORIES:
            return "factory", name
        if name in _SHARDING_CTORS:
            return "ctor", name
        # helper call: resolve through its returns (same/cross module)
        for cand_mod, cand in _call_defs(prog, mod, site, expr):
            for ret in _return_exprs(cand):
                st, label = _resolve_spec(prog, cand_mod,
                                          _site_in(cand_mod, cand, site),
                                          ret, depth + 1)
                if st in ("factory", "ctor"):
                    return st, f"{name}->{label}"
        return "opaque", name or ast.unparse(expr)[:40]
    if isinstance(expr, (ast.Tuple, ast.List, ast.Dict)):
        elts = (list(expr.values) if isinstance(expr, ast.Dict)
                else list(expr.elts))
        labels = []
        for elt in elts:
            if elt is None:
                continue
            st, label = _resolve_spec(prog, mod, site, elt, depth + 1)
            if st == "ctor":
                return "ctor", label
            labels.append(label)
        return "tree", "(" + ", ".join(dict.fromkeys(labels)) + ")"
    if isinstance(expr, ast.Name):
        for scope in site.scopes:
            if expr.id in mod.params.get(id(scope), ()):  # parameter
                return "param", expr.id
            vals = mod.envs.get(id(scope), {}).get(expr.id)
            if vals:
                for val in vals:
                    st, label = _resolve_spec(prog, mod, site, val,
                                              depth + 1)
                    if st != "opaque":
                        return st, label
                return "opaque", expr.id
        return "opaque", expr.id
    if isinstance(expr, ast.Attribute):
        if (isinstance(expr.value, ast.Name) and expr.value.id == "self"
                and site.cls):
            vals = mod.self_attrs.get(site.cls, {}).get(expr.attr, ())
            for val in vals:
                st, label = _resolve_spec(prog, mod, site, val, depth + 1)
                if st != "opaque":
                    return st, label
        return "opaque", ast.unparse(expr)
    if isinstance(expr, ast.IfExp):
        for branch in (expr.body, expr.orelse):
            st, label = _resolve_spec(prog, mod, site, branch, depth + 1)
            if st != "opaque":
                return st, label
        return "opaque", ast.unparse(expr)[:40]
    return "opaque", ast.unparse(expr)[:40]


def _site_in(mod: _Mod, fn: ast.AST, site: _ShardingSite) -> _ShardingSite:
    """A resolution context rooted at ``fn`` (for helper-return chasing)."""
    return _ShardingSite(path=mod.ctx.path, line=site.line, col=site.col,
                         kind=site.kind, expr=site.expr,
                         scopes=(fn, mod.ctx.tree), cls=site.cls)


def _call_defs(prog: _Program, mod: _Mod, site, expr: ast.Call
               ) -> list[tuple[_Mod, ast.AST]]:
    """Defs a helper call can reach: same-class ``self._m()`` methods,
    then bare-name candidates (same module first, then program-wide)."""
    func = expr.func
    if (isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name)
            and func.value.id == "self"):
        name = func.attr
    else:
        name = last_part(dotted_name(func))
    if not name:
        return []
    local = [(mod, n) for n in mod.by_bare.get(name, ())]
    if local:
        return local
    return list(prog.by_bare.get(name, ()))[:4]


def _return_exprs(fn: ast.AST) -> list[ast.expr]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            out.append(node.value)
    if isinstance(fn, ast.Lambda):
        out.append(fn.body)
    return out


def _check_shardings(prog: _Program, graph: "MeshGraph", emit) -> None:
    for mod in prog.mods:
        if _is_partition_module(mod.ctx.path):
            # the factory core itself constructs PS/NamedSharding — the
            # same exemption family 15 grants it
            continue
        for site in mod.shardings:
            status, label = _resolve_spec(prog, mod, site, site.expr)
            where = f"{_short(site.path)}:{site.line}"
            graph.shardings.append((where, site.kind, label, status))
            if status == "ctor":
                emit("sharding-spec-drift", site.path, site.line, site.col,
                     f"{site.kind} resolves to raw {label} construction "
                     f"outside parallel/partition.py — sharding specs flow "
                     f"from the partition-rule factories so layout "
                     f"decisions stay in one audited table")
        _check_reshard_flow(prog, mod, graph, emit)


def _check_reshard_flow(prog: _Program, mod: _Mod, graph: "MeshGraph",
                        emit) -> None:
    """Implicit reshard: within one function, a value placed under one
    rule-resolved factory and later re-placed under a DIFFERENT one —
    the device round of copies family 20's runtime twin
    (``ReshardSentinel``) counts in compiled HLO."""
    for fn_id, env in list(mod.envs.items()):
        producers: dict[str, tuple[str, int]] = {}
        sites = []
        for name, vals in env.items():
            for val in vals:
                if not (isinstance(val, ast.Call)
                        and last_part(dotted_name(val.func)) == "device_put"
                        and len(val.args) > 1):
                    continue
                fake = _ShardingSite(path=mod.ctx.path, line=val.lineno,
                                     col=val.col_offset, kind="device_put",
                                     expr=val.args[1],
                                     scopes=_scopes_for(mod, fn_id),
                                     cls=_cls_for(mod, fn_id))
                st, label = _resolve_spec(prog, mod, fake, val.args[1])
                if st != "factory":
                    continue
                src = val.args[0]
                sites.append((name, label, val))
                if isinstance(src, ast.Name) and src.id in producers:
                    prev_label, prev_line = producers[src.id]
                    if prev_label != label:
                        emit("sharding-spec-drift", mod.ctx.path,
                             val.lineno, val.col_offset,
                             f"tree {src.id!r} placed under "
                             f"partition.{prev_label} (line {prev_line}) "
                             f"is re-placed under partition.{label} — an "
                             f"implicit reshard (a full device-to-device "
                             f"copy); place it once under the spec its "
                             f"consumer needs")
                producers[name] = (label, val.lineno)


def _scopes_for(mod: _Mod, scope_id: int) -> tuple[ast.AST, ...]:
    for nodes in mod.by_bare.values():
        for node in nodes:
            if id(node) == scope_id:
                return (node, mod.ctx.tree)
    return (mod.ctx.tree,)


def _cls_for(mod: _Mod, scope_id: int) -> str | None:
    qual = None
    for nodes in mod.by_bare.values():
        for node in nodes:
            if id(node) == scope_id:
                qual = mod.qual_of.get(id(node))
    if qual and "." in qual:
        head = qual.split(".", 1)[0]
        if head in mod.self_attrs or head[:1].isupper():
            return head
    return None


# --------------------------------------------------------------------------
# Family 21 — donation-alias
# --------------------------------------------------------------------------

def _intersect(sets: list[set[int]]) -> tuple[int, ...]:
    """Must-donate set: a handle resolving to several jit bindings (the
    two branches of a factory) is treated as donating only the argnums
    EVERY binding donates — family 21 flags certainly-donated arguments,
    never maybe-donated ones."""
    live = [s for s in sets if s]
    if not live:
        return ()
    out = set(live[0])
    for s in live[1:]:
        out &= s
    return tuple(sorted(out))


def _donate_of_expr(prog: _Program, mod: _Mod, scopes, cls,
                    expr: ast.expr, depth: int = 0) -> tuple[int, ...]:
    """donate_argnums a callable-valued expression certainly resolves to
    (intersection over branches/returns); () when none or
    unresolvable."""
    if depth > _MAX_DEPTH:
        return ()
    jit = _jit_call(expr) if isinstance(expr, ast.Call) else None
    if jit is not None:
        kw = {k.arg: k.value for k in jit.keywords if k.arg}
        return _int_tuple(kw.get("donate_argnums"))
    if isinstance(expr, ast.Call):
        sets = [set(_donate_of_fn_returns(prog, cand_mod, cand, depth + 1))
                for cand_mod, cand in _call_defs(prog, mod, None, expr)]
        return _intersect(sets)
    if isinstance(expr, ast.Name):
        for scope in scopes:
            vals = mod.envs.get(id(scope), {}).get(expr.id)
            if vals:
                return _intersect([
                    set(_donate_of_expr(prog, mod, scopes, cls, val,
                                        depth + 1))
                    for val in vals])
        binding = mod.ctx.jit_bindings.get(expr.id)
        if binding is not None and binding.donate_argnums:
            return binding.donate_argnums
        return _intersect([
            set(_int_tuple(
                _decorator_jit_kwargs(node).get("donate_argnums")))
            for node in mod.by_bare.get(expr.id, ())])
    if isinstance(expr, ast.Attribute):
        if (isinstance(expr.value, ast.Name) and expr.value.id == "self"
                and cls):
            return _intersect([
                set(_donate_of_expr(prog, mod, scopes, cls, val,
                                    depth + 1))
                for val in mod.self_attrs.get(cls, {}).get(expr.attr, ())])
    return ()


def _donate_of_fn_returns(prog: _Program, mod: _Mod, fn: ast.AST,
                          depth: int) -> tuple[int, ...]:
    sets: list[set[int]] = []
    scopes = (fn, mod.ctx.tree)
    cls = _cls_for(mod, id(fn))
    for ret in _return_exprs(fn):
        got = set(_donate_of_expr(prog, mod, scopes, cls, ret, depth))
        # ``return name`` where name is a jit-decorated nested def
        if isinstance(ret, ast.Name):
            for node in ast.walk(fn):
                if (isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                        and node.name == ret.id):
                    got |= set(_int_tuple(_decorator_jit_kwargs(node)
                                          .get("donate_argnums")))
        sets.append(got)
    return _intersect(sets)


def _stmt_targets(stmt: ast.stmt | None) -> list[str]:
    if not isinstance(stmt, ast.Assign):
        return []
    out = []
    for t in stmt.targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        out.extend(ast.unparse(e) for e in elts)
    return out


def _handed_back(fn: ast.AST | None, stmt: ast.stmt | None,
                 base_src: str, bound_names: list[str]) -> bool:
    """True when a statement after ``stmt`` passes one of the call's
    result names back into the donated reference's owner — the
    ``self._store.swap_arrays(storage)`` shape — or rebinds the donated
    expression directly."""
    if fn is None or stmt is None:
        return False
    after = [n for n in ast.walk(fn)
             if isinstance(n, ast.stmt) and n.lineno > stmt.lineno]
    for n in after:
        for targ in _stmt_targets(n):
            if targ == base_src or targ.startswith(base_src + "."):
                return True
        for call in ast.walk(n):
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            recv = ast.unparse(func.value)
            if recv != base_src and not base_src.startswith(recv + "."):
                continue
            for arg in call.args:
                if (isinstance(arg, ast.Name)
                        and arg.id in bound_names):
                    return True
    return False


def _check_donations(prog: _Program, graph: "MeshGraph", emit) -> None:
    for mod in prog.mods:
        for cs in mod.calls:
            donated = _donate_of_expr(prog, mod, cs.scopes, cs.cls,
                                      cs.call.func)
            if not donated:
                continue
            where = f"{_short(cs.path)}:{cs.call.lineno}"
            target = ast.unparse(cs.call.func)
            targets = _stmt_targets(cs.stmt)
            bound_names = [t for t in targets if "." not in t
                           and "[" not in t]
            status = "ok"
            args = cs.call.args
            for idx in donated:
                if idx >= len(args):
                    continue
                arg = args[idx]
                arg_src = ast.unparse(arg)
                for j, other in enumerate(args):
                    if j != idx and ast.unparse(other) == arg_src:
                        status = "alias"
                        emit("donation-alias", cs.path, cs.call.lineno,
                             cs.call.col_offset,
                             f"{target}: donated argument {idx} "
                             f"({arg_src}) aliases argument {j} — XLA "
                             f"frees the buffer while the aliased operand "
                             f"still reads it; pass an independent copy "
                             f"(the replica deep-copy defect shape)")
                        break
                if status == "alias":
                    continue
                if isinstance(arg, (ast.Attribute, ast.Subscript)):
                    if arg_src in targets:
                        continue   # rebound by the same statement
                    base = ast.unparse(arg.value)
                    if _handed_back(cs.fn, cs.stmt, base, bound_names):
                        status = "handoff" if status == "ok" else status
                        continue
                    status = "captured"
                    emit("donation-alias", cs.path, cs.call.lineno,
                         cs.call.col_offset,
                         f"{target}: donated argument {idx} ({arg_src}) "
                         f"is a live captured reference the call neither "
                         f"rebinds nor hands back to its owner — after "
                         f"donation the holder points at freed memory; "
                         f"rebind the attribute from the result (or swap "
                         f"it back through the owning object)")
            graph.donations.append(
                (where, target, ",".join(map(str, donated)), status))


# --------------------------------------------------------------------------
# Graph artifact + analyze
# --------------------------------------------------------------------------

@dataclass
class MeshGraph:
    functions: int = 0
    modules: int = 0
    # declared axis mirror (constant name -> axis string)
    axes: dict[str, str] = field(default_factory=dict)
    # shard_map rows: (site, body src, bound-axes csv)
    shard_maps: list[tuple[str, str, str]] = field(default_factory=list)
    # collective rows: (site, op, axis, binding witness, status)
    collectives: list[tuple[str, str, str, str, str]] = field(
        default_factory=list)
    # sharding dataflow rows: (site, kind, resolution, status)
    shardings: list[tuple[str, str, str, str]] = field(default_factory=list)
    # donation rows: (site, callee, donated argnums csv, status)
    donations: list[tuple[str, str, str, str]] = field(default_factory=list)
    # axis-bound-by audit surface: spec -> ok | unresolved | weak
    handlers: dict[str, str] = field(default_factory=dict)
    findings: list[Finding] = field(default_factory=list)


def analyze(ctxs: list[ModuleContext],
            rules: list[str] | None = None) -> MeshGraph:
    prog = build_program(ctxs)
    n_fns = sum(len(nodes) for mod in prog.mods
                for nodes in mod.by_bare.values())
    graph = MeshGraph(functions=n_fns, modules=len(prog.mods),
                      axes=dict(_DECLARED_AXES))
    active = set(rules if rules is not None else MESH_RULES)

    def emit(rule: str, path: str, line: int, col: int, msg: str) -> None:
        if rule in active:
            graph.findings.append(Finding(path, line, col, rule, msg))

    for mod in prog.mods:
        for site in mod.shard_maps:
            graph.shard_maps.append(
                (f"{_short(site.path)}:{site.line}", site.body_src,
                 ",".join(sorted(site.axes))))

    _check_collectives(prog, graph, emit)
    _check_shardings(prog, graph, emit)
    _check_donations(prog, graph, emit)
    return graph


def format_meshgraph(graph: MeshGraph) -> str:
    lines = [
        f"meshgraph: {graph.modules} modules, {graph.functions} functions, "
        f"{len(graph.shard_maps)} shard_map sites, "
        f"{len(graph.collectives)} collective uses, "
        f"{len(graph.shardings)} sharding consumers, "
        f"{len(graph.donations)} donation calls",
        "",
        "declared axes (parallel/mesh.py mirror):",
    ]
    for const, value in graph.axes.items():
        lines.append(f"  {const} = {value!r}")
    lines.append("")
    lines.append("shard_map sites (site -> body [bound axes]):")
    for site, body, axes in sorted(graph.shard_maps):
        lines.append(f"  {site} -> {body} [{axes}]")
    lines.append("")
    lines.append("collectives (site, op(axis), binding witness, status):")
    for site, op, axis, witness, status in sorted(graph.collectives):
        lines.append(f"  {site} {op}({axis}) <- {witness} [{status}]")
    lines.append("")
    lines.append("sharding dataflow (site, kind, resolution, status):")
    for site, kind, label, status in sorted(graph.shardings):
        lines.append(f"  {site} {kind} = {label} [{status}]")
    lines.append("")
    lines.append("donation sites (site, callee, donated, status):")
    for site, callee, donated, status in sorted(graph.donations):
        lines.append(f"  {site} {callee}({donated}) [{status}]")
    if graph.handlers:
        lines.append("")
        lines.append("declared axis binders:")
        for spec, status in sorted(graph.handlers.items()):
            lines.append(f"  axis-bound-by={spec} [{status}]")
    lines.append("")
    if graph.findings:
        lines.append(f"{len(graph.findings)} finding(s):")
        for f in graph.findings:
            lines.append(f"  {f.format()}")
    else:
        lines.append("findings: none")
    return "\n".join(lines)
