"""jaxlint driver: file discovery, rule execution, suppression filtering.

Two rule scopes (``rules.Rule.scope``): *module* rules run per file, the
*program* families (the interprocedural lock graph — ``lock-cycle``,
``unguarded-shared-write`` — and the wire-protocol registry —
``wire-magic-registry``, ``codec-asymmetry``, ``unchecked-frame``,
``flag-bit-collision``) run ONCE over every parsed module of the
invocation so cross-module call edges (``replay_service`` into
``staging``) and import chains (plane modules into ``core/wire.py``)
exist. ``lint_source`` treats its single module as a whole program,
which is what the fixture tests drive.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from d4pg_tpu.lint.context import ModuleContext, build_context
from d4pg_tpu.lint.findings import Finding, Suppressions
from d4pg_tpu.lint.rules import RULES


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)  # unsuppressed
    suppressed: list[Finding] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)  # unparseable files

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors


def iter_py_files(paths: list[str]):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        else:
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in {"__pycache__", ".git", "_native"})
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _split_rules(rules: list[str] | None) -> tuple[list, list[str]]:
    active = [RULES[r] for r in rules] if rules else list(RULES.values())
    module_rules = [r for r in active if r.scope == "module"]
    program_ids = [r.id for r in active if r.scope == "program"]
    return module_rules, program_ids


def _sift(collected: list[Finding], sup: Suppressions,
          result: LintResult) -> None:
    for f in sorted(collected, key=lambda f: (f.line, f.col, f.rule)):
        if sup.covers(f):
            f.suppressed = True
            result.suppressed.append(f)
        else:
            result.findings.append(f)


def _run_program_rules(ctxs: list[ModuleContext], program_ids: list[str],
                       sups: dict[str, Suppressions],
                       result: LintResult) -> None:
    if not program_ids or not ctxs:
        return
    from d4pg_tpu.lint.failgraph import FAIL_RULES
    from d4pg_tpu.lint.meshgraph import MESH_RULES
    from d4pg_tpu.lint.rnggraph import RNG_RULES
    from d4pg_tpu.lint.wiregraph import WIRE_RULES

    lock_ids = [r for r in program_ids
                if r not in WIRE_RULES and r not in FAIL_RULES
                and r not in MESH_RULES and r not in RNG_RULES]
    wire_ids = [r for r in program_ids if r in WIRE_RULES]
    fail_ids = [r for r in program_ids if r in FAIL_RULES]
    mesh_ids = [r for r in program_ids if r in MESH_RULES]
    rng_ids = [r for r in program_ids if r in RNG_RULES]
    per_file: dict[str, list[Finding]] = {}
    if lock_ids:
        from d4pg_tpu.lint import lockgraph

        for f in lockgraph.analyze(ctxs, rules=lock_ids).findings:
            per_file.setdefault(f.file, []).append(f)
    if wire_ids:
        from d4pg_tpu.lint import wiregraph

        for f in wiregraph.analyze(ctxs, rules=wire_ids).findings:
            per_file.setdefault(f.file, []).append(f)
    if fail_ids:
        from d4pg_tpu.lint import failgraph

        for f in failgraph.analyze(ctxs, rules=fail_ids).findings:
            per_file.setdefault(f.file, []).append(f)
    if mesh_ids:
        from d4pg_tpu.lint import meshgraph

        for f in meshgraph.analyze(ctxs, rules=mesh_ids).findings:
            per_file.setdefault(f.file, []).append(f)
    if rng_ids:
        from d4pg_tpu.lint import rnggraph

        for f in rnggraph.analyze(ctxs, rules=rng_ids).findings:
            per_file.setdefault(f.file, []).append(f)
    for path, found in sorted(per_file.items()):
        _sift(found, sups.get(path, Suppressions()), result)


def lint_source(source: str, path: str = "<string>",
                rules: list[str] | None = None) -> LintResult:
    """Lint one source string; the unit the fixture tests drive. The
    program families see a one-module program."""
    result = LintResult()
    try:
        ctx = build_context(path, source)
    except SyntaxError as e:
        result.errors.append(f"{path}: syntax error: {e}")
        return result
    sup = Suppressions.parse(source)
    module_rules, program_ids = _split_rules(rules)
    collected: list[Finding] = []
    for rule in module_rules:
        collected.extend(rule.check(ctx))
    _sift(collected, sup, result)
    _run_program_rules([ctx], program_ids, {path: sup}, result)
    return result


def lint_paths(paths: list[str],
               rules: list[str] | None = None) -> LintResult:
    result = LintResult()
    module_rules, program_ids = _split_rules(rules)
    ctxs: list[ModuleContext] = []
    sups: dict[str, Suppressions] = {}
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            result.errors.append(f"{path}: {e}")
            continue
        try:
            ctx = build_context(path, source)
        except SyntaxError as e:
            result.errors.append(f"{path}: syntax error: {e}")
            continue
        ctxs.append(ctx)
        sups[path] = Suppressions.parse(source)
        collected: list[Finding] = []
        for rule in module_rules:
            collected.extend(rule.check(ctx))
        _sift(collected, sups[path], result)
    _run_program_rules(ctxs, program_ids, sups, result)
    return result


def build_lock_graph(paths: list[str]):
    """The ``--locks`` review artifact: the whole-program lock graph over
    ``paths`` (nodes, edges with witnesses, cycles)."""
    from d4pg_tpu.lint import lockgraph

    ctxs: list[ModuleContext] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctxs.append(build_context(path, source))
        except (OSError, SyntaxError) as e:
            errors.append(f"{path}: {e}")
    graph = lockgraph.analyze(ctxs)
    return graph, errors


def build_wire_graph(paths: list[str]):
    """The ``--wire`` review artifact: the discovered wire-protocol
    registry over ``paths`` (magics, owners, pack/unpack witnesses,
    flag-bit map, findings)."""
    from d4pg_tpu.lint import wiregraph

    ctxs: list[ModuleContext] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctxs.append(build_context(path, source))
        except (OSError, SyntaxError) as e:
            errors.append(f"{path}: {e}")
    graph = wiregraph.analyze(ctxs)
    return graph, errors


def build_fail_graph(paths: list[str]):
    """The ``--fail`` review artifact: thread roles with containment
    status, span lifecycle sites, and the admission-counter ledger over
    ``paths`` (plus findings from families 16-18)."""
    from d4pg_tpu.lint import failgraph

    ctxs: list[ModuleContext] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctxs.append(build_context(path, source))
        except (OSError, SyntaxError) as e:
            errors.append(f"{path}: {e}")
    graph = failgraph.analyze(ctxs)
    return graph, errors


def build_mesh_graph(paths: list[str]):
    """The ``--mesh`` review artifact: shard_map sites with bound axes,
    collective uses with binding witnesses, the sharding dataflow table,
    and donation call sites over ``paths`` (plus findings from families
    19-21)."""
    from d4pg_tpu.lint import meshgraph

    ctxs: list[ModuleContext] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctxs.append(build_context(path, source))
        except (OSError, SyntaxError) as e:
            errors.append(f"{path}: {e}")
    graph = meshgraph.analyze(ctxs)
    return graph, errors


def build_rng_graph(paths: list[str]):
    """The ``--rng`` review artifact: the discovered RNG stream table
    (owner, constructor, seed provenance, draw sites, thread
    reachability) and SeedSequence branch sites over ``paths`` (plus
    findings from families 22-24 and the interprocedural key-reuse
    check)."""
    from d4pg_tpu.lint import rnggraph

    ctxs: list[ModuleContext] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctxs.append(build_context(path, source))
        except (OSError, SyntaxError) as e:
            errors.append(f"{path}: {e}")
    graph = rnggraph.analyze(ctxs)
    return graph, errors
