"""jaxlint driver: file discovery, rule execution, suppression filtering."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from d4pg_tpu.lint.context import build_context
from d4pg_tpu.lint.findings import Finding, Suppressions
from d4pg_tpu.lint.rules import RULES


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)  # unsuppressed
    suppressed: list[Finding] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)  # unparseable files

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors


def iter_py_files(paths: list[str]):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        else:
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in {"__pycache__", ".git", "_native"})
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_source(source: str, path: str = "<string>",
                rules: list[str] | None = None) -> LintResult:
    """Lint one source string; the unit the fixture tests drive."""
    result = LintResult()
    try:
        ctx = build_context(path, source)
    except SyntaxError as e:
        result.errors.append(f"{path}: syntax error: {e}")
        return result
    sup = Suppressions.parse(source)
    active = [RULES[r] for r in rules] if rules else list(RULES.values())
    collected: list[Finding] = []
    for rule in active:
        collected.extend(rule.check(ctx))
    for f in sorted(collected, key=lambda f: (f.line, f.col, f.rule)):
        if sup.covers(f):
            f.suppressed = True
            result.suppressed.append(f)
        else:
            result.findings.append(f)
    return result


def lint_paths(paths: list[str],
               rules: list[str] | None = None) -> LintResult:
    result = LintResult()
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            result.errors.append(f"{path}: {e}")
            continue
        one = lint_source(source, path, rules=rules)
        result.findings.extend(one.findings)
        result.suppressed.extend(one.suppressed)
        result.errors.extend(one.errors)
    return result
