"""Interprocedural concurrency analysis: the lock graph.

The 7th rule family (``lock-order``) is purely syntactic — one function
body, ``with``/``acquire`` shapes. It cannot see an ABBA cycle that
spans a call (``_pop_ready`` holding the merge condition into a helper
that takes a ring lock, while a worker nests them the other way), nor a
shared counter mutated off its owning lock. This module is the
whole-program complement, families 8 and 9:

- ``lock-cycle`` — build a held-while-acquiring graph over EVERY module
  analyzed together: nodes are lock objects identified by attribute
  path (``self._commit_cond`` → ``_commit_cond``; ``ring._leaf_lock``
  and ``self._ring_locks[i]`` normalize the same way, so all shard
  conditions share one node — deliberately conservative), edges mean
  "some thread can acquire B while holding A", where the acquisition of
  B may be any number of calls deep (acquisition sets propagate through
  the call graph to a fixpoint, the same machinery shape as the
  traced-fn taint in ``context.py``). Any cycle — including a
  length-one cycle, a non-reentrant lock re-taken under itself — is a
  deadlock an interleaving can reach.
- ``unguarded-shared-write`` — for every attribute written outside
  ``__init__``, infer its owning lock from the majority of accesses:
  if all other reads/writes happen with some lock L held (directly, or
  inherited from every call site of the enclosing function), a write
  without L is flagged. Where inference is wrong or the caller holds
  the lock beyond what the analysis can see, declare it:
  ``# jaxlint: guarded-by=<lock>`` on the write line (or on the
  ``def`` line to cover a whole helper) asserts the contract instead
  of suppressing the rule.

Lock identity is by attribute NAME, not object — ``cond`` on any shard
is one node. That merges instances (all ring locks collapse), which is
exactly the right abstraction for ordering: the discipline "ring locks
are leaves" is a statement about the class of lock, not one instance.
Names are discovered from ``threading.Lock/RLock/Condition`` and
``core.locking.TieredLock/TieredCondition`` construction sites plus a
conservative name pattern (``*_lock``, ``*_locks``, ``cond``/``*_cond``,
``*_mutex``).

``python -m d4pg_tpu.lint --locks`` prints the discovered graph (nodes,
edges with witnesses, cycles) as a review artifact.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from d4pg_tpu.lint.context import (
    FunctionNode, ModuleContext, dotted_name, iter_defs, last_part,
)
from d4pg_tpu.lint.findings import Finding

# Constructors whose assignment target becomes a known lock name.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "TieredLock", "TieredCondition",
               "Semaphore", "BoundedSemaphore"}
# Fallback pattern for modules that only USE a lock they didn't build
# (and for fixtures): the receiver name itself says lock.
_LOCK_NAME = re.compile(
    r"(?:^|_)(?:lock|locks|cond|condition|mutex)$")
# Methods that operate ON a lock object — lock events or no-ops, never
# call-graph edges into same-named program functions.
_LOCK_METHODS = {"acquire", "release", "locked", "wait", "wait_for",
                 "notify", "notify_all"}
# Method names too generic to resolve by name across the program when
# they appear on a non-lock receiver AND collide with stdlib container
# APIs; resolution noise here would swamp the graph (``self._conns.add``
# is a set, not a replay buffer; ``self._skip.update`` is a set, not the
# obs normalizer).
_NO_RESOLVE = {"append", "appendleft", "extend", "popleft", "discard",
               "items", "keys", "values", "get", "setdefault", "join",
               "start", "put", "clear", "copy", "close", "set", "is_set",
               "add", "update", "remove", "insert", "count", "index",
               "sort", "wait"}
_MAX_CANDIDATES = 12

_GUARDED_BY = re.compile(r"#\s*jaxlint:\s*guarded-by=([\w\-,]+)")

_INIT_FNS = {"__init__", "__post_init__", "__new__", "__init_subclass__"}

# The declared attribute-path -> tier mapping for the sharded ingest
# plane (the same source of truth as core.locking.HIERARCHY; TieredLock
# construction sites override/extend it). Used for the leaf-ascent
# check: LEAF tiers (shard, ring) admit no further tiered acquisition —
# an edge out of a leaf into an equal-or-higher tier is the merge-wedge
# shape even when no full cycle (yet) closes it.
_DEFAULT_TIERS = {
    "_elastic_cond": "elastic",
    "_lock": "service",
    "_buffer_lock": "buffer",
    "_commit_cond": "commit",
    "_replica_lock": "replica",
    "_agg_cond": "agg",
    "_relay_lock": "wrelay",
    "_frame_lock": "wserve",
    "_pserve_cond": "pserve",
    "_store_lock": "wstore",
    "cond": "shard",
    "shard_lock": "shard",
    "_shard_locks": "shard",
    "_sampler_lock": "sampler",
    "_ring_locks": "ring",
    "ring_lock": "ring",
    "_leaf_lock": "ring",
}

# Static mirror of ``core.locking.HIERARCHY``. Mirrored, not imported:
# the lint package is stdlib-only by contract (``d4pg_tpu.core``'s
# package __init__ pulls jax). tests/test_locking.py pins the two
# tables equal, so they cannot drift.
_TIER_VALUES = {"elastic": 60, "service": 50, "buffer": 40, "replica": 36,
                "agg": 34, "commit": 30, "wrelay": 28, "wserve": 26,
                "pserve": 25, "wstore": 24, "shard": 20, "sampler": 15,
                "ring": 10}


def _tier_values() -> dict[str, int]:
    return _TIER_VALUES


@dataclass
class _Acq:
    lock: str
    line: int
    col: int
    held: tuple[str, ...]
    path: str
    func: str


@dataclass
class _Call:
    callee: str
    recv_self: bool
    held: tuple[str, ...]
    line: int
    path: str
    func: str


@dataclass
class _Access:
    attr: str
    write: bool
    line: int
    col: int
    held: tuple[str, ...]
    path: str
    func: str  # qualified key of enclosing function ('' = module level)


@dataclass
class _FnInfo:
    key: str            # "<path>::<qualname>" — unique per program
    name: str           # bare name for call resolution
    cls: str | None
    path: str
    acqs: list[_Acq] = field(default_factory=list)
    calls: list[_Call] = field(default_factory=list)
    accesses: list[_Access] = field(default_factory=list)
    guards: tuple[str, ...] = ()  # guarded-by on the def line


def _lock_expr_name(expr: ast.expr, known: set[str]) -> str | None:
    """The lock node name for a with-item / acquire receiver, or None."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Call):
        return None
    name = last_part(dotted_name(expr) or "")
    if not name:
        return None
    if name in known or _LOCK_NAME.search(name):
        return name
    return None


def _guards_at(guard_lines: dict[int, tuple[str, ...]],
               node: ast.AST) -> tuple[str, ...]:
    out: tuple[str, ...] = ()
    for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
        out += guard_lines.get(ln, ())
    return out


class _FunctionWalker:
    """One function body, statements in order, tracking the held-lock
    set through ``with`` nesting and bare ``acquire()`` calls (held to
    the end of the enclosing block — an over-approximation that matches
    the ``acquire/try/finally: release`` idiom)."""

    def __init__(self, info: _FnInfo, known: set[str],
                 guard_lines: dict[int, tuple[str, ...]], cls: str | None):
        self.info = info
        self.known = known
        self.guard_lines = guard_lines
        self.cls = cls
        # ``commit = getattr(buf, "commit_staged", None)`` — later
        # ``commit()`` calls resolve to the string-named method, not to
        # every program function that happens to be named ``commit``
        self.aliases: dict[str, str] = {}

    def walk(self, body: list[ast.stmt]) -> None:
        self._block(body, ())

    # -- helpers -----------------------------------------------------------
    def _record_acq(self, lock: str, node: ast.AST,
                    held: tuple[str, ...]) -> None:
        self.info.acqs.append(_Acq(
            lock, node.lineno, node.col_offset, held,
            self.info.path, self.info.key))

    def _visit_expr(self, expr: ast.expr, held: tuple[str, ...],
                    acquired: list[tuple[str, str]]) -> None:
        """Record calls, lock events and attribute reads inside one
        expression. ``acquired`` collects (lock, dotted-path) pairs from
        bare ``.acquire()`` calls for block-scope held extension."""
        func_of_call: set[int] = set()
        lambdas: set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                func_of_call.add(id(node.func))
            if isinstance(node, ast.Lambda):
                for inner in ast.walk(node):
                    if inner is not node:
                        lambdas.add(id(inner))
        for node in ast.walk(expr):
            if id(node) in lambdas:
                continue
            if isinstance(node, ast.Call):
                self._visit_call(node, held, acquired)
            elif (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and id(node) not in func_of_call):
                self.info.accesses.append(_Access(
                    node.attr, False, node.lineno, node.col_offset,
                    held + _guards_at(self.guard_lines, node),
                    self.info.path, self.info.key))

    def _visit_call(self, call: ast.Call, held: tuple[str, ...],
                    acquired: list[tuple[str, str]]) -> None:
        f = call.func
        if isinstance(f, ast.Attribute):
            recv_lock = _lock_expr_name(f.value, self.known)
            if f.attr in _LOCK_METHODS:
                if recv_lock is not None:
                    if f.attr == "acquire":
                        path_str = dotted_name(f.value) or recv_lock
                        # a retry of the SAME dotted path (nonblocking
                        # probe then blocking acquire) is one logical
                        # acquisition, not self-nesting
                        if (recv_lock, path_str) not in acquired:
                            self._record_acq(recv_lock, call, held)
                            if recv_lock not in held:
                                acquired.append((recv_lock, path_str))
                    return  # wait/notify/release on a lock: not a call
                if f.attr in {"acquire", "release"}:
                    return  # unknown receiver named like a lock method
            if f.attr in _NO_RESOLVE or f.attr.startswith("__"):
                return
            recv_self = (isinstance(f.value, ast.Name)
                         and f.value.id == "self")
            self.info.calls.append(_Call(
                f.attr, recv_self, held, call.lineno,
                self.info.path, self.info.key))
        elif isinstance(f, ast.Name):
            self.info.calls.append(_Call(
                self.aliases.get(f.id, f.id), False, held, call.lineno,
                self.info.path, self.info.key))

    def _record_write_target(self, target: ast.expr,
                             held: tuple[str, ...]) -> None:
        # self.x = / obj.x += / self.d[k] = — all writes to attribute x/d
        node = target
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            self.info.accesses.append(_Access(
                node.attr, True, node.lineno, node.col_offset,
                held + _guards_at(self.guard_lines, target),
                self.info.path, self.info.key))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write_target(elt, held)

    # -- statement driver --------------------------------------------------
    def _block(self, body: list[ast.stmt], held: tuple[str, ...]) -> None:
        acquired: list[tuple[str, str]] = []  # bare-acquire extensions
        for stmt in body:
            eff = held + tuple(l for l, _ in acquired if l not in held)
            self._stmt(stmt, eff, acquired)

    def _stmt(self, stmt: ast.stmt, held: tuple[str, ...],
              acquired: list[tuple[str, str]]) -> None:
        if isinstance(stmt, FunctionNode + (ast.ClassDef,)):
            return  # separate scope: walked as its own _FnInfo
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                lock = _lock_expr_name(item.context_expr, self.known)
                self._visit_expr(item.context_expr, inner, acquired)
                if lock is not None:
                    self._record_acq(lock, item.context_expr, inner)
                    if lock not in inner:
                        inner = inner + (lock,)
            self._block(stmt.body, inner)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._visit_expr(stmt.value, held, acquired)
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Name)
                    and stmt.value.func.id == "getattr"
                    and len(stmt.value.args) >= 2
                    and isinstance(stmt.value.args[1], ast.Constant)
                    and isinstance(stmt.value.args[1].value, str)):
                self.aliases[stmt.targets[0].id] = stmt.value.args[1].value
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                self._record_write_target(t, held)
                # subscripted/attribute targets also READ their base
                if isinstance(t, ast.Subscript):
                    self._visit_expr(t.slice, held, acquired)
            if isinstance(stmt, ast.AugAssign) and isinstance(
                    stmt.target, ast.Attribute):
                pass  # covered by _record_write_target
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter, held, acquired)
            self._block(stmt.body, held)
            self._block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.While):
            self._visit_expr(stmt.test, held, acquired)
            self._block(stmt.body, held)
            self._block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.If):
            self._visit_expr(stmt.test, held, acquired)
            self._block(stmt.body, held)
            self._block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, held)
            for h in stmt.handlers:
                self._block(h.body, held)
            self._block(stmt.orelse, held)
            self._block(stmt.finalbody, held)
            return
        # leaf statements: Expr, Return, Raise, Assert, Delete, ...
        for value in ast.iter_child_nodes(stmt):
            if isinstance(value, ast.expr):
                self._visit_expr(value, held, acquired)


# --------------------------------------------------------------------------
# program assembly
# --------------------------------------------------------------------------


@dataclass
class LockGraph:
    """The artifact ``--locks`` prints and the rules consume."""

    nodes: dict[str, str | None] = field(default_factory=dict)  # name->tier
    # (held, acquired) -> list of witness strings "path:line (func)"
    edges: dict[tuple[str, str], list[str]] = field(default_factory=dict)
    cycles: list[list[str]] = field(default_factory=list)
    findings: list[Finding] = field(default_factory=list)
    functions: int = 0


def _collect_lock_names(trees: list[tuple[str, ast.Module]]
                        ) -> tuple[set[str], dict[str, str]]:
    """Program-wide lock names + tier-name labels from TieredLock ctors."""
    names: set[str] = set()
    tiers: dict[str, str] = {}

    def ctor_of(value: ast.expr) -> ast.Call | None:
        if isinstance(value, (ast.ListComp, ast.GeneratorExp)):
            value = value.elt
        if (isinstance(value, ast.Call)
                and last_part(dotted_name(value.func) or "") in _LOCK_CTORS):
            return value
        return None

    for _path, tree in trees:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if value is None:
                continue
            call = ctor_of(value)
            if call is None:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                name = None
                if isinstance(t, ast.Attribute):
                    name = t.attr
                elif isinstance(t, ast.Name):
                    name = t.id
                if name is None:
                    continue
                names.add(name)
                if (last_part(dotted_name(call.func) or "")
                        in {"TieredLock", "TieredCondition"}
                        and call.args
                        and isinstance(call.args[0], ast.Constant)
                        and isinstance(call.args[0].value, str)):
                    tiers[name] = call.args[0].value
    return names, tiers


def _guard_lines_of(source: str) -> dict[int, tuple[str, ...]]:
    out: dict[int, tuple[str, ...]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _GUARDED_BY.search(text)
        if m:
            out[i] = tuple(r.strip() for r in m.group(1).split(",")
                           if r.strip())
    return out


def build_program(ctxs: list[ModuleContext]) -> tuple[
        list[_FnInfo], set[str], dict[str, str]]:
    trees = [(c.path, c.tree) for c in ctxs]
    known, tiers = _collect_lock_names(trees)
    infos: list[_FnInfo] = []
    for ctx in ctxs:
        guard_lines = _guard_lines_of(ctx.source)
        for node, qual, cls in iter_defs(ctx.tree):
            info = _FnInfo(
                key=f"{ctx.path}::{qual}", name=node.name, cls=cls,
                path=ctx.path,
                guards=guard_lines.get(node.lineno, ()))
            walker = _FunctionWalker(info, known, guard_lines, cls)
            walker.walk(node.body)
            infos.append(info)
        # module-level statements get a pseudo-function
        mod_stmts = [s for s in ctx.tree.body
                     if not isinstance(s, FunctionNode + (ast.ClassDef,))]
        if mod_stmts:
            info = _FnInfo(key=f"{ctx.path}::<module>", name="<module>",
                           cls=None, path=ctx.path)
            _FunctionWalker(info, known, guard_lines, None).walk(mod_stmts)
            infos.append(info)
    return infos, known, tiers


def _resolve(call: _Call, caller: _FnInfo,
             by_name: dict[str, list[_FnInfo]],
             by_class: dict[tuple[str | None, str], list[_FnInfo]]
             ) -> list[_FnInfo]:
    """Candidate callees for one call site. ``self.m()`` binds to the
    caller's own class when it defines ``m``; other receivers resolve by
    bare name across the program, EXCLUDING the caller's own class (a
    same-class method would have been written ``self.m()``) and bailing
    out when the name is too popular to mean anything."""
    if call.recv_self and caller.cls is not None:
        own = by_class.get((caller.cls, call.callee))
        if own:
            return own
    cands = [f for f in by_name.get(call.callee, ())
             if not (call.recv_self is False and caller.cls is not None
                     and f.cls == caller.cls and f.path == caller.path)]
    if len(cands) > _MAX_CANDIDATES:
        return []
    return cands


def analyze(ctxs: list[ModuleContext],
            rules: list[str] | None = None) -> LockGraph:
    """Run the whole-program pass; ``rules`` filters which families emit
    findings (both always contribute to the printed graph)."""
    infos, known, tiers = build_program(ctxs)
    graph = LockGraph(functions=len(infos))
    graph.nodes = {}

    by_name: dict[str, list[_FnInfo]] = {}
    by_class: dict[tuple[str | None, str], list[_FnInfo]] = {}
    for f in infos:
        by_name.setdefault(f.name, []).append(f)
        by_class.setdefault((f.cls, f.name), []).append(f)

    resolved: dict[str, list[tuple[_Call, list[_FnInfo]]]] = {}
    for f in infos:
        resolved[f.key] = [(c, _resolve(c, f, by_name, by_class))
                           for c in f.calls]

    # ---- acquisition closure (fixpoint, cf. context.py taint mark) ------
    closure: dict[str, set[str]] = {
        f.key: {a.lock for a in f.acqs} for f in infos}
    changed = True
    while changed:
        changed = False
        for f in infos:
            acc = closure[f.key]
            before = len(acc)
            for _call, cands in resolved[f.key]:
                for g in cands:
                    acc |= closure[g.key]
            if len(acc) != before:
                changed = True

    # ---- held-while-acquiring edges -------------------------------------
    def add_edge(a: str, b: str, witness: str) -> None:
        graph.edges.setdefault((a, b), [])
        if len(graph.edges[(a, b)]) < 4:
            graph.edges[(a, b)].append(witness)

    anchor: dict[tuple[str, str], _Acq | _Call] = {}
    for f in infos:
        for acq in f.acqs:
            graph.nodes.setdefault(acq.lock, tiers.get(acq.lock))
            for h in acq.held:
                if h == acq.lock:
                    continue  # same-name nesting under a with is covered
                              # by lock-order's leaf analysis; keep the
                              # interprocedural graph for cross-name order
                add_edge(h, acq.lock,
                         f"{f.path}:{acq.line} ({_short(f.key)})")
                anchor.setdefault((h, acq.lock), acq)
        for call, cands in resolved[f.key]:
            if not call.held:
                continue
            for g in cands:
                for b in closure[g.key]:
                    for h in call.held:
                        if h == b:
                            continue
                        add_edge(h, b,
                                 f"{f.path}:{call.line} "
                                 f"({_short(f.key)} -> {_short(g.key)})")
                        anchor.setdefault((h, b), call)
    for h, _t in list(graph.edges):
        graph.nodes.setdefault(h, tiers.get(h))

    # ---- cycles ---------------------------------------------------------
    graph.cycles = _find_cycles(graph.edges)
    want = set(rules) if rules is not None else {"lock-cycle",
                                                "unguarded-shared-write"}
    if "lock-cycle" in want:
        cycle_edges = {
            (cyc[i], cyc[(i + 1) % len(cyc)])
            for cyc in graph.cycles for i in range(len(cyc))}
        # leaf-tier ascent: holding a shard/ring leaf while acquiring an
        # equal-or-higher declared tier — the merge-wedge shape — is a
        # finding even before a reverse edge closes a full cycle. Edges
        # already inside a reported cycle are not double-reported.
        tiers = dict(_DEFAULT_TIERS)
        tiers.update({k: v for k, v in graph.nodes.items() if v})
        tval = _tier_values()
        leaf_floor = tval.get("shard", 20)
        for (h, b), wits in sorted(graph.edges.items()):
            th, tb = tval.get(tiers.get(h, "")), tval.get(tiers.get(b, ""))
            if th is None or tb is None or (h, b) in cycle_edges:
                continue
            if th <= leaf_floor and tb >= th:
                site = anchor[(h, b)]
                graph.findings.append(Finding(
                    site.path, site.line, getattr(site, "col", 0),
                    "lock-cycle",
                    f"'{b}' ({tiers.get(b)} tier) acquired while holding "
                    f"leaf-tier '{h}' ({tiers.get(h)}) at {wits[0]} — "
                    "shard/ring locks admit no further tiered "
                    "acquisition (the PR-4 merge-wedge shape); release "
                    "the leaf first (core.locking.HIERARCHY)"))
        for cyc in graph.cycles:
            a, b = cyc[0], cyc[1 % len(cyc)]
            site = anchor.get((a, b)) or anchor.get((b, a))
            path_desc = " -> ".join(cyc + [cyc[0]])
            hops = []
            for i, x in enumerate(cyc):
                y = cyc[(i + 1) % len(cyc)]
                wit = graph.edges.get((x, y), ["?"])[0]
                hops.append(f"'{x}'->'{y}' at {wit}")
            graph.findings.append(Finding(
                site.path if site is not None else ctxs[0].path,
                site.line if site is not None else 1,
                getattr(site, "col", 0) if site is not None else 0,
                "lock-cycle",
                f"lock cycle {path_desc}: " + "; ".join(hops)
                + " — some interleaving deadlocks here; acquire these "
                "locks in one declared order (core.locking.HIERARCHY)"))

    if "unguarded-shared-write" in want:
        graph.findings.extend(
            _unguarded_writes(infos, resolved, known))

    graph.findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return graph


def _short(key: str) -> str:
    return key.rsplit("::", 1)[-1]


def _find_cycles(edges: dict[tuple[str, str], list[str]]) -> list[list[str]]:
    """Elementary cycles via SCC + per-SCC DFS (graphs here are tiny).
    Self-loops come out as single-node cycles."""
    adj: dict[str, set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    # Tarjan SCC
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(adj[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    cycles: list[list[str]] = []
    for comp in sccs:
        comp_set = set(comp)
        if len(comp) == 1:
            v = comp[0]
            if v in adj.get(v, ()):  # self-loop
                cycles.append([v])
            continue
        # one representative cycle per SCC: walk from the smallest node
        start = min(comp)
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxts = [w for w in sorted(adj[cur]) if w in comp_set]
            nxt = next((w for w in nxts if w == start), None)
            if nxt is not None and len(path) > 1:
                break
            nxt = next((w for w in nxts if w not in seen), None)
            if nxt is None:
                # fall back: close through any in-SCC successor
                break
            path.append(nxt)
            seen.add(nxt)
            cur = nxt
        cycles.append(path)
    return cycles


def _unguarded_writes(infos: list[_FnInfo],
                      resolved: dict[str, list[tuple[_Call, list[_FnInfo]]]],
                      known: set[str]) -> list[Finding]:
    by_key = {f.key: f for f in infos}

    # ---- inherited held context: ∩ over call sites of (site-held ∪
    # caller-inherited); entry points (no resolved callers) inherit {}.
    sites: dict[str, list[tuple[str, tuple[str, ...]]]] = {}
    for f in infos:
        for call, cands in resolved[f.key]:
            for g in cands:
                sites.setdefault(g.key, []).append((f.key, call.held))
    TOP = frozenset(known) | {"<top>"}
    inherited: dict[str, frozenset] = {
        f.key: (TOP if f.key in sites else frozenset()) for f in infos}
    changed = True
    iters = 0
    while changed and iters < 50:
        changed = False
        iters += 1
        for f in infos:
            cur = inherited[f.key]
            if f.key not in sites:
                continue
            acc = None
            for caller_key, held in sites[f.key]:
                eff = frozenset(held) | inherited.get(caller_key,
                                                     frozenset())
                acc = eff if acc is None else (acc & eff)
            acc = acc if acc is not None else frozenset()
            if acc != cur:
                inherited[f.key] = acc
                changed = True

    # ---- group accesses by attribute ------------------------------------
    per_attr: dict[str, list[tuple[_Access, frozenset]]] = {}
    writers: set[str] = set()
    for f in infos:
        base = frozenset(f.guards) | (inherited[f.key] - {"<top>"})
        in_init = _short(f.key).split(".")[-1] in _INIT_FNS
        for a in f.accesses:
            if a.attr in known or a.attr.startswith("__"):
                continue
            if in_init:
                continue  # construction is single-threaded
            eff = frozenset(a.held) | base
            per_attr.setdefault(a.attr, []).append((a, eff))
            if a.write:
                writers.add(a.attr)

    findings: list[Finding] = []
    for attr, accesses in per_attr.items():
        if attr not in writers:
            continue
        if len(accesses) < 3:
            continue  # too few sites to infer ownership
        # candidate owner: the lock held at the most accesses
        counts: dict[str, int] = {}
        for _a, eff in accesses:
            for lock in eff:
                counts[lock] = counts.get(lock, 0) + 1
        if not counts:
            continue
        owner = max(sorted(counts), key=lambda k: counts[k])
        covered = [x for x in accesses if owner in x[1]]
        uncovered = [x for x in accesses if owner not in x[1]]
        if not uncovered or len(covered) < 2:
            continue
        # "elsewhere only touched under the lock": every access we are
        # NOT flagging holds the owner — unguarded reads elsewhere mean
        # the attribute isn't lock-owned (single-writer patterns), so
        # stay silent rather than guess.
        if any(not a.write for a, _ in uncovered):
            continue
        if len(uncovered) >= len(covered):
            continue
        sample = covered[0][0]
        for a, _eff in uncovered:
            findings.append(Finding(
                a.path, a.line, a.col, "unguarded-shared-write",
                f"write to '{attr}' without holding '{owner}' — "
                f"{len(covered)} of {len(accesses)} accesses hold it "
                f"(e.g. {sample.path}:{sample.line}); take the lock, or "
                f"declare the caller's contract with "
                f"`# jaxlint: guarded-by={owner}`"))
    return findings


# --------------------------------------------------------------------------
# review artifact (CLI --locks)
# --------------------------------------------------------------------------


def format_graph(graph: LockGraph) -> str:
    lines = [f"lock graph: {len(graph.nodes)} lock(s), "
             f"{len(graph.edges)} held-while-acquiring edge(s), "
             f"{len(graph.cycles)} cycle(s) over {graph.functions} "
             f"function(s)"]
    lines.append("nodes:")
    for name in sorted(graph.nodes):
        tier = graph.nodes[name]
        lines.append(f"  {name}" + (f"  [tier: {tier}]" if tier else ""))
    lines.append("edges (held -> acquired):")
    for (a, b) in sorted(graph.edges):
        wits = graph.edges[(a, b)]
        lines.append(f"  {a} -> {b}   ({wits[0]}"
                     + (f" +{len(wits) - 1} more" if len(wits) > 1 else "")
                     + ")")
    if graph.cycles:
        lines.append("cycles:")
        for cyc in graph.cycles:
            lines.append("  " + " -> ".join(cyc + [cyc[0]]))
    else:
        lines.append("cycles: none")
    return "\n".join(lines)
