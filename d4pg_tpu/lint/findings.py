"""Finding records and `# jaxlint: disable=` suppression handling.

Suppression syntax (documented in docs/architecture.md):

- ``# jaxlint: disable=rule-a,rule-b`` on the flagged line suppresses
  those rules for that line only. ``disable=all`` suppresses everything.
- ``# jaxlint: disable-file=rule-a`` anywhere in a file suppresses a rule
  for the whole file (reserve for generated or deliberately-hostile code;
  fixtures in tests use inline suppressions instead).

Suppressed findings are still collected (``Finding.suppressed=True``) so
the CLI can report how many deliberate exceptions a file carries.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

_INLINE = re.compile(r"#\s*jaxlint:\s*disable=([\w\-,]+)")
_FILE = re.compile(r"#\s*jaxlint:\s*disable-file=([\w\-,]+)")


@dataclass
class Finding:
    file: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.file}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"


@dataclass
class Suppressions:
    """Per-file suppression table parsed once from source text."""

    by_line: dict[int, set[str]] = field(default_factory=dict)
    file_wide: set[str] = field(default_factory=set)

    @classmethod
    def parse(cls, source: str) -> "Suppressions":
        sup = cls()
        for i, text in enumerate(source.splitlines(), start=1):
            m = _INLINE.search(text)
            if m:
                sup.by_line.setdefault(i, set()).update(
                    r.strip() for r in m.group(1).split(",") if r.strip())
            m = _FILE.search(text)
            if m:
                sup.file_wide.update(
                    r.strip() for r in m.group(1).split(",") if r.strip())
        return sup

    def covers(self, finding: Finding) -> bool:
        if {finding.rule, "all"} & self.file_wide:
            return True
        rules = self.by_line.get(finding.line, ())
        return finding.rule in rules or "all" in rules
