"""failgraph — exception-flow & ledger-conservation whole-program pass.

Third member of the whole-program family (lockgraph: tiers/cycles,
wiregraph: frame registry symmetry).  This one models the *failure*
surface of the five wire planes: a dozen long-lived thread roles whose
reliability story — zero trace orphans, every admitted frame counted
exactly once — was previously enforced only by runtime chaos oracles.
An uncontained exception between chaos runs silently kills a plane;
these rules make that a lint failure instead.

Three families over an exception-edge-aware CFG:

- ``thread-crash-containment`` (16): any callable reachable as a
  ``threading.Thread`` target must catch-and-COUNT at its top frame
  (broad handler whose body increments a registry counter / records a
  flight event), or carry an audited ``# jaxlint: contained-by=<handler>``
  declaration naming a contained-and-counted wrapper.  An escaping raise
  is a dead plane.
- ``span-terminal-missing`` (17): every trace ``begin`` site must reach
  a commit/shed terminal on all paths *including exception edges* — the
  static form of the zero-orphan invariant the chaos smokes assert at
  runtime.  Begins whose trace root is handed off (returned, stored into
  a structure, passed to a non-obs call) are *escrowed*: lifecycle
  responsibility moved to the receiving frame, which is analyzed there.
- ``ledger-conservation`` (18): paths from a frame-admission counter
  increment that reach function exit with neither a disposition counter
  nor a terminal hand-off are flagged — rows admitted on such a path
  vanish from the ledger.  Counter identity is the bare attribute/key
  name, same resolution bar as lockgraph's lock names.

The CFG is statement-granularity with per-``try`` dispatch nodes: a
raising statement gets an exception edge to the innermost enclosing
dispatch, which fans out to handler entries plus (when no handler is
broad) an escape continuation — the exceptional copy of any ``finally``
body, then the parent dispatch, ultimately EXIT_EXC.  Declared
simplifications: ``return`` jumps straight to EXIT_NORM, ``break``/
``continue`` straight to their loop targets (intervening finallys are
assumed non-raising for control-transfer purposes), and a small no-raise
allowlist (obs calls, container ops, time/threading probes) keeps
exception edges to the calls that can actually fail.

Pure stdlib (ast) — same contract as the rest of the package.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from d4pg_tpu.lint.context import (
    FunctionNode,
    ModuleContext,
    dotted_name,
    iter_defs,
    last_part,
)
from d4pg_tpu.lint.findings import Finding

FAIL_RULES = (
    "thread-crash-containment",
    "span-terminal-missing",
    "ledger-conservation",
)

_CONTAINED_BY = re.compile(r"#\s*jaxlint:\s*contained-by=([\w\.\-,]+)")

# Receivers whose ``.begin(tid, ...)`` opens a trace span (obs/trace.py
# module singletons and test-local recorders).
_TRACE_RECV = re.compile(r"(?i)(trace|recorder|tracer)")

# Trace-terminal methods: reaching one settles a span's lifecycle.
_TERMINALS = {"terminal_shed", "mark_committed", "mark_grad"}

# Frame-admission counters (family 18 anchors).  Declared, like the wire
# registry: these are the names whose increment means "work entered the
# system here and the ledger owes a disposition for it".
_ADMISSION_COUNTERS = {"frames", "rows_in", "requests"}

# Counter names that ARE dispositions — an admission path that bumps one
# of these has accounted for the admitted work.  Substring match on the
# bare attribute/key name.
_DISPOSITION = re.compile(
    r"(applied|fenced|fence|torn|shed|commit|reject|drop|fail|skip|error"
    r"|crash|evict|tombston|order_break|responses|no_params|bad_request"
    r"|decode_err|retr|dead|stale)")

# Hand-off calls: the admitted work (or span root) moves to another
# frame's custody — conservation holds, the receiving frame is analyzed
# separately.
_HANDOFF_ATTRS = {"append", "appendleft", "extend", "put", "add",
                  "publish", "publish_versioned", "submit", "insert"}

# Calls that count a crash / record evidence (family 16 counting check).
_COUNT_ATTRS = {"inc", "observe", "record", "set"}
_COUNT_NAMES = {"record_event", "contained_crash"}

# No-raise allowlist for CFG exception edges (families 17/18): obs
# primitives, container ops, time/threading probes.  Everything else —
# including ``with``-enters (tiered-lock hierarchy checks raise) — gets
# an exception edge.
_NO_RAISE_ATTRS = {
    "begin", "record_span", "terminal_shed", "mark_committed", "mark_grad",
    "record", "record_event", "inc", "observe", "set", "clear",
    "is_set", "wait", "notify", "notify_all", "is_alive",
    "append", "appendleft", "extend", "popleft", "pop", "discard", "add",
    "get", "items", "keys", "values", "monotonic", "time", "perf_counter",
    "sleep",
}
_NO_RAISE_NAMES = {
    "len", "isinstance", "hasattr", "getattr", "id", "bool", "repr", "str",
    "int", "float", "min", "max", "abs", "round", "sorted", "list", "dict",
    "set", "tuple", "range", "enumerate", "zip", "print", "next",
    "record_event", "monotonic", "perf_counter",
}

_MAX_CANDIDATES = 8


# --------------------------------------------------------------------------
# Program index
# --------------------------------------------------------------------------

@dataclass
class _FnInfo:
    key: str
    name: str
    qual: str
    cls: str | None
    path: str
    node: ast.AST
    ctx: ModuleContext
    contained_by: tuple[str, ...] = ()   # annotation on the def line


@dataclass
class _Spawn:
    """One ``threading.Thread(target=...)`` call site."""

    path: str
    line: int
    col: int
    src: str                  # textual form of the target expr
    owner: _FnInfo            # enclosing function (or <module> pseudo-fn)
    target: ast.expr
    contained_by: tuple[str, ...] = ()


@dataclass
class _Program:
    infos: list[_FnInfo]
    by_key: dict[str, _FnInfo]
    by_name: dict[str, list[_FnInfo]]
    by_class: dict[tuple[str | None, str], list[_FnInfo]]
    bases: dict[str, set[str]]        # class -> base names (textual)
    spawns: list[_Spawn]


def _contained_lines(source: str) -> dict[int, tuple[str, ...]]:
    out: dict[int, tuple[str, ...]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _CONTAINED_BY.search(text)
        if m:
            out[i] = tuple(h.strip() for h in m.group(1).split(",")
                           if h.strip())
    return out


def _spawn_annotation(lines: dict[int, tuple[str, ...]],
                      call: ast.Call) -> tuple[str, ...]:
    end = getattr(call, "end_lineno", call.lineno) or call.lineno
    for ln in range(call.lineno, end + 1):
        if ln in lines:
            return lines[ln]
    return ()


class _SpawnWalker(ast.NodeVisitor):
    """Collect Thread(target=...) spawns and local name aliases inside one
    function body (nested defs excluded — they are their own functions)."""

    def __init__(self) -> None:
        self.spawns: list[tuple[ast.Call, ast.expr]] = []
        self.aliases: dict[str, ast.expr] = {}

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            self.aliases[node.targets[0].id] = node.value
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if last_part(dotted_name(node.func)) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self.spawns.append((node, kw.value))
        self.generic_visit(node)


def build_program(ctxs: list[ModuleContext]) -> _Program:
    infos: list[_FnInfo] = []
    bases: dict[str, set[str]] = {}
    spawn_raw: list[tuple[ModuleContext, _FnInfo, ast.Call, ast.expr,
                          dict[str, ast.expr]]] = []
    for ctx in ctxs:
        ann = _contained_lines(ctx.source)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                bases.setdefault(node.name, set()).update(
                    b for b in (last_part(dotted_name(e))
                                for e in node.bases) if b)
        mod_fns: list[tuple[_FnInfo, ast.AST]] = []
        for node, qual, cls in iter_defs(ctx.tree):
            info = _FnInfo(
                key=f"{ctx.path}::{qual}", name=node.name, qual=qual,
                cls=cls, path=ctx.path, node=node, ctx=ctx,
                contained_by=ann.get(node.lineno, ()))
            infos.append(info)
            mod_fns.append((info, node))
        mod_stmts = [s for s in ctx.tree.body
                     if not isinstance(s, FunctionNode + (ast.ClassDef,))]
        mod_info = _FnInfo(key=f"{ctx.path}::<module>", name="<module>",
                           qual="<module>", cls=None, path=ctx.path,
                           node=ast.Module(body=mod_stmts, type_ignores=[]),
                           ctx=ctx)
        infos.append(mod_info)
        for info, node in mod_fns + [(mod_info, mod_info.node)]:
            w = _SpawnWalker()
            for stmt in node.body:
                w.visit(stmt)
            for call, target in w.spawns:
                spawn_raw.append((ctx, info, call, target, w.aliases))

    by_key = {f.key: f for f in infos}
    by_name: dict[str, list[_FnInfo]] = {}
    by_class: dict[tuple[str | None, str], list[_FnInfo]] = {}
    for f in infos:
        by_name.setdefault(f.name, []).append(f)
        by_class.setdefault((f.cls, f.name), []).append(f)

    spawns: list[_Spawn] = []
    for ctx, owner, call, target, aliases in spawn_raw:
        ann = _contained_lines(ctx.source)
        spawns.append(_Spawn(
            path=ctx.path, line=call.lineno, col=call.col_offset,
            src=ast.unparse(target), owner=owner, target=target,
            contained_by=_spawn_annotation(ann, call)))
    prog = _Program(infos=infos, by_key=by_key, by_name=by_name,
                    by_class=by_class, bases=bases, spawns=spawns)
    prog._aliases = {id(s): a for (c, o, call, t, a), s    # type: ignore[attr-defined]
                     in zip(spawn_raw, spawns)}
    return prog


def _class_family(prog: _Program, cls: str) -> set[str]:
    """cls plus textual ancestors and descendants — the set a ``self.m``
    spawn can dynamically bind into (covers subclass overrides like
    WeightPlaneServer._serve spawned from WeightServer._accept).
    Siblings through a shared base are NOT family: ``self.m`` from class
    C never dispatches into an unrelated subclass of C's base."""
    up = {cls}
    changed = True
    while changed:
        changed = False
        for c in list(up):
            bs = prog.bases.get(c, set())
            if not bs <= up:
                up |= bs
                changed = True
    down = {cls}
    changed = True
    while changed:
        changed = False
        for c, bs in prog.bases.items():
            if bs & down and c not in down:
                down.add(c)
                changed = True
    return up | down


def _resolve_target(prog: _Program, spawn: _Spawn) -> list[_FnInfo]:
    """Candidate functions a Thread target expression can invoke."""
    expr = spawn.target
    aliases = getattr(prog, "_aliases", {}).get(id(spawn), {})
    exprs = [expr]
    if isinstance(expr, ast.Name) and expr.id in aliases:
        al = aliases[expr.id]
        exprs = ([al.body, al.orelse] if isinstance(al, ast.IfExp)
                 else [al])
    out: list[_FnInfo] = []
    for e in exprs:
        out.extend(_resolve_one(prog, spawn, e))
    seen: set[str] = set()
    uniq = [f for f in out if not (f.key in seen or seen.add(f.key))]
    return uniq


def _resolve_one(prog: _Program, spawn: _Spawn,
                 expr: ast.expr) -> list[_FnInfo]:
    owner = spawn.owner
    if isinstance(expr, ast.Attribute):
        meth = expr.attr
        recv_self = (isinstance(expr.value, ast.Name)
                     and expr.value.id in ("self", "cls"))
        if recv_self and owner.cls:
            fam = _class_family(prog, owner.cls)
            cands = [f for f in prog.by_name.get(meth, ())
                     if f.cls in fam]
            if cands:
                return cands
        cands = prog.by_name.get(meth, [])
        return cands if 0 < len(cands) <= 1 else []
    if isinstance(expr, ast.Name):
        name = expr.id
        # nested def of the spawning function
        parents = owner.ctx.parents
        nested = [f for f in prog.by_name.get(name, ())
                  if f.path == owner.path
                  and parents.get(f.node) is (None if owner.name == "<module>"
                                              else owner.node)]
        if nested:
            return nested
        local = [f for f in prog.by_name.get(name, ())
                 if f.path == owner.path]
        if local:
            return local
        cands = prog.by_name.get(name, [])
        return cands if 0 < len(cands) <= _MAX_CANDIDATES else []
    if isinstance(expr, ast.Lambda):
        return []
    return []


# --------------------------------------------------------------------------
# Family 16 — containment analysis (ancestry-based, no CFG needed)
# --------------------------------------------------------------------------

def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = ([last_part(dotted_name(e)) for e in t.elts]
             if isinstance(t, ast.Tuple) else [last_part(dotted_name(t))])
    return bool({"Exception", "BaseException"} & set(names))


def _expr_raises_strict(node: ast.AST) -> int:
    """Family 16 bar: ANY call / raise / assert can kill the thread.
    Returns the first raising line, or 0."""
    for sub in ast.walk(node):
        if isinstance(sub, FunctionNode):
            continue
        if isinstance(sub, (ast.Call, ast.Raise, ast.Assert)):
            return getattr(sub, "lineno", 0) or 0
    return 0


def _strip_nested_stmts(stmts: list[ast.stmt]):
    for s in stmts:
        yield from _strip_nested(s)


def _strip_nested(node: ast.AST):
    """Walk a subtree, skipping nested function/class bodies."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, FunctionNode + (ast.ClassDef,)):
                continue
            stack.append(child)


@dataclass
class _ContainScan:
    escapes: list[int] = field(default_factory=list)
    # (handler, was_try_already_protected)
    broads: list[tuple[ast.ExceptHandler, bool]] = field(default_factory=list)
    any_raising: bool = False


def _scan_contain(stmts: list[ast.stmt], protected: bool,
                  out: _ContainScan) -> None:
    for s in stmts:
        if isinstance(s, FunctionNode + (ast.ClassDef,)):
            continue
        if isinstance(s, ast.Try):
            broad = any(_is_broad(h) for h in s.handlers)
            _scan_contain(s.body, protected or broad, out)
            for h in s.handlers:
                if _is_broad(h):
                    # The broad handler IS the containment: its body is the
                    # crash path, so bookkeeping calls there don't re-open
                    # the escape.  An explicit raise still does.
                    out.broads.append((h, protected))
                    _scan_contain(h.body, True, out)
                    if not protected:
                        for sub in _strip_nested_stmts(h.body):
                            if isinstance(sub, ast.Raise):
                                out.escapes.append(sub.lineno)
                                break
                else:
                    _scan_contain(h.body, protected, out)
            _scan_contain(s.orelse, protected, out)
            _scan_contain(s.finalbody, protected, out)
            continue
        head_exprs: list[ast.AST] = []
        bodies: list[list[ast.stmt]] = []
        if isinstance(s, ast.If):
            head_exprs, bodies = [s.test], [s.body, s.orelse]
        elif isinstance(s, ast.While):
            head_exprs, bodies = [s.test], [s.body, s.orelse]
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            head_exprs, bodies = [s.iter], [s.body, s.orelse]
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            head_exprs, bodies = list(s.items), [s.body]
        if bodies:
            for e in head_exprs:
                line = _expr_raises_strict(e)
                if line:
                    out.any_raising = True
                    if not protected:
                        out.escapes.append(line)
            for b in bodies:
                _scan_contain(b, protected, out)
            continue
        line = _expr_raises_strict(s)
        if line:
            out.any_raising = True
            if not protected:
                out.escapes.append(line)


def _body_counts(prog: _Program, owner: _FnInfo, stmts: list[ast.stmt],
                 depth: int = 0) -> bool:
    """Does this statement list count the crash?  Direct counter/flight
    call, an AugAssign on a counter attribute, or a call resolving to a
    function whose body counts (depth-bounded — covers the shared
    ``obs.containment.contained_crash`` helper)."""
    callees: list[tuple[str, bool]] = []
    for s in stmts:
        for sub in _strip_nested(s):
            if isinstance(sub, ast.AugAssign) and isinstance(
                    sub.target, (ast.Attribute, ast.Subscript)):
                return True
            if not isinstance(sub, ast.Call):
                continue
            name = last_part(dotted_name(sub.func))
            if name in _COUNT_NAMES:
                return True
            if isinstance(sub.func, ast.Attribute):
                if sub.func.attr in _COUNT_ATTRS:
                    return True
                recv_self = (isinstance(sub.func.value, ast.Name)
                             and sub.func.value.id == "self")
                callees.append((sub.func.attr, recv_self))
            elif isinstance(sub.func, ast.Name):
                callees.append((sub.func.id, False))
    if depth >= 2:
        return False
    for name, recv_self in callees:
        if recv_self and owner.cls:
            cands = prog.by_class.get((owner.cls, name), [])
        else:
            cands = prog.by_name.get(name, [])
        if len(cands) > _MAX_CANDIDATES:
            continue
        for cand in cands:
            if _body_counts(prog, cand, list(cand.node.body), depth + 1):
                return True
    return False


def _containment(prog: _Program, fn: _FnInfo) -> tuple[str, int]:
    """('contained'|'no-raise'|'escapes'|'uncounted', witness_line)."""
    cached = getattr(prog, "_contain_cache", None)
    if cached is None:
        cached = prog._contain_cache = {}        # type: ignore[attr-defined]
    if fn.key in cached:
        return cached[fn.key]
    cached[fn.key] = ("no-raise", 0)             # recursion guard
    out = _ContainScan()
    _scan_contain(list(fn.node.body), False, out)
    if out.escapes:
        res = ("escapes", out.escapes[0])
    elif not out.any_raising:
        res = ("no-raise", 0)
    else:
        uncounted = [h for h, prot in out.broads if not prot
                     and not _body_counts(prog, fn, h.body)]
        res = (("uncounted", uncounted[0].lineno) if uncounted
               else ("contained", 0))
    cached[fn.key] = res
    return res


def _resolve_handler(prog: _Program, owner: _FnInfo,
                     spec: str) -> list[_FnInfo]:
    if "." in spec:
        cls, meth = spec.rsplit(".", 1)
        return prog.by_class.get((cls, meth), [])
    cands = [f for f in prog.by_name.get(spec, ())
             if f.path == owner.path] or list(prog.by_name.get(spec, ()))
    return cands if len(cands) <= _MAX_CANDIDATES else []


# --------------------------------------------------------------------------
# CFG with exception edges (families 17/18)
# --------------------------------------------------------------------------

class _Node:
    __slots__ = ("line", "stmt", "succ", "exc", "kind", "guard")

    def __init__(self, kind: str = "stmt", line: int = 0,
                 stmt: ast.stmt | None = None) -> None:
        self.kind = kind              # stmt | dispatch | exit | exit_exc
        self.line = line
        self.stmt = stmt
        self.succ: list["_Node"] = []
        self.exc: "_Node | None" = None
        # (var_name, truthy_branch_index) for If tests like ``if tid:``
        self.guard: tuple[str, int] | None = None


def _call_no_raise(call: ast.Call) -> bool:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr in _NO_RAISE_ATTRS
    if isinstance(call.func, ast.Name):
        return call.func.id in _NO_RAISE_NAMES
    return False


def _expr_raises(node: ast.AST) -> bool:
    """Families 17/18 bar: calls outside the no-raise allowlist, raise,
    assert, and with-enters."""
    for sub in ast.walk(node):
        if isinstance(sub, FunctionNode):
            continue
        if isinstance(sub, (ast.Raise, ast.Assert, ast.withitem)):
            return True
        if isinstance(sub, ast.Call) and not _call_no_raise(sub):
            return True
    return False


class _CFG:
    def __init__(self) -> None:
        self.exit_norm = _Node("exit")
        self.exit_exc = _Node("exit_exc")
        self.entry: _Node = self.exit_norm
        self.stmt_nodes: dict[int, list[_Node]] = {}   # id(stmt) -> nodes

    def _node(self, stmt: ast.stmt, succ: list[_Node],
              disp: _Node, raising: bool) -> _Node:
        n = _Node("stmt", getattr(stmt, "lineno", 0) or 0, stmt)
        n.succ = succ
        if raising:
            n.exc = disp
        self.stmt_nodes.setdefault(id(stmt), []).append(n)
        return n

    def seq(self, stmts: list[ast.stmt], succ: _Node, disp: _Node,
            loops: list[tuple[_Node, _Node]]) -> _Node:
        nxt = succ
        for s in reversed(stmts):
            nxt = self.stmt(s, nxt, disp, loops)
        return nxt

    def stmt(self, s: ast.stmt, succ: _Node, disp: _Node,
             loops: list[tuple[_Node, _Node]]) -> _Node:
        if isinstance(s, FunctionNode + (ast.ClassDef,)):
            return self._node(s, [succ], disp, raising=False)
        if isinstance(s, ast.Try):
            return self._try(s, succ, disp, loops)
        if isinstance(s, ast.If):
            n = self._node(s, [], disp, raising=_expr_raises(s.test))
            n.succ = [self.seq(s.body, succ, disp, loops),
                      self.seq(s.orelse, succ, disp, loops)
                      if s.orelse else succ]
            n.guard = _guard_of(s.test)
            return n
        if isinstance(s, ast.While):
            n = self._node(s, [], disp, raising=_expr_raises(s.test))
            body = self.seq(s.body, n, disp, loops + [(succ, n)])
            infinite = (isinstance(s.test, ast.Constant)
                        and bool(s.test.value))
            n.succ = [body] if infinite else [body, succ]
            return n
        if isinstance(s, (ast.For, ast.AsyncFor)):
            n = self._node(s, [], disp, raising=_expr_raises(s.iter))
            body = self.seq(s.body, n, disp, loops + [(succ, n)])
            after = (self.seq(s.orelse, succ, disp, loops)
                     if s.orelse else succ)
            n.succ = [body, after]
            return n
        if isinstance(s, (ast.With, ast.AsyncWith)):
            body = self.seq(s.body, succ, disp, loops)
            return self._node(s, [body], disp, raising=True)
        if isinstance(s, ast.Return):
            n = self._node(s, [self.exit_norm], disp,
                           raising=s.value is not None
                           and _expr_raises(s.value))
            return n
        if isinstance(s, ast.Raise):
            n = self._node(s, [], disp, raising=True)
            return n
        if isinstance(s, ast.Break):
            return self._node(s, [loops[-1][0] if loops else succ],
                              disp, raising=False)
        if isinstance(s, ast.Continue):
            return self._node(s, [loops[-1][1] if loops else succ],
                              disp, raising=False)
        return self._node(s, [succ], disp, raising=_expr_raises(s))

    def _try(self, s: ast.Try, succ: _Node, disp: _Node,
             loops: list[tuple[_Node, _Node]]) -> _Node:
        # escape continuation: exceptional finally copy -> parent dispatch
        if s.finalbody:
            fin_exc = self.seq(s.finalbody, disp, disp, loops)
            after = self.seq(s.finalbody, succ, disp, loops)
        else:
            fin_exc = disp
            after = succ
        dispatch = _Node("dispatch", s.lineno)
        broad = any(_is_broad(h) for h in s.handlers)
        for h in s.handlers:
            dispatch.succ.append(self.seq(h.body, after, fin_exc, loops))
        if not broad:
            dispatch.succ.append(fin_exc)
        body_succ = (self.seq(s.orelse, after, fin_exc, loops)
                     if s.orelse else after)
        return self.seq(s.body, body_succ, dispatch, loops)


def _guard_of(test: ast.expr) -> tuple[str, int] | None:
    """Recognize truthiness guards on a single name: ``if tid:`` (truthy
    branch 0), ``if not tid:`` / ``if tid is None:`` (truthy branch 1),
    ``if tid is not None:`` (truthy branch 0)."""
    if isinstance(test, ast.Name):
        return (test.id, 0)
    if (isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not)
            and isinstance(test.operand, ast.Name)):
        return (test.operand.id, 1)
    if (isinstance(test, ast.Compare) and isinstance(test.left, ast.Name)
            and len(test.ops) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None):
        if isinstance(test.ops[0], ast.Is):
            return (test.left.id, 1)
        if isinstance(test.ops[0], ast.IsNot):
            return (test.left.id, 0)
    return None


def _build_cfg(prog: _Program, fn: _FnInfo) -> _CFG:
    cached = getattr(prog, "_cfg_cache", None)
    if cached is None:
        cached = prog._cfg_cache = {}            # type: ignore[attr-defined]
    if fn.key in cached:
        return cached[fn.key]
    cfg = _CFG()
    cfg.entry = cfg.seq(list(fn.node.body), cfg.exit_norm,
                        cfg.exit_exc, [])
    cached[fn.key] = cfg
    return cfg


def _reach_exit(cfg: _CFG, start_stmt: ast.stmt, root: str | None,
                settles, want_exc_only: bool) -> tuple[int, int] | None:
    """BFS from the node(s) of ``start_stmt``.  Returns (exit_line_kind
    witness) as (witness_line, 1 if exceptional else 0) for the first
    unsettled path reaching a forbidden exit, else None.  ``settles`` is
    a predicate over ast.stmt; settled nodes are not expanded.  ``root``
    enables guard refinement: begin/admission implies root is truthy."""
    starts = cfg.stmt_nodes.get(id(start_stmt), [])
    if not starts:
        return None
    seen: set[int] = set()
    # queue entries: (node, witness_line_of_last_exc_edge)
    queue: list[tuple[_Node, int]] = []
    for n in starts:
        for s2 in n.succ:
            queue.append((s2, 0))
        if n.exc is not None:
            queue.append((n.exc, n.line))
    while queue:
        node, wit = queue.pop(0)
        if id(node) in seen:
            continue
        seen.add(id(node))
        if node.kind == "exit_exc":
            return (wit, 1)
        if node.kind == "exit":
            if not want_exc_only:
                return (wit or node.line, 0)
            continue
        if node.kind == "stmt" and node.stmt is not None \
                and settles(node.stmt):
            continue
        succ = node.succ
        if node.guard and root and node.guard[0] == root:
            succ = [node.succ[node.guard[1]]] \
                if len(node.succ) > node.guard[1] else node.succ
        for s2 in succ:
            queue.append((s2, wit))
        if node.exc is not None:
            queue.append((node.exc, node.line))
    return None


# --------------------------------------------------------------------------
# Family 17 — span terminals
# --------------------------------------------------------------------------

def _is_trace_begin(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "begin"):
        return False
    recv = last_part(dotted_name(call.func.value)) or ""
    return bool(_TRACE_RECV.search(recv))


def _begin_root(call: ast.Call, stmt: ast.stmt) -> str | None:
    """The local name carrying the trace id: assignment target of the
    begin, else the begin's first argument name."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name):
        return stmt.targets[0].id
    if call.args:
        a = call.args[0]
        if isinstance(a, ast.Name):
            return a.id
        if isinstance(a, ast.Subscript) and isinstance(a.value, ast.Name):
            return a.value.id
    return None


def _is_obs_call(call: ast.Call) -> bool:
    name = last_part(dotted_name(call.func))
    return name in (_TERMINALS | {"begin", "record_span", "record_event",
                                  "record", "inc", "observe"})


def _root_escrowed(fn: _FnInfo, begin_stmt: ast.stmt, root: str) -> bool:
    """True when the trace root is handed off out of this frame: returned,
    yielded, stored into a structure, or passed to a non-obs call."""
    def uses_root(e: ast.AST) -> bool:
        return any(isinstance(x, ast.Name) and x.id == root
                   for x in ast.walk(e))

    for sub in _strip_nested(fn.node):
        if sub is begin_stmt:
            continue
        if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
            if sub.value is not None and uses_root(sub.value):
                return True
        elif isinstance(sub, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in sub.targets) and uses_root(sub.value):
                return True
        elif isinstance(sub, ast.Call) and not _is_obs_call(sub):
            args: list[ast.AST] = list(sub.args)
            args.extend(kw.value for kw in sub.keywords)
            if any(uses_root(a) for a in args):
                return True
    return False


def _stmt_settles_span(stmt: ast.stmt) -> bool:
    for sub in _strip_nested(stmt):
        if not isinstance(sub, ast.Call):
            continue
        name = last_part(dotted_name(sub.func))
        if name in _TERMINALS:
            return True
        if name == "record_span" and len(sub.args) >= 2 \
                and isinstance(sub.args[1], ast.Constant) \
                and sub.args[1].value in ("commit", "grad", "shed"):
            return True
    return False


@dataclass
class _SpanSite:
    fn: _FnInfo
    line: int
    root: str | None
    status: str            # settled | escrow | orphan
    witness: int = 0


def _check_spans(prog: _Program, fn: _FnInfo) -> list[_SpanSite]:
    sites: list[_SpanSite] = []
    begin_stmts: list[tuple[ast.stmt, ast.Call]] = []
    for sub in _strip_nested(fn.node):
        if isinstance(sub, ast.stmt):
            for inner in ast.walk(sub):
                if isinstance(inner, ast.Call) and _is_trace_begin(inner) \
                        and getattr(sub, "lineno", None) == inner.lineno:
                    begin_stmts.append((sub, inner))
                    break
    if not begin_stmts:
        return sites
    cfg = _build_cfg(prog, fn)
    for stmt, call in begin_stmts:
        root = _begin_root(call, stmt)
        if root and _root_escrowed(fn, stmt, root):
            sites.append(_SpanSite(fn, stmt.lineno, root, "escrow"))
            continue
        hit = _reach_exit(cfg, stmt, root, _stmt_settles_span,
                          want_exc_only=True)
        if hit:
            sites.append(_SpanSite(fn, stmt.lineno, root, "orphan",
                                   witness=hit[0]))
        else:
            sites.append(_SpanSite(fn, stmt.lineno, root, "settled"))
    return sites


# --------------------------------------------------------------------------
# Family 18 — ledger conservation
# --------------------------------------------------------------------------

def _counter_name(target: ast.expr) -> str | None:
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Subscript) \
            and isinstance(target.slice, ast.Constant) \
            and isinstance(target.slice.value, str):
        return target.slice.value
    return None


def _stmt_settles_ledger(stmt: ast.stmt) -> bool:
    for sub in _strip_nested(stmt):
        if isinstance(sub, ast.AugAssign):
            name = _counter_name(sub.target)
            if name and name not in _ADMISSION_COUNTERS \
                    and _DISPOSITION.search(name):
                return True
        if not isinstance(sub, ast.Call):
            continue
        name = last_part(dotted_name(sub.func))
        if name in _TERMINALS or name in ("record_event", "inc", "observe"):
            return True
        if isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _HANDOFF_ATTRS:
            return True
    return False


@dataclass
class _LedgerSite:
    fn: _FnInfo
    line: int
    counter: str
    status: str            # balanced | leak
    witness: int = 0
    exceptional: bool = False


def _check_ledger(prog: _Program, fn: _FnInfo) -> list[_LedgerSite]:
    sites: list[_LedgerSite] = []
    anchors: list[tuple[ast.stmt, str]] = []
    for sub in _strip_nested(fn.node):
        if isinstance(sub, ast.AugAssign):
            name = _counter_name(sub.target)
            if name in _ADMISSION_COUNTERS:
                anchors.append((sub, name))
    if not anchors:
        return sites
    cfg = _build_cfg(prog, fn)
    for stmt, name in anchors:
        hit = _reach_exit(cfg, stmt, None, _stmt_settles_ledger,
                          want_exc_only=False)
        if hit:
            sites.append(_LedgerSite(fn, stmt.lineno, name, "leak",
                                     witness=hit[0], exceptional=bool(hit[1])))
        else:
            sites.append(_LedgerSite(fn, stmt.lineno, name, "balanced"))
    return sites


# --------------------------------------------------------------------------
# Graph artifact + analyze
# --------------------------------------------------------------------------

@dataclass
class FailGraph:
    functions: int = 0
    modules: int = 0
    # thread role rows: (spawn_site, target_qual_or_src, status)
    threads: list[tuple[str, str, str]] = field(default_factory=list)
    # span rows: (site, root, status)
    spans: list[tuple[str, str, str]] = field(default_factory=list)
    # ledger rows: (site, counter, status)
    ledger: list[tuple[str, str, str]] = field(default_factory=list)
    # contained-by annotation audit surface: spec -> ok | unresolved | weak
    handlers: dict[str, str] = field(default_factory=dict)
    findings: list[Finding] = field(default_factory=list)


def _short(path: str) -> str:
    return path.rsplit("/d4pg_tpu/", 1)[-1] if "/d4pg_tpu/" in path else path


def analyze(ctxs: list[ModuleContext],
            rules: list[str] | None = None) -> FailGraph:
    prog = build_program(ctxs)
    graph = FailGraph(functions=len(prog.infos), modules=len(ctxs))
    active = set(rules if rules is not None else FAIL_RULES)

    def emit(rule: str, path: str, line: int, col: int, msg: str) -> None:
        if rule in active:
            graph.findings.append(Finding(path, line, col, rule, msg))

    # ---- family 16 ------------------------------------------------------
    def check_declared(spawn: _Spawn, specs: tuple[str, ...]) -> str:
        status = "contained-by"
        for spec in specs:
            cands = _resolve_handler(prog, spawn.owner, spec)
            if not cands:
                graph.handlers[spec] = "unresolved"
                emit("thread-crash-containment", spawn.path, spawn.line,
                     spawn.col,
                     f"contained-by={spec}: handler does not resolve to a "
                     f"known function — the containment declaration is "
                     f"unauditable")
                status = "contained-by!"
                continue
            bad = [c for c in cands
                   if _containment(prog, c)[0] not in ("contained",
                                                       "no-raise")]
            if bad:
                st, wit = _containment(prog, bad[0])
                graph.handlers[spec] = "weak"
                emit("thread-crash-containment", spawn.path, spawn.line,
                     spawn.col,
                     f"contained-by={spec}: declared handler "
                     f"{bad[0].qual} is not itself contained-and-counted "
                     f"({st} at {_short(bad[0].path)}:{wit}) — same bar "
                     f"as an inline containment")
                status = "contained-by!"
            else:
                graph.handlers.setdefault(spec, "ok")
        return status

    for spawn in prog.spawns:
        site = f"{_short(spawn.path)}:{spawn.line}"
        if spawn.contained_by:
            status = check_declared(spawn, spawn.contained_by)
            graph.threads.append((site, spawn.src, status))
            continue
        cands = _resolve_target(prog, spawn)
        if not cands:
            emit("thread-crash-containment", spawn.path, spawn.line,
                 spawn.col,
                 f"threading.Thread target {spawn.src!r} does not resolve "
                 f"to a known function — an uncontained raise there is a "
                 f"silently dead plane; name the containing frame with "
                 f"`# jaxlint: contained-by=<handler>` or pass a def the "
                 f"graph can see")
            graph.threads.append((site, spawn.src, "unresolved"))
            continue
        worst = "contained"
        for cand in cands:
            if cand.contained_by:
                status = check_declared(spawn, cand.contained_by)
                if status.endswith("!"):
                    worst = status
                continue
            st, wit = _containment(prog, cand)
            if st == "escapes":
                worst = st
                emit("thread-crash-containment", spawn.path, spawn.line,
                     spawn.col,
                     f"thread target {cand.qual} can die silently: "
                     f"{_short(cand.path)}:{wit} raises outside any "
                     f"except-Exception containment — a dead plane; wrap "
                     f"the top frame and count the crash "
                     f"(obs.containment.contained_crash)")
            elif st == "uncounted":
                if worst == "contained":
                    worst = st
                emit("thread-crash-containment", spawn.path, spawn.line,
                     spawn.col,
                     f"thread target {cand.qual}: broad handler at "
                     f"{_short(cand.path)}:{wit} swallows crashes without "
                     f"counting them — increment a registry counter or "
                     f"record a flight event so the death is observable")
        graph.threads.append(
            (site, " | ".join(c.qual for c in cands), worst))

    # ---- families 17/18 -------------------------------------------------
    for fn in prog.infos:
        if fn.name == "<module>":
            continue
        for span in _check_spans(prog, fn):
            site = f"{_short(fn.path)}:{span.line}"
            graph.spans.append((site, span.root or "?", span.status))
            if span.status == "orphan":
                emit("span-terminal-missing", fn.path, span.line, 0,
                     f"trace begin in {fn.qual} can exit on an exception "
                     f"edge (via line {span.witness}) without reaching a "
                     f"commit/shed terminal — orphaned span; shed in an "
                     f"except/finally before the raise escapes")
        for led in _check_ledger(prog, fn):
            site = f"{_short(fn.path)}:{led.line}"
            graph.ledger.append((site, led.counter, led.status))
            if led.status == "leak":
                how = ("an exception edge" if led.exceptional
                       else "a normal path")
                emit("ledger-conservation", fn.path, led.line, 0,
                     f"admission counter '{led.counter}' incremented in "
                     f"{fn.qual} but {how} (via line {led.witness}) "
                     f"reaches function exit with neither a disposition "
                     f"counter nor a terminal hand-off — rows admitted "
                     f"there vanish from the ledger")
    return graph


def format_failgraph(graph: FailGraph) -> str:
    lines = [
        f"failgraph: {graph.modules} modules, {graph.functions} functions, "
        f"{len(graph.threads)} thread spawns, {len(graph.spans)} span "
        f"begins, {len(graph.ledger)} admission counters",
        "",
        "thread roles (spawn site -> target [containment]):",
    ]
    for site, target, status in sorted(graph.threads):
        lines.append(f"  {site} -> {target} [{status}]")
    lines.append("")
    lines.append("span lifecycle (begin site, root, status):")
    for site, root, status in sorted(graph.spans):
        lines.append(f"  {site} {root} [{status}]")
    lines.append("")
    lines.append("ledger (admission site, counter, status):")
    for site, counter, status in sorted(graph.ledger):
        lines.append(f"  {site} {counter} [{status}]")
    if graph.handlers:
        lines.append("")
        lines.append("declared containment handlers:")
        for spec, status in sorted(graph.handlers.items()):
            lines.append(f"  contained-by={spec} [{status}]")
    lines.append("")
    if graph.findings:
        lines.append(f"{len(graph.findings)} finding(s):")
        for f in graph.findings:
            lines.append(f"  {f.format()}")
    else:
        lines.append("findings: none")
    return "\n".join(lines)
