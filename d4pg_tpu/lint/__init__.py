"""jaxlint — JAX/TPU-aware static analysis for this codebase.

Run as ``python -m d4pg_tpu.lint [paths]``; library API:

    from d4pg_tpu.lint import lint_paths, lint_source, RULES

The hazards it targets (PRNG key reuse, host syncs under jit, recompile
traps, donation misuse, tracer leaks) are exactly the ones that silently
erode the learner's on-device throughput story — see the "Static analysis
& perf sentinels" section of docs/architecture.md. The runtime complements
(RecompileSentinel / TransferSentinel) live in ``d4pg_tpu.io.profiling``.

Pure stdlib (ast) — importing this package must never initialize JAX, so
the linter stays runnable in CI images without an accelerator.
"""

from d4pg_tpu.lint.engine import LintResult, lint_paths, lint_source
from d4pg_tpu.lint.findings import Finding, Suppressions
from d4pg_tpu.lint.rules import RULES

__all__ = ["Finding", "LintResult", "RULES", "Suppressions", "lint_paths",
           "lint_source"]
