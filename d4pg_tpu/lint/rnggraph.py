"""rnggraph — whole-program RNG-provenance & determinism pass.

Fifth member of the whole-program family (lockgraph: tiers/cycles,
wiregraph: protocol registry, failgraph: exception flow, meshgraph:
sharding & collectives).  This one models the *determinism* surface:
every gating oracle in the repo — chaos scripts bit-for-bit from
``(seed, k, i)``, the elastic traffic model's pure offered-load
recurrence, the seeded-stream sampler oracles — stands on hand-kept RNG
stream discipline (one SeedSequence branch per component, fixed draws
per event, skip-before-RNG-use), none of which was checked statically.
The same defect class has bitten twice (the PR-12 backpressure stream
desync, the PR-14 layout-dependent ``random_shift`` draw); a silently
diverged stream shows up as an unattributable return-curve bug, not a
loud failure.

The pass discovers every RNG stream in the analyzed program —
``np.random.SeedSequence`` spawn/branch sites, ``default_rng(...)``
constructors, stdlib ``random.Random``, ``jax.random`` key makers —
and builds a provenance table (owning component, branch site, draw
sites, thread reachability via failgraph's spawn-target resolution).
Three families run over it, scoped to *determinism-scoped* code — the
fleet/elastic/replay/obs/analysis planes plus chaos/traffic/sampler/
ledger/bench modules, widened through the cross-module call graph to a
fixpoint (a helper called from scoped code is scoped):

- ``rng-ambient-stream`` (22): a draw from numpy's module-level legacy
  global (``np.random.randn`` &c), a stdlib ``random.*`` draw, an
  unseeded ``default_rng()`` / ``RandomState()`` / ``SeedSequence()``,
  or an RNG constructor seeded from wall clock / pid / urandom.  Any
  of these inside determinism-scoped code breaks seeded replay.
- ``rng-stream-thread-escape`` (23): one Generator whose draw sites
  are reachable from two *distinct* thread-spawn targets without its
  own SeedSequence branch — thread interleaving then orders the draws,
  which silently voids every per-actor ``(seed, k, i)`` claim.  A
  ``# jaxlint: stream-owner=<Component.attr>`` annotation declares a
  caller-owned branch and is audited like ``contained-by=``.
- ``rng-draw-count-drift`` (24): a seeded stream drawn conditionally
  on one path and reused — the PR-12 desync shape.  The documented
  skip-before-RNG-use idiom is the ONE clean form: an event either
  consumes its full fixed draw count or exits before the first draw.
  Per loop iteration (= one event) the body's nonzero draw counts
  must be a single value; a draw reached with a path-dependent stream
  offset fires at the draw site.

Plus the interprocedural upgrade of family 1: per-function summaries
of which key parameters are consumed by ``jax.random`` samplers,
propagated through bare-name call edges to fixpoint, so a key passed
to a consuming helper and then reused at the caller fires under the
existing ``prng-key-reuse`` id (module scope only sees one frame).

Pure stdlib (ast) — same contract as the rest of the package.
"""

from __future__ import annotations

import ast
import builtins
import re
from dataclasses import dataclass, field

from d4pg_tpu.lint.context import ModuleContext, dotted_name, last_part
from d4pg_tpu.lint.failgraph import (
    _MAX_CANDIDATES,
    _class_family,
    _FnInfo,
    _Program,
    _resolve_target,
    _short,
    _strip_nested,
    build_program,
)
from d4pg_tpu.lint.findings import Finding

RNG_RULES = (
    "rng-ambient-stream",
    "rng-stream-thread-escape",
    "rng-draw-count-drift",
)

_STREAM_OWNER = re.compile(r"#\s*jaxlint:\s*stream-owner=([\w\.\-,]+)")

# Determinism scope roots: package directories whose code carries a
# seeded-replay contract, plus module stems that do wherever they live
# (bench.py sits at the package root).  lint/ is never scoped — its
# sources *name* these APIs without running them.
_SCOPE_DIRS = {"fleet", "elastic", "replay", "obs", "analysis"}
_SCOPE_STEM = re.compile(r"(chaos|traffic|sampler|ledger|bench)")

# Generator draw surface (modern Generator + legacy RandomState + stdlib
# Random).  Draws are only attributed to receivers the pass has already
# resolved to a stream, so generic names here cannot misfire on
# unrelated objects.
_DRAW_METHODS = frozenset({
    "beta", "binomial", "bytes", "chisquare", "choice", "dirichlet",
    "exponential", "gamma", "geometric", "gumbel", "hypergeometric",
    "integers", "laplace", "logistic", "lognormal", "logseries",
    "multinomial", "multivariate_normal", "negative_binomial",
    "noncentral_chisquare", "normal", "pareto", "permutation", "permuted",
    "poisson", "power", "random", "rayleigh", "shuffle",
    "standard_cauchy", "standard_exponential", "standard_gamma",
    "standard_normal", "standard_t", "triangular", "uniform", "vonmises",
    "wald", "weibull", "zipf",
    "rand", "randn", "randint", "random_sample", "sample", "choices",
    "randrange", "gauss", "normalvariate", "betavariate", "expovariate",
    "getrandbits", "randbytes",
})

# Ambient numpy legacy-global surface: any of these dotted off
# ``np.random`` draws from (or mutates) the hidden process-wide stream.
_LEGACY_GLOBAL = _DRAW_METHODS | {"seed", "get_state", "set_state"}

# stdlib ``random.<fn>`` module-level draws (the hidden global Random).
_STDLIB_DRAWS = frozenset({
    "random", "randint", "uniform", "choice", "choices", "shuffle",
    "sample", "randrange", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "getrandbits", "randbytes", "seed",
})

# Calls whose result is nondeterministic across runs: seeding an RNG
# from one of these destroys replay even though the ctor "has a seed".
_WALLCLOCK = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns", "monotonic",
    "monotonic_ns", "urandom", "uuid1", "uuid4", "getpid",
})

_NP_BASES = {"np", "numpy", "onp"}

# Bare-name calls spelled like builtins are the builtin (``next(it)``,
# ``set(...)``): resolving them into same-named methods would invent
# call edges the program never takes.
_BUILTIN_NAMES = frozenset(dir(builtins))

# Bounded path-sensitivity for the family-24 interpreter: a count-set
# larger than this collapses to its {min, max} envelope.
_MAX_COUNTS = 6


# --------------------------------------------------------------------------
# Stream discovery
# --------------------------------------------------------------------------

@dataclass
class _Stream:
    key: str                 # 'Cls.attr' | 'mod:NAME' | 'qual:name@line'
    kind: str                # 'attr' | 'module' | 'local'
    path: str
    line: int
    col: int
    owner: str               # owning component (class, module, function)
    name: str                # attribute / variable name
    cls: str | None          # class for attr streams
    ctor: str                # default_rng | RandomState | Random | PRNGKey
    seed: str                # branched | seeded | unseeded | wallclock
    wrap: str = ""           # DrawLedger.wrap() stream label, if any
    owner_decl: tuple[str, ...] = ()   # stream-owner= annotation specs
    draws: list[tuple[str, int, str]] = field(default_factory=list)
    threads: set[str] = field(default_factory=set)
    fn_key: str = ""         # enclosing function (local streams)


def _owner_lines(source: str) -> dict[int, tuple[str, ...]]:
    out: dict[int, tuple[str, ...]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _STREAM_OWNER.search(text)
        if m:
            out[i] = tuple(s.strip() for s in m.group(1).split(",")
                           if s.strip())
    return out


def _stmt_annotation(lines: dict[int, tuple[str, ...]],
                     stmt: ast.stmt) -> tuple[str, ...]:
    end = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
    for ln in range(stmt.lineno, end + 1):
        if ln in lines:
            return lines[ln]
    return ()


def _rng_ctor_kind(call: ast.Call) -> str | None:
    """'default_rng' | 'RandomState' | 'Generator' | 'Random' |
    'SeedSequence' | 'PRNGKey' when ``call`` constructs an RNG stream /
    key, else None."""
    dotted = dotted_name(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    fn = parts[-1]
    if fn in ("default_rng", "RandomState", "Generator", "SeedSequence"):
        return fn
    if fn == "Random" and (len(parts) == 1 or parts[0] == "random"):
        return "Random"
    if fn in ("PRNGKey", "key") and (
            "random" in parts[:-1] or parts[0] in {"jr", "jrandom"}):
        return "PRNGKey"
    return None


def _unwrap_ledger(call: ast.Call) -> tuple[ast.Call, str]:
    """See through ``LEDGER.wrap("name", <ctor>)`` — the runtime twin's
    counting proxy — to the wrapped constructor."""
    if (isinstance(call.func, ast.Attribute) and call.func.attr == "wrap"
            and len(call.args) == 2
            and isinstance(call.args[1], ast.Call)):
        label = ""
        if isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            label = call.args[0].value
        return call.args[1], label
    return call, ""


def _seed_status(call: ast.Call, kind: str,
                 aliases: dict[str, ast.expr]) -> str:
    """branched | seeded | unseeded | wallclock for an RNG ctor call."""
    args = list(call.args) + [kw.value for kw in call.keywords
                              if kw.arg in ("seed", "entropy", None)]
    for a in args:
        for sub in ast.walk(a):
            if isinstance(sub, ast.Call):
                name = last_part(dotted_name(sub.func))
                if name in _WALLCLOCK:
                    return "wallclock"
    if not args:
        return "unseeded"
    if len(args) == 1 and isinstance(args[0], ast.Constant) \
            and args[0].value is None:
        return "unseeded"
    for a in args:
        exprs = [a]
        if isinstance(a, ast.Name) and a.id in aliases:
            exprs.append(aliases[a.id])
        for e in exprs:
            for sub in ast.walk(e):
                if isinstance(sub, ast.Call):
                    name = last_part(dotted_name(sub.func))
                    if name == "SeedSequence" or name == "spawn":
                        return "branched"
    return "seeded"


def _discover_streams(prog: _Program) -> list[_Stream]:
    streams: list[_Stream] = []
    for fn in prog.infos:
        ann = _owner_lines(fn.ctx.source)
        aliases: dict[str, ast.expr] = {}
        for stmt in fn.node.body if hasattr(fn.node, "body") else []:
            for sub in _strip_nested(stmt):
                if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                    continue
                target, value = sub.targets[0], sub.value
                if isinstance(target, ast.Name) \
                        and isinstance(value, ast.Call):
                    aliases[target.id] = value
                if not isinstance(value, ast.Call):
                    continue
                call, wrap_label = _unwrap_ledger(value)
                kind = _rng_ctor_kind(call)
                if kind is None or kind in ("SeedSequence", "Generator"):
                    continue
                seed = _seed_status(call, kind, aliases)
                specs = _stmt_annotation(ann, sub)
                if isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id == "self" and fn.cls:
                    streams.append(_Stream(
                        key=f"{fn.cls}.{target.attr}", kind="attr",
                        path=fn.path, line=sub.lineno, col=sub.col_offset,
                        owner=fn.cls, name=target.attr, cls=fn.cls,
                        ctor=kind, seed=seed, wrap=wrap_label,
                        owner_decl=specs, fn_key=fn.key))
                elif isinstance(target, ast.Name):
                    if fn.name == "<module>":
                        streams.append(_Stream(
                            key=f"{_short(fn.path)}:{target.id}",
                            kind="module", path=fn.path, line=sub.lineno,
                            col=sub.col_offset, owner=_short(fn.path),
                            name=target.id, cls=None, ctor=kind, seed=seed,
                            wrap=wrap_label, owner_decl=specs,
                            fn_key=fn.key))
                    else:
                        streams.append(_Stream(
                            key=f"{fn.qual}:{target.id}@{sub.lineno}",
                            kind="local", path=fn.path, line=sub.lineno,
                            col=sub.col_offset, owner=fn.qual,
                            name=target.id, cls=fn.cls, ctor=kind,
                            seed=seed, wrap=wrap_label, owner_decl=specs,
                            fn_key=fn.key))
    return streams


def _branch_sites(prog: _Program) -> list[tuple[str, str]]:
    """SeedSequence constructions and ``.spawn()`` calls — the branch
    points of the stream tree, listed for the review artifact."""
    out: list[tuple[str, str]] = []
    seen: set[tuple[str, int]] = set()
    for fn in prog.infos:
        if fn.name == "<module>" and not fn.node.body:
            continue
        for sub in _strip_nested(fn.node):
            if not isinstance(sub, ast.Call):
                continue
            name = last_part(dotted_name(sub.func))
            if name not in ("SeedSequence", "spawn"):
                continue
            at = (fn.path, sub.lineno)
            if at in seen:
                continue
            seen.add(at)
            src = ast.unparse(sub)
            if len(src) > 72:
                src = src[:69] + "..."
            out.append((f"{_short(fn.path)}:{sub.lineno}", src))
    return out


# --------------------------------------------------------------------------
# Call graph (conservative: self-family methods + bare local names) and
# determinism-scope fixpoint
# --------------------------------------------------------------------------

def _call_edges(prog: _Program) -> dict[str, set[str]]:
    edges: dict[str, set[str]] = {}
    for fn in prog.infos:
        out: set[str] = set()
        for sub in _strip_nested(fn.node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Name):
                cands = prog.by_name.get(f.id, [])
                local = [c for c in cands if c.path == fn.path]
                if not local and f.id in _BUILTIN_NAMES:
                    continue
                cands = local or (cands if len(cands) <= _MAX_CANDIDATES
                                  else [])
                out.update(c.key for c in cands)
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in ("self", "cls") and fn.cls:
                fam = _class_family(prog, fn.cls)
                out.update(c.key for c in prog.by_name.get(f.attr, ())
                           if c.cls in fam)
        edges[fn.key] = out
    return edges


def _path_scoped(path: str) -> bool:
    short = _short(path)
    if "/lint/" in path or short.startswith("lint/"):
        return False
    parts = short.split("/")
    if set(parts[:-1]) & _SCOPE_DIRS:
        return True
    return bool(_SCOPE_STEM.search(parts[-1]))


def _scoped_keys(prog: _Program, edges: dict[str, set[str]]) -> set[str]:
    scoped = {f.key for f in prog.infos if _path_scoped(f.path)}
    frontier = list(scoped)
    while frontier:
        k = frontier.pop()
        for c in edges.get(k, ()):
            if c not in scoped:
                scoped.add(c)
                frontier.append(c)
    return scoped


def _closure(edges: dict[str, set[str]], root: str,
             cache: dict[str, set[str]]) -> set[str]:
    if root in cache:
        return cache[root]
    seen = {root}
    frontier = [root]
    while frontier:
        k = frontier.pop()
        for c in edges.get(k, ()):
            if c not in seen:
                seen.add(c)
                frontier.append(c)
    cache[root] = seen
    return seen


# --------------------------------------------------------------------------
# Draw-site attribution + thread reachability
# --------------------------------------------------------------------------

def _attach_draws(prog: _Program, streams: list[_Stream]) -> None:
    by_attr: dict[str, list[_Stream]] = {}
    by_module: dict[tuple[str, str], _Stream] = {}
    by_local: dict[tuple[str, str], _Stream] = {}
    for s in streams:
        if s.kind == "attr":
            by_attr.setdefault(s.name, []).append(s)
        elif s.kind == "module":
            by_module[(s.path, s.name)] = s
        else:
            by_local[(s.fn_key, s.name)] = s
    fam_cache: dict[str, set[str]] = {}
    for fn in prog.infos:
        for sub in _strip_nested(fn.node):
            if not isinstance(sub, ast.Call) \
                    or not isinstance(sub.func, ast.Attribute) \
                    or sub.func.attr not in _DRAW_METHODS:
                continue
            recv = dotted_name(sub.func.value)
            if not recv:
                continue
            site = (fn.path, sub.lineno, fn.key)
            if recv.startswith("self.") and recv.count(".") == 1 and fn.cls:
                attr = recv.split(".", 1)[1]
                if fn.cls not in fam_cache:
                    fam_cache[fn.cls] = _class_family(prog, fn.cls)
                for s in by_attr.get(attr, ()):
                    if s.cls in fam_cache[fn.cls]:
                        s.draws.append(site)
            elif "." not in recv:
                local = by_local.get((fn.key, recv))
                if local is not None:
                    local.draws.append(site)
                else:
                    mod = by_module.get((fn.path, recv))
                    if mod is not None:
                        mod.draws.append(site)


def _thread_reach(prog: _Program, edges: dict[str, set[str]],
                  streams: list[_Stream]) -> None:
    cache: dict[str, set[str]] = {}
    targets: dict[str, set[str]] = {}
    for spawn in prog.spawns:
        for cand in _resolve_target(prog, spawn):
            targets.setdefault(cand.qual, set()).update(
                _closure(edges, cand.key, cache))
    for s in streams:
        draw_fns = {fk for (_, _, fk) in s.draws}
        for qual, reach in targets.items():
            if draw_fns & reach:
                s.threads.add(qual)


# --------------------------------------------------------------------------
# Family 22 — ambient / nondeterministic streams in determinism scope
# --------------------------------------------------------------------------

def _check_ambient(prog: _Program, scoped: set[str], emit) -> None:
    for fn in prog.infos:
        if fn.key not in scoped:
            continue
        aliases: dict[str, ast.expr] = {}
        for sub in _strip_nested(fn.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call):
                aliases[sub.targets[0].id] = sub.value
            if not isinstance(sub, ast.Call):
                continue
            dotted = dotted_name(sub.func)
            if not dotted:
                continue
            parts = dotted.split(".")
            where = "in determinism-scoped code"
            if len(parts) == 3 and parts[0] in _NP_BASES \
                    and parts[1] == "random" and parts[2] in _LEGACY_GLOBAL:
                emit("rng-ambient-stream", fn.path, sub.lineno,
                     sub.col_offset,
                     f"np.random.{parts[2]} draws from numpy's hidden "
                     f"module-level global stream {where} ({fn.qual}) — "
                     f"seeded replay cannot own it; use a component "
                     f"default_rng(SeedSequence(seed, spawn_key=...)) "
                     f"branch instead")
                continue
            if len(parts) == 2 and parts[0] == "random" \
                    and parts[1] in _STDLIB_DRAWS:
                emit("rng-ambient-stream", fn.path, sub.lineno,
                     sub.col_offset,
                     f"stdlib random.{parts[1]} draws from the hidden "
                     f"process-global Random {where} ({fn.qual}) — "
                     f"replace with a seeded component stream")
                continue
            kind = _rng_ctor_kind(sub)
            if kind is None:
                continue
            status = _seed_status(sub, kind, aliases)
            if status == "wallclock":
                emit("rng-ambient-stream", fn.path, sub.lineno,
                     sub.col_offset,
                     f"{kind} seeded from a wall-clock/pid/urandom value "
                     f"{where} ({fn.qual}) — the seed changes every run, "
                     f"so the stream can never replay; derive it from "
                     f"the component SeedSequence instead")
            elif status == "unseeded" and kind != "Generator":
                emit("rng-ambient-stream", fn.path, sub.lineno,
                     sub.col_offset,
                     f"unseeded {kind}() {where} ({fn.qual}) — OS-entropy "
                     f"streams break seeded replay; pass a seed or a "
                     f"SeedSequence branch")


# --------------------------------------------------------------------------
# Family 23 — stream shared across thread-spawn targets
# --------------------------------------------------------------------------

def _check_thread_escape(streams: list[_Stream],
                         handlers: dict[str, str],
                         resolve_owner, emit) -> None:
    for s in streams:
        if s.kind == "local" or len(s.threads) < 2:
            continue
        if s.owner_decl:
            for spec in s.owner_decl:
                status = resolve_owner(spec)
                if status != "ok":
                    emit("rng-stream-thread-escape", s.path, s.line, s.col,
                         f"stream-owner={spec} on {s.key} does not resolve "
                         f"to a SeedSequence-branched (or seeded) stream "
                         f"the graph can see — the ownership declaration "
                         f"is unauditable")
            continue
        if s.seed == "branched":
            continue
        roles = " and ".join(sorted(s.threads)[:4])
        emit("rng-stream-thread-escape", s.path, s.line, s.col,
             f"stream {s.key} is drawn from {len(s.threads)} distinct "
             f"thread-spawn targets ({roles}) without its own "
             f"SeedSequence branch — interleaving orders the draws and "
             f"silently voids the per-component (seed, k, i) replay "
             f"claim; give each consumer its own "
             f"SeedSequence(seed, spawn_key=...) branch or declare "
             f"`# jaxlint: stream-owner=<Component.attr>`")


# --------------------------------------------------------------------------
# Family 24 — draw-count drift (the PR-12 desync shape)
# --------------------------------------------------------------------------

class _DriftScan:
    """Per-function abstract interpreter: tracks, per stream, the set of
    possible draw counts since function (or loop-body) entry.  A draw
    reached with more than one possible count has a path-dependent
    stream offset → drift.  Loop bodies are one *event*: the body's
    nonzero per-iteration draw counts must be a single value (paths that
    exit before the first draw are the documented skip-before-RNG-use
    idiom and stay clean)."""

    def __init__(self, fn: _FnInfo, tracked: set[str], emit) -> None:
        self.fn = fn
        self.tracked = set(tracked)   # receiver spellings: self.X / name
        self.emit = emit
        self.first_draw: dict[str, tuple[int, int]] = {}
        self.returns: list[dict[str, frozenset]] = []
        self._fired: set[tuple[str, int]] = set()

    # -- state helpers -----------------------------------------------------
    @staticmethod
    def _cap(counts: frozenset) -> frozenset:
        if len(counts) > _MAX_COUNTS:
            return frozenset({min(counts), max(counts)})
        return counts

    def _merge(self, states: list[dict]) -> dict | None:
        live = [st for st in states if st is not None]
        if not live:
            return None
        out: dict[str, frozenset] = {}
        for key in {k for st in live for k in st}:
            out[key] = self._cap(frozenset().union(
                *(st.get(key, frozenset({0})) for st in live)))
        return out

    def _fire(self, stream: str, line: int, col: int, why: str) -> None:
        at = (stream, line)
        if at in self._fired:
            return
        self._fired.add(at)
        self.emit("rng-draw-count-drift", self.fn.path, line, col,
                  f"seeded stream '{stream}' in {self.fn.qual} {why} — "
                  f"the PR-12 desync shape; draw a fixed count per event "
                  f"and put any skip BEFORE the first draw "
                  f"(skip-before-RNG-use), so the event index stays "
                  f"aligned with the RNG state")

    # -- expression scan ---------------------------------------------------
    def _scan_expr(self, expr: ast.AST, state: dict) -> None:
        for sub in _strip_nested(expr):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _DRAW_METHODS:
                recv = dotted_name(sub.func.value)
                if recv in self.tracked:
                    counts = state.get(recv, frozenset({0}))
                    self.first_draw.setdefault(
                        recv, (sub.lineno, sub.col_offset))
                    if len(counts) > 1:
                        self._fire(
                            recv, sub.lineno, sub.col_offset,
                            f"is drawn at a point its offset is "
                            f"path-dependent (possible prior draws: "
                            f"{sorted(counts)})")
                    state[recv] = self._cap(
                        frozenset(c + 1 for c in counts))
                    continue
            # a tracked stream handed to another frame: its draw count
            # becomes that frame's business — resync, don't guess
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                if isinstance(arg, ast.Name) and arg.id in self.tracked:
                    state[arg.id] = frozenset({0})

    # -- statement walk ----------------------------------------------------
    def run(self, stmts: list[ast.stmt]) -> None:
        state: dict[str, frozenset] = {}
        end = self._block(stmts, state, loops=0, conts=None, brks=None)
        if end is not None:
            self.returns.append(end)

    def _block(self, stmts, state, loops, conts, brks):
        """Returns the fall-through state (None if unreachable); early
        returns land in self.returns, continue/break states in
        conts/brks."""
        cur: dict | None = state
        for stmt in stmts:
            if cur is None:
                return None
            cur = self._stmt(stmt, cur, loops, conts, brks)
        return cur

    def _stmt(self, stmt, state, loops, conts, brks):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            self._scan_expr(stmt.value, state)
            target = stmt.targets[0]
            if isinstance(target, ast.Name) \
                    and isinstance(stmt.value, ast.Call):
                call, _ = _unwrap_ledger(stmt.value)
                kind = _rng_ctor_kind(call)
                if kind in ("default_rng", "RandomState", "Random"):
                    self.tracked.add(target.id)
                    state[target.id] = frozenset({0})
                    return state
            if isinstance(target, ast.Name) and target.id in state:
                del state[target.id]
                self.tracked.discard(target.id)
            return state
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, state)
            a, b = dict(state), dict(state)
            ea = self._block(stmt.body, a, loops, conts, brks)
            eb = self._block(stmt.orelse, b, loops, conts, brks)
            return self._merge([ea, eb])
        if isinstance(stmt, (ast.While, ast.For)):
            head = stmt.test if isinstance(stmt, ast.While) else stmt.iter
            self._scan_expr(head, state)
            # one iteration == one event: analyze the body from a zeroed
            # ledger and require its nonzero draw counts to agree
            body_state: dict[str, frozenset] = {}
            body_conts: list[dict] = []
            body_brks: list[dict] = []
            end = self._block(stmt.body, body_state, loops + 1,
                              body_conts, body_brks)
            outcomes = [o for o in [end] + body_conts if o is not None]
            drawn = {k for o in outcomes for k in o}
            for key in drawn:
                nonzero = {c for o in outcomes
                           for c in o.get(key, frozenset({0})) if c > 0}
                if len(nonzero) > 1:
                    line, col = self.first_draw.get(
                        key, (stmt.lineno, stmt.col_offset))
                    self._fire(
                        key, line, col,
                        f"draws a path-dependent count per loop "
                        f"iteration ({sorted(nonzero)} possible)")
            self._block(stmt.orelse, dict(state), loops, conts, brks)
            return state
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, state)
            return self._block(stmt.body, state, loops, conts, brks)
        if isinstance(stmt, ast.Try):
            a = dict(state)
            ea = self._block(stmt.body, a, loops, conts, brks)
            ends = [ea]
            for h in stmt.handlers:
                hb = dict(state)
                ends.append(self._block(h.body, hb, loops, conts, brks))
            merged = self._merge(ends)
            if merged is None:
                return None
            if stmt.orelse:
                merged = self._block(stmt.orelse, merged, loops, conts,
                                     brks)
            if merged is not None and stmt.finalbody:
                merged = self._block(stmt.finalbody, merged, loops,
                                     conts, brks)
            return merged
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value, state)
            self.returns.append(state)
            return None
        if isinstance(stmt, ast.Raise):
            return None
        if isinstance(stmt, ast.Continue):
            if conts is not None:
                conts.append(state)
            return None
        if isinstance(stmt, ast.Break):
            if brks is not None:
                brks.append(state)
            return None
        for value in ast.iter_child_nodes(stmt):
            if isinstance(value, ast.expr):
                self._scan_expr(value, state)
        return state


def _check_drift(prog: _Program, streams: list[_Stream],
                 scoped: set[str], emit) -> None:
    by_fn_attr: dict[str | None, set[str]] = {}
    by_module: dict[str, set[str]] = {}
    fam_cache: dict[str, set[str]] = {}
    for s in streams:
        if s.kind == "attr":
            by_fn_attr.setdefault(s.cls, set()).add(f"self.{s.name}")
        elif s.kind == "module":
            by_module.setdefault(s.path, set()).add(s.name)
    for fn in prog.infos:
        if fn.key not in scoped or fn.name == "<module>":
            continue
        tracked: set[str] = set(by_module.get(fn.path, ()))
        if fn.cls:
            if fn.cls not in fam_cache:
                fam_cache[fn.cls] = _class_family(prog, fn.cls)
            for cls in fam_cache[fn.cls]:
                tracked |= by_fn_attr.get(cls, set())
        scan = _DriftScan(fn, tracked, emit)
        scan.run(list(fn.node.body))
        # persistent streams (attr/module) outlive the frame: distinct
        # nonzero per-call totals desync every later consumer
        persistent = {t for t in scan.tracked
                      if t.startswith("self.") or t in tracked}
        for key in persistent:
            totals = {c for st in scan.returns
                      for c in st.get(key, frozenset({0}))}
            nonzero = {c for c in totals if c > 0}
            if len(nonzero) > 1 and key in scan.first_draw:
                line, col = scan.first_draw[key]
                scan._fire(key, line, col,
                           f"leaves the frame having drawn a "
                           f"path-dependent total ({sorted(nonzero)} "
                           f"possible)")


# --------------------------------------------------------------------------
# Interprocedural family 1 — prng-key-reuse across call boundaries
# --------------------------------------------------------------------------

def _fn_params(fn: _FnInfo) -> list[str]:
    node = fn.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    args = node.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _resolve_bare(prog: _Program, fn: _FnInfo,
                  name: str) -> _FnInfo | None:
    cands = prog.by_name.get(name, [])
    local = [c for c in cands if c.path == fn.path
             and c.name != "<module>"]
    if not local and name in _BUILTIN_NAMES:
        return None
    cands = local or cands
    return cands[0] if len(cands) == 1 else None


def _key_summaries(prog: _Program) -> dict[str, set[int]]:
    """fn key -> positional indices of parameters consumed by a
    jax.random sampler (directly or through a callee), to fixpoint."""
    from d4pg_tpu.lint.rules import _random_call

    params: dict[str, list[str]] = {}
    consumed: dict[str, set[int]] = {}
    for fn in prog.infos:
        if fn.name == "<module>":
            continue
        names = _fn_params(fn)
        params[fn.key] = names
        direct: set[int] = set()
        for sub in _strip_nested(fn.node):
            if isinstance(sub, ast.Call) and _random_call(sub) \
                    and sub.args and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id in names:
                direct.add(names.index(sub.args[0].id))
        consumed[fn.key] = direct
    changed = True
    while changed:
        changed = False
        for fn in prog.infos:
            if fn.name == "<module>":
                continue
            names = params[fn.key]
            for sub in _strip_nested(fn.node):
                if not isinstance(sub, ast.Call) \
                        or not isinstance(sub.func, ast.Name):
                    continue
                callee = _resolve_bare(prog, fn, sub.func.id)
                if callee is None or not consumed.get(callee.key):
                    continue
                cal_names = params.get(callee.key, [])
                for i, arg in enumerate(sub.args):
                    if not (isinstance(arg, ast.Name)
                            and arg.id in names):
                        continue
                    if i in consumed[callee.key]:
                        pi = names.index(arg.id)
                        if pi not in consumed[fn.key]:
                            consumed[fn.key].add(pi)
                            changed = True
                # keyword args: match by callee parameter name
                for kw in sub.keywords:
                    if kw.arg is None or not (isinstance(kw.value, ast.Name)
                                              and kw.value.id in names):
                        continue
                    if kw.arg in cal_names \
                            and cal_names.index(kw.arg) \
                            in consumed[callee.key]:
                        pi = names.index(kw.value.id)
                        if pi not in consumed[fn.key]:
                            consumed[fn.key].add(pi)
                            changed = True
    return consumed


def _check_key_reuse(prog: _Program, emit) -> None:
    from d4pg_tpu.lint.rules import SequentialRule, _random_call

    summaries = _key_summaries(prog)
    params: dict[str, list[str]] = {
        fn.key: _fn_params(fn) for fn in prog.infos
        if fn.name != "<module>"}

    class _KeyFlow(SequentialRule):
        """State: key name -> (line, via, interproc).  Emits only when
        at least one of the two consumptions crosses a call boundary —
        the module-scope family already covers same-frame pairs."""

        owner: _FnInfo | None = None

        def on_call(self, call: ast.Call, state: dict) -> None:
            events: list[tuple[str, str, bool]] = []
            sampler = _random_call(call)
            if sampler and call.args and isinstance(call.args[0], ast.Name):
                events.append(
                    (call.args[0].id, f"jax.random.{sampler}", False))
            elif isinstance(call.func, ast.Name) and self.owner:
                callee = _resolve_bare(prog, self.owner, call.func.id)
                if callee is not None and summaries.get(callee.key):
                    cal_names = params.get(callee.key, [])
                    for i, arg in enumerate(call.args):
                        if isinstance(arg, ast.Name) \
                                and i in summaries[callee.key]:
                            events.append((arg.id, callee.qual, True))
                    for kw in call.keywords:
                        if kw.arg in cal_names \
                                and isinstance(kw.value, ast.Name) \
                                and cal_names.index(kw.arg) \
                                in summaries[callee.key]:
                            events.append((kw.value.id, callee.qual, True))
            for name, via, inter in events:
                prior = state.get(name)
                if prior is None:
                    state[name] = (call.lineno, via, inter)
                    continue
                pline, pvia, pinter = prior
                if inter or pinter:
                    self.emit(
                        call, "prng-key-reuse",
                        f"key '{name}' already consumed by {pvia} at "
                        f"line {pline}; consumed again by {via} — the "
                        f"callee draws from it, so split() or fold_in() "
                        f"a fresh key per consumer")

    for fn in prog.infos:
        if fn.name == "<module>" or isinstance(fn.node, ast.Lambda):
            continue
        checker = _KeyFlow(fn.ctx)
        checker.owner = fn
        checker.run_function(fn.node)
        for f in checker.findings:
            emit("prng-key-reuse-x", f.file, f.line, f.col, f.message)


# --------------------------------------------------------------------------
# Graph artifact + analyze
# --------------------------------------------------------------------------

@dataclass
class RngGraph:
    functions: int = 0
    modules: int = 0
    scoped: int = 0
    # stream rows: (ctor site, owner key, ctor, seed, draws, threads)
    streams: list[tuple[str, str, str, str, int, str]] = field(
        default_factory=list)
    # branch rows: (site, source text)
    branches: list[tuple[str, str]] = field(default_factory=list)
    # stream-owner annotation audit: spec -> ok | weak | unresolved
    handlers: dict[str, str] = field(default_factory=dict)
    findings: list[Finding] = field(default_factory=list)


def analyze(ctxs: list[ModuleContext],
            rules: list[str] | None = None) -> RngGraph:
    prog = build_program(ctxs)
    graph = RngGraph(functions=len(prog.infos), modules=len(ctxs))
    active = set(rules if rules is not None else RNG_RULES)

    def emit(rule: str, path: str, line: int, col: int, msg: str) -> None:
        if rule == "prng-key-reuse-x":
            # interprocedural upgrade of the module-scope family 1:
            # rides the flagship rng family's activation, reports under
            # the established id
            if "rng-ambient-stream" in active:
                graph.findings.append(
                    Finding(path, line, col, "prng-key-reuse", msg))
            return
        if rule in active:
            graph.findings.append(Finding(path, line, col, rule, msg))

    streams = _discover_streams(prog)
    graph.branches = _branch_sites(prog)
    edges = _call_edges(prog)
    scoped = _scoped_keys(prog, edges)
    graph.scoped = len(scoped)
    _attach_draws(prog, streams)
    _thread_reach(prog, edges, streams)

    # stream-owner audit: a spec must name a discovered attr stream with
    # a visible seeded (or SeedSequence-branched) constructor
    by_key = {s.key: s for s in streams if s.kind == "attr"}

    def resolve_owner(spec: str) -> str:
        owner = by_key.get(spec)
        if owner is None:
            graph.handlers[spec] = "unresolved"
            return "unresolved"
        if owner.seed in ("branched", "seeded"):
            graph.handlers.setdefault(spec, "ok")
            return "ok"
        graph.handlers[spec] = "weak"
        return "weak"

    for s in streams:
        for spec in s.owner_decl:
            status = resolve_owner(spec)
            if status != "ok" and s.threads is not None \
                    and len(s.threads) < 2:
                # not the thread-escape path: still surface the broken
                # declaration under the ambient family so it can't rot
                emit("rng-ambient-stream", s.path, s.line, s.col,
                     f"stream-owner={spec} on {s.key} is {status}: the "
                     f"named owner stream must be a discovered, seeded "
                     f"(or SeedSequence-branched) component stream")

    _check_ambient(prog, scoped, emit)
    _check_thread_escape(streams, graph.handlers, resolve_owner, emit)
    _check_drift(prog, streams, scoped, emit)
    _check_key_reuse(prog, emit)

    for s in streams:
        site = f"{_short(s.path)}:{s.line}"
        seed = s.seed if not s.wrap else f"{s.seed}+ledger:{s.wrap}"
        threads = "|".join(sorted(s.threads)) if s.threads else "-"
        graph.streams.append(
            (site, s.key, s.ctor, seed, len(s.draws), threads))
    return graph


def format_rnggraph(graph: RngGraph) -> str:
    lines = [
        f"rnggraph: {graph.modules} modules, {graph.functions} functions "
        f"({graph.scoped} determinism-scoped), {len(graph.streams)} "
        f"streams, {len(graph.branches)} branch sites",
        "",
        "streams (ctor site -> owner [ctor/seed] draws threads):",
    ]
    for site, owner, ctor, seed, draws, threads in sorted(graph.streams):
        lines.append(f"  {site} -> {owner} [{ctor}/{seed}] "
                     f"draws={draws} threads={threads}")
    lines.append("")
    lines.append("branch sites (SeedSequence / spawn):")
    for site, src in sorted(graph.branches):
        lines.append(f"  {site} {src}")
    if graph.handlers:
        lines.append("")
        lines.append("declared stream owners:")
        for spec, status in sorted(graph.handlers.items()):
            lines.append(f"  stream-owner={spec} [{status}]")
    lines.append("")
    if graph.findings:
        lines.append(f"{len(graph.findings)} finding(s):")
        for f in graph.findings:
            lines.append(f"  {f.format()}")
    else:
        lines.append("findings: none")
    return "\n".join(lines)
