"""Module-level analysis context shared by all jaxlint rules.

The central question every rule asks is "does this code run under a JAX
trace?" — ``.item()`` on the host is fine, inside ``jit`` it is a silent
device sync (or a concretization error). ``ModuleContext`` answers it
statically and conservatively:

- a function is *traced* when it is decorated with a tracing transform
  (``jit``/``pmap``/``vmap``/``grad``/``checkpoint``/``custom_vjp``, bare
  or dotted or under ``functools.partial``),
- or its name/lambda is passed to a trace-inducing call
  (``jax.jit(f)``, ``lax.scan(body, ...)``, ``shard_map(f, ...)``,
  ``pl.pallas_call(kernel, ...)`` …),
- or it is lexically nested inside a traced function,
- or it is CALLED from a traced function in the same module (transitive:
  ``jax.jit(lambda s, b: update_step(cfg, s, b))`` taints ``update_step``
  and everything update_step calls). A sync point reached from a traced
  caller is a bug no matter how many plain-function hops sit in between.

The context also records *jit bindings* — ``g = jax.jit(f, donate_argnums=…,
static_argnums=…)`` — so call-site rules (donation, static-arg hazards)
can reason about ``g(...)`` later in the same module.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# Transforms that trace their operand eagerly or at call time.
TRACE_WRAPPERS = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "checkpoint", "remat",
    "custom_jvp", "custom_vjp", "shard_map", "named_call", "pallas_call",
}
# lax control-flow primitives whose function-valued args are traced, plus
# custom-derivative registration (fn.defvjp(fwd, bwd) traces both rules).
TRACE_HOFS = {
    "scan", "fori_loop", "while_loop", "cond", "switch", "map",
    "associative_scan", "defvjp", "defjvp", "defjvps",
}


def dotted_name(node: ast.AST) -> str | None:
    """'jax.lax.scan' for an Attribute chain, 'jit' for a Name, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_part(dotted: str | None) -> str | None:
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _unwrap_partial(call: ast.Call) -> ast.expr | None:
    """partial(jit, ...) / functools.partial(jax.jit, ...) -> the jit expr."""
    if last_part(dotted_name(call.func)) == "partial" and call.args:
        return call.args[0]
    return None


def is_trace_wrapper_expr(node: ast.expr) -> bool:
    """True for an expression denoting a tracing transform: ``jax.jit``,
    ``jit``, ``partial(jax.jit, static_argnums=0)`` …"""
    if isinstance(node, ast.Call):
        inner = _unwrap_partial(node)
        if inner is not None:
            return is_trace_wrapper_expr(inner)
        # jax.jit(...) as a decorator factory: @jax.jit(donate_argnums=0)
        return is_trace_wrapper_expr(node.func)
    return last_part(dotted_name(node)) in TRACE_WRAPPERS


def call_kind(call: ast.Call) -> str | None:
    """'wrapper' for jit/pmap/… calls, 'hof' for lax.scan-style calls."""
    target = _unwrap_partial(call)
    name = last_part(dotted_name(target if target is not None else call.func))
    if name in TRACE_WRAPPERS:
        return "wrapper"
    if name in TRACE_HOFS:
        return "hof"
    return None


@dataclass
class JitBinding:
    """``name = jax.jit(fn, donate_argnums=…, static_argnums=…)``."""

    name: str
    line: int
    donate_argnums: tuple[int, ...] = ()
    static_argnums: tuple[int, ...] = ()


@dataclass
class ModuleContext:
    path: str
    source: str
    tree: ast.Module
    traced: set[ast.AST] = field(default_factory=set)
    # function-name -> binding, module-scope only (conservative)
    jit_bindings: dict[str, JitBinding] = field(default_factory=dict)
    # every FunctionDef/Lambda -> its immediate parent function (or None)
    parents: dict[ast.AST, ast.AST | None] = field(default_factory=dict)

    def is_traced(self, func: ast.AST) -> bool:
        return func in self.traced


def iter_defs(tree: ast.Module):
    """Yield ``(node, qualname, class_name)`` for every function/method in
    a module — the def index the interprocedural lock-graph pass
    (``lint/lockgraph.py``) resolves call sites against, mirroring how
    the traced-fn propagation above indexes same-module defs. Lambdas
    are skipped (they cannot be called by name across functions);
    ``qualname`` is dotted through enclosing classes and functions."""
    def walk(node, prefix: str, cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield child, qual, cls
                yield from walk(child, qual + ".", cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.", child.name)
            elif not isinstance(child, ast.Lambda):
                yield from walk(child, prefix, cls)

    yield from walk(tree, "", None)


def _int_tuple(node: ast.expr | None) -> tuple[int, ...]:
    """Literal int / tuple-or-list of ints -> tuple; anything else -> ()."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return ()
        return tuple(out)
    return ()


def build_context(path: str, source: str) -> ModuleContext:
    tree = ast.parse(source, filename=path)
    ctx = ModuleContext(path=path, source=source, tree=tree)

    # ---- index functions: defs AND `name = lambda`/`name = def` aliases,
    # keyed by (scope id, name); record lexical scope chains ----------------
    defs_by_name: dict[tuple[int, str], ast.AST] = {}
    scope_chain: dict[ast.AST, tuple[int, ...]] = {}  # innermost first

    def index(node: ast.AST, parent_func: ast.AST | None,
              chain: tuple[int, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FunctionNode):
                ctx.parents[child] = parent_func
                scope_chain[child] = chain
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs_by_name[(chain[0], child.name)] = child
                index(child, child, (id(child), *chain))
            else:
                if (isinstance(child, ast.Assign) and len(child.targets) == 1
                        and isinstance(child.targets[0], ast.Name)
                        and isinstance(child.value, ast.Lambda)):
                    defs_by_name[(chain[0], child.targets[0].id)] = child.value
                index(child, parent_func, chain)

    index(tree, None, (id(tree),))

    def resolve(chain: tuple[int, ...], expr: ast.expr) -> ast.AST | None:
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Name):
            for scope in chain:
                hit = defs_by_name.get((scope, expr.id))
                if hit is not None:
                    return hit
        return None

    # ---- find traced roots ----------------------------------------------
    roots: set[ast.AST] = set()

    def scan_for_roots(node: ast.AST, chain: tuple[int, ...]):
        for child in ast.iter_child_nodes(node):
            child_chain = ((id(child), *chain)
                           if isinstance(child, FunctionNode) else chain)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(is_trace_wrapper_expr(d) for d in child.decorator_list):
                    roots.add(child)
            if isinstance(child, ast.Call):
                kind = call_kind(child)
                if kind == "wrapper" and child.args:
                    f = resolve(chain, child.args[0])
                    if f is not None:
                        roots.add(f)
                elif kind == "hof":
                    # every function-valued positional arg is a traced body
                    for a in child.args:
                        f = resolve(chain, a)
                        if f is not None:
                            roots.add(f)
            scan_for_roots(child, child_chain)

    scan_for_roots(tree, (id(tree),))

    # ---- record module-scope jit bindings -------------------------------
    for stmt in tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
            continue
        target = stmt.targets[0]
        if not isinstance(target, ast.Name) or not isinstance(stmt.value, ast.Call):
            continue
        if call_kind(stmt.value) != "wrapper":
            continue
        kwargs = {k.arg: k.value for k in stmt.value.keywords if k.arg}
        ctx.jit_bindings[target.id] = JitBinding(
            name=target.id, line=stmt.lineno,
            donate_argnums=_int_tuple(kwargs.get("donate_argnums")),
            static_argnums=_int_tuple(kwargs.get("static_argnums")),
        )

    # ---- same-module call graph for transitive taint --------------------
    # F -> {G}: F's body mentions G by a name that resolves through F's
    # lexical scope chain (a call or a bare reference — passing update_step
    # into a helper taints it just as calling it does)
    calls: dict[ast.AST, set[ast.AST]] = {}
    for func, chain in scope_chain.items():
        out: set[ast.AST] = set()
        own_chain = (id(func), *chain)
        for node in ast.walk(func):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                target = resolve(own_chain, node)
                if target is not None and target is not func:
                    out.add(target)
        calls[func] = out

    # ---- propagate: lexical nesting + call edges, to fixpoint -----------
    def mark(node: ast.AST):
        stack = [node]
        while stack:
            cur = stack.pop()
            if cur in ctx.traced:
                continue
            ctx.traced.add(cur)
            for child in ast.walk(cur):
                if isinstance(child, FunctionNode) and child not in ctx.traced:
                    stack.append(child)
            for callee in calls.get(cur, ()):
                if callee not in ctx.traced:
                    stack.append(callee)

    for root in roots:
        mark(root)
    return ctx
