"""Interprocedural wire-protocol analysis: the frame registry mirror.

Five hand-rolled wire planes (ingest 0xD4F6/0xD4F8, weights
0xD4F7/0xD4FC, updates 0xD4AB, serving 0xD4E2/0xD4E3, plus the 0xD4FA
generation greeting and the D4RS snapshot sidecar) depend on encoder
and decoder agreeing byte-for-byte. The declared truth lives in
``d4pg_tpu.core.wire``; this module is the whole-program complement,
families 11-14 (the same shape as ``lockgraph`` for locks): it
independently *discovers* the protocol surface from the AST —
pack/unpack call sites, magic constants and the import chains that
carry them, flag-byte bit constants, recv-rooted decode paths — and
checks the discovery against the declaration:

- ``wire-magic-registry`` — a 0xD4xx literal or flag-bit constant
  packed into (or compared against) a frame that is absent from the
  declared table, or privately re-declared outside ``core/wire.py``.
  Seed-derivation uses (``SeedSequence(spawn_key=(0xD4E4,…))``,
  ``default_rng(seed ^ 0xD4E3)``) are exempt: those literals never
  reach a socket.
- ``codec-asymmetry`` — every pack/unpack format at a use site must be
  a contiguous field segment of a declared header/extension format of
  the magic (or plane) it serves; argument/target counts must match
  the format's field count; a ``*_SIZE``/``*_LEN`` constant shadowing a
  Struct must equal its ``calcsize``; a magic that is packed somewhere
  must be unpacked (or magic-checked) somewhere.
- ``unchecked-frame`` — a socket-facing decode (recv → ``unpack`` /
  ``np.load`` / ``np.frombuffer``) reachable without ``struct.error``/
  ``ValueError`` containment, or — where the table declares a CRC —
  without a crc32 check before payload use. This is the hostile-frame
  class the PR-4 review patched by hand; the pass keeps it closed.
- ``flag-bit-collision`` — two extensions claiming the same bit of the
  same plane's flag byte.

``python -m d4pg_tpu.lint --wire`` prints the discovered registry
(magics, owning planes, pack/unpack witnesses, flag-bit map) as the
protocol review artifact; exit 1 on any finding.
"""

from __future__ import annotations

import ast
import os
import re
import struct
from dataclasses import dataclass, field

from d4pg_tpu.lint.context import (
    FunctionNode, ModuleContext, dotted_name, iter_defs, last_part,
)
from d4pg_tpu.lint.findings import Finding

WIRE_RULES = ("wire-magic-registry", "codec-asymmetry", "unchecked-frame",
              "flag-bit-collision")

# Static mirror of ``core.wire.REGISTRY``. Mirrored, not imported: the
# lint package is stdlib-only by contract (``d4pg_tpu.core``'s package
# __init__ pulls jax). tests/test_lint_clean.py pins the two tables
# equal, so they cannot drift. Rows: (name, plane, magic, header format,
# crc discipline, ((bit, meaning), ...), (extension formats, ...)).
_DECLARED = (
    ("ingest-v1", "ingest", 0xD4F6, "!II", "none", (), ()),
    ("ingest-v2", "ingest", 0xD4F8, "!II", "none",
     ((0x01, "count"), (0x02, "trace"), (0x04, "generation")),
     ("!BB", "!Qd", "!I", "!B", "!BB")),
    ("gen-greeting", "ingest", 0xD4FA, "!HI", "none", (), ()),
    ("weights-v1-req", "weights", 0xD4F7, "!Iq", "none", (), ()),
    ("weights-v1-resp", "weights", 0xD4F7, "!II", "none", (), ()),
    ("weights-v2-req", "weights", 0xD4FC, "!IqIBB", "none",
     ((0x01, "delta"),), ()),
    ("weights-v2-resp", "weights", 0xD4FC, "!IBII", "crc32-payload", (), ()),
    ("update-req", "updates", 0xD4AB, "!IIIIqqqdBII", "crc32-payload",
     (), ()),
    ("update-ack", "updates", 0xD4AB, "!IBqqdB", "none", (), ()),
    ("serve-request", "serving", 0xD4E2, "!II", "crc32-payload",
     ((0x01, "trace"),), ("!BIHHI", "!Qd")),
    ("serve-response", "serving", 0xD4E3, "!II", "crc32-payload",
     (), ("!BIIIHHI",)),
    ("sidecar", "recovery", b"D4RS", "!4sBI", "crc32-payload", (), ()),
)

_DECLARED_MAGICS = {row[2] for row in _DECLARED}
_MAGIC_PLANE = {row[2]: row[1] for row in _DECLARED}
_MAGIC_NAMES: dict = {}
for _row in _DECLARED:
    _MAGIC_NAMES.setdefault(_row[2], _row[0].rsplit("-", 1)[0])
_CRC_MAGICS = {row[2] for row in _DECLARED if row[4] != "none"}

_MAGIC_FMTS: dict = {}
_PLANE_FMTS: dict = {}
_PLANE_FLAGS: dict = {}
for _row in _DECLARED:
    _MAGIC_FMTS.setdefault(_row[2], set()).update((_row[3],) + _row[6])
    _PLANE_FMTS.setdefault(_row[1], set()).update((_row[3],) + _row[6])
    for _bit, _meaning in _row[5]:
        _PLANE_FLAGS.setdefault(_row[1], {})[_bit] = _meaning

# Calls whose argument literals are seed derivations, not wire magics.
_SEED_CALLS = {"SeedSequence", "default_rng", "PRNGKey", "fold_in",
               "Philox", "seed", "spawn"}

# Flag-bit constant shapes: F_COUNT, _F_TRACE, FLAG_TRACE, _FLAG_DELTA,
# WFLAG_DELTA, SFLAG_TRACE. Value must be a single bit of one byte.
_FLAG_NAME = re.compile(r"^_{0,2}(?:[A-Z]{0,3}FLAGS?_|F_)[A-Z0-9_]+$")
_SIZE_NAME = re.compile(r"^(?P<stem>.+?)(?:_SIZE|_LEN|_BYTES)$")

# Same spirit as lockgraph._NO_RESOLVE: method names too generic to
# resolve by bare name across the program, plus struct/socket/numpy
# methods that are codec events rather than call-graph edges.
_NO_RESOLVE = {"append", "appendleft", "extend", "popleft", "discard",
               "items", "keys", "values", "get", "setdefault", "join",
               "start", "put", "clear", "copy", "close", "set", "is_set",
               "add", "update", "remove", "insert", "count", "index",
               "sort", "wait", "pack", "unpack", "unpack_from", "calcsize",
               "load", "frombuffer", "crc32", "sendall", "send", "recv",
               "connect", "bind", "listen", "accept", "encode", "decode",
               "read", "write", "acquire", "release", "notify",
               "notify_all", "wait_for", "info", "debug", "warning",
               "error", "format", "split", "strip", "lower", "upper"}
_MAX_CANDIDATES = 12

_VALUE_CATCHES = {"ValueError", "Exception", "BaseException"}
_STRUCT_CATCHES = {"struct.error", "Exception", "BaseException"}

_MAX_DEPTH = 8


def _is_magic(value) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return 0xD400 <= value <= 0xD4FF
    return (isinstance(value, bytes) and len(value) == 4
            and value.startswith(b"D4"))


def _magic_str(value) -> str:
    return f"0x{value:04X}" if isinstance(value, int) else value.decode(
        "ascii", "replace")


def _tokens(fmt: str) -> list[str]:
    """Field tokens of a struct format: ``"!IqBB"`` -> [I, q, B, B];
    ``"4s"`` stays one field; repeat counts expand."""
    body = fmt[1:] if fmt[:1] in "@=<>!" else fmt
    toks: list[str] = []
    for cnt, code in re.findall(r"(\d*)([a-zA-Z?])", body):
        if code in "sp":
            toks.append((cnt or "1") + code)
        elif code == "x":
            continue
        else:
            toks.extend([code] * int(cnt or "1"))
    return toks


def _is_segment(small: list[str], big: list[str]) -> bool:
    n = len(small)
    return n > 0 and any(big[i:i + n] == small
                         for i in range(len(big) - n + 1))


# ---------------------------------------------------------------------------
# discovery data model
# ---------------------------------------------------------------------------


@dataclass
class _Pack:
    fmt: str | None
    nargs: int | None  # None when *args present
    magics: tuple  # magic values resolved among the packed args
    line: int
    col: int
    path: str
    func: str


@dataclass
class _Unpack:
    fmt: str | None
    ntargets: int | None  # tuple-target arity, when statically visible
    buf: str | None  # buffer variable name, when it is a plain Name
    buf_literal: bool  # buffer is a bytes literal
    exact: bool  # buffer provably read with exactly calcsize(fmt) bytes
    caught: frozenset  # exception names of enclosing try blocks
    line: int
    col: int
    path: str
    func: str


@dataclass
class _Load:
    kind: str  # "np.load" | "np.frombuffer"
    buf: str | None
    caught: frozenset
    line: int
    col: int
    path: str
    func: str


@dataclass
class _WCall:
    callee: str
    recv_self: bool
    caught: frozenset
    line: int


@dataclass
class _Fn:
    key: str
    name: str
    cls: str | None
    path: str
    mod: "_Mod"
    magic_refs: set = field(default_factory=set)
    packs: list = field(default_factory=list)
    unpacks: list = field(default_factory=list)
    loads: list = field(default_factory=list)
    compares: list = field(default_factory=list)  # (magic, line)
    crc_lines: list = field(default_factory=list)
    calls: list = field(default_factory=list)
    len_checked: set = field(default_factory=set)
    recv_call: bool = False


@dataclass
class _Mod:
    path: str
    stem: str
    tree: ast.AST
    discover: bool  # sites/findings collected (False for wire.py, lint/)
    consts: dict = field(default_factory=dict)  # name -> (value, line, col)
    structs: dict = field(default_factory=dict)  # name -> (fmt, line)
    imports: dict = field(default_factory=dict)  # name -> (stem, orig)
    mod_aliases: dict = field(default_factory=dict)  # local -> module stem
    size_consts: dict = field(default_factory=dict)  # name -> (value, line, col)
    flag_consts: dict = field(default_factory=dict)  # name -> (value, line, col)
    fns: list = field(default_factory=list)


@dataclass
class _Prog:
    mods: list = field(default_factory=list)
    by_stem: dict = field(default_factory=dict)
    fns: list = field(default_factory=list)


def _is_declaration_module(path: str) -> bool:
    return path.replace(os.sep, "/").endswith("d4pg_tpu/core/wire.py")


def _is_lint_module(path: str) -> bool:
    return (os.sep + "lint" + os.sep) in path or "/lint/" in path


def _collect_env(ctx: ModuleContext) -> _Mod:
    stem = os.path.splitext(os.path.basename(ctx.path))[0]
    mod = _Mod(path=ctx.path, stem=stem, tree=ctx.tree,
               discover=not (_is_declaration_module(ctx.path)
                             or _is_lint_module(ctx.path)))
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, (int, bytes)) \
                    and not isinstance(node.value.value, bool):
                val = node.value.value
                mod.consts[name] = (val, node.lineno, node.col_offset)
                if isinstance(val, int) and _SIZE_NAME.match(name):
                    mod.size_consts[name] = (val, node.lineno,
                                             node.col_offset)
                if (isinstance(val, int) and _FLAG_NAME.match(name)
                        and 0 < val <= 0x80 and val & (val - 1) == 0):
                    mod.flag_consts[name] = (val, node.lineno,
                                             node.col_offset)
            elif isinstance(node.value, ast.Call):
                fname = dotted_name(node.value.func)
                if (fname and last_part(fname) == "Struct"
                        and node.value.args
                        and isinstance(node.value.args[0], ast.Constant)
                        and isinstance(node.value.args[0].value, str)):
                    mod.structs[name] = (node.value.args[0].value,
                                         node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.module:
            src_stem = node.module.rsplit(".", 1)[-1]
            for alias in node.names:
                local = alias.asname or alias.name
                mod.imports[local] = (src_stem, alias.name)
                # ``from pkg import submodule`` makes the name a module
                # alias too; harmless when it was actually a symbol.
                mod.mod_aliases.setdefault(local, alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".", 1)[0]
                mod.mod_aliases[local] = alias.name.rsplit(".", 1)[-1]
    return mod


def _resolve_const(prog: _Prog, mod: _Mod | None, name: str,
                   depth: int = 0):
    if mod is None or depth > 4:
        return None
    if name in mod.consts:
        return mod.consts[name][0]
    if name in mod.imports:
        src_stem, orig = mod.imports[name]
        return _resolve_const(prog, prog.by_stem.get(src_stem), orig,
                              depth + 1)
    return None


def _resolve_fmt(prog: _Prog, mod: _Mod | None, name: str,
                 depth: int = 0) -> str | None:
    if mod is None or depth > 4:
        return None
    if name in mod.structs:
        return mod.structs[name][0]
    if name in mod.imports:
        src_stem, orig = mod.imports[name]
        return _resolve_fmt(prog, prog.by_stem.get(src_stem), orig,
                            depth + 1)
    return None


def _fmt_of_dotted(prog: _Prog, mod: _Mod, dotted: str) -> str | None:
    parts = dotted.split(".")
    if len(parts) == 1:
        return _resolve_fmt(prog, mod, parts[0])
    if len(parts) == 2 and parts[0] in mod.mod_aliases:
        target = prog.by_stem.get(mod.mod_aliases[parts[0]])
        if target is not None:
            return _resolve_fmt(prog, target, parts[1])
    return None


def _flag_origin(prog: _Prog, mod: _Mod, name: str,
                 depth: int = 0) -> tuple[str, str] | None:
    """(module stem, const name) where a flag constant is actually
    defined — import aliases chase back to the declaring module."""
    if mod is None or depth > 4:
        return None
    if name in mod.consts:
        return (mod.stem, name)
    if name in mod.imports:
        src_stem, orig = mod.imports[name]
        target = prog.by_stem.get(src_stem)
        if target is not None:
            return _flag_origin(prog, target, orig, depth + 1)
        return (src_stem, orig)
    return None


def _flag_base(name: str) -> str:
    base = re.sub(r"^(?:[a-z]{0,3}flags?_|f_)", "", name.lower().lstrip("_"))
    return base


def _handler_names(handlers) -> frozenset:
    names: set[str] = set()
    for h in handlers:
        if h.type is None:
            names.add("BaseException")
        elif isinstance(h.type, ast.Tuple):
            for elt in h.type.elts:
                d = dotted_name(elt)
                if d:
                    names.add(d)
        else:
            d = dotted_name(h.type)
            if d:
                names.add(d)
    return frozenset(names)


def _exempt_ids(tree: ast.AST) -> set[int]:
    """ids of Constant nodes inside seed-derivation calls."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname and last_part(fname) in _SEED_CALLS:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant):
                        out.add(id(sub))
    return out


class _FnWalker:
    """One function body -> pack/unpack/load/call/crc/magic facts, with
    enclosing-try exception names tracked per site."""

    def __init__(self, fn: _Fn, mod: _Mod, prog: _Prog, exempt: set[int]):
        self.fn = fn
        self.mod = mod
        self.prog = prog
        self.exempt = exempt
        self.recv_bufs: list = []  # (name, line, size value|None)
        self._site_meta: dict[int, int] = {}  # id(call node) -> ntargets

    # -- constant / format / size resolution at a use site ---------------

    def _const_of(self, node):
        if isinstance(node, ast.Constant):
            if id(node) in self.exempt:
                return None
            v = node.value
            return v if isinstance(v, (int, bytes)) \
                and not isinstance(v, bool) else None
        if isinstance(node, ast.Name):
            return _resolve_const(self.prog, self.mod, node.id)
        if isinstance(node, ast.Attribute):
            d = dotted_name(node)
            if d:
                parts = d.split(".")
                if len(parts) == 2 and parts[0] in self.mod.mod_aliases:
                    target = self.prog.by_stem.get(
                        self.mod.mod_aliases[parts[0]])
                    if target is not None:
                        return _resolve_const(self.prog, target, parts[1])
        return None

    def _size_of(self, node):
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.Name):
            v = _resolve_const(self.prog, self.mod, node.id)
            return v if isinstance(v, int) else None
        if isinstance(node, ast.Attribute):
            d = dotted_name(node)
            if d and d.endswith(".size"):
                fmt = _fmt_of_dotted(self.prog, self.mod, d[:-len(".size")])
                if fmt is not None:
                    try:
                        return struct.calcsize(fmt)
                    except struct.error:
                        return None
            return None
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Add, ast.Sub, ast.Mult)):
            left = self._size_of(node.left)
            right = self._size_of(node.right)
            if left is None or right is None:
                return None
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            return left * right
        return None

    # -- statement driver -------------------------------------------------

    def walk(self, stmts, caught: frozenset = frozenset()) -> None:
        for s in stmts:
            self._stmt(s, caught)

    def _stmt(self, s, caught: frozenset) -> None:
        if isinstance(s, FunctionNode + (ast.ClassDef,)):
            return  # nested defs are separate _Fn entries
        if isinstance(s, ast.Try) or (hasattr(ast, "TryStar")
                                      and isinstance(s, ast.TryStar)):
            names = _handler_names(s.handlers)
            self.walk(s.body, caught | names)
            for h in s.handlers:
                if h.type is not None:
                    self._expr(h.type, caught)
                self.walk(h.body, caught)
            self.walk(s.orelse, caught)
            self.walk(s.finalbody, caught)
            return
        if isinstance(s, ast.Assign):
            self._assign_meta(s)
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.stmt):
                self._stmt(child, caught)
            elif not isinstance(child, (ast.expr_context, ast.operator,
                                        ast.boolop, ast.unaryop,
                                        ast.cmpop)):
                self._expr(child, caught)

    def _assign_meta(self, s: ast.Assign) -> None:
        if not isinstance(s.value, ast.Call):
            return
        fname = dotted_name(s.value.func)
        callee = last_part(fname) if fname else getattr(
            s.value.func, "attr", None)
        if callee is None:
            return
        if "recv" in callee and len(s.targets) == 1 \
                and isinstance(s.targets[0], ast.Name):
            args = s.value.args
            if callee == "recv":
                size_node = args[0] if args else None
            else:
                size_node = args[1] if len(args) >= 2 else (
                    args[0] if args else None)
            size = self._size_of(size_node) if size_node is not None \
                else None
            self.recv_bufs.append((s.targets[0].id, s.lineno, size))
        if callee in ("unpack", "unpack_from") and len(s.targets) == 1:
            tgt = s.targets[0]
            if isinstance(tgt, ast.Tuple) and not any(
                    isinstance(e, ast.Starred) for e in tgt.elts):
                self._site_meta[id(s.value)] = len(tgt.elts)

    # -- expression visitor -----------------------------------------------

    def _expr(self, node, caught: frozenset) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self._call(n, caught)
            elif isinstance(n, ast.Compare):
                self._compare(n)
            elif isinstance(n, (ast.Name, ast.Attribute)):
                v = self._const_of(n)
                if v is not None and _is_magic(v):
                    self.fn.magic_refs.add(v)

    def _compare(self, n: ast.Compare) -> None:
        for comp in [n.left] + list(n.comparators):
            elts = comp.elts if isinstance(comp, ast.Tuple) else [comp]
            for elt in elts:
                v = self._const_of(elt)
                if v is not None and _is_magic(v):
                    self.fn.compares.append((v, elt.lineno))
                    self.fn.magic_refs.add(v)

    def _buf_facts(self, buf_node, fmt: str | None):
        """(name, is_literal, exact) for an unpack buffer argument."""
        name = buf_node.id if isinstance(buf_node, ast.Name) else None
        literal = isinstance(buf_node, ast.Constant) and isinstance(
            getattr(buf_node, "value", None), bytes)
        exact = False
        if name is not None and fmt is not None:
            try:
                want = struct.calcsize(fmt)
            except struct.error:
                want = None
            got = None
            for bname, bline, bsize in self.recv_bufs:
                if bname == name and bline <= buf_node.lineno:
                    got = bsize  # latest assignment before the site wins
            if want is not None and got is not None and got == want:
                exact = True
        return name, literal, exact

    def _call(self, n: ast.Call, caught: frozenset) -> None:
        fname = dotted_name(n.func)
        callee = last_part(fname) if fname else getattr(
            n.func, "attr", None)
        if callee is None:
            return
        prefix = fname.rsplit(".", 1)[0] if fname and "." in fname else None

        if "recv" in callee:
            self.fn.recv_call = True

        if callee == "crc32":
            self.fn.crc_lines.append(n.lineno)

        if callee == "pack":
            if prefix == "struct" or (
                    prefix and self.mod.mod_aliases.get(prefix) == "struct"):
                fmt = (n.args[0].value
                       if n.args and isinstance(n.args[0], ast.Constant)
                       and isinstance(n.args[0].value, str) else None)
                payload_args = n.args[1:]
            else:
                fmt = _fmt_of_dotted(self.prog, self.mod, prefix) \
                    if prefix else None
                payload_args = n.args
            starred = any(isinstance(a, ast.Starred) for a in payload_args)
            magics = []
            for a in payload_args:
                v = self._const_of(a)
                if v is not None and _is_magic(v):
                    magics.append(v)
                    self.fn.magic_refs.add(v)
            self.fn.packs.append(_Pack(
                fmt=fmt, nargs=None if starred else len(payload_args),
                magics=tuple(magics), line=n.lineno, col=n.col_offset,
                path=self.fn.path, func=self.fn.key))
            return

        if callee in ("unpack", "unpack_from"):
            if prefix == "struct" or (
                    prefix and self.mod.mod_aliases.get(prefix) == "struct"):
                fmt = (n.args[0].value
                       if n.args and isinstance(n.args[0], ast.Constant)
                       and isinstance(n.args[0].value, str) else None)
                buf_node = n.args[1] if len(n.args) >= 2 else None
            else:
                fmt = _fmt_of_dotted(self.prog, self.mod, prefix) \
                    if prefix else None
                buf_node = n.args[0] if n.args else None
            name, literal, exact = (None, False, False)
            if buf_node is not None:
                name, literal, exact = self._buf_facts(buf_node, fmt)
            self.fn.unpacks.append(_Unpack(
                fmt=fmt, ntargets=self._site_meta.get(id(n)), buf=name,
                buf_literal=literal, exact=exact, caught=caught,
                line=n.lineno, col=n.col_offset, path=self.fn.path,
                func=self.fn.key))
            return

        if callee == "load" and prefix in ("np", "numpy"):
            self.fn.loads.append(_Load(
                kind="np.load", buf=None, caught=caught, line=n.lineno,
                col=n.col_offset, path=self.fn.path, func=self.fn.key))
            return

        if callee == "frombuffer" and prefix in ("np", "numpy"):
            buf = n.args[0] if n.args else None
            if isinstance(buf, ast.Name):
                self.fn.loads.append(_Load(
                    kind="np.frombuffer", buf=buf.id, caught=caught,
                    line=n.lineno, col=n.col_offset, path=self.fn.path,
                    func=self.fn.key))
            return

        if callee == "len" and n.args and isinstance(n.args[0], ast.Name):
            self.fn.len_checked.add(n.args[0].id)
            return

        if callee in _NO_RESOLVE or callee.startswith("__"):
            return
        recv_self = bool(fname) and fname.startswith("self.") \
            and fname.count(".") == 1
        self.fn.calls.append(_WCall(callee=callee, recv_self=recv_self,
                                    caught=caught, line=n.lineno))


# ---------------------------------------------------------------------------
# program build + call resolution (lockgraph's shape)
# ---------------------------------------------------------------------------


def _build_program(ctxs: list[ModuleContext]) -> _Prog:
    prog = _Prog()
    for ctx in ctxs:
        prog.mods.append(_collect_env(ctx))
    for mod in prog.mods:
        # first module wins a stem; ambiguous stems (``__init__``) are
        # never import targets in practice
        prog.by_stem.setdefault(mod.stem, mod)
    for mod in prog.mods:
        if not mod.discover:
            continue
        exempt = _exempt_ids(mod.tree)
        for node, qual, cls in iter_defs(mod.tree):
            fn = _Fn(key=f"{mod.path}::{qual}", name=node.name, cls=cls,
                     path=mod.path, mod=mod)
            _FnWalker(fn, mod, prog, exempt).walk(node.body)
            mod.fns.append(fn)
        mod_stmts = [s for s in mod.tree.body
                     if not isinstance(s, FunctionNode + (ast.ClassDef,))]
        if mod_stmts:
            fn = _Fn(key=f"{mod.path}::<module>", name="<module>",
                     cls=None, path=mod.path, mod=mod)
            _FnWalker(fn, mod, prog, exempt).walk(mod_stmts)
            mod.fns.append(fn)
        prog.fns.extend(mod.fns)
    return prog


def _resolve_call(call: _WCall, caller: _Fn,
                  by_name: dict, by_class: dict) -> list:
    if call.recv_self and caller.cls is not None:
        own = by_class.get((caller.cls, call.callee))
        if own:
            return own
    cands = [f for f in by_name.get(call.callee, ())
             if not (call.recv_self is False and caller.cls is not None
                     and f.cls == caller.cls and f.path == caller.path)]
    if len(cands) > _MAX_CANDIDATES:
        return []
    return cands


# ---------------------------------------------------------------------------
# graph + analysis
# ---------------------------------------------------------------------------


@dataclass
class WireGraph:
    functions: int = 0
    modules: int = 0
    # magic value -> {"plane", "name", "packs": [wit], "unpacks": [wit]}
    magics: dict = field(default_factory=dict)
    # plane -> {bit: meaning}
    flags: dict = field(default_factory=dict)
    findings: list = field(default_factory=list)


def _short(key: str) -> str:
    path, _, qual = key.partition("::")
    return f"{os.path.basename(path)}::{qual}"


def _witness(path: str, line: int, func: str) -> str:
    return f"{path}:{line} ({func.partition('::')[2]})"


def _module_planes(mod: _Mod) -> set:
    planes = set()
    for name, (value, _l, _c) in mod.consts.items():
        if _is_magic(value) and value in _MAGIC_PLANE:
            planes.add(_MAGIC_PLANE[value])
    for fn in mod.fns:
        for v in fn.magic_refs:
            if v in _MAGIC_PLANE:
                planes.add(_MAGIC_PLANE[v])
    return planes


def analyze(ctxs: list[ModuleContext],
            rules: list[str] | None = None) -> WireGraph:
    """Run the whole-program wire pass; ``rules`` filters which families
    emit findings (all families always contribute to the printed
    registry)."""
    active = set(rules) if rules is not None else set(WIRE_RULES)
    prog = _build_program(ctxs)
    graph = WireGraph(functions=len(prog.fns),
                      modules=sum(1 for m in prog.mods if m.discover))
    out: list[Finding] = []

    by_name: dict = {}
    by_class: dict = {}
    for f in prog.fns:
        by_name.setdefault(f.name, []).append(f)
        by_class.setdefault((f.cls, f.name), []).append(f)
    resolved = {f.key: [(c, _resolve_call(c, f, by_name, by_class))
                        for c in f.calls] for f in prog.fns}

    _discover_registry(prog, resolved, graph)
    _check_magic_registry(prog, graph, out)
    _check_codec(prog, graph, out)
    _check_flags(prog, graph, out)
    _check_unchecked(prog, resolved, out)

    graph.findings = sorted(
        (f for f in out if f.rule in active),
        key=lambda f: (f.file, f.line, f.col, f.rule))
    return graph


def _reach(fn: _Fn, resolved: dict, depth: int = 3) -> list:
    """Functions reachable from ``fn`` within ``depth`` calls (incl. fn)."""
    seen = {fn.key}
    frontier, out = [fn], [fn]
    for _ in range(depth):
        nxt = []
        for f in frontier:
            for _call, cands in resolved[f.key]:
                for g in cands:
                    if g.key not in seen:
                        seen.add(g.key)
                        nxt.append(g)
                        out.append(g)
        frontier = nxt
    return out


def _discover_registry(prog: _Prog, resolved: dict,
                       graph: WireGraph) -> None:
    """The printed surface: per magic, where it is packed and where it
    is unpacked/checked. Attribution: a pack carrying the magic as an
    argument is direct; otherwise every pack/unpack/compare site within
    a short call radius of a function that references the magic counts
    as a witness for it."""

    def entry(m):
        return graph.magics.setdefault(m, {
            "plane": _MAGIC_PLANE.get(m),
            "name": _MAGIC_NAMES.get(m),
            "packs": [], "unpacks": []})

    def add(lst, wit):
        if wit not in lst and len(lst) < 6:
            lst.append(wit)

    for fn in prog.fns:
        for p in fn.packs:
            for m in p.magics:
                add(entry(m)["packs"], _witness(p.path, p.line, p.func))
        for m, line in fn.compares:
            add(entry(m)["unpacks"], _witness(fn.path, line, fn.key))

    for fn in prog.fns:
        if not fn.magic_refs:
            continue
        nearby = _reach(fn, resolved)
        for m in fn.magic_refs:
            e = entry(m)
            for g in nearby:
                for p in g.packs:
                    if not p.magics:
                        add(e["packs"], _witness(p.path, p.line, p.func))
                for u in g.unpacks:
                    add(e["unpacks"], _witness(u.path, u.line, u.func))

    for plane, bits in _PLANE_FLAGS.items():
        graph.flags[plane] = dict(bits)


def _check_magic_registry(prog: _Prog, graph: WireGraph,
                          out: list) -> None:
    used: set = set()  # magic values reaching a pack/compare anywhere
    for fn in prog.fns:
        for p in fn.packs:
            used.update(p.magics)
        for m, _line in fn.compares:
            used.add(m)

    for fn in prog.fns:
        for p in fn.packs:
            for m in p.magics:
                if m not in _DECLARED_MAGICS:
                    out.append(Finding(
                        file=fn.path, line=p.line, col=p.col,
                        rule="wire-magic-registry",
                        message=(
                            f"magic {_magic_str(m)} is packed into a frame "
                            f"but is absent from the declared registry "
                            f"(d4pg_tpu/core/wire.py)")))
        for m, line in fn.compares:
            if m not in _DECLARED_MAGICS:
                out.append(Finding(
                    file=fn.path, line=line, col=0,
                    rule="wire-magic-registry",
                    message=(
                        f"magic {_magic_str(m)} is checked on a frame "
                        f"but is absent from the declared registry "
                        f"(d4pg_tpu/core/wire.py)")))

    for mod in prog.mods:
        if not mod.discover:
            continue
        for name, (value, line, col) in mod.consts.items():
            if _is_magic(value) and value in _DECLARED_MAGICS \
                    and value in used:
                out.append(Finding(
                    file=mod.path, line=line, col=col,
                    rule="wire-magic-registry",
                    message=(
                        f"{name} re-declares wire magic "
                        f"{_magic_str(value)} (plane "
                        f"{_MAGIC_PLANE[value]}) privately; import it "
                        f"from d4pg_tpu.core.wire")))


def _check_codec(prog: _Prog, graph: WireGraph, out: list) -> None:
    for mod in prog.mods:
        if not mod.discover:
            continue
        planes = _module_planes(mod)

        # header-length constant vs calcsize of the sibling Struct
        for name, (value, line, col) in mod.size_consts.items():
            stem = _SIZE_NAME.match(name).group("stem")
            if stem in mod.structs:
                fmt = mod.structs[stem][0]
                try:
                    want = struct.calcsize(fmt)
                except struct.error:
                    continue
                if want != value:
                    out.append(Finding(
                        file=mod.path, line=line, col=col,
                        rule="codec-asymmetry",
                        message=(
                            f"{name} = {value} disagrees with "
                            f"calcsize({fmt!r}) = {want} of {stem}")))

        for fn in mod.fns:
            declared_refs = {m for m in fn.magic_refs
                             if m in _DECLARED_MAGICS}
            if declared_refs:
                allowed = set()
                for m in declared_refs:
                    allowed |= _MAGIC_FMTS[m]
            elif len(planes) >= 1:
                allowed = set()
                for p in planes:
                    allowed |= _PLANE_FMTS[p]
            else:
                allowed = None  # no wire context: not a codec site

            for site in fn.packs + fn.unpacks:
                if site.fmt is None:
                    continue
                toks = _tokens(site.fmt)
                if allowed is not None and not any(
                        _is_segment(toks, _tokens(a)) for a in allowed):
                    kind = "pack" if isinstance(site, _Pack) else "unpack"
                    out.append(Finding(
                        file=mod.path, line=site.line, col=site.col,
                        rule="codec-asymmetry",
                        message=(
                            f"{kind} format {site.fmt!r} is not a field "
                            f"segment of any declared header/extension "
                            f"format for its magic/plane "
                            f"({', '.join(sorted(allowed))})")))
                    continue
                if isinstance(site, _Pack) and site.nargs is not None \
                        and site.nargs != len(toks):
                    out.append(Finding(
                        file=mod.path, line=site.line, col=site.col,
                        rule="codec-asymmetry",
                        message=(
                            f"pack format {site.fmt!r} declares "
                            f"{len(toks)} field(s) but {site.nargs} "
                            f"argument(s) are packed")))
                if isinstance(site, _Unpack) and site.ntargets is not None \
                        and site.ntargets != len(toks):
                    out.append(Finding(
                        file=mod.path, line=site.line, col=site.col,
                        rule="codec-asymmetry",
                        message=(
                            f"unpack format {site.fmt!r} yields "
                            f"{len(toks)} field(s) but {site.ntargets} "
                            f"target(s) are bound")))

    # one-sided codec: a declared magic packed somewhere must be
    # unpacked or magic-checked somewhere in the program
    for m, e in graph.magics.items():
        if m in _DECLARED_MAGICS and e["packs"] and not e["unpacks"]:
            path, _, rest = e["packs"][0].partition(":")
            line = int(rest.split(" ")[0])
            out.append(Finding(
                file=path, line=line, col=0, rule="codec-asymmetry",
                message=(
                    f"magic {_magic_str(m)} is packed but never "
                    f"unpacked or checked anywhere in the program "
                    f"(one-sided codec)")))


def _check_flags(prog: _Prog, graph: WireGraph, out: list) -> None:
    # plane -> bit -> list of (base meaning, origin, path, line, col, name)
    claims: dict = {}
    for plane, bits in _PLANE_FLAGS.items():
        for bit, meaning in bits.items():
            claims.setdefault(plane, {}).setdefault(bit, []).append(
                (meaning, ("registry", meaning), None, 0, 0, "registry"))

    for mod in prog.mods:
        if not mod.discover:
            continue
        planes = _module_planes(mod)
        if len(planes) != 1:
            continue  # no unambiguous flag-byte namespace
        plane = next(iter(planes))
        declared_bits = _PLANE_FLAGS.get(plane, {})
        for name, (value, line, col) in mod.flag_consts.items():
            origin = _flag_origin(prog, mod, name) or (mod.stem, name)
            if origin[0] == "wire":
                continue  # the declaration itself, via import
            base = _flag_base(name)
            if value in declared_bits:
                meaning = declared_bits[value]
                if base in meaning or meaning in base:
                    continue  # consistent local mirror of a declared bit
                out.append(Finding(
                    file=mod.path, line=line, col=col,
                    rule="flag-bit-collision",
                    message=(
                        f"{name} claims bit {value:#04x} of the "
                        f"{plane} flag byte, already allocated to "
                        f"'{meaning}' in the declared registry")))
            else:
                prior = claims.get(plane, {}).get(value, [])
                local_prior = [c for c in prior if c[1] != origin
                               and _flag_base(c[5]) != base]
                if local_prior:
                    out.append(Finding(
                        file=mod.path, line=line, col=col,
                        rule="flag-bit-collision",
                        message=(
                            f"{name} claims bit {value:#04x} of the "
                            f"{plane} flag byte, already claimed by "
                            f"{local_prior[0][5]}")))
                else:
                    out.append(Finding(
                        file=mod.path, line=line, col=col,
                        rule="wire-magic-registry",
                        message=(
                            f"{name} allocates flag bit {value:#04x} of "
                            f"the {plane} flag byte outside the declared "
                            f"registry (d4pg_tpu/core/wire.py)")))
            claims.setdefault(plane, {}).setdefault(value, []).append(
                (base, origin, mod.path, line, col, name))
            graph.flags.setdefault(plane, {}).setdefault(value, base)


def _check_unchecked(prog: _Prog, resolved: dict, out: list) -> None:
    # socket-facing closure: calls recv, or calls something that does
    facing = {f.key for f in prog.fns if f.recv_call}
    changed = True
    while changed:
        changed = False
        for f in prog.fns:
            if f.key in facing:
                continue
            if any(g.key in facing
                   for _c, cands in resolved[f.key] for g in cands):
                facing.add(f.key)
                changed = True

    by_key = {f.key: f for f in prog.fns}
    reported: set = set()
    seen: set = set()

    def report(site, reason: str) -> None:
        key = (site.path, site.line, reason)
        if key in reported:
            return
        reported.add(key)
        out.append(Finding(
            file=site.path, line=site.line, col=site.col,
            rule="unchecked-frame", message=reason))

    def crc_before(fn: _Fn, line: int) -> bool:
        return any(c < line for c in fn.crc_lines)

    def visit(fn: _Fn, has_struct: bool, has_value: bool,
              crc_ok: bool, crc_req: bool, depth: int) -> None:
        crc_req = crc_req or any(m in _CRC_MAGICS for m in fn.magic_refs)
        sig = (fn.key, has_struct, has_value, crc_ok, crc_req)
        if sig in seen or depth > _MAX_DEPTH:
            return
        seen.add(sig)

        for u in fn.unpacks:
            if u.exact or u.buf_literal:
                continue
            if u.buf is not None and u.buf in fn.len_checked:
                continue
            if has_struct or u.caught & _STRUCT_CATCHES:
                continue
            report(u, (
                "socket-facing unpack of an unverified buffer without "
                "struct.error containment on the recv path"))

        for ld in fn.loads:
            contained = has_value or bool(ld.caught & _VALUE_CATCHES)
            site_crc = crc_ok or crc_before(fn, ld.line)
            if ld.kind == "np.load":
                if not contained:
                    report(ld, (
                        "socket-facing np.load of a received payload "
                        "without ValueError containment on the recv "
                        "path (hostile frame kills the thread)"))
                if crc_req and not site_crc:
                    report(ld, (
                        "payload parsed before any crc32 check on a "
                        "plane whose registry entry declares "
                        "crc32-payload"))
            else:  # np.frombuffer on a named buffer
                if ld.buf not in fn.len_checked and not contained:
                    report(ld, (
                        "socket-facing np.frombuffer of an unverified "
                        "buffer without ValueError containment on the "
                        "recv path"))
                if crc_req and not site_crc:
                    report(ld, (
                        "payload parsed before any crc32 check on a "
                        "plane whose registry entry declares "
                        "crc32-payload"))

        for call, cands in resolved[fn.key]:
            if not cands:
                continue
            n_struct = has_struct or bool(call.caught & _STRUCT_CATCHES)
            n_value = has_value or bool(call.caught & _VALUE_CATCHES)
            n_crc = crc_ok or crc_before(fn, call.line)
            for g in cands:
                visit(g, n_struct, n_value, n_crc, crc_req, depth + 1)

    for key in sorted(facing):
        fn = by_key[key]
        visit(fn, False, False, False, False, 0)


def format_registry(graph: WireGraph) -> str:
    """Human-readable artifact for ``--wire``, mirroring the ``--locks``
    lock-graph printout."""
    n_pack = sum(len(e["packs"]) for e in graph.magics.values())
    n_unpack = sum(len(e["unpacks"]) for e in graph.magics.values())
    lines = [
        f"wire registry: {len(graph.magics)} magic(s), "
        f"{n_pack} pack witness(es), {n_unpack} unpack witness(es) over "
        f"{graph.functions} function(s) in {graph.modules} module(s)",
        "magics:",
    ]

    def sort_key(item):
        m = item[0]
        return (0, m, "") if isinstance(m, int) else (1, 0, m)

    for m, e in sorted(graph.magics.items(), key=sort_key):
        plane = e["plane"] or "UNREGISTERED"
        name = e["name"] or "?"
        lines.append(f"  {_magic_str(m)}  {plane:<9} {name}")
        for kind in ("packs", "unpacks"):
            wits = e[kind]
            label = kind[:-1]
            if not wits:
                lines.append(f"    {label}: none")
            else:
                first = wits[0]
                more = f" [+{len(wits) - 1} more]" if len(wits) > 1 else ""
                lines.append(f"    {label}: {first}{more}")
    lines.append("flag bits:")
    for plane in sorted(graph.flags):
        bits = graph.flags[plane]
        cols = "  ".join(
            f"bit{bit.bit_length() - 1}={meaning}"
            for bit, meaning in sorted(bits.items()))
        lines.append(f"  {plane:<9} {cols}")
    if graph.findings:
        lines.append(f"findings: {len(graph.findings)}")
        for f in graph.findings:
            lines.append(f"  {f.format()}")
    else:
        lines.append("findings: none")
    return "\n".join(lines)
