"""The jaxlint rule catalog.

Twenty-four rule families, each targeting a hazard that silently costs
throughput or correctness on this stack (see docs/architecture.md "Static
analysis & perf sentinels" for the rationale and suppression policy):

- ``prng-key-reuse``       — same key consumed by two samplers
- ``host-sync-in-jit``     — host/device sync points under a trace
- ``recompile-hazard``     — patterns that defeat the jit cache
- ``use-after-donation``   — reading a buffer after ``donate_argnums`` took it
- ``tracer-leak``          — mutating outer state from inside a trace
- ``device-put-in-loop``   — per-item H2D transfers in a Python loop
- ``host-time-in-jit``     — host clock reads / obs-plane calls under a trace
- ``lock-order``           — service/buffer lock acquired under a shard lock
- ``sharding-rule-bypass`` — NamedSharding/PartitionSpec built outside the
  partition-rule core (``parallel/partition.py``)
- ``lock-cycle``           — interprocedural ABBA cycle in the lock graph
- ``unguarded-shared-write`` — shared attribute mutated off its owning lock
- ``wire-magic-registry``  — frame magic/flag bit outside the declared table
- ``codec-asymmetry``      — pack/unpack format or field-count drift
- ``unchecked-frame``      — recv-rooted decode without error/crc containment
- ``flag-bit-collision``   — one flag-byte bit claimed by two extensions
- ``thread-crash-containment`` — Thread target that can die uncaught (or
  caught-but-uncounted); ``# jaxlint: contained-by=<handler>`` declares
  an audited wrapper
- ``span-terminal-missing`` — trace begin with an exception-edge path to
  exit that never reaches a commit/shed terminal
- ``ledger-conservation``  — admission-counter bump whose path to exit
  records no disposition and no hand-off
- ``collective-axis-unbound`` — psum/pmean/axis_index axis_name with no
  reachable shard_map binding, or an axis hand-spelled/undeclared;
  ``# jaxlint: axis-bound-by=<caller>`` declares an audited binder
- ``sharding-spec-drift``  — in/out_shardings/device_put spec reaching a
  raw sharding constructor through dataflow, or a tree re-placed under a
  different partition factory (implicit reshard)
- ``donation-alias``       — donate_argnums call whose donated argument
  aliases another argument or a live captured reference
- ``rng-ambient-stream``   — numpy/stdlib global-RNG draw, unseeded
  ctor, or wall-clock seed inside determinism-scoped code
- ``rng-stream-thread-escape`` — one Generator drawn from two
  thread-spawn targets without its own SeedSequence branch;
  ``# jaxlint: stream-owner=<Component.attr>`` declares a caller-owned
  branch
- ``rng-draw-count-drift`` — seeded stream drawn a path-dependent
  count per event (the PR-12 desync shape); only skip-before-RNG-use
  is clean

The last fifteen are PROGRAM-scope families implemented in
``lint/lockgraph.py`` (locks), ``lint/wiregraph.py`` (wire protocol),
``lint/failgraph.py`` (exception flow / ledger), ``lint/meshgraph.py``
(sharding & collectives) and ``lint/rnggraph.py`` (RNG provenance &
determinism — which also upgrades family 1 interprocedurally): they
analyze every module of a lint run together (cross-module call graph),
where everything above is per-module.

Every rule is a function ``(ModuleContext) -> list[Finding]`` registered in
``RULES``. Rules are deliberately conservative: a finding should be either
a true positive or a line whose suppression comment is itself useful
documentation. Branchy dataflow uses *all-paths* (intersection) merging so
an ``if/else`` that consumes a key once per arm never fires.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from d4pg_tpu.lint.context import (
    FunctionNode, JitBinding, ModuleContext, _int_tuple, dotted_name,
    call_kind, is_trace_wrapper_expr, last_part,
)
from d4pg_tpu.lint.findings import Finding

# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------


def walk_own(node: ast.AST):
    """Walk ``node``'s subtree WITHOUT descending into nested functions —
    each function is analyzed in its own pass."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, FunctionNode):
            continue
        yield child
        yield from walk_own(child)


def all_functions(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, FunctionNode):
            yield node


def _body_of(func: ast.AST) -> list[ast.stmt]:
    if isinstance(func, ast.Lambda):
        return [ast.Expr(value=func.body)]
    return func.body


def _bound_names(target: ast.expr) -> set[str]:
    """Names bound by an assignment target (tuple-aware)."""
    out: set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
    return out


def _ordered(nodes):
    return sorted(nodes, key=lambda n: (n.lineno, n.col_offset))


# --------------------------------------------------------------------------
# a tiny sequential interpreter for dataflow-ish rules (R1, R4)
#
# Rules subclass SequentialRule and implement on_call / on_load; the driver
# walks statements in execution order, forks state at branches, merges with
# set-intersection (all-paths semantics), and runs loop bodies twice to
# catch cross-iteration hazards. State is a dict name -> info; rebinding a
# name always clears it.
# --------------------------------------------------------------------------


class SequentialRule:
    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: list[Finding] = []
        self._seen: set[tuple] = set()

    # -- overridables ------------------------------------------------------
    def on_call(self, call: ast.Call, state: dict) -> None: ...
    def on_load(self, name: ast.Name, state: dict) -> None: ...

    # -- driver ------------------------------------------------------------
    def emit(self, node: ast.AST, rule: str, msg: str) -> None:
        key = (node.lineno, node.col_offset, rule, msg)
        if key not in self._seen:
            self._seen.add(key)
            self.findings.append(Finding(
                self.ctx.path, node.lineno, node.col_offset, rule, msg))

    def run_function(self, func: ast.AST) -> None:
        self._exec_block(_body_of(func), {})

    def _visit_expr(self, expr: ast.AST, state: dict) -> None:
        """Calls and loads in source order; nested defs are other scopes."""
        nodes = [n for n in ast.walk(expr)
                 if isinstance(n, (ast.Call, ast.Name, ast.Lambda))]
        skip: set[int] = set()
        for n in nodes:
            if isinstance(n, ast.Lambda):
                for inner in ast.walk(n):
                    skip.add(id(inner))
        def order(n):
            # a call's effect (key consumption, donation) lands when the
            # call completes: order it by END position so loads of its own
            # arguments are processed first
            if isinstance(n, ast.Call):
                return (n.end_lineno or n.lineno,
                        n.end_col_offset or n.col_offset)
            return (n.lineno, n.col_offset)

        for n in sorted((n for n in nodes if id(n) not in skip), key=order):
            if isinstance(n, ast.Call):
                self.on_call(n, state)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                self.on_load(n, state)

    def _exec_block(self, body: list[ast.stmt], state: dict) -> None:
        for stmt in body:
            self._exec_stmt(stmt, state)

    def _merge(self, state: dict, branches: list[dict]) -> None:
        """All-paths merge: keep entries present in EVERY branch outcome."""
        state.clear()
        if not branches:
            return
        common = set(branches[0])
        for b in branches[1:]:
            common &= set(b)
        for k in common:
            state[k] = branches[0][k]

    def _exec_stmt(self, stmt: ast.stmt, state: dict) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scope; analyzed in its own pass
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._visit_expr(stmt.value, state)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                for name in _bound_names(t):
                    state.pop(name, None)
            return
        if isinstance(stmt, ast.If):
            self._visit_expr(stmt.test, state)
            a, b = dict(state), dict(state)
            self._exec_block(stmt.body, a)
            self._exec_block(stmt.orelse, b)
            self._merge(state, [a, b])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter, state)
            for name in _bound_names(stmt.target):
                state.pop(name, None)
            # run the body twice: the second pass catches hazards that only
            # appear across iterations (key consumed, never re-split)
            self._exec_block(stmt.body, state)
            for name in _bound_names(stmt.target):
                state.pop(name, None)
            self._exec_block(stmt.body, state)
            self._exec_block(stmt.orelse, state)
            return
        if isinstance(stmt, ast.While):
            self._visit_expr(stmt.test, state)
            self._exec_block(stmt.body, state)
            self._exec_block(stmt.body, state)
            self._exec_block(stmt.orelse, state)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._visit_expr(item.context_expr, state)
                if item.optional_vars is not None:
                    for name in _bound_names(item.optional_vars):
                        state.pop(name, None)
            self._exec_block(stmt.body, state)
            return
        if isinstance(stmt, ast.Try):
            a = dict(state)
            self._exec_block(stmt.body, a)
            outcomes = [a]
            for h in stmt.handlers:
                b = dict(state)
                self._exec_block(h.body, b)
                outcomes.append(b)
            self._merge(state, outcomes)
            self._exec_block(stmt.orelse, state)
            self._exec_block(stmt.finalbody, state)
            return
        # leaf statements: Expr, Return, Raise, Assert, Delete, ...
        for value in ast.iter_child_nodes(stmt):
            if isinstance(value, ast.expr):
                self._visit_expr(value, state)
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                for name in _bound_names(t):
                    state.pop(name, None)


# --------------------------------------------------------------------------
# R1: prng-key-reuse
# --------------------------------------------------------------------------

_SAMPLERS = {
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical", "cauchy",
    "chisquare", "choice", "dirichlet", "double_sided_maxwell", "exponential",
    "gamma", "generalized_normal", "geometric", "gumbel", "laplace",
    "loggamma", "logistic", "lognormal", "maxwell", "multivariate_normal",
    "normal", "orthogonal", "pareto", "permutation", "poisson", "rademacher",
    "randint", "rayleigh", "shuffle", "t", "triangular", "truncated_normal",
    "uniform", "wald", "weibull_min",
}
_NP_BASES = {"np", "numpy", "onp"}


def _random_call(call: ast.Call) -> str | None:
    """'normal' if this is a jax.random sampler call, else None."""
    dotted = dotted_name(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    if parts[0] in _NP_BASES:
        return None  # numpy's random API takes no key
    fn = parts[-1]
    if fn not in _SAMPLERS:
        return None
    if "random" in parts[:-1] or parts[0] in {"jr", "jrandom"}:
        return fn
    return None


class _KeyReuse(SequentialRule):
    def on_call(self, call: ast.Call, state: dict) -> None:
        fn = _random_call(call)
        if fn is None or not call.args:
            return
        key = call.args[0]
        if not isinstance(key, ast.Name):
            return
        prior = state.get(key.id)
        if prior is not None:
            pline, pfn = prior
            self.emit(
                call, "prng-key-reuse",
                f"key '{key.id}' already consumed by jax.random.{pfn} at "
                f"line {pline}; split() or fold_in() before reusing it")
        else:
            state[key.id] = (call.lineno, fn)


def rule_prng_key_reuse(ctx: ModuleContext) -> list[Finding]:
    checker = _KeyReuse(ctx)
    for func in all_functions(ctx):
        checker.run_function(func)
    return checker.findings


# --------------------------------------------------------------------------
# R2: host-sync-in-jit
# --------------------------------------------------------------------------

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_CONVERTERS = {"asarray", "array"}


def _static_param_names(func: ast.AST) -> set[str]:
    """Parameters marked static by a jit decorator: concrete Python values
    at trace time, so concretizing them (float()/int()) is legitimate."""
    if isinstance(func, ast.Lambda):
        return set()
    params = [a.arg for a in (*func.args.posonlyargs, *func.args.args)]
    out: set[str] = set()
    for dec in func.decorator_list:
        if not (isinstance(dec, ast.Call) and is_trace_wrapper_expr(dec)):
            continue
        kwargs = {k.arg: k.value for k in dec.keywords if k.arg}
        for i in _int_tuple(kwargs.get("static_argnums")):
            if i < len(params):
                out.add(params[i])
        names = kwargs.get("static_argnames")
        if isinstance(names, ast.Constant) and isinstance(names.value, str):
            out.add(names.value)
        elif isinstance(names, (ast.Tuple, ast.List)):
            out.update(e.value for e in names.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str))
    return out


def _root_name(expr: ast.expr) -> str | None:
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def rule_host_sync_in_jit(ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []

    def emit(node, msg):
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "host-sync-in-jit", msg))

    for func in all_functions(ctx):
        if not ctx.is_traced(func):
            continue
        static_names = _static_param_names(func)
        for node in walk_own(func):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
                emit(node, f".{f.attr}() inside traced code forces a "
                           "host-device sync (or a concretization error)")
                continue
            dotted = dotted_name(f) or ""
            parts = dotted.split(".")
            if (len(parts) > 1 and parts[0] in _NP_BASES
                    and parts[-1] in _CONVERTERS):
                emit(node, f"{dotted}() inside traced code pulls the value "
                           "to host; use jnp instead")
            elif parts[-1] == "device_get" and parts[0] in {"jax", "device_get"}:
                emit(node, "jax.device_get() inside traced code is a "
                           "host-device sync")
            elif (isinstance(f, ast.Name) and f.id in {"float", "int", "bool"}
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)
                    and _root_name(node.args[0]) not in static_names):
                emit(node, f"{f.id}() on a traced value forces concretization;"
                           " keep it an array (jnp.asarray / astype)")
    return findings


# --------------------------------------------------------------------------
# R3: recompile-hazard
# --------------------------------------------------------------------------


def _is_jit_or_pmap_call(call: ast.Call) -> bool:
    if call_kind(call) != "wrapper":
        return False
    target = call.func
    if last_part(dotted_name(target)) == "partial" and call.args:
        target = call.args[0]
    return last_part(dotted_name(target)) in {"jit", "pmap"}


def rule_recompile_hazard(ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []

    def emit(node, msg):
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "recompile-hazard", msg))

    # parent map for loop-ancestry and loop-variable checks
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def enclosing_loop_vars(node: ast.AST) -> set[str]:
        """Induction variables of For loops between node and its function."""
        out: set[str] = set()
        cur = parents.get(node)
        while cur is not None and not isinstance(cur, FunctionNode):
            if isinstance(cur, ast.For):
                out |= _bound_names(cur.target)
            cur = parents.get(cur)
        return out

    def inside_loop(node: ast.AST) -> bool:
        cur = parents.get(node)
        while cur is not None and not isinstance(cur, FunctionNode):
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            cur = parents.get(cur)
        return False

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        # (a) jit() created inside a loop: a fresh wrapper (and cache) per
        # iteration — nothing is ever a cache hit
        if _is_jit_or_pmap_call(node) and inside_loop(node):
            emit(node, "jax.jit/pmap created inside a loop builds a fresh "
                       "compilation cache every iteration; hoist it out")
        # (b) jax.jit(f)(x): wrapper discarded after one call
        if (isinstance(node.func, ast.Call)
                and _is_jit_or_pmap_call(node.func)):
            emit(node, "jax.jit(f)(...) compiles and discards the wrapper; "
                       "bind the jitted function once and reuse it")
        # (c) hazards at call sites of known jit bindings with static args
        if isinstance(node.func, ast.Name):
            binding = ctx.jit_bindings.get(node.func.id)
            if binding is not None and binding.static_argnums:
                loop_vars = enclosing_loop_vars(node)
                for pos in binding.static_argnums:
                    if pos >= len(node.args):
                        continue
                    arg = node.args[pos]
                    if isinstance(arg, ast.Name) and arg.id in loop_vars:
                        emit(arg, f"loop variable '{arg.id}' passed as "
                                  f"static arg {pos} of '{binding.name}': "
                                  "recompiles every iteration")
                    elif isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                        emit(arg, f"unhashable literal as static arg {pos} "
                                  f"of '{binding.name}': jit cache lookup "
                                  "raises or always misses")
    return findings


# --------------------------------------------------------------------------
# R4: use-after-donation
# --------------------------------------------------------------------------


class _UseAfterDonation(SequentialRule):
    def on_call(self, call: ast.Call, state: dict) -> None:
        # reads inside the call expression itself happen before donation,
        # so on_load (driven in source order) has already seen them
        if not isinstance(call.func, ast.Name):
            return
        binding: JitBinding | None = self.ctx.jit_bindings.get(call.func.id)
        if binding is None or not binding.donate_argnums:
            return
        for pos in binding.donate_argnums:
            if pos < len(call.args) and isinstance(call.args[pos], ast.Name):
                state[call.args[pos].id] = (call.lineno, binding.name)

    def on_load(self, name: ast.Name, state: dict) -> None:
        info = state.get(name.id)
        if info is not None:
            dline, gname = info
            self.emit(
                name, "use-after-donation",
                f"'{name.id}' was donated to '{gname}' at line {dline}; its "
                "buffer is gone — rebind the result or drop the reference")


def rule_use_after_donation(ctx: ModuleContext) -> list[Finding]:
    checker = _UseAfterDonation(ctx)
    for func in all_functions(ctx):
        checker.run_function(func)
    # module-level straight-line code can donate too
    checker._exec_block(
        [s for s in ctx.tree.body
         if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))],
        {})
    return checker.findings


# --------------------------------------------------------------------------
# R5: tracer-leak
# --------------------------------------------------------------------------

_MUTATORS = {"append", "extend", "add", "insert", "update", "setdefault",
             "pop", "popleft", "appendleft", "remove", "clear"}


def _local_names(func: ast.AST) -> set[str]:
    out: set[str] = set()
    if not isinstance(func, ast.Lambda):
        args = func.args
    else:
        args = func.args
    for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
        out.add(a.arg)
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    for node in walk_own(func):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
    return out


def rule_tracer_leak(ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []

    def emit(node, msg):
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "tracer-leak", msg))

    for func in all_functions(ctx):
        if not ctx.is_traced(func):
            continue
        locals_ = _local_names(func)
        # container mutators return None, so a real mutation is a bare
        # expression statement; a used return value means it's an ordinary
        # function that merely shares a name with list.insert/dict.update
        bare_calls = {
            id(n.value) for n in walk_own(func)
            if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call)
        }
        for node in walk_own(func):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(node, ast.Global) else "nonlocal"
                emit(node, f"'{kw}' write inside traced code leaks tracers "
                           "into outer state (stale after the first trace)")
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        emit(t, "attribute assignment inside traced code "
                                "stores a tracer on a host object; thread "
                                "state through the function instead")
                    elif (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id not in locals_):
                        emit(t, f"writing into closed-over '{t.value.id}' "
                                "inside traced code leaks tracers")
            elif (isinstance(node, ast.Call)
                    and id(node) in bare_calls
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id not in locals_):
                emit(node, f"mutating closed-over '{node.func.value.id}."
                           f"{node.func.attr}(...)' inside traced code leaks "
                           "tracers (and re-runs only at trace time)")
    return findings


# --------------------------------------------------------------------------
# R6: device-put-in-loop
# --------------------------------------------------------------------------


def rule_device_put_in_loop(ctx: ModuleContext) -> list[Finding]:
    """``jax.device_put`` inside a Python loop: per-item H2D transfers
    serialize against dispatch and pay per-call overhead every iteration —
    the exact ingest anti-pattern the block drain removed
    (``replay/fused_buffer.py``: coalesce rows into a block and transfer
    ONCE). Loops here are ``for``/``while`` statements in the same
    function (a comprehension builds one value and a nested function is
    its own scope, analyzed separately)."""
    findings: list[Finding] = []

    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def inside_loop(node: ast.AST) -> bool:
        cur = parents.get(node)
        while cur is not None and not isinstance(cur, FunctionNode):
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            cur = parents.get(cur)
        return False

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = dotted_name(node.func) or ""
        parts = dotted.split(".")
        if parts[-1] != "device_put":
            continue
        if len(parts) > 1 and parts[0] not in {"jax"}:
            continue  # some_obj.device_put: not the jax entry point
        if inside_loop(node):
            findings.append(Finding(
                ctx.path, node.lineno, node.col_offset, "device-put-in-loop",
                "device_put inside a loop transfers per item; coalesce the "
                "rows into one block and transfer once (see the block "
                "drain in replay/fused_buffer.py)"))
    return findings


# --------------------------------------------------------------------------
# R10: host-time-in-jit
# --------------------------------------------------------------------------

# time-module entry points whose value is a HOST clock read: under a
# trace they execute once at trace time and bake into the jaxpr as a
# constant — every later call of the compiled function reports the same
# "timestamp", silently. (The observability plane makes this hazard
# live: span stamps are cheap enough that someone WILL eventually try
# to time a jitted body from inside.)
_TIME_FNS = {"time", "time_ns", "perf_counter", "perf_counter_ns",
             "monotonic", "monotonic_ns", "process_time",
             "process_time_ns", "thread_time", "thread_time_ns"}
# bare-name clock reads distinctive enough to flag without a module
# root (`from time import perf_counter`); bare `time()` stays unflagged
# (too generic a name to claim).
_TIME_BARE = _TIME_FNS - {"time"}
# obs-plane entry points (d4pg_tpu/obs): recorder spans and registry
# mutations are host side effects — traced code calling them records
# once at trace time and never again (the tracer-leak failure mode,
# with a clock attached).
_OBS_FNS = {"record_span", "mark_grad", "mark_committed", "terminal_shed",
            "new_trace_id", "record_event", "latency_block"}
_OBS_METHODS = {"inc", "observe"}
_OBS_RECV_HINTS = ("registry", "counter", "gauge", "histogram", "metric",
                   "recorder", "tracer")


def rule_host_time_in_jit(ctx: ModuleContext) -> list[Finding]:
    """Flag host clock reads (``time.time()``/``perf_counter()``/...)
    and observability-plane calls (trace spans, registry counters)
    inside jit-traced code: they run at TRACE time, bake into the jaxpr
    as constants, and silently lie on every compiled call. Move the
    measurement to the dispatch site (bracket the jitted call), or
    thread real timestamps in as arguments."""
    findings: list[Finding] = []

    def emit(node, msg):
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "host-time-in-jit", msg))

    for func in all_functions(ctx):
        if not ctx.is_traced(func):
            continue
        for node in walk_own(func):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func) or ""
            parts = dotted.split(".")
            fn = parts[-1]
            if fn in _TIME_FNS and len(parts) > 1 and parts[0] == "time":
                emit(node, f"{dotted}() inside traced code reads the host "
                           "clock at TRACE time and bakes it in as a "
                           "constant — every compiled call reports the "
                           "same timestamp; time the dispatch site "
                           "instead")
            elif fn in _TIME_BARE and len(parts) == 1:
                emit(node, f"{fn}() inside traced code reads the host "
                           "clock at TRACE time (constant thereafter); "
                           "time the dispatch site instead")
            elif fn in _OBS_FNS:
                emit(node, f"observability call {dotted}() inside traced "
                           "code runs ONCE at trace time — the span/"
                           "event it records never fires again; hoist it "
                           "to the dispatch site")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _OBS_METHODS):
                # receiver may be a name chain (counter.inc) or a call
                # chain (REGISTRY.counter("x").inc); take whichever
                # dotted path exists and look for obs-plane hints
                recv = node.func.value
                recv_dotted = dotted_name(
                    recv.func if isinstance(recv, ast.Call) else recv) or ""
                if any(h in part.lower() for part in recv_dotted.split(".")
                       for h in _OBS_RECV_HINTS):
                    emit(node, f"registry mutation .{node.func.attr}() on "
                               f"'{recv_dotted}' inside traced code runs "
                               "ONCE at trace time — the counter silently "
                               "stops counting; hoist it to the dispatch "
                               "site")
    return findings


# --------------------------------------------------------------------------
# R7: lock-order
# --------------------------------------------------------------------------

# The sharded ingest plane's locking discipline (distributed/
# replay_service.py): shard/ring locks are LEAF locks. The commit thread
# holds the buffer or service lock and may wait for shard work to land;
# a thread that takes the buffer/service lock while already inside a
# shard/ring lock closes the classic ABBA cycle. Tiers by attribute name
# (conservative: only these exact suffixes participate):
_LEAF_LOCKS = {"cond", "_cond", "ring_lock", "shard_lock", "_ring_locks",
               "_shard_locks"}
_OUTER_LOCKS = {"_buffer_lock", "_lock", "_commit_cond"}


def _lock_tier(expr: ast.expr) -> str | None:
    """'leaf' / 'outer' / None for a with-item or .acquire() receiver."""
    # unwrap subscripts: with self._ring_locks[i]: ...
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    name = last_part(dotted_name(expr) or "")
    if name in _LEAF_LOCKS:
        return "leaf"
    if name in _OUTER_LOCKS:
        return "outer"
    return None


def rule_lock_order(ctx: ModuleContext) -> list[Finding]:
    """Flags acquiring a buffer/service-tier lock while holding a
    shard/ring-tier (leaf) lock — the deadlock shape the sharded ingest
    refactor introduces. Detects both ``with`` nesting and bare
    ``.acquire()`` calls lexically inside a leaf ``with`` block, within
    one function (cross-function flows are the suppression-documented
    exception)."""
    findings: list[Finding] = []

    def emit(node, held: str):
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "lock-order",
            f"outer-tier lock acquired while holding leaf lock '{held}' — "
            "shard/ring locks are leaf locks; take the buffer/service "
            "lock first or split the critical section"))

    def scan(body: list[ast.stmt], held: str | None) -> None:
        for stmt in body:
            inner_held = held
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    tier = _lock_tier(item.context_expr)
                    if tier == "outer" and held is not None:
                        emit(item.context_expr, held)
                    elif tier == "leaf":
                        nm = last_part(
                            dotted_name(
                                item.context_expr.value
                                if isinstance(item.context_expr,
                                              ast.Subscript)
                                else item.context_expr) or "")
                        inner_held = nm or "leaf"
                scan(stmt.body, inner_held)
                continue
            if isinstance(stmt, FunctionNode):
                continue  # new scope, analyzed by its own pass
            if held is not None:
                for node in walk_own(stmt):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "acquire"
                            and _lock_tier(node.func.value) == "outer"):
                        emit(node, held)
            # generic recursion into compound statements
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    scan(sub, held)
            for handler in getattr(stmt, "handlers", []) or []:
                scan(handler.body, held)

    for func in all_functions(ctx):
        scan(_body_of(func), None)
    scan([s for s in ctx.tree.body if not isinstance(s, FunctionNode)], None)
    return findings


# --------------------------------------------------------------------------
# R15: sharding-rule-bypass
# --------------------------------------------------------------------------

# The partition-rule core (parallel/partition.py) is the single source of
# sharding truth: every layout the package places on an array resolves
# through its regex rule table (or a factory wrapping it), so ONE
# printable table owns every placement decision. A raw constructor call
# anywhere else re-opens the hand-wired-axis drift the core closed.
_SHARDING_CTORS = {"NamedSharding", "PartitionSpec"}
_SHARDING_MODULES = {"jax.sharding"}
# dotted-call roots distinctive enough to claim without import tracking
_SHARDING_ROOTS = {"jax", "sharding", "partition"}


def rule_sharding_rule_bypass(ctx: ModuleContext) -> list[Finding]:
    """Flag ``NamedSharding(...)`` / ``PartitionSpec(...)`` construction —
    including import aliases (``PartitionSpec as P``, ``partition.PS``) —
    anywhere outside ``parallel/partition.py``. Layouts come from the
    rule core (``partition.spec``/``sharding``/``match_partition_rules``
    or a ``*_sharding`` factory); a raw constructor bypasses the table."""
    if ctx.path.replace("\\", "/").endswith("parallel/partition.py"):
        return []  # the rule core is where the constructors BELONG

    aliases: dict[str, str] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in _SHARDING_MODULES or mod.endswith("parallel.partition"):
                for a in node.names:
                    if a.name in _SHARDING_CTORS or a.name == "PS":
                        canon = ("PartitionSpec" if a.name == "PS"
                                 else a.name)
                        aliases[a.asname or a.name] = canon
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            # re-aliasing (P = partition.PS): calls through it bypass too
            src = dotted_name(node.value) or ""
            if "." in src and last_part(src) in _SHARDING_CTORS | {"PS"}:
                aliases[node.targets[0].id] = (
                    "PartitionSpec" if last_part(src) == "PS"
                    else last_part(src))

    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = dotted_name(node.func) or ""
        parts = dotted.split(".")
        ctor = None
        if len(parts) == 1:
            ctor = aliases.get(parts[0])
        elif parts[0] in _SHARDING_ROOTS:
            if parts[-1] in _SHARDING_CTORS:
                ctor = parts[-1]
            elif parts[-1] == "PS" and parts[0] == "partition":
                ctor = "PartitionSpec"
        if ctor is None:
            continue
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "sharding-rule-bypass",
            f"{ctor} constructed outside parallel/partition.py — resolve "
            "the layout through the partition-rule core (partition.spec/"
            "sharding/match_partition_rules or a *_sharding factory) so "
            "the rule table stays the single source of placement truth"))
    return findings


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    check: object  # (ModuleContext) -> list[Finding]
    # 'module' rules see one file at a time; 'program' rules (the lock
    # graph) run ONCE over every analyzed module together — the engine
    # dispatches them to lint/lockgraph.py instead of the per-file loop.
    scope: str = "module"


def _program_rule(rule_id: str):
    """Single-module fallback so ``lint_source`` (fixtures, snippets)
    drives the program families through the same registry entry; whole
    trees go through ``engine.lint_paths``'s one-shot program pass."""
    def check(ctx: ModuleContext) -> list[Finding]:
        from d4pg_tpu.lint import lockgraph

        return lockgraph.analyze([ctx], rules=[rule_id]).findings

    return check


def _wire_rule(rule_id: str):
    """Same single-module fallback for the wire-protocol families
    (``lint/wiregraph.py``)."""
    def check(ctx: ModuleContext) -> list[Finding]:
        from d4pg_tpu.lint import wiregraph

        return wiregraph.analyze([ctx], rules=[rule_id]).findings

    return check


def _fail_rule(rule_id: str):
    """Same single-module fallback for the exception-flow families
    (``lint/failgraph.py``)."""
    def check(ctx: ModuleContext) -> list[Finding]:
        from d4pg_tpu.lint import failgraph

        return failgraph.analyze([ctx], rules=[rule_id]).findings

    return check


def _mesh_rule(rule_id: str):
    """Same single-module fallback for the sharding/collective families
    (``lint/meshgraph.py``)."""
    def check(ctx: ModuleContext) -> list[Finding]:
        from d4pg_tpu.lint import meshgraph

        return meshgraph.analyze([ctx], rules=[rule_id]).findings

    return check


def _rng_rule(rule_id: str):
    """Same single-module fallback for the RNG-provenance families
    (``lint/rnggraph.py``)."""
    def check(ctx: ModuleContext) -> list[Finding]:
        from d4pg_tpu.lint import rnggraph

        return rnggraph.analyze([ctx], rules=[rule_id]).findings

    return check


RULES: dict[str, Rule] = {r.id: r for r in [
    Rule("prng-key-reuse",
         "same PRNG key consumed by two jax.random samplers without an "
         "intervening split/fold_in",
         rule_prng_key_reuse),
    Rule("host-sync-in-jit",
         ".item()/float()/np.asarray/device_get/block_until_ready inside "
         "traced code",
         rule_host_sync_in_jit),
    Rule("recompile-hazard",
         "jit built in a loop, jit(f)(x) immediate calls, value-varying or "
         "unhashable static args",
         rule_recompile_hazard),
    Rule("use-after-donation",
         "reading an argument after a donate_argnums call consumed its "
         "buffer",
         rule_use_after_donation),
    Rule("tracer-leak",
         "traced code mutating outer state (global/nonlocal/attribute/"
         "closure writes)",
         rule_tracer_leak),
    Rule("device-put-in-loop",
         "jax.device_put called inside a Python loop — per-item H2D; "
         "coalesce into a block and transfer once",
         rule_device_put_in_loop),
    Rule("host-time-in-jit",
         "time.time()/perf_counter()/trace-span/registry calls inside "
         "traced code — they run once at trace time and silently lie",
         rule_host_time_in_jit),
    Rule("lock-order",
         "buffer/service lock acquired while holding a shard/ring leaf "
         "lock — the sharded-ingest deadlock shape",
         rule_lock_order),
    Rule("sharding-rule-bypass",
         "NamedSharding/PartitionSpec (or an alias: P, partition.PS) "
         "constructed outside parallel/partition.py — layouts resolve "
         "through the partition-rule table, not hand-wired axes",
         rule_sharding_rule_bypass),
    Rule("lock-cycle",
         "cycle in the interprocedural held-while-acquiring lock graph "
         "(ABBA across any number of calls) — see lint/lockgraph.py",
         _program_rule("lock-cycle"), scope="program"),
    Rule("unguarded-shared-write",
         "attribute written without the lock every other access holds "
         "(ownership inferred; declare `# jaxlint: guarded-by=<lock>`)",
         _program_rule("unguarded-shared-write"), scope="program"),
    Rule("wire-magic-registry",
         "0xD4xx magic or flag bit packed into a frame but absent from / "
         "re-declared outside the declared registry (core/wire.py); "
         "seed-derivation literals are exempt",
         _wire_rule("wire-magic-registry"), scope="program"),
    Rule("codec-asymmetry",
         "pack/unpack format not a field segment of its magic's declared "
         "header, arg/target count drift, *_SIZE constant != calcsize, or "
         "a magic packed but never unpacked",
         _wire_rule("codec-asymmetry"), scope="program"),
    Rule("unchecked-frame",
         "socket-facing decode (recv -> unpack/np.load/np.frombuffer) "
         "without struct.error/ValueError containment, or payload use "
         "before the declared crc32 check",
         _wire_rule("unchecked-frame"), scope="program"),
    Rule("flag-bit-collision",
         "two extensions claiming the same bit of the same plane's flag "
         "byte — see core/wire.py for the allocations",
         _wire_rule("flag-bit-collision"), scope="program"),
    Rule("thread-crash-containment",
         "threading.Thread target that can die on an uncaught raise, or "
         "whose broad handler swallows the crash uncounted — declare "
         "`# jaxlint: contained-by=<handler>` for wrapped targets",
         _fail_rule("thread-crash-containment"), scope="program"),
    Rule("span-terminal-missing",
         "trace begin whose exception edges can exit the frame without a "
         "commit/shed terminal — the static zero-orphan invariant",
         _fail_rule("span-terminal-missing"), scope="program"),
    Rule("ledger-conservation",
         "frame-admission counter bump with a path to exit that records "
         "neither a disposition counter nor a terminal hand-off",
         _fail_rule("ledger-conservation"), scope="program"),
    Rule("collective-axis-unbound",
         "psum/pmean/all_gather/axis_index axis_name with no reachable "
         "shard_map binding, or an axis hand-spelled/undeclared — "
         "declare `# jaxlint: axis-bound-by=<caller>` for helpers bound "
         "by their callers",
         _mesh_rule("collective-axis-unbound"), scope="program"),
    Rule("sharding-spec-drift",
         "in_shardings/out_shardings/device_put spec that resolves "
         "through dataflow to a raw sharding constructor outside "
         "parallel/partition.py, or a tree re-placed under a different "
         "partition factory (implicit reshard)",
         _mesh_rule("sharding-spec-drift"), scope="program"),
    Rule("donation-alias",
         "donate_argnums call site whose donated argument aliases "
         "another argument or a live captured reference the call never "
         "rebinds — the replica deep-copy defect, statically",
         _mesh_rule("donation-alias"), scope="program"),
    Rule("rng-ambient-stream",
         "numpy module-level global draw, stdlib random.* draw, "
         "unseeded default_rng()/RandomState(), or wall-clock-derived "
         "seed reachable from determinism-scoped code (fleet/chaos/"
         "traffic/sampler/ledger paths)",
         _rng_rule("rng-ambient-stream"), scope="program"),
    Rule("rng-stream-thread-escape",
         "one Generator drawn from two distinct thread-spawn targets "
         "without its own SeedSequence branch — declare "
         "`# jaxlint: stream-owner=<Component.attr>` for caller-owned "
         "branches",
         _rng_rule("rng-stream-thread-escape"), scope="program"),
    Rule("rng-draw-count-drift",
         "seeded stream drawn a path-dependent count per event — the "
         "PR-12 backpressure desync shape; clean only under the "
         "documented skip-before-RNG-use idiom",
         _rng_rule("rng-draw-count-drift"), scope="program"),
]}
