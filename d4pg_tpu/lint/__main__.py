"""CLI: ``python -m d4pg_tpu.lint [paths] [--rules a,b] [--list-rules]``.

Exit code 0 when every finding is suppressed (or none exist), 1 otherwise.
With no paths, lints the ``d4pg_tpu`` package itself.

``--locks`` prints the discovered whole-program lock graph (nodes, edges
with witness sites, cycles) instead of findings — the review artifact
for concurrency-touching PRs; exit 1 iff the graph has a cycle.

``--wire`` prints the discovered wire-protocol registry (magics, owning
planes, pack/unpack witness sites, flag-bit map) — the review artifact
for protocol-touching PRs; exit 1 iff any wire family fires.
"""

from __future__ import annotations

import argparse
import os
import sys

from d4pg_tpu.lint.engine import build_lock_graph, build_wire_graph, lint_paths
from d4pg_tpu.lint.rules import RULES


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m d4pg_tpu.lint",
        description="JAX/TPU-aware static analysis for the d4pg_tpu stack")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the d4pg_tpu "
                             "package)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run (default all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--locks", action="store_true",
                        help="print the whole-program lock graph (nodes, "
                             "edges, cycles) instead of findings; exit 1 "
                             "iff a cycle exists")
    parser.add_argument("--wire", action="store_true",
                        help="print the discovered wire-protocol registry "
                             "(magics, pack/unpack witnesses, flag bits) "
                             "instead of findings; exit 1 iff any wire "
                             "family fires")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id:22s} {rule.summary}")
        return 0

    if args.locks:
        from d4pg_tpu.lint.lockgraph import format_graph

        paths = args.paths or [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))]
        graph, errors = build_lock_graph(paths)
        print(format_graph(graph))
        for e in errors:
            print(e, file=sys.stderr)
        return 1 if graph.cycles else 0

    if args.wire:
        from d4pg_tpu.lint.wiregraph import format_registry

        paths = args.paths or [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))]
        graph, errors = build_wire_graph(paths)
        print(format_registry(graph))
        for e in errors:
            print(e, file=sys.stderr)
        return 1 if graph.findings else 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    result = lint_paths(paths, rules=rules)

    for f in result.findings:
        print(f.format())
    if args.show_suppressed:
        for f in result.suppressed:
            print(f.format())
    for e in result.errors:
        print(e, file=sys.stderr)
    n, s = len(result.findings), len(result.suppressed)
    print(f"jaxlint: {n} finding(s), {s} suppressed", file=sys.stderr)
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
