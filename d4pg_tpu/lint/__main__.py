"""CLI: ``python -m d4pg_tpu.lint [paths] [--rules a,b] [--list-rules]``.

Exit code 0 when every finding is suppressed (or none exist), 1 otherwise.
With no paths, lints the ``d4pg_tpu`` package itself.

``--locks`` prints the discovered whole-program lock graph (nodes, edges
with witness sites, cycles) instead of findings — the review artifact
for concurrency-touching PRs; exit 1 iff the graph has a cycle.

``--wire`` prints the discovered wire-protocol registry (magics, owning
planes, pack/unpack witness sites, flag-bit map) — the review artifact
for protocol-touching PRs; exit 1 iff any wire family fires.

``--fail`` prints the thread-role/containment/span-lifecycle graph from
the exception-flow pass (families 16-18) — the review artifact for
thread- or obs-touching PRs; exit 1 iff any fail family fires.

``--mesh`` prints the sharding/collective graph (shard_map sites with
bound axes, collective uses with binding witnesses, the sharding
dataflow table, donation sites) from the mesh pass (families 19-21) —
the review artifact for sharding-touching PRs; exit 1 iff any mesh
family fires.

``--rng`` prints the RNG stream table (owner, constructor, seed
provenance, draw sites, thread reachability) and SeedSequence branch
sites from the determinism pass (families 22-24) — the review artifact
for chaos/traffic/sampler-touching PRs; exit 1 iff any rng family
fires.

``--all`` runs the syntactic families AND all six graph modes and
emits ONE merged document — the single entrypoint CI gates on.

``--json`` switches any mode to a machine-readable document on stdout:
``{"schema": 1, "mode": ..., "findings": [...], ...}`` — the contract
tests/test_lint_clean.py gates so CI tooling never scrapes the
human-oriented text.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from d4pg_tpu.lint.engine import (
    build_fail_graph,
    build_lock_graph,
    build_mesh_graph,
    build_rng_graph,
    build_wire_graph,
    lint_paths,
)
from d4pg_tpu.lint.rules import RULES

JSON_SCHEMA_VERSION = 1


def _magic_key(m) -> str:
    # magics are u16 ints except the ASCII resync sentinel (bytes)
    return f"0x{m:04X}" if isinstance(m, int) else m.decode("ascii")


def _finding_doc(f) -> dict:
    return {"file": f.file, "line": f.line, "col": f.col, "rule": f.rule,
            "message": f.message, "suppressed": f.suppressed}


def _doc(mode: str, findings, errors, **extra) -> dict:
    doc = {"schema": JSON_SCHEMA_VERSION, "mode": mode,
           "findings": [_finding_doc(f) for f in findings],
           "errors": list(errors)}
    doc.update(extra)
    return doc


# Per-mode artifact keys, shared by the single-mode ``--json`` documents
# and the merged ``--all`` document (one encoder per artifact — the two
# paths cannot drift).

def _locks_extra(graph) -> dict:
    return {
        "functions": graph.functions,
        "nodes": {n: t for n, t in sorted(graph.nodes.items())},
        "edges": [{"held": a, "acquired": b, "witnesses": w}
                  for (a, b), w in sorted(graph.edges.items())],
        "cycles": graph.cycles,
    }


def _wire_extra(graph) -> dict:
    return {
        "functions": graph.functions, "modules": graph.modules,
        "magics": {_magic_key(m): info
                   for m, info in sorted(graph.magics.items(),
                                         key=lambda kv: _magic_key(kv[0]))},
        "flags": {plane: {str(bit): meaning
                          for bit, meaning in sorted(bits.items())}
                  for plane, bits in sorted(graph.flags.items())},
    }


def _fail_extra(graph) -> dict:
    return {
        "functions": graph.functions, "modules": graph.modules,
        "threads": [{"site": s, "target": t, "status": st}
                    for s, t, st in sorted(graph.threads)],
        "spans": [{"site": s, "root": r, "status": st}
                  for s, r, st in sorted(graph.spans)],
        "ledger": [{"site": s, "counter": c, "status": st}
                   for s, c, st in sorted(graph.ledger)],
        "handlers": dict(sorted(graph.handlers.items())),
    }


def _mesh_extra(graph) -> dict:
    return {
        "functions": graph.functions, "modules": graph.modules,
        "axes": dict(graph.axes),
        "shard_maps": [{"site": s, "body": b, "axes": a}
                       for s, b, a in sorted(graph.shard_maps)],
        "collectives": [{"site": s, "op": op, "axis": ax, "witness": w,
                         "status": st}
                        for s, op, ax, w, st in sorted(graph.collectives)],
        "shardings": [{"site": s, "kind": k, "resolution": r, "status": st}
                      for s, k, r, st in sorted(graph.shardings)],
        "donations": [{"site": s, "callee": c, "donated": d, "status": st}
                      for s, c, d, st in sorted(graph.donations)],
        "handlers": dict(sorted(graph.handlers.items())),
    }


def _rng_extra(graph) -> dict:
    return {
        "functions": graph.functions, "modules": graph.modules,
        "scoped": graph.scoped,
        "streams": [{"site": s, "owner": o, "ctor": c, "seed": sd,
                     "draws": d, "threads": t}
                    for s, o, c, sd, d, t in sorted(graph.streams)],
        "branches": [{"site": s, "src": x}
                     for s, x in sorted(graph.branches)],
        "handlers": dict(sorted(graph.handlers.items())),
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m d4pg_tpu.lint",
        description="JAX/TPU-aware static analysis for the d4pg_tpu stack")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the d4pg_tpu "
                             "package)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run (default all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--locks", action="store_true",
                        help="print the whole-program lock graph (nodes, "
                             "edges, cycles) instead of findings; exit 1 "
                             "iff a cycle exists")
    parser.add_argument("--wire", action="store_true",
                        help="print the discovered wire-protocol registry "
                             "(magics, pack/unpack witnesses, flag bits) "
                             "instead of findings; exit 1 iff any wire "
                             "family fires")
    parser.add_argument("--fail", action="store_true",
                        help="print the thread-role/containment/"
                             "span-lifecycle graph (families 16-18) "
                             "instead of findings; exit 1 iff any fail "
                             "family fires")
    parser.add_argument("--mesh", action="store_true",
                        help="print the sharding/collective graph "
                             "(shard_map sites, collective bindings, "
                             "sharding dataflow, donation sites; "
                             "families 19-21) instead of findings; exit "
                             "1 iff any mesh family fires")
    parser.add_argument("--rng", action="store_true", dest="rng_mode",
                        help="print the RNG stream/provenance table "
                             "(owners, seed provenance, draw sites, "
                             "thread reachability; families 22-24) "
                             "instead of findings; exit 1 iff any rng "
                             "family fires")
    parser.add_argument("--all", action="store_true", dest="all_modes",
                        help="run the syntactic families AND all six "
                             "graph modes; emit ONE merged document "
                             "(--json) or every artifact in sequence")
    parser.add_argument("--json", action="store_true",
                        help="emit a machine-readable document instead of "
                             "the human-oriented text (all modes)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id:22s} {rule.summary}")
        return 0

    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]

    if args.locks:
        from d4pg_tpu.lint.lockgraph import format_graph

        graph, errors = build_lock_graph(paths)
        if args.json:
            print(json.dumps(_doc(
                "locks", graph.findings, errors,
                **_locks_extra(graph)), indent=2))
        else:
            print(format_graph(graph))
            for e in errors:
                print(e, file=sys.stderr)
        return 1 if graph.cycles else 0

    if args.wire:
        from d4pg_tpu.lint.wiregraph import format_registry

        graph, errors = build_wire_graph(paths)
        if args.json:
            print(json.dumps(_doc(
                "wire", graph.findings, errors,
                **_wire_extra(graph)), indent=2))
        else:
            print(format_registry(graph))
            for e in errors:
                print(e, file=sys.stderr)
        return 1 if graph.findings else 0

    if args.fail:
        from d4pg_tpu.lint.failgraph import format_failgraph

        graph, errors = build_fail_graph(paths)
        if args.json:
            print(json.dumps(_doc(
                "fail", graph.findings, errors,
                **_fail_extra(graph)), indent=2))
        else:
            print(format_failgraph(graph))
            for e in errors:
                print(e, file=sys.stderr)
        return 1 if graph.findings else 0

    if args.mesh:
        from d4pg_tpu.lint.meshgraph import format_meshgraph

        graph, errors = build_mesh_graph(paths)
        if args.json:
            print(json.dumps(_doc(
                "mesh", graph.findings, errors,
                **_mesh_extra(graph)), indent=2))
        else:
            print(format_meshgraph(graph))
            for e in errors:
                print(e, file=sys.stderr)
        return 1 if graph.findings else 0

    if args.rng_mode:
        from d4pg_tpu.lint.rnggraph import format_rnggraph

        graph, errors = build_rng_graph(paths)
        if args.json:
            print(json.dumps(_doc(
                "rng", graph.findings, errors,
                **_rng_extra(graph)), indent=2))
        else:
            print(format_rnggraph(graph))
            for e in errors:
                print(e, file=sys.stderr)
        return 1 if graph.findings else 0

    if args.all_modes:
        from d4pg_tpu.lint.failgraph import format_failgraph
        from d4pg_tpu.lint.lockgraph import format_graph
        from d4pg_tpu.lint.meshgraph import format_meshgraph
        from d4pg_tpu.lint.rnggraph import format_rnggraph
        from d4pg_tpu.lint.wiregraph import format_registry

        result = lint_paths(paths)
        locks, lock_errs = build_lock_graph(paths)
        wire, wire_errs = build_wire_graph(paths)
        fail, fail_errs = build_fail_graph(paths)
        mesh, mesh_errs = build_mesh_graph(paths)
        rng, rng_errs = build_rng_graph(paths)
        # lint_paths already runs every program family, so its findings
        # list IS the merged findings list; the per-mode sections carry
        # the review artifacts (and re-state each mode's own findings)
        dirty = (not result.clean) or bool(locks.cycles)
        if args.json:
            print(json.dumps(_doc(
                "all", result.findings, result.errors,
                suppressed=len(result.suppressed),
                locks={"findings": [_finding_doc(f)
                                    for f in locks.findings],
                       "errors": lock_errs, **_locks_extra(locks)},
                wire={"findings": [_finding_doc(f) for f in wire.findings],
                      "errors": wire_errs, **_wire_extra(wire)},
                fail={"findings": [_finding_doc(f) for f in fail.findings],
                      "errors": fail_errs, **_fail_extra(fail)},
                mesh={"findings": [_finding_doc(f) for f in mesh.findings],
                      "errors": mesh_errs, **_mesh_extra(mesh)},
                rng={"findings": [_finding_doc(f) for f in rng.findings],
                     "errors": rng_errs, **_rng_extra(rng)}),
                indent=2))
            return 1 if dirty else 0
        for block in (format_graph(locks), format_registry(wire),
                      format_failgraph(fail), format_meshgraph(mesh),
                      format_rnggraph(rng)):
            print(block)
            print()
        for f in result.findings:
            print(f.format())
        for e in (result.errors + lock_errs + wire_errs + fail_errs
                  + mesh_errs + rng_errs):
            print(e, file=sys.stderr)
        n, s = len(result.findings), len(result.suppressed)
        print(f"jaxlint: {n} finding(s), {s} suppressed", file=sys.stderr)
        return 1 if dirty else 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    result = lint_paths(paths, rules=rules)

    if args.json:
        shown = list(result.findings)
        if args.show_suppressed:
            shown += result.suppressed
        print(json.dumps(_doc(
            "findings", shown, result.errors,
            suppressed=len(result.suppressed)), indent=2))
        return 0 if result.clean else 1

    for f in result.findings:
        print(f.format())
    if args.show_suppressed:
        for f in result.suppressed:
            print(f.format())
    for e in result.errors:
        print(e, file=sys.stderr)
    n, s = len(result.findings), len(result.suppressed)
    print(f"jaxlint: {n} finding(s), {s} suppressed", file=sys.stderr)
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
