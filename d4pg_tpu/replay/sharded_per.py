"""Sharded device-resident replay: ring + PER trees distributed over the
learner mesh's ``data`` axis.

The multi-chip extension of the fused replay path (``device_ring.py`` /
``device_per.py`` hold everything on ONE device). Here every device of
the data axis owns a shard of the transition ring and its own PER
sum/min tree pair; sampling, gathering and priority write-back run
per-shard inside the sharded learner dispatch (``learner/fused.py``'s
``make_sharded_fused_chunk``) — so the production configuration
(K-step scan x data parallelism) keeps ZERO per-chunk host round trips
and the batch rows never cross devices (each shard contributes
``B / n_shards`` rows; only gradients ride the ICI collectives).

This is the Ape-X sharded-replay layout made device-native. Sampling
semantics: each shard draws B/N proportional samples from ITS shard
(stratified across shards by construction); the importance weights
correct for the true per-draw probability ``(1/N) * p_i / total_h``
with a GLOBAL max-weight normalizer computed by ``lax.pmin`` over the
data axis — reducing exactly to the reference formula
(``prioritized_replay_memory.py:299-313``) at N=1.

Host-side bookkeeping mirrors ``fused_buffer.FusedDeviceReplay``:
``add`` stages rows (bounded), ``drain`` flushes at chunk boundaries on
the learner thread (single owner of the donated device handles),
splitting rows round-robin so shard sizes stay balanced.

MULTI-HOST: the same buffer runs over a global (multi-process) mesh —
the production pod shape the reference approximates with one host's
shared memory (``main.py:371-405``). Each host owns the data-axis
shards of its LOCAL devices (the Ape-X layout: rows never cross hosts;
only gradients and the one ``pmin`` scalar ride DCN). Host-side state
(`_head`/`_size`/staging) covers only the owned shards; ``drain`` and
``state_dict`` become collective calls — every host participates in the
same SPMD insert with a globally-agreed pad width (one tiny allgather),
and checkpoints hold each host's own shard-set (restored via the
per-host sidecar scheme in ``train.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from d4pg_tpu.replay.segment_tree import next_pow2
from d4pg_tpu.replay.uniform import TransitionBatch, pack_rows, validate_rows


def _owned_data_rows(mesh) -> tuple[list[int], bool]:
    """Global data-axis indices whose devices ALL belong to this process,
    and whether the mesh spans any remote devices at all. A data row split
    across processes cannot host a replay shard (its ring rows would need
    cross-host writes), so that layout is rejected outright."""
    import jax

    from d4pg_tpu.parallel.mesh import DATA_AXIS

    axis = mesh.axis_names.index(DATA_AXIS)
    rows = np.moveaxis(mesh.devices, axis, 0)
    me = jax.process_index()
    owned, remote = [], False
    for i in range(rows.shape[0]):
        procs = {d.process_index for d in rows[i].flat}
        if procs == {me}:
            owned.append(i)
        else:
            remote = True
            if me in procs:
                raise ValueError(
                    f"data-axis row {i} is split across processes "
                    f"{sorted(procs)}; replay shards must be host-local "
                    "(put the model axis within a host)")
    return owned, remote


class ShardedPerTrees(NamedTuple):
    """Per-shard tree pair, leading axis = shard (sharded over ``data``)."""

    sum_tree: "jax.Array"  # [n_shards, 2 * cap_shard]
    min_tree: "jax.Array"  # [n_shards, 2 * cap_shard]
    max_priority: "jax.Array"  # [n_shards] per-shard running max

    @property
    def cap_shard(self) -> int:
        return self.sum_tree.shape[1] // 2


class ShardedFusedReplay:
    """Device-sharded ring + trees for the mesh fused learner path."""

    def __init__(
        self,
        capacity: int,
        obs_dim: int | tuple,
        act_dim: int,
        mesh,
        alpha: float = 0.6,
        prioritized: bool = True,
        obs_dtype=None,
    ):
        import jax
        import jax.numpy as jnp

        from d4pg_tpu.parallel import partition
        from d4pg_tpu.parallel.mesh import DATA_AXIS

        self.mesh = mesh
        self.n_shards = int(mesh.shape[DATA_AXIS])
        # per-shard capacity, power of two for the tree layout
        self.cap_shard = next_pow2(
            max(1, int(np.ceil(capacity / self.n_shards))))
        self.capacity = self.cap_shard * self.n_shards
        obs_shape = (obs_dim,) if np.isscalar(obs_dim) else tuple(obs_dim)
        if obs_dtype is None:
            obs_dtype = np.float32 if len(obs_shape) == 1 else np.uint8
        self.prioritized = bool(prioritized)
        self.alpha = float(alpha)

        # multi-host: this process's contiguous block of data-axis shards
        # (contiguity is what make_array_from_process_local_data assembles
        # from; global_mesh()'s process-contiguous device order guarantees
        # it, and anything else is rejected here instead of mis-assembling)
        self._owned, self._multiproc = _owned_data_rows(mesh)
        self.n_local = len(self._owned)
        if self._multiproc:
            if not self.n_local:
                raise ValueError(
                    "this process owns no data-axis shard of the replay "
                    "mesh; every participating host needs local devices "
                    "on the data axis")
            if self._owned != list(range(self._owned[0],
                                         self._owned[0] + self.n_local)):
                raise ValueError(
                    f"this process's data-axis shards {self._owned} are "
                    "not contiguous; build the mesh with global_mesh() "
                    "(process-contiguous device order)")
        self.local_start = self._owned[0] if self._owned else 0

        shard = partition.batch_sharding(mesh)
        n, c = self.n_shards, self.cap_shard

        def _zero_storage():
            return TransitionBatch(
                obs=jnp.zeros((n, c, *obs_shape), obs_dtype),
                action=jnp.zeros((n, c, act_dim), jnp.float32),
                reward=jnp.zeros((n, c), jnp.float32),
                next_obs=jnp.zeros((n, c, *obs_shape), obs_dtype),
                done=jnp.zeros((n, c), jnp.float32),
                discount=jnp.zeros((n, c), jnp.float32),
            )

        def _zero_trees():
            return ShardedPerTrees(
                sum_tree=jnp.zeros((n, 2 * c), jnp.float32),
                min_tree=jnp.full((n, 2 * c), jnp.inf, jnp.float32),
                max_priority=jnp.ones((n,), jnp.float32),
            )

        if self._multiproc:
            # host-local device_put cannot address other hosts' devices;
            # construct inside jit with sharded outputs (SPMD — every
            # process traces the same zeros)
            # one-shot by design (runs once in __init__): jit-with-
            # out_shardings is the only way to materialize the buffer on
            # every process's devices
            self.storage = jax.jit(_zero_storage, out_shardings=shard)()  # jaxlint: disable=recompile-hazard
            self.trees = (jax.jit(_zero_trees, out_shardings=shard)()  # jaxlint: disable=recompile-hazard
                          if prioritized else None)
        else:
            self.storage = jax.device_put(_zero_storage(), shard)
            self.trees = (jax.device_put(_zero_trees(), shard)
                          if prioritized else None)
        # ring cursors / live sizes for the OWNED shards (host ints; the
        # device twin of sizes is the chunk's [n_shards] ``size`` operand)
        self._head = np.zeros(self.n_local, np.int64)
        self._size = np.zeros(self.n_local, np.int64)
        self._size_global = None  # cached global [n_shards] device array
        # round-robin cursor: which LOCAL shard receives the next staged row
        self._rr = 0
        self._staged: list[TransitionBatch] = []
        self._staged_rows = 0
        self._insert_fn = None

    @property
    def local_capacity(self) -> int:
        """Rows this host's shard-set can hold (== capacity single-host)."""
        return self.cap_shard * self.n_local

    # -- ingest side (drain thread, under the service's buffer lock) -------
    def add(self, batch: TransitionBatch) -> None:
        """Stage host rows; bounded at ~local capacity like the
        single-device fused buffer (oldest staged dropped — the next drain
        would overwrite them anyway)."""
        nrows = batch.obs.shape[0]
        if nrows == 0:
            return
        if nrows > self.local_capacity:
            raise ValueError(
                f"batch of {nrows} exceeds capacity {self.local_capacity}")
        self._staged.append(
            TransitionBatch(*[np.asarray(v) for v in batch]))
        self._staged_rows += nrows
        while (self._staged_rows - self._staged[0].obs.shape[0]
               >= self.local_capacity):
            self._staged_rows -= self._staged.pop(0).obs.shape[0]

    def __len__(self) -> int:
        """THIS host's row count (live + staged) — the per-host warmup
        gate; the global count is the sum over hosts."""
        return int(min(self._size.sum() + self._staged_rows,
                       self.local_capacity))

    @property
    def size(self):
        """Per-shard live sizes [n_shards] (the chunk's ``size`` operand).
        Multi-host: a globally-sharded device array assembled from each
        host's local sizes (cached until the next drain/restore)."""
        if not self._multiproc:
            return self._size.astype(np.int32)
        if self._size_global is None:
            import jax

            from d4pg_tpu.parallel import partition

            self._size_global = jax.make_array_from_process_local_data(
                partition.batch_sharding(self.mesh),
                self._size.astype(np.int32), (self.n_shards,))
        return self._size_global

    # -- learner side ------------------------------------------------------
    def _make_insert(self):
        """shard_map'd insert: each device scatters its rows into its ring
        shard and stamps ``max_priority ** alpha`` into its trees. Pad
        rows carry local idx == cap_shard, which both the ring scatter
        (``mode='drop'``) and the tree write (``set_leaves``'s pad-drop
        convention) discard."""
        import jax
        from d4pg_tpu.parallel.compat import shard_map

        from d4pg_tpu.parallel import partition
        from d4pg_tpu.replay import device_per as dper

        alpha = self.alpha

        def local_insert(storage, trees, idx, rows):
            # locals: storage [1, c, ...], trees [1, ...], idx [1, m],
            # rows [1, m, ...]; pad entries carry idx == cap_shard and are
            # dropped by both the ring scatter and the tree write
            new_storage = TransitionBatch(*[
                arr.at[0, idx[0]].set(v[0].astype(arr.dtype), mode="drop")
                for arr, v in zip(storage, rows)
            ])
            if trees is None:
                return new_storage, None
            t = dper.PerTrees(trees.sum_tree[0], trees.min_tree[0],
                              trees.max_priority[0])
            t = dper.insert(t, idx[0], alpha)
            return new_storage, ShardedPerTrees(
                t.sum_tree[None], t.min_tree[None], t.max_priority[None])

        specs = partition.data_spec()
        if self.trees is not None:
            fn = shard_map(
                local_insert, mesh=self.mesh,
                in_specs=(specs, specs, specs, specs),
                out_specs=(specs, specs), check_vma=False)
            return jax.jit(fn, donate_argnums=(0, 1))
        fn2 = shard_map(
            lambda s, i, r: local_insert(s, None, i, r)[0],
            mesh=self.mesh, in_specs=(specs, specs, specs),
            out_specs=specs, check_vma=False)
        return jax.jit(fn2, donate_argnums=(0,))

    def drain(self) -> int:
        """Flush staged rows round-robin across this host's shards.
        Learner thread only (single owner of the donated handles).

        MULTI-HOST: a COLLECTIVE call — every host must reach it at the
        same point (train.py's chunk boundaries are lockstep). One scalar
        allgather agrees on the pad width so all hosts execute the same
        SPMD insert; a host with nothing staged contributes all-pad rows.
        """
        if not self._staged and not self._multiproc:
            return 0
        if self._staged:
            batch = (self._staged[0] if len(self._staged) == 1 else
                     TransitionBatch(*[
                         np.concatenate(
                             [np.asarray(b[f]) for b in self._staged])
                         for f in range(len(self._staged[0]))]))
            nrows = batch.obs.shape[0]
        else:
            batch, nrows = None, 0
        self._staged.clear()
        self._staged_rows = 0
        if nrows > self.local_capacity:
            # keep exactly the newest rows that fit: a larger backlog
            # would hand some shard more than cap_shard rows, i.e.
            # duplicate slots in one scatter (unspecified winner)
            batch = TransitionBatch(
                *[v[-self.local_capacity:] for v in batch])
            nrows = self.local_capacity
        n, cap = self.n_local, self.cap_shard

        # pad width m: power of two for the jit cache; multi-host takes
        # the max over hosts so every process runs the same program
        m = next_pow2(int(np.ceil(nrows / n))) if nrows else 0
        if self._multiproc:
            from jax.experimental import multihost_utils

            m = int(np.max(multihost_utils.process_allgather(
                np.int64(m))))
        if m == 0:
            return 0

        # round-robin shard assignment, then per-shard local slots; with
        # nothing staged locally (multi-host, a peer had rows) the arrays
        # stay all-pad — shapes/dtypes come from the ring itself
        local_idx = np.full((n, m), cap, np.int32)  # cap -> dropped pad
        rows = TransitionBatch(*[
            np.zeros((n, m, *arr.shape[2:]), arr.dtype)
            for arr in self.storage
        ])
        if nrows:
            shard_of = (self._rr + np.arange(nrows)) % n
            self._rr = int((self._rr + nrows) % n)
            for s in range(n):
                take = np.flatnonzero(shard_of == s)
                cnt = len(take)
                if cnt == 0:
                    continue
                local_idx[s, :cnt] = (self._head[s] + np.arange(cnt)) % cap
                for f in range(len(rows)):
                    rows[f][s, :cnt] = np.asarray(batch[f])[take]
                self._head[s] = int((self._head[s] + cnt) % cap)
                self._size[s] = int(min(self._size[s] + cnt, cap))
            self._size_global = None

        if self._multiproc:
            local_idx, rows = self._assemble_global(local_idx, rows)
        if self._insert_fn is None:
            self._insert_fn = self._make_insert()
        if self.trees is not None:
            self.storage, self.trees = self._insert_fn(
                self.storage, self.trees, local_idx, rows)
        else:
            self.storage = self._insert_fn(self.storage, local_idx, rows)
        return nrows

    def _assemble_global(self, local_idx, rows):
        """Lift this host's [n_local, m, ...] staging arrays to global
        [n_shards, m, ...] arrays sharded over the data axis (each process
        contributes its own block; nothing crosses DCN)."""
        import jax

        from d4pg_tpu.parallel import partition

        shard = partition.batch_sharding(self.mesh)

        def to_global(x):
            x = np.asarray(x)
            return jax.make_array_from_process_local_data(
                shard, x, (self.n_shards, *x.shape[1:]))

        return to_global(local_idx), TransitionBatch(
            *[to_global(v) for v in rows])

    # -- checkpointing -----------------------------------------------------
    def _local_block(self, arr, axis: int = 0):
        """This host's contiguous block of a data-axis-sharded array as
        host numpy (dedups model-axis replicas by shard start index)."""
        seen = {}
        for s in arr.addressable_shards:
            start = s.index[axis].start or 0
            if start not in seen:
                seen[start] = np.asarray(s.data)
        return np.concatenate([seen[k] for k in sorted(seen)], axis=axis)

    def state_dict(self) -> dict:
        """Checkpoint payload for THIS host's shard-set. Single-host that
        is the whole buffer; multi-host each host snapshots only its own
        shards (the per-host sidecar scheme in ``train.py``) — collective
        (the leading drain), so all hosts must checkpoint in lockstep."""
        self.drain()
        host = TransitionBatch(
            *[self._local_block(v) for v in self.storage])
        d = pack_rows(host, 0, 0, self.capacity)
        d["sharded"] = {
            "head": self._head.copy(),
            "size": self._size.copy(),
            "rr": self._rr,
            "n_shards": self.n_shards,
            "n_local": self.n_local,
            "local_start": self.local_start,
        }
        if self.trees is not None:
            d["sharded"]["leaf_priorities"] = self._local_block(
                self.trees.sum_tree)[:, self.cap_shard:]
            d["sharded"]["max_priority"] = self._local_block(
                self.trees.max_priority)
        return d

    def load_state_dict(self, d: dict) -> None:
        """Restore this host's shard-set. Multi-host: collective — every
        host loads ITS OWN snapshot at the same point (train.py agrees on
        snapshot availability across hosts before any host calls this)."""
        import jax
        import jax.numpy as jnp

        from d4pg_tpu.parallel import partition

        s = d.get("sharded")
        if s is None:
            raise ValueError(
                "replay checkpoint was saved by a non-sharded buffer; "
                "resume with the same replay layout (data_parallel=1 or "
                "host storage)")
        if int(s["n_shards"]) != self.n_shards:
            raise ValueError(
                "sharded replay checkpoint requires the same data-parallel "
                f"degree (got {s['n_shards']}, have {self.n_shards})")
        n_local = int(s.get("n_local", s["n_shards"]))
        start = int(s.get("local_start", 0))
        if n_local != self.n_local or start != self.local_start:
            raise ValueError(
                f"replay snapshot covers shards [{start}, {start + n_local})"
                f" but this host owns [{self.local_start}, "
                f"{self.local_start + self.n_local}); resume with the same "
                "host topology (process count and devices per host)")
        validate_rows({k: v for k, v in d.items() if k != "sharded"},
                      self.capacity)
        shard = partition.batch_sharding(self.mesh)
        n, c = self.n_local, self.cap_shard

        def to_global(x):
            x = np.asarray(x)
            if not self._multiproc:
                return jax.device_put(jnp.asarray(x), shard)
            return jax.make_array_from_process_local_data(
                shard, x, (self.n_shards, *x.shape[1:]))

        self.storage = TransitionBatch(
            *[to_global(d["rows"][f]) for f in TransitionBatch._fields])
        self._head = np.asarray(s["head"]).astype(np.int64).copy()
        self._size = np.asarray(s["size"]).astype(np.int64).copy()
        self._size_global = None
        self._rr = int(s["rr"])
        if self.trees is not None:
            leaves = np.asarray(s["leaf_priorities"], np.float32)
            sum_tree = np.zeros((n, 2 * c), np.float32)
            min_tree = np.full((n, 2 * c), np.inf, np.float32)
            for sh in range(n):
                sz = int(self._size[sh])
                sum_tree[sh, c:c + sz] = leaves[sh, :sz]
                min_tree[sh, c:c + sz] = leaves[sh, :sz]
            # rebuild internal nodes level by level, vectorized across
            # shards (a per-node Python loop would be ~1M iterations at
            # production capacities)
            lo = c
            while lo > 1:
                lo //= 2
                kids_s = sum_tree[:, 2 * lo:4 * lo].reshape(n, -1, 2)
                kids_m = min_tree[:, 2 * lo:4 * lo].reshape(n, -1, 2)
                sum_tree[:, lo:2 * lo] = kids_s.sum(-1)
                min_tree[:, lo:2 * lo] = kids_m.min(-1)
            self.trees = ShardedPerTrees(
                sum_tree=to_global(sum_tree),
                min_tree=to_global(min_tree),
                max_priority=to_global(
                    np.asarray(s["max_priority"], np.float32)),
            )
