"""Sharded device-resident replay: ring + PER trees distributed over the
learner mesh's ``data`` axis.

The multi-chip extension of the fused replay path (``device_ring.py`` /
``device_per.py`` hold everything on ONE device). Here every device of
the data axis owns a shard of the transition ring and its own PER
sum/min tree pair; sampling, gathering and priority write-back run
per-shard inside the sharded learner dispatch (``learner/fused.py``'s
``make_sharded_fused_chunk``) — so the production configuration
(K-step scan x data parallelism) keeps ZERO per-chunk host round trips
and the batch rows never cross devices (each shard contributes
``B / n_shards`` rows; only gradients ride the ICI collectives).

This is the Ape-X sharded-replay layout made device-native. Sampling
semantics: each shard draws B/N proportional samples from ITS shard
(stratified across shards by construction); the importance weights
correct for the true per-draw probability ``(1/N) * p_i / total_h``
with a GLOBAL max-weight normalizer computed by ``lax.pmin`` over the
data axis — reducing exactly to the reference formula
(``prioritized_replay_memory.py:299-313``) at N=1.

Host-side bookkeeping mirrors ``fused_buffer.FusedDeviceReplay``:
``add`` stages rows (bounded), ``drain`` flushes at chunk boundaries on
the learner thread (single owner of the donated device handles),
splitting rows round-robin so shard sizes stay balanced.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from d4pg_tpu.replay.segment_tree import next_pow2
from d4pg_tpu.replay.uniform import TransitionBatch, pack_rows, validate_rows


class ShardedPerTrees(NamedTuple):
    """Per-shard tree pair, leading axis = shard (sharded over ``data``)."""

    sum_tree: "jax.Array"  # [n_shards, 2 * cap_shard]
    min_tree: "jax.Array"  # [n_shards, 2 * cap_shard]
    max_priority: "jax.Array"  # [n_shards] per-shard running max

    @property
    def cap_shard(self) -> int:
        return self.sum_tree.shape[1] // 2


class ShardedFusedReplay:
    """Device-sharded ring + trees for the mesh fused learner path."""

    def __init__(
        self,
        capacity: int,
        obs_dim: int | tuple,
        act_dim: int,
        mesh,
        alpha: float = 0.6,
        prioritized: bool = True,
        obs_dtype=None,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from d4pg_tpu.parallel.mesh import DATA_AXIS

        self.mesh = mesh
        self.n_shards = int(mesh.shape[DATA_AXIS])
        # per-shard capacity, power of two for the tree layout
        self.cap_shard = next_pow2(
            max(1, int(np.ceil(capacity / self.n_shards))))
        self.capacity = self.cap_shard * self.n_shards
        obs_shape = (obs_dim,) if np.isscalar(obs_dim) else tuple(obs_dim)
        if obs_dtype is None:
            obs_dtype = np.float32 if len(obs_shape) == 1 else np.uint8
        self.prioritized = bool(prioritized)
        self.alpha = float(alpha)

        shard = NamedSharding(mesh, P(DATA_AXIS))
        n, c = self.n_shards, self.cap_shard
        self.storage = jax.device_put(TransitionBatch(
            obs=jnp.zeros((n, c, *obs_shape), obs_dtype),
            action=jnp.zeros((n, c, act_dim), jnp.float32),
            reward=jnp.zeros((n, c), jnp.float32),
            next_obs=jnp.zeros((n, c, *obs_shape), obs_dtype),
            done=jnp.zeros((n, c), jnp.float32),
            discount=jnp.zeros((n, c), jnp.float32),
        ), shard)
        self.trees = (
            jax.device_put(ShardedPerTrees(
                sum_tree=jnp.zeros((n, 2 * c), jnp.float32),
                min_tree=jnp.full((n, 2 * c), jnp.inf, jnp.float32),
                max_priority=jnp.ones((n,), jnp.float32),
            ), shard)
            if prioritized else None
        )
        # per-shard ring cursors / live sizes (host ints; device twin of
        # sizes is passed to the chunk as a [n_shards] array)
        self._head = np.zeros(n, np.int64)
        self._size = np.zeros(n, np.int64)
        # round-robin cursor: which shard receives the next staged row
        self._rr = 0
        self._staged: list[TransitionBatch] = []
        self._staged_rows = 0
        self._insert_fn = None

    # -- ingest side (drain thread, under the service's buffer lock) -------
    def add(self, batch: TransitionBatch) -> None:
        """Stage host rows; bounded at ~capacity like the single-device
        fused buffer (oldest staged dropped — the next drain would
        overwrite them anyway)."""
        nrows = batch.obs.shape[0]
        if nrows == 0:
            return
        if nrows > self.capacity:
            raise ValueError(
                f"batch of {nrows} exceeds capacity {self.capacity}")
        self._staged.append(
            TransitionBatch(*[np.asarray(v) for v in batch]))
        self._staged_rows += nrows
        while (self._staged_rows - self._staged[0].obs.shape[0]
               >= self.capacity):
            self._staged_rows -= self._staged.pop(0).obs.shape[0]

    def __len__(self) -> int:
        return int(min(self._size.sum() + self._staged_rows, self.capacity))

    @property
    def size(self):
        """Per-shard live sizes [n_shards] (the chunk's ``size`` operand)."""
        return self._size.astype(np.int32)

    # -- learner side ------------------------------------------------------
    def _make_insert(self):
        """shard_map'd insert: each device scatters its rows into its ring
        shard and stamps ``max_priority ** alpha`` into its trees. Pad
        rows carry local idx == cap_shard, which both the ring scatter
        (``mode='drop'``) and the tree write (``set_leaves``'s pad-drop
        convention) discard."""
        import jax
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from d4pg_tpu.parallel.mesh import DATA_AXIS
        from d4pg_tpu.replay import device_per as dper

        alpha = self.alpha

        def local_insert(storage, trees, idx, rows):
            # locals: storage [1, c, ...], trees [1, ...], idx [1, m],
            # rows [1, m, ...]; pad entries carry idx == cap_shard and are
            # dropped by both the ring scatter and the tree write
            new_storage = TransitionBatch(*[
                arr.at[0, idx[0]].set(v[0].astype(arr.dtype), mode="drop")
                for arr, v in zip(storage, rows)
            ])
            if trees is None:
                return new_storage, None
            t = dper.PerTrees(trees.sum_tree[0], trees.min_tree[0],
                              trees.max_priority[0])
            t = dper.insert(t, idx[0], alpha)
            return new_storage, ShardedPerTrees(
                t.sum_tree[None], t.min_tree[None], t.max_priority[None])

        specs = P(DATA_AXIS)
        if self.trees is not None:
            fn = shard_map(
                local_insert, mesh=self.mesh,
                in_specs=(specs, specs, specs, specs),
                out_specs=(specs, specs), check_vma=False)
            return jax.jit(fn, donate_argnums=(0, 1))
        fn2 = shard_map(
            lambda s, i, r: local_insert(s, None, i, r)[0],
            mesh=self.mesh, in_specs=(specs, specs, specs),
            out_specs=specs, check_vma=False)
        return jax.jit(fn2, donate_argnums=(0,))

    def drain(self) -> int:
        """Flush staged rows round-robin across shards. Learner thread
        only (single owner of the donated handles)."""
        if not self._staged:
            return 0
        batch = (self._staged[0] if len(self._staged) == 1 else
                 TransitionBatch(*[
                     np.concatenate([np.asarray(b[f]) for b in self._staged])
                     for f in range(len(self._staged[0]))]))
        self._staged.clear()
        self._staged_rows = 0
        nrows = batch.obs.shape[0]
        if nrows > self.capacity:
            # keep exactly the newest `capacity` rows: a larger backlog
            # would hand some shard more than cap_shard rows, i.e.
            # duplicate slots in one scatter (unspecified winner)
            batch = TransitionBatch(*[v[-self.capacity:] for v in batch])
            nrows = self.capacity
        n, cap = self.n_shards, self.cap_shard

        # round-robin shard assignment, then per-shard local slots
        shard_of = (self._rr + np.arange(nrows)) % n
        self._rr = int((self._rr + nrows) % n)
        m = next_pow2(int(np.ceil(nrows / n)))
        local_idx = np.full((n, m), cap, np.int32)  # cap -> dropped pad
        rows = TransitionBatch(*[
            np.zeros((n, m, *np.asarray(v).shape[1:]), np.asarray(v).dtype)
            for v in batch
        ])
        for s in range(n):
            take = np.flatnonzero(shard_of == s)
            cnt = len(take)
            if cnt == 0:
                continue
            local_idx[s, :cnt] = (self._head[s] + np.arange(cnt)) % cap
            for f in range(len(rows)):
                rows[f][s, :cnt] = np.asarray(batch[f])[take]
            self._head[s] = int((self._head[s] + cnt) % cap)
            self._size[s] = int(min(self._size[s] + cnt, cap))

        if self._insert_fn is None:
            self._insert_fn = self._make_insert()
        if self.trees is not None:
            self.storage, self.trees = self._insert_fn(
                self.storage, self.trees, local_idx, rows)
        else:
            self.storage = self._insert_fn(self.storage, local_idx, rows)
        return nrows

    # -- checkpointing -----------------------------------------------------
    def state_dict(self) -> dict:
        import jax

        self.drain()
        host = jax.device_get(self.storage)
        d = pack_rows(
            TransitionBatch(*[np.asarray(v) for v in host]),
            0, 0, self.capacity)
        d["sharded"] = {
            "head": self._head.copy(),
            "size": self._size.copy(),
            "rr": self._rr,
            "n_shards": self.n_shards,
        }
        if self.trees is not None:
            t = jax.device_get(self.trees)
            d["sharded"]["leaf_priorities"] = np.asarray(
                t.sum_tree[:, self.cap_shard:])
            d["sharded"]["max_priority"] = np.asarray(t.max_priority)
        return d

    def load_state_dict(self, d: dict) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from d4pg_tpu.parallel.mesh import DATA_AXIS

        s = d.get("sharded")
        if s is None:
            raise ValueError(
                "replay checkpoint was saved by a non-sharded buffer; "
                "resume with the same replay layout (data_parallel=1 or "
                "host storage)")
        if int(s["n_shards"]) != self.n_shards:
            raise ValueError(
                "sharded replay checkpoint requires the same data-parallel "
                f"degree (got {s['n_shards']}, have {self.n_shards})")
        validate_rows({k: v for k, v in d.items() if k != "sharded"},
                      self.capacity)
        shard = NamedSharding(self.mesh, P(DATA_AXIS))
        self.storage = jax.device_put(TransitionBatch(
            *[jnp.asarray(d["rows"][f]) for f in TransitionBatch._fields]),
            shard)
        self._head = np.asarray(s["head"]).astype(np.int64).copy()
        self._size = np.asarray(s["size"]).astype(np.int64).copy()
        self._rr = int(s["rr"])
        if self.trees is not None:
            n, c = self.n_shards, self.cap_shard
            leaves = np.asarray(s["leaf_priorities"], np.float32)
            sum_tree = np.zeros((n, 2 * c), np.float32)
            min_tree = np.full((n, 2 * c), np.inf, np.float32)
            for sh in range(n):
                sz = int(self._size[sh])
                sum_tree[sh, c:c + sz] = leaves[sh, :sz]
                min_tree[sh, c:c + sz] = leaves[sh, :sz]
            # rebuild internal nodes level by level, vectorized across
            # shards (a per-node Python loop would be ~1M iterations at
            # production capacities)
            lo = c
            while lo > 1:
                lo //= 2
                kids_s = sum_tree[:, 2 * lo:4 * lo].reshape(n, -1, 2)
                kids_m = min_tree[:, 2 * lo:4 * lo].reshape(n, -1, 2)
                sum_tree[:, lo:2 * lo] = kids_s.sum(-1)
                min_tree[:, lo:2 * lo] = kids_m.min(-1)
            self.trees = jax.device_put(ShardedPerTrees(
                sum_tree=jnp.asarray(sum_tree),
                min_tree=jnp.asarray(min_tree),
                max_priority=jnp.asarray(s["max_priority"], jnp.float32),
            ), shard)
