"""Replay: storage, prioritization, n-step folding, staging — host or HBM.

Three interchangeable data-plane tiers (docs/architecture.md): host numpy
ring + vectorized/C++ segment trees (the reference-shaped layout,
``replay_memory.py:14-19`` / ``prioritized_replay_memory.py``), a
device-resident ring with host trees (``device_ring``), and fully
device-resident ring + trees fused into the learner dispatch
(``device_per``/``fused_buffer``; sharded over the mesh in
``sharded_per``).
"""

from d4pg_tpu.replay.schedule import LinearSchedule
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch
from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.nstep import NStepFolder
from d4pg_tpu.replay.staging import DeviceStager
from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay
from d4pg_tpu.replay.sharded_per import ShardedFusedReplay

__all__ = [
    "LinearSchedule",
    "ReplayBuffer",
    "TransitionBatch",
    "SumTree",
    "MinTree",
    "PrioritizedReplayBuffer",
    "NStepFolder",
    "DeviceStager",
    "FusedDeviceReplay",
    "ShardedFusedReplay",
]
