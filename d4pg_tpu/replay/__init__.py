"""Host-side replay: storage, prioritization, n-step folding, device staging.

Replay lives in TPU-VM host RAM (preallocated numpy arrays, not the
reference's Python tuple lists, ``replay_memory.py:14-19``), with vectorized
segment trees for PER sampling and an async host->device staging pipeline so
batch transfer hides under the XLA learner step.
"""

from d4pg_tpu.replay.schedule import LinearSchedule
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch
from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.nstep import NStepFolder
from d4pg_tpu.replay.staging import DeviceStager

__all__ = [
    "LinearSchedule",
    "ReplayBuffer",
    "TransitionBatch",
    "SumTree",
    "MinTree",
    "PrioritizedReplayBuffer",
    "NStepFolder",
    "DeviceStager",
]
