"""Device-resident PER sampling: the stratified descent fused onto the
commit dispatch.

PR 12 (``replay/sampler.SampleDealer``) moved sampling off the learner
onto the commit thread, but the draw still walks HOST trees and the
sampled rows still round-trip through host RAM — the host-side sampling
bottleneck "In-Network Experience Sampling" (PAPERS.md, arXiv
2110.13506) measures as the dominant ingest cost. This module finishes
the move: the sum/min trees stay the DEVICE arrays the fused commit
already maintains (``replay/fused_buffer.FusedDeviceReplay`` in
``gen_tracked`` mode), the seeded stratified descent runs on device
immediately after the commit dispatch, and dealt blocks are emitted as
device-resident gathers — zero host tree math, zero sampled-row H2D
(TransferSentinel-checked in bench.py), and the replica sample path
keeps PR 12's zero buffer-lock acquisitions (ring pop + sampler-tier
write-back enqueue only).

Division of labor per ``ingest_and_deal`` tick (commit thread, inside
the ONE buffer-lock window the commit already owned):

  1. mirror the tick's inserts into the HOST bookkeeping (generation
     fence, ticket seqs, trace ids) — index arithmetic, no tree math;
  2. ``buffer.drain()``: the fused commit dispatch lands the staged rows
     AND their entry priorities (``max_priority ** alpha``, computed on
     the host in float64 and cast float32) AND bumps the device
     generation array;
  3. settle queued priority write-backs: generation-fenced on the host
     mirror, last-wins deduplicated (XLA leaves duplicate-scatter
     winners unspecified; numpy fancy assignment — the twin — is
     last-wins), padded to a fixed bucket, ONE jitted scatter into the
     device trees;
  4. draw: unit uniforms from the dealer's seeded HOST stream (the
     bitwise-oracle stream; skipped-before-RNG backpressure rules are
     inherited unchanged), then ONE jitted deal dispatch — strata mass,
     descent, row gather, leaf-priority gather, generation snapshot —
     plus the shared weight transform (``device_per.block_weights``).

Bitwise oracle: with the same seed and insert/write-back order, blocks
equal ``SampleDealer(scheme='device')`` — the float32 HOST twin — in
``(idx, weights, beta, rows, gen)`` exactly (tests/test_devsample.py).
The twin-vs-float64-legacy relation is pinned separately on
dyadic-rational priorities, where float32 and float64 trees agree
exactly. What is NOT preserved from the float64 host dealer is the
rounding of tree aggregates for arbitrary priorities — a documented
consequence of float32 device trees, not of the descent logic (the tie
rule ``mass >= left_sum`` -> RIGHT is shared by every implementation,
see ``device_per.descend``).

The descent implementation is an autotune surface (``--sampler``,
``ops/autotune.select_sampler``): ``'scan'`` is the jnp gather descent,
``'pallas'`` the VMEM-resident kernel (``ops/sampler_descent``), and
``'host'`` the PR-12 host dealer as the fallback arm (constructed by the
caller, not here). Host->device bytes on the deal path are the [K, B]
float32 uniforms and two scalars per block — O(K*B) floats against the
O(K*B*obs_dim) row bytes the host dealer ships, and none of it an
explicit ``device_put`` of sampled rows.

Trace spans: sampled indices never visit the host (the audit mode below
is the chaos-only exception), so the ``deal`` span is stamped on the
NEWEST COMMITTED insert's trace id rather than the newest sampled
constituent — still a real, committed frame (commit_to_deal >= 0), still
zero-orphan. ``audit=True`` pays one explicit per-deal D2H of the
sampled indices to run the dead-ticket cross-check; it is a chaos-rig
knob, never a shipped-path default.
"""

from __future__ import annotations

import time

import numpy as np

from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.sampler import DealtBlock, SampleDealer

# Write-back scatter bucket: settles pad (idx = tree capacity, dropped)
# or split to this many rows so the jitted scatter compiles ONCE.
_WB_BUCKET = 2048


class DeviceSampleDealer(SampleDealer):
    """``SampleDealer`` with the sample path on the device.

    Drop-in for the host dealer at every ``ReplayService`` touchpoint
    (``attach_dealer``/``ingest_and_deal``/``publish``/
    ``queue_writeback``/``resync``/``close``); requires the buffer to be
    a ``FusedDeviceReplay(gen_tracked=True)``. Single-writer discipline
    tightens to: the COMMIT THREAD owns every device handle (storage,
    trees, generation array) — stage, commit, deal and write-back
    dispatches all run inside its buffer-lock windows, which is why
    :meth:`drain_writebacks_for_shard` is a no-op here (settles ride the
    commit/idle ticks instead of shard workers; there is no host tree to
    shard-own). Replicas still only ever enqueue write-backs under the
    ``sampler`` tier.

    The inherited host slice trees stay empty (float32, ~16 bytes/slot;
    the geometry still routes write-back queues and sizes the
    generation mirror) — the authoritative trees are the buffer's device
    arrays.
    """

    # The attached service's commit thread is the ONLY ingest-dispatch
    # driver: this dealer drains the staged slot inside every
    # ingest's buffer-lock window. learner/pipeline.IngestOverlap
    # checks this flag and refuses to claim the slot.
    owns_commit = True

    def __init__(self, capacity: int, rings, *, k: int, batch_size: int,
                 alpha: float = 0.6, beta_schedule=None, min_size: int = 1,
                 seed: int = 0, ring_capacity: int = 4,
                 max_deals_per_tick: int = 1, audit: bool = False,
                 arm: str = "scan", interpret: bool | None = None):
        if arm not in ("scan", "pallas"):
            raise ValueError(f"unknown device sampler arm {arm!r} "
                             "(want 'scan' or 'pallas'; 'host' is the "
                             "plain SampleDealer, constructed by the "
                             "caller)")
        super().__init__(capacity, rings, n_shards=1, k=k,
                         batch_size=batch_size, alpha=alpha,
                         beta_schedule=beta_schedule, min_size=min_size,
                         seed=seed, ring_capacity=ring_capacity,
                         max_deals_per_tick=max_deals_per_tick,
                         audit=audit, scheme="device")
        self.arm = arm
        if interpret is None:
            import jax

            interpret = jax.default_backend() == "cpu"
        self._interpret = bool(interpret)
        self._buffer = None
        self._deal_fn = self._make_deal()

    # -- the fused deal dispatch -------------------------------------------
    def _make_deal(self):
        import jax
        import jax.numpy as jnp

        k, b, arm = self.k, self.batch_size, self.arm
        treecap = self._trees.capacity  # next_pow2(ring capacity)
        interpret = self._interpret

        if arm == "pallas":
            from d4pg_tpu.ops.sampler_descent import descend_pallas

            def _descend(sum_tree, mass):
                # flat [K*B] queries; bitwise-equal to the jnp arm by
                # the kernel's one-hot-gather construction
                return descend_pallas(sum_tree, mass.reshape(-1),
                                      interpret).reshape(k, b)
        else:
            def _descend(sum_tree, mass):
                return dper.descend(sum_tree, mass)

        def deal(storage, sum_tree, min_tree, gen, u, size):
            total = sum_tree[1]
            mass = dper.strata_mass(u, total)  # [K, B] float32
            idx = _descend(sum_tree, mass)
            idx = jnp.minimum(idx, jnp.maximum(size - 1, 0))
            # device-resident gathers: the dealt rows never exist on the
            # host (DealtBlock.batches are device arrays [K, B, ...])
            rows = jax.tree_util.tree_map(lambda a: a[idx], storage)
            leaf_p = sum_tree[treecap + idx]
            gen_blk = gen[idx]
            return rows, idx, leaf_p, gen_blk, total, min_tree[1]

        return jax.jit(deal)

    @property
    def deal_fn(self):
        """The jitted deal dispatch — exposed so bench/tests can run
        ``ReshardSentinel.inspect`` over its compiled HLO (the fused
        sample dispatch must contain 0 resharding collectives)."""
        return self._deal_fn

    # -- commit-thread hooks (sampler lock held, buffer lock above it) ------
    def _apply_insert_locked(self, idx: np.ndarray) -> None:
        # entry priorities land in the DEVICE trees via the fused commit
        # (_post_ingest_locked drains); the host slice trees stay empty
        pass

    def _post_ingest_locked(self, buffer) -> None:
        self._buffer = buffer
        # land every staged row + entry priority + generation bump NOW,
        # in the same buffer-lock window as the adds: slot pre-assignment
        # order (buffer.add) == commit order, the invariant gen_tracked
        # mode is built on
        buffer.drain()

    def _settle_locked(self, owner: int | None = None) -> None:
        buffer = self._buffer
        if buffer is None or self._wb_depth == 0:
            return
        idx_parts, pri_parts = [], []
        for q in self._wb:
            while q:
                idx, pri, gen, t_enq = q.popleft()
                self._wb_depth -= 1
                self._wb_lag.observe(1e3 * (time.monotonic() - t_enq))
                live = self._gen[idx] == gen
                if not live.all():
                    # counter bump is guarded by the caller: base
                    # ingest_and_deal holds the sampler lock across
                    # every _settle_locked call
                    self.writeback_dropped_stale += int((~live).sum())  # jaxlint: guarded-by=_sampler_lock
                    idx, pri = idx[live], pri[live]
                if len(idx):
                    idx_parts.append(idx)
                    pri_parts.append(pri)
        if not idx_parts:
            return
        idx = np.concatenate(idx_parts)
        pri = np.concatenate(pri_parts)
        # last-wins dedup in queue order: numpy fancy assignment (the
        # host twin) keeps the LAST duplicate write; XLA scatter leaves
        # the winner unspecified, so the duplicates must never reach it
        last = {int(s): j for j, s in enumerate(idx)}
        keep = np.fromiter(last.values(), np.int64, len(last))
        idx_u = idx[keep]
        # host float64 pow, float32 cast — the same rounding the twin's
        # trees.set applies, so both trees hold identical leaf bits
        p_u = (pri[keep] ** self.alpha).astype(np.float32)
        treecap = self._trees.capacity
        for c0 in range(0, len(idx_u), _WB_BUCKET):
            ci = idx_u[c0:c0 + _WB_BUCKET].astype(np.int32)
            cp = p_u[c0:c0 + _WB_BUCKET]
            if len(ci) < _WB_BUCKET:  # pad rows park at treecap: dropped
                pad = _WB_BUCKET - len(ci)
                ci = np.concatenate([ci, np.full(pad, treecap, np.int32)])
                cp = np.concatenate([cp, np.zeros(pad, np.float32)])
            buffer.apply_priorities(ci, cp)
        self.max_priority = max(self.max_priority, float(pri.max()))
        # the buffer's host scalar feeds the NEXT commit's p_ins operand
        buffer.max_priority = self.max_priority

    def _draw_block_locked(self, buffer):
        # priorities are strictly positive in the dealt plane (entry
        # p_ins > 0, write-backs assert > 0), so size > 0 <=> total > 0
        # — the host guard without a device sync
        size = self._size
        if size <= 0:
            return None
        t = self._beta.current_step()
        beta = self._beta.beta_at(t)
        # K*B doubles off the seeded host stream, cast f32 — the same
        # consumption (count AND values) as K twin strata draws
        u = self._rng.uniform(0.0, 1.0, (self.k, self.batch_size)).astype(
            np.float32)
        rows, idx, leaf_p, gen_blk, total, min_root = self._deal_fn(
            buffer.storage, buffer.trees.sum_tree, buffer.trees.min_tree,
            buffer.gen, u, np.int32(size))
        w = dper.block_weights_jitted(total, min_root, leaf_p,
                                      np.float32(beta), np.int32(size))
        if self._audit and self._dead:
            # audit is the one deliberate D2H on this path (chaos only):
            # the dead-ticket cross-check needs the sampled slots' seqs
            flat = np.asarray(idx).ravel()
            hits = {int(s) for s in self._src_seq[flat]} & self._dead
            self.dealt_dead_tickets += len(hits)  # jaxlint: guarded-by=_sampler_lock
        tid = self._last_tid  # newest committed insert (module docstring)
        self._beta.advance(self.k)
        self._deal_seq += 1
        self.dealt_blocks += 1  # jaxlint: guarded-by=_sampler_lock
        self.dealt_rows += self.k * self.batch_size  # jaxlint: guarded-by=_sampler_lock
        return DealtBlock(rows, w, idx, gen_blk, beta, t, tid,
                          self._deal_seq)

    # -- shard-worker side --------------------------------------------------
    def drain_writebacks_for_shard(self, shard_idx: int) -> None:
        """No-op: device tree writes belong to the commit thread (the
        single owner of the device handles); settles ride its commit and
        idle ticks instead of shard workers."""

    # -- lifecycle ----------------------------------------------------------
    def resync(self, buffer) -> None:
        """Adopt ``buffer``'s device PER state (attach / restore). The
        trees stay where they are — in the buffer — so unlike the host
        dealer there is nothing to rebuild; only the host mirrors
        (generation fence, max_priority, bookkeeping) re-derive."""
        if not getattr(buffer, "gen_tracked", False):
            raise ValueError(
                "DeviceSampleDealer needs a FusedDeviceReplay("
                "gen_tracked=True) buffer — the deal dispatch reads its "
                "device trees and generation array")
        with self._sampler_lock:
            self._buffer = buffer
            self._size = int(buffer.size)
            self.max_priority = float(buffer.max_priority)
            self._gen = np.asarray(buffer.generation).copy()
            self._src_seq.fill(-1)
            self._tid_of.fill(0)
            self._ins_seq.fill(0)
            self._last_tid = 0
            for q in self._wb:
                q.clear()
            self._wb_depth = 0
