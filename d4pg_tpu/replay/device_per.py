"""Device-resident PER sum/min trees: priority state lives in HBM.

TPU-native redesign of the prioritized-replay data path (the reference
keeps its segment trees in host Python lists and walks them one sample at
a time, ``prioritized_replay_memory.py:33-162``). On a tunneled or
PCIe-attached accelerator every host round trip costs more than the whole
K-step update, so the trees move onto the device next to the transition
ring (``replay/device_ring.py``) and the ENTIRE per-step replay protocol
— stratified proportional sampling, importance weights, priority
write-back — becomes pure ``jnp`` ops that fuse into the scanned learner
update (``learner/fused.py``). One dispatch then carries K grad steps
with zero host involvement and zero priority staleness (the reference
writes priorities once per step, ``ddpg.py:252-255``; the host-pipelined
chunk path bounds staleness by (depth+1)K; this path restores exact per-step
semantics *inside* the scan).

Layout matches the host trees (``replay/segment_tree.py``): one flat
array of ``2 * capacity`` (power of two) nodes, root at 1, leaf ``i`` at
``capacity + i``. All ops are batched:

  - ``set_leaves``: scatter the B leaves, then repair ancestors level by
    level — every touched parent is recomputed from its (already-written)
    children, so duplicate parents among the B paths all write identical
    values and need no dedup;
  - ``sample``: B stratified inverse-CDF queries descend in lock-step,
    log2(N) gather/where rounds;
  - trees are float32 (device-friendly); with ~1e6 leaves the prefix-sum
    rounding error is ~1e-7 of total mass per level — sampling noise well
    below the stochasticity already present. IS weights read exact leaf
    values.

Duplicate sampled indices within a batch: ``set_leaves`` keeps one
write-back winner per slot (scatter set), matching the reference's
last-write-wins sequential loop up to ordering.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from d4pg_tpu.replay.segment_tree import next_pow2


class PerTrees(NamedTuple):
    """Device PER state; a pure pytree (donate/checkpoint-able)."""

    sum_tree: Array  # [2 * capacity] float32, node 1 is the root
    min_tree: Array  # [2 * capacity] float32
    max_priority: Array  # [] float32, running max of RAW priorities

    @property
    def capacity(self) -> int:
        return self.sum_tree.shape[0] // 2


def _levels(capacity: int) -> int:
    # capacity comes from Array.shape — a static Python int at trace time,
    # so this is host shape math (it sizes the descent loop), not a sync
    return int(math.log2(capacity))  # jaxlint: disable=host-sync-in-jit


def init(capacity: int) -> PerTrees:
    """Fresh trees for ``capacity`` (rounded up to a power of two) slots."""
    cap = next_pow2(int(capacity))
    return PerTrees(
        sum_tree=jnp.zeros(2 * cap, jnp.float32),
        min_tree=jnp.full(2 * cap, jnp.inf, jnp.float32),
        max_priority=jnp.ones((), jnp.float32),
    )


def set_leaves(trees: PerTrees, idx: Array, p_alpha: Array) -> PerTrees:
    """Write ``p_alpha`` ([B], already ``priority ** alpha``) at leaves
    ``idx`` ([B] int) and repair both trees' ancestors.

    Entries with ``idx >= capacity`` are PADS and are dropped entirely —
    their scatter node is parked out of bounds through every repair level
    (``mode='drop'`` discards the writes; the paired gathers clamp but
    only feed dropped writes). Callers bucket batch sizes with such pads
    for compile-count control; a pad-only call is a no-op."""
    cap = trees.capacity
    idx32 = idx.astype(jnp.int32)
    valid = idx32 < cap
    # pads park at 2*cap (one past the array): writes there are dropped;
    # re-parked after every shift so they never alias a real node. (A
    # shifted-high sentinel like (2*cap) << levels would overflow int32
    # at realistic capacities — 2*cap^2 >= 2^41 for a 1M ring.)
    node = jnp.where(valid, idx32 + cap, 2 * cap)
    s = trees.sum_tree.at[node].set(p_alpha.astype(jnp.float32),
                                    mode="drop")
    # XLA leaves the winner among duplicate scatter indices unspecified, so
    # the min tree copies the sum tree's POST-scatter leaf values — both
    # trees then agree on the same winner by construction (two independent
    # scatters could record different priorities for the same slot, making
    # min_tree report a phantom minimum).
    m = trees.min_tree.at[node].set(s[jnp.minimum(node, 2 * cap - 1)],
                                    mode="drop")
    for _ in range(_levels(cap)):
        node = jnp.where(valid, node >> 1, 2 * cap)
        left = jnp.minimum(node << 1, 2 * cap - 2)
        s = s.at[node].set(s[left] + s[left | 1], mode="drop")
        m = m.at[node].set(jnp.minimum(m[left], m[left | 1]), mode="drop")
    return PerTrees(s, m, trees.max_priority)


def insert(trees: PerTrees, idx: Array, alpha: float) -> PerTrees:
    """New transitions enter with ``max_priority ** alpha``
    (``prioritized_replay_memory.py:251-256``). Pad ``idx`` with
    ``capacity`` (dropped) to bucket sizes for compile-count control."""
    p = jnp.full(idx.shape, trees.max_priority**alpha, jnp.float32)
    return set_leaves(trees, idx, p)


def update_from_td(
    trees: PerTrees, idx: Array, td_error: Array, alpha: float,
    eps: float = 1e-6,
) -> PerTrees:
    """Priority write-back from the TD errors of a sampled batch
    (``ddpg.py:252-255``: priority = |td| + eps, stored as ``p ** alpha``,
    running max tracked on the raw priority)."""
    p = jnp.abs(td_error) + eps
    trees = set_leaves(trees, idx, p**alpha)
    return trees._replace(
        max_priority=jnp.maximum(trees.max_priority, p.max())
    )


def strata_mass(u: Array, total: Array) -> Array:
    """Stratified prefix masses from unit uniforms ``u`` ([..., B]):
    stratum ``i`` draws mass ``(i + u_i) * (total / B)``. Factored out so
    the host twin oracle (``sampler.SampleDealer`` in ``dtype='float32'``
    mode) can reproduce the exact float32 arithmetic with numpy — add,
    divide and multiply are correctly-rounded IEEE ops, bitwise identical
    between numpy and XLA CPU (unlike ``**``, see :func:`block_weights`)."""
    b = u.shape[-1]
    return (jnp.arange(b) + u) * (total / b)


def descend(sum_tree: Array, mass: Array) -> Array:
    """Lock-step inverse-CDF descent of prefix masses ``mass`` (any
    shape) through ``sum_tree`` ([2 * capacity]); returns leaf slots.

    TIE RULE (the bitwise-oracle contract, shared with the host trees'
    ``segment_tree.SumTree.find_prefixsum`` / ``ShardSlicePerTrees``): at
    every node, ``mass >= left_subtree_sum`` descends RIGHT (and
    subtracts); strictly less descends left. A prefix equal to a left
    subtree's sum therefore always resolves to the first leaf of the
    RIGHT subtree — in particular a zero-mass query at a zero-priority
    left leaf skips to the first nonzero leaf, and duplicate prefix
    values (two strata colliding after float rounding) resolve to the
    same slot on host and device alike."""
    cap = sum_tree.shape[0] // 2
    p = mass
    node = jnp.ones(mass.shape, jnp.int32)
    for _ in range(_levels(cap)):
        left = node << 1
        left_sum = sum_tree[left]
        go_right = p >= left_sum
        p = jnp.where(go_right, p - left_sum, p)
        node = jnp.where(go_right, left | 1, left)
    return node - cap


def sample_from_uniforms(trees: PerTrees, u: Array, limit: Array) -> Array:
    """Stratified proportional sampling from caller-supplied unit
    uniforms ``u`` ([..., B]) — the descent half of :func:`sample`, split
    out so the dealt plane can feed uniforms drawn from the dealer's
    seeded HOST stream (the bitwise-oracle stream) instead of a device
    PRNG key. ``limit`` clips prefix overshoot onto written leaves."""
    total = trees.sum_tree[1]
    idx = descend(trees.sum_tree, strata_mass(u, total))
    return jnp.minimum(idx, jnp.maximum(limit - 1, 0))


def sample(
    trees: PerTrees, key: Array, batch_size: int, limit: Array
) -> Array:
    """Stratified proportional sampling: B strata over the total mass, one
    uniform draw each, lock-step inverse-CDF descent (the vectorized form
    of ``prioritized_replay_memory.py:258-265``). ``limit`` (traced int,
    the buffer's live size) clips prefix overshoot onto written leaves."""
    u = jax.random.uniform(key, (batch_size,))
    return sample_from_uniforms(trees, u, limit)


def is_weights(
    trees: PerTrees, idx: Array, beta: Array, size: Array
) -> Array:
    """``(p_i * N) ** -beta`` normalized by the max weight (computed from
    the min tree) — ``prioritized_replay_memory.py:299-313``."""
    total = trees.sum_tree[1]
    n = size.astype(jnp.float32)
    p_min = trees.min_tree[1] / total
    max_weight = (p_min * n) ** (-beta)
    p = trees.sum_tree[trees.capacity + idx] / total
    return ((p * n) ** (-beta) / max_weight).astype(jnp.float32)


def block_weights(
    total: Array, min_root: Array, leaf_p: Array, beta: Array, size: Array
) -> Array:
    """IS weights for a dealt block from its tree scalars and gathered
    leaf priorities — the float32 mirror of the host dealer's
    ``_draw_block_locked`` weight expression (``weight_base`` +
    ``(p * N) ** -beta / max_weight``).

    Kept as ONE shared function because float32 ``**`` is NOT bitwise
    portable between numpy and XLA (measured 1-ulp divergence on CPU):
    the device deal dispatch and the host twin oracle both call the SAME
    compiled transform (:func:`block_weights_jitted`), so the oracle's
    weight comparison is exact by construction instead of hostage to
    libm rounding."""
    n = size.astype(jnp.float32)
    z = min_root / total * n
    max_weight = z ** (-beta)
    p = leaf_p / total
    return ((p * n) ** (-beta) / max_weight).astype(jnp.float32)


_block_weights_jit = None


def block_weights_jitted(total, min_root, leaf_p, beta, size) -> Array:
    """Dispatch :func:`block_weights` as one cached jit — the single
    compiled artifact both the device dealer and the twin oracle share."""
    global _block_weights_jit
    if _block_weights_jit is None:
        _block_weights_jit = jax.jit(block_weights)
    return _block_weights_jit(total, min_root, leaf_p, beta, size)


_set_leaves_jit = None


def set_leaves_jitted(trees: PerTrees, idx, p_alpha) -> PerTrees:
    """Dispatch :func:`set_leaves` as ONE device computation (eager jnp
    pays a per-op round trip — ~50 ops of tree repair — on a tunneled
    accelerator; checkpoint restore rebuilds the whole tree this way).
    Donates ``trees``; caller owns the handle."""
    global _set_leaves_jit
    if _set_leaves_jit is None:
        _set_leaves_jit = jax.jit(set_leaves, donate_argnums=(0,))
    return _set_leaves_jit(trees, idx, p_alpha)


_insert_jit = None


def insert_jitted(trees: PerTrees, idx, alpha: float) -> PerTrees:
    """Dispatch :func:`insert` as ONE device computation (eager jnp would
    pay a per-op round trip on a tunneled accelerator). Donates ``trees``
    — the caller must own the handle (single-writer: the learner thread).
    Callers bucket ``idx`` length (pad by repeating a live slot) so only
    O(log n) shapes compile."""
    global _insert_jit
    if _insert_jit is None:
        _insert_jit = jax.jit(insert, static_argnames=("alpha",),
                              donate_argnums=(0,))
    return _insert_jit(trees, idx, alpha=alpha)


def beta_schedule(step: Array, beta0: float, beta_steps: int) -> Array:
    """PER beta annealing as a pure in-jit function of the learner step —
    the device twin of ``replay/schedule.py``'s LinearSchedule (beta0 -> 1
    over ``beta_steps``, then clamped)."""
    frac = jnp.clip(step.astype(jnp.float32) / float(beta_steps), 0.0, 1.0)
    return beta0 + frac * (1.0 - beta0)
