"""ctypes binding for the C++ PER trees, with transparent numpy fallback.

``load_native()`` returns the shared library handle or None; build it with
``make -C native`` (g++ only, no third-party deps — pybind11 is not
available on this image, hence the plain C ABI + ctypes). The
``NativePerTrees`` class exposes the same operations as the numpy
``SumTree``/``MinTree`` pair (``segment_tree.py``) behind one object, since
PER always writes identical priorities to both trees.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "_native", "libper_trees.so")
_lib = None
_loaded = False


def build_native(quiet: bool = True) -> bool:
    """Best-effort `make -C native`; returns True if the .so exists after."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    native_dir = os.path.join(repo_root, "native")
    if not os.path.isdir(native_dir):
        return os.path.exists(_LIB_PATH)
    try:
        subprocess.run(
            ["make", "-C", native_dir],
            check=True,
            capture_output=quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    return os.path.exists(_LIB_PATH)


def load_native(autobuild: bool = True):
    """Load (building if needed) the native library; None on failure."""
    global _lib, _loaded
    if _loaded:
        return _lib
    _loaded = True
    if not os.path.exists(_LIB_PATH) and autobuild:
        build_native()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = _bind(ctypes.CDLL(_LIB_PATH))
    except (OSError, AttributeError):
        # OSError: wrong platform/ABI for the checked-in .so;
        # AttributeError: a stale .so missing expected symbols. Either way
        # the numpy backend takes over — rebuild with `make -C native`.
        return None
    _lib = lib
    return _lib


def _bind(lib):
    lib.pt_new.restype = ctypes.c_void_p
    lib.pt_new.argtypes = [ctypes.c_int64]
    lib.pt_free.argtypes = [ctypes.c_void_p]
    lib.pt_capacity.restype = ctypes.c_int64
    lib.pt_capacity.argtypes = [ctypes.c_void_p]
    lib.pt_set.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
    ]
    lib.pt_total.restype = ctypes.c_double
    lib.pt_total.argtypes = [ctypes.c_void_p]
    lib.pt_min.restype = ctypes.c_double
    lib.pt_min.argtypes = [ctypes.c_void_p]
    lib.pt_get.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
    ]
    lib.pt_find_prefix.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    return lib


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


class NativePerTrees:
    """Sum+min segment trees backed by the C++ extension."""

    def __init__(self, capacity: int):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native per_trees library unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.pt_new(int(capacity)))
        self.capacity = int(lib.pt_capacity(self._h))

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.pt_free(h)

    def set(self, idx: np.ndarray, values: np.ndarray) -> None:
        idx = np.ascontiguousarray(idx, np.int64)
        values = np.ascontiguousarray(values, np.float64)
        self._lib.pt_set(self._h, _i64(idx), _f64(values), len(idx))

    def sum(self) -> float:
        return float(self._lib.pt_total(self._h))

    def min(self) -> float:
        return float(self._lib.pt_min(self._h))

    def get(self, idx: np.ndarray) -> np.ndarray:
        idx = np.ascontiguousarray(idx, np.int64)
        out = np.empty(len(idx), np.float64)
        self._lib.pt_get(self._h, _i64(idx), _f64(out), len(idx))
        return out

    def find_prefixsum(self, prefix: np.ndarray) -> np.ndarray:
        prefix = np.ascontiguousarray(prefix, np.float64)
        out = np.empty(len(prefix), np.int64)
        self._lib.pt_find_prefix(self._h, _f64(prefix), _i64(out), len(prefix))
        return out
