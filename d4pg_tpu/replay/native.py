"""ctypes binding for the C++ PER trees, with transparent numpy fallback.

``load_native()`` returns the shared library handle or None; build it with
``make -C native`` (g++ only, no third-party deps — pybind11 is not
available on this image, hence the plain C ABI + ctypes). The
``NativePerTrees`` class exposes the same operations as the numpy
``SumTree``/``MinTree`` pair (``segment_tree.py``) behind one object, since
PER always writes identical priorities to both trees.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "_native", "libper_trees.so")
_lib = None
_loaded = False


def build_native(quiet: bool = True) -> bool:
    """Best-effort `make -C native`; returns True if the .so exists after."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    native_dir = os.path.join(repo_root, "native")
    if not os.path.isdir(native_dir):
        return os.path.exists(_LIB_PATH)
    try:
        subprocess.run(
            ["make", "-C", native_dir],
            check=True,
            capture_output=quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    return os.path.exists(_LIB_PATH)


def load_native(autobuild: bool = True):
    """Load (building if needed) the native library; None on failure."""
    global _lib, _loaded
    if _loaded:
        return _lib
    _loaded = True
    if not os.path.exists(_LIB_PATH) and autobuild:
        build_native()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = _bind(ctypes.CDLL(_LIB_PATH))
    except (OSError, AttributeError):
        # OSError: wrong platform/ABI for the checked-in .so;
        # AttributeError: a stale .so missing expected symbols. Either way
        # the numpy backend takes over — rebuild with `make -C native`.
        return None
    _lib = lib
    return _lib


def _bind(lib):
    lib.pt_new.restype = ctypes.c_void_p
    lib.pt_new.argtypes = [ctypes.c_int64]
    lib.pt_free.argtypes = [ctypes.c_void_p]
    lib.pt_capacity.restype = ctypes.c_int64
    lib.pt_capacity.argtypes = [ctypes.c_void_p]
    lib.pt_set.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
    ]
    lib.pt_total.restype = ctypes.c_double
    lib.pt_total.argtypes = [ctypes.c_void_p]
    lib.pt_min.restype = ctypes.c_double
    lib.pt_min.argtypes = [ctypes.c_void_p]
    lib.pt_get.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
    ]
    lib.pt_find_prefix.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    return lib


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


class NativePerTrees:
    """Sum+min segment trees backed by the C++ extension."""

    def __init__(self, capacity: int):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native per_trees library unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.pt_new(int(capacity)))
        self.capacity = int(lib.pt_capacity(self._h))

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.pt_free(h)

    def set(self, idx: np.ndarray, values: np.ndarray) -> None:
        # ravel: callers pass [K, B] chunk indices (update_priorities);
        # the C ABI takes flat arrays and an ELEMENT count — len() of a
        # 2D array is its outer dim and would silently drop K*(B-1)
        # writes. Flattened order keeps numpy's last-wins on duplicates.
        idx = np.ascontiguousarray(np.asarray(idx, np.int64).ravel())
        values = np.ascontiguousarray(np.asarray(values, np.float64).ravel())
        self._lib.pt_set(self._h, _i64(idx), _f64(values), idx.size)

    def sum(self) -> float:
        return float(self._lib.pt_total(self._h))

    def min(self) -> float:
        return float(self._lib.pt_min(self._h))

    def get(self, idx: np.ndarray) -> np.ndarray:
        idx = np.asarray(idx, np.int64)
        flat = np.ascontiguousarray(idx.ravel())
        out = np.empty(flat.size, np.float64)
        self._lib.pt_get(self._h, _i64(flat), _f64(out), flat.size)
        return out.reshape(idx.shape)  # shape parity with the numpy trees

    def find_prefixsum(self, prefix: np.ndarray) -> np.ndarray:
        prefix = np.asarray(prefix, np.float64)
        flat = np.ascontiguousarray(prefix.ravel())
        out = np.empty(flat.size, np.int64)
        self._lib.pt_find_prefix(self._h, _f64(flat), _i64(out), flat.size)
        return out.reshape(prefix.shape)
