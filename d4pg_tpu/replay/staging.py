"""Async host->device batch staging.

SURVEY.md §7 "hard parts": replay sampling + H2D transfer must hide under
the XLA learner step. ``DeviceStager`` keeps one batch in flight: while the
TPU executes step t on batch t, the host samples and ``device_put``s batch
t+1 (JAX dispatch is async, so ``device_put`` returns immediately and the
transfer overlaps with compute).

``MultiRingStaging`` is the host half of the SHARDED ingest plane: K
private column-major staging rings (one per ingest shard, so K workers
copy rows concurrently without sharing a cache line of bookkeeping) whose
contents merge back into ONE fixed-shape frame stream for the existing
single-``device_put`` + single-jitted-commit fused dispatch.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Callable, Iterator

import jax

from d4pg_tpu.core.locking import TieredCondition, TieredLock
from d4pg_tpu.obs.registry import REGISTRY


class MultiRingStaging:
    """K per-shard host staging rings + a ticket-ordered merge frame.

    Interface-compatible with ``HostStagingRing`` on the consumer side
    (``frame()``/``pop()``/``take()``/``__len__``), so the fused buffer's
    ``stage_block``/``commit_staged``/``drain_per_row`` run unchanged on
    the merged stream — the ≤1-device_put-per-block invariant and the
    per-row bitwise oracle survive sharding untouched.

    Ownership: shard ``i``'s worker is the only pusher of ring ``i``;
    each ring (and its record deque) is guarded by one leaf lock
    (``core.locking.TieredLock`` at the bottom ``ring`` tier), held only
    for the slice-copy — never while taking any service or buffer lock.
    The direction is enforced by the ``lock-order``/``lock-cycle``
    jaxlint rules statically and by the tier assertions at runtime.

    Merge-commit ordering rule: every pushed batch carries a monotonic
    admission ticket (per-ring ascending; globally unique). ``frame()``
    refills an internal merge ring by repeatedly draining the record
    with the SMALLEST ticket among the shard ring heads, so rows land on
    the device in admission order whenever the plane is quiescent (the
    bitwise K=1↔K=2 equivalence bar); rows still being decoded on a
    straggler shard can be overtaken mid-flight — the merge never
    blocks the learner's stage call on a slow shard.
    """

    def __init__(self, specs, block_rows: int, n_blocks: int,
                 shards: int):
        from d4pg_tpu.replay.fused_buffer import HostStagingRing

        self.shards = max(1, int(shards))
        self.block_rows = int(block_rows)
        self._rings = [HostStagingRing(specs, block_rows, n_blocks)
                       for _ in range(self.shards)]
        self._ring_locks = [TieredLock("ring") for _ in range(self.shards)]
        # per-ring (ticket, rows) records, ticket-ascending
        self._records: list[deque] = [deque() for _ in range(self.shards)]
        self._merge = HostStagingRing(specs, block_rows, 2)
        self._ticket = itertools.count()

    def __len__(self) -> int:
        n = len(self._merge)
        for i in range(self.shards):
            with self._ring_locks[i]:
                n += len(self._rings[i])
        return n

    # -- producer side (one worker per shard) ------------------------------
    def push(self, batch, shard: int = 0, ticket: int | None = None) -> None:
        i = shard % self.shards
        ring, records = self._rings[i], self._records[i]
        # per-frame registry inc, OUTSIDE the ring leaf lock (the obs
        # plane is terminal-locked but ring hold times stay honest)
        REGISTRY.counter("staging.rows_pushed").inc(
            int(batch.obs.shape[0]))
        with self._ring_locks[i]:
            t = next(self._ticket) if ticket is None else ticket
            n = min(int(batch.obs.shape[0]), ring.size)
            overflow = max(0, len(ring) + n - ring.size)
            ring.push(batch)
            # the ring dropped its oldest rows to admit these: trim the
            # same rows off the oldest records so tickets stay aligned
            # with ring contents
            while overflow and records:
                t0, n0 = records[0]
                if n0 <= overflow:
                    records.popleft()
                    overflow -= n0
                else:
                    records[0] = (t0, n0 - overflow)
                    overflow = 0
            records.append((t, n))

    # -- consumer side (learner thread) ------------------------------------
    def _refill(self) -> None:
        """Move rows into the merge ring, smallest head ticket first,
        until it holds a full block or the shard rings run dry."""
        while len(self._merge) < self.block_rows:
            best = None
            for i in range(self.shards):
                with self._ring_locks[i]:
                    if self._records[i]:
                        t = self._records[i][0][0]
                        if best is None or t < best[0]:
                            best = (t, i)
            if best is None:
                return
            _t, i = best
            with self._ring_locks[i]:
                if not self._records[i] or self._records[i][0][0] != _t:
                    continue  # a push overflowed the head away; re-scan
                _t, n = self._records[i].popleft()
                room = self._merge.size - len(self._merge)
                if n > room:
                    # only part of the record fits this pass: keep the
                    # remainder (same ticket) at the head for the next
                    self._records[i].appendleft((_t, n - room))
                    n = room
                for piece in self._rings[i].take(n):
                    self._merge.push(piece)

    # -- crash-recovery cut -------------------------------------------------
    def snapshot(self) -> dict:
        """Ticket floor + residual depth at a (drained) cut. The fused
        buffer's ``state_dict`` drains every ring before calling this,
        so ``staged_rows`` is 0 on a consistent snapshot — recorded
        anyway so a non-quiesced cut is self-describing. Consuming one
        ticket to learn the floor is benign: tickets only need to ascend
        per ring, gaps never block the merge."""
        floor = next(self._ticket)
        return {"ticket_floor": int(floor), "staged_rows": len(self)}

    def restore(self, d: dict) -> None:
        """Reseat the ticket counter ABOVE the snapshot's floor so every
        post-restore push stays merge-ordered after every pre-crash
        ticket. Ring contents are NOT restored — a consistent cut has
        none (see ``snapshot``); rows in flight at the crash are the
        declared fence/shed losses of the recovery plane."""
        self._ticket = itertools.count(int(d.get("ticket_floor", 0)) + 1)

    def frame(self):
        self._refill()
        return self._merge.frame()

    def pop(self, n: int) -> None:
        self._merge.pop(n)

    def take(self, n: int):
        self._refill()
        return self._merge.take(n)


class DeviceStager:
    """Double-buffered prefetch of host batches onto a device (or sharding).

    With ``with_aux=True`` the sample_fn returns ``(payload, aux)``: the
    payload is ``device_put`` (async), the aux rides along untouched on the
    host — e.g. PER sample indices that must come back to the host for the
    priority write-back (``ddpg.py:252-255``).
    """

    def __init__(
        self,
        sample_fn: Callable[[], object],
        device=None,
        with_aux: bool = False,
        put_fn: Callable | None = None,
    ):
        self._sample = sample_fn
        self._device = device
        self._with_aux = with_aux
        # Custom staging (e.g. multi-host: a host-local device_put cannot
        # address other hosts' devices, so the multi-host runtime stages
        # via jax.make_array_from_process_local_data instead —
        # parallel/multihost.make_global_chunk).
        self._put_fn = put_fn
        self._inflight = None

    def _put(self):
        sampled = self._sample()
        batch, aux = sampled if self._with_aux else (sampled, None)
        if self._put_fn is not None:
            staged = self._put_fn(batch)
        elif self._device is not None:
            staged = jax.device_put(batch, self._device)
        else:
            staged = jax.device_put(batch)
        return (staged, aux) if self._with_aux else staged

    def next(self, prefetch: bool = True):
        """Return the prefetched batch and (unless ``prefetch=False``) start
        staging the following one. Pass ``prefetch=False`` on the last batch
        a consumer will take before an ``invalidate()`` — otherwise that
        trailing sample is staged only to be thrown away."""
        out = self._inflight if self._inflight is not None else self._put()
        self._inflight = self._put() if prefetch else None
        return out

    def invalidate(self) -> None:
        """Drop the in-flight batch (e.g. after a buffer mutation that makes
        the prefetched sample undesirable). The next ``next()`` samples
        fresh."""
        self._inflight = None

    def __iter__(self) -> Iterator:
        while True:
            yield self.next()


class DealtBlockRing:
    """Bounded ring of ready-to-train dealt blocks, one per learner
    replica (the sample-on-ingest plane, ``replay/sampler.py``).

    Ownership: single producer — the commit thread's dealer — and a
    single consumer — the owning replica. The dealer reserves room under
    its own ``sampler``-tier critical section (``room()``) and pushes
    AFTER releasing it; since only consumers shrink the queue between
    the reservation and the push, a reserved push can only fail if the
    ring was closed. All queue state lives under one bottom-tier
    ``ring`` condition, so the replica's blocking ``pop`` holds nothing
    above the leaf tier — the replica sample path never touches the
    buffer lock.
    """

    def __init__(self, capacity: int = 4):
        self.capacity = max(1, int(capacity))
        self._cond = TieredCondition("ring")
        self._q: deque = deque()
        self._closed = False
        # Demand kick, set by ReplayService.attach_dealer: called after a
        # pop frees room — with the ring condition RELEASED, so the
        # callback may take the commit condition at top level (a ring ->
        # commit ascent under the leaf lock would be the merge-wedge
        # shape) — to wake the commit loop for an immediate top-up tick.
        # Without it the ring refills only on ingest commits and the
        # ~10 Hz idle tick, and a consumer faster than the commit cadence
        # starves on an almost-always-empty ring.
        self.on_room: Callable[[], None] | None = None

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def room(self) -> int:
        with self._cond:
            return 0 if self._closed else max(0, self.capacity - len(self._q))

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def offer(self, block) -> bool:
        """Producer push (named uniquely on purpose: ``push`` would
        name-collide with ``HostStagingRing.push`` in the lint lock
        graph's call resolution, manufacturing a ring->ring edge)."""
        with self._cond:
            if self._closed or len(self._q) >= self.capacity:
                return False
            self._q.append(block)
            self._cond.notify_all()
            return True

    def pop(self, timeout: float | None = None):
        """Next dealt block, blocking up to ``timeout`` seconds (forever
        when None); None on timeout or close."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._q:
                if self._closed:
                    return None
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cond.wait(remaining)
            block = self._q.popleft()
            self._cond.notify_all()
        kick = self.on_room
        if kick is not None:
            kick()
        return block

    def clear(self) -> int:
        """Drop all queued blocks (replica respawn: a fresh consumer must
        not train on blocks dealt to its dead predecessor mid-kill).
        Returns the number dropped."""
        with self._cond:
            n = len(self._q)
            self._q.clear()
            self._cond.notify_all()
        kick = self.on_room
        if n and kick is not None:
            kick()
        return n

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class DeviceDealtBlockRing(DealtBlockRing):
    """``DealtBlockRing`` for DEVICE-resident dealt blocks
    (``replay/device_sampler.DeviceSampleDealer``): queue mechanics are
    identical, but ``clear`` — the replica-kill / restore path — also
    explicitly ``delete()``s each dropped block's device buffers. A
    host block's rows are reclaimed by the GC the moment the ring drops
    its reference; a device block's rows are HBM that would otherwise
    linger until the next GC cycle, so a kill burst could transiently
    hold ring_capacity * K * B rows of dead sample memory per replica.
    Deleting eagerly makes clear-on-kill reclaim immediate (pinned by
    the devsample chaos test).
    """

    def clear(self) -> int:
        with self._cond:
            dropped = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for block in dropped:
            for arr in (*block.batches, block.weights, block.idx,
                        block.gen):
                delete = getattr(arr, "delete", None)
                if delete is not None:
                    try:
                        delete()
                    except Exception:
                        pass  # already consumed/donated elsewhere
        kick = self.on_room
        if dropped and kick is not None:
            kick()
        return len(dropped)
