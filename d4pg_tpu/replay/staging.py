"""Async host->device batch staging.

SURVEY.md §7 "hard parts": replay sampling + H2D transfer must hide under
the XLA learner step. ``DeviceStager`` keeps one batch in flight: while the
TPU executes step t on batch t, the host samples and ``device_put``s batch
t+1 (JAX dispatch is async, so ``device_put`` returns immediately and the
transfer overlaps with compute).
"""

from __future__ import annotations

from typing import Callable, Iterator

import jax


class DeviceStager:
    """Double-buffered prefetch of host batches onto a device (or sharding)."""

    def __init__(
        self,
        sample_fn: Callable[[], object],
        device=None,
    ):
        self._sample = sample_fn
        self._device = device
        self._inflight = None

    def _put(self):
        batch = self._sample()
        if self._device is not None:
            return jax.device_put(batch, self._device)
        return jax.device_put(batch)

    def next(self):
        """Return the prefetched batch and immediately start staging the
        following one."""
        out = self._inflight if self._inflight is not None else self._put()
        self._inflight = self._put()
        return out

    def __iter__(self) -> Iterator:
        while True:
            yield self.next()
