"""Async host->device batch staging.

SURVEY.md §7 "hard parts": replay sampling + H2D transfer must hide under
the XLA learner step. ``DeviceStager`` keeps one batch in flight: while the
TPU executes step t on batch t, the host samples and ``device_put``s batch
t+1 (JAX dispatch is async, so ``device_put`` returns immediately and the
transfer overlaps with compute).
"""

from __future__ import annotations

from typing import Callable, Iterator

import jax


class DeviceStager:
    """Double-buffered prefetch of host batches onto a device (or sharding).

    With ``with_aux=True`` the sample_fn returns ``(payload, aux)``: the
    payload is ``device_put`` (async), the aux rides along untouched on the
    host — e.g. PER sample indices that must come back to the host for the
    priority write-back (``ddpg.py:252-255``).
    """

    def __init__(
        self,
        sample_fn: Callable[[], object],
        device=None,
        with_aux: bool = False,
        put_fn: Callable | None = None,
    ):
        self._sample = sample_fn
        self._device = device
        self._with_aux = with_aux
        # Custom staging (e.g. multi-host: a host-local device_put cannot
        # address other hosts' devices, so the multi-host runtime stages
        # via jax.make_array_from_process_local_data instead —
        # parallel/multihost.make_global_chunk).
        self._put_fn = put_fn
        self._inflight = None

    def _put(self):
        sampled = self._sample()
        batch, aux = sampled if self._with_aux else (sampled, None)
        if self._put_fn is not None:
            staged = self._put_fn(batch)
        elif self._device is not None:
            staged = jax.device_put(batch, self._device)
        else:
            staged = jax.device_put(batch)
        return (staged, aux) if self._with_aux else staged

    def next(self, prefetch: bool = True):
        """Return the prefetched batch and (unless ``prefetch=False``) start
        staging the following one. Pass ``prefetch=False`` on the last batch
        a consumer will take before an ``invalidate()`` — otherwise that
        trailing sample is staged only to be thrown away."""
        out = self._inflight if self._inflight is not None else self._put()
        self._inflight = self._put() if prefetch else None
        return out

    def invalidate(self) -> None:
        """Drop the in-flight batch (e.g. after a buffer mutation that makes
        the prefetched sample undesirable). The next ``next()`` samples
        fresh."""
        self._inflight = None

    def __iter__(self) -> Iterator:
        while True:
            yield self.next()
