"""Uniform replay: a preallocated numpy ring buffer of transitions.

Parity: the reference's ``Replay`` (``replay_memory.py:14-80``) and
``ReplayBuffer`` base (``prioritized_replay_memory.py:164-222``) — a ring of
``(s, a, r, s', done)`` tuples with uniform sampling. TPU-first differences:

  - storage is preallocated contiguous float32 arrays (the reference appends
    python tuples and re-stacks to float64 on every sample,
    ``replay_memory.py:61-80``), so sampling is a single fancy-index gather
    ready for zero-copy ``device_put``;
  - each transition carries an explicit ``discount`` = gamma^m * (1 - done)
    folded at insert time by the n-step machinery (resurrecting the
    reference's dead n-step code path, ``replay_memory.py:21-58`` /
    ``main.py:209-242``, properly);
  - batched vectorized ``add``; no per-step Python loop;
  - sampling is with replacement by default (like the PER base ring,
    ``prioritized_replay_memory.py:221``); ``replace=False`` gives the
    uniform ``Replay.sample`` behavior (``replay_memory.py:61``).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class TransitionBatch(NamedTuple):
    """A batch of (possibly n-step-folded) transitions, host numpy arrays."""

    obs: np.ndarray  # [B, obs_dim] float32
    action: np.ndarray  # [B, act_dim] float32
    reward: np.ndarray  # [B] float32 (n-step folded return)
    next_obs: np.ndarray  # [B, obs_dim] float32 (s_{t+n})
    done: np.ndarray  # [B] float32
    discount: np.ndarray  # [B] float32 = gamma^m * (1 - done)


def pack_rows(rows: TransitionBatch, head: int, size: int,
              capacity: int) -> dict:
    """Checkpoint payload for ring contents — shared by the host buffers
    and the fused device buffer so the restore guards live in one place."""
    return {
        "rows": {f: np.asarray(v) for f, v in
                 zip(TransitionBatch._fields, rows)},
        "head": head,
        "size": size,
        "capacity": capacity,
    }


def validate_rows(d: dict, capacity: int) -> None:
    """Reject a :func:`pack_rows` payload whose layout cannot restore into
    a ``capacity``-sized ring. Capacity must match exactly: a wrapped ring
    re-laid into a different capacity leaves head/size pointing at the
    wrong slots (live rows silently overwritten or zero-garbage samples)."""
    if "sharded" in d:
        raise ValueError(
            "replay checkpoint was saved by a sharded (data_parallel) "
            "buffer; resume with the same --data_parallel degree")
    ckpt_cap = int(d.get("capacity", -1))
    if ckpt_cap != capacity:
        raise ValueError(
            f"replay checkpoint capacity {ckpt_cap} != buffer capacity "
            f"{capacity}; resume with the same --rmsize")


def unpack_rows(d: dict, capacity: int):
    """Validate + unpack a :func:`pack_rows` payload. Returns
    ``(batch_or_None, head, size)``."""
    validate_rows(d, capacity)
    size = int(d["size"])
    batch = (TransitionBatch(*[d["rows"][f] for f in TransitionBatch._fields])
             if size else None)
    return batch, int(d["head"]) % capacity, size


class HostStore:
    """Preallocated contiguous numpy storage (the default)."""

    def __init__(self, capacity: int, obs_shape: tuple, act_dim: int, obs_dtype):
        self.obs = np.zeros((capacity, *obs_shape), obs_dtype)
        self.action = np.zeros((capacity, act_dim), np.float32)
        self.reward = np.zeros((capacity,), np.float32)
        self.next_obs = np.zeros((capacity, *obs_shape), obs_dtype)
        self.done = np.zeros((capacity,), np.float32)
        self.discount = np.zeros((capacity,), np.float32)

    def write(self, idx: np.ndarray, batch: TransitionBatch) -> None:
        self.obs[idx] = batch.obs
        self.action[idx] = batch.action
        self.reward[idx] = batch.reward
        self.next_obs[idx] = batch.next_obs
        self.done[idx] = batch.done
        self.discount[idx] = batch.discount

    def read(self, idx: np.ndarray) -> TransitionBatch:
        return TransitionBatch(
            obs=self.obs[idx],
            action=self.action[idx],
            reward=self.reward[idx],
            next_obs=self.next_obs[idx],
            done=self.done[idx],
            discount=self.discount[idx],
        )


class ReplayBuffer:
    """Fixed-capacity ring buffer over pluggable storage.

    ``obs_dim`` is an int for vector observations or a shape tuple for
    structured ones (e.g. ``(H, W, C)`` pixels, stored uint8 to keep a
    1M-frame buffer in host RAM; BASELINE.md config #4).

    ``storage='host'`` (default) keeps numpy arrays in host RAM;
    ``storage='device'`` keeps the ring in accelerator HBM
    (``replay/device_ring.py``) — the host picks indices, the device
    gathers rows, and per-dispatch host<->device traffic is O(indices)
    instead of O(batch bytes).
    """

    def __init__(
        self,
        capacity: int,
        obs_dim: int | tuple,
        act_dim: int,
        seed: int = 0,
        obs_dtype=None,
        storage: str = "host",
        device=None,
    ):
        self.capacity = int(capacity)
        obs_shape = (obs_dim,) if np.isscalar(obs_dim) else tuple(obs_dim)
        if obs_dtype is None:
            obs_dtype = np.float32 if len(obs_shape) == 1 else np.uint8
        if storage == "device":
            from d4pg_tpu.replay.device_ring import DeviceStore

            self._store = DeviceStore(self.capacity, obs_shape, act_dim,
                                      obs_dtype, device=device)
        elif storage == "host":
            self._store = HostStore(self.capacity, obs_shape, act_dim,
                                    obs_dtype)
            # direct-array aliases (tests, offline analysis)
            self.obs = self._store.obs
            self.action = self._store.action
            self.reward = self._store.reward
            self.next_obs = self._store.next_obs
            self.done = self._store.done
            self.discount = self._store.discount
        else:
            raise ValueError(f"unknown storage {storage!r}")
        self.storage = storage
        self.size = 0
        self.head = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self.size

    def add(self, batch: TransitionBatch) -> np.ndarray:
        """Insert a batch of transitions; returns the slot indices written."""
        n = batch.obs.shape[0]
        if n > self.capacity:
            raise ValueError(f"batch of {n} exceeds capacity {self.capacity}")
        idx = (self.head + np.arange(n)) % self.capacity
        self._store.write(idx, batch)
        self.head = int((self.head + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))
        return idx

    def gather(self, idx: np.ndarray) -> TransitionBatch:
        """Rows at ``idx`` ([B] or stacked [K, B]); device storage returns
        device arrays without a host round trip."""
        return self._store.read(idx)

    def sample(self, batch_size: int, replace: bool = True) -> TransitionBatch:
        if self.size == 0:
            raise ValueError("cannot sample from an empty buffer")
        idx = self._rng.choice(self.size, size=batch_size, replace=replace)
        return self.gather(idx)

    def sample_chunk(self, k: int, batch_size: int):
        """K stacked batches in ONE storage gather: (batches [K, B, ...],
        None, idx [K, B]). Feeds the K-updates-per-dispatch learner path;
        with device storage the rows never touch the host."""
        if self.size == 0:
            raise ValueError("cannot sample from an empty buffer")
        idx = self._rng.choice(self.size, size=(k, batch_size), replace=True)
        return self.gather(idx), None, idx

    def state_dict(self) -> dict:
        """Buffer contents as host numpy for checkpointing (SURVEY.md §5
        elastic recovery — the reference checkpoints nothing but net
        weights, ``main.py:367-368``). Only the live rows are captured."""
        return pack_rows(self.gather(np.arange(self.size)), self.head,
                         self.size, self.capacity)

    def load_state_dict(self, d: dict) -> None:
        """Restore contents saved by :meth:`state_dict` (same capacity)."""
        batch, head, size = unpack_rows(d, self.capacity)
        if batch is not None:
            self._store.write(np.arange(size), batch)
        self.size = size
        self.head = head
