"""N-step transition folding at insert time.

The reference ships n-step machinery only as dead code (the commented-out
warmup in ``replay_memory.py:21-58`` and ``main.py:209-242``; ``--n_steps``
otherwise only scales the discount of the *unused* projection,
``ddpg.py:24,129``). SURVEY.md §7 capability 5 mandates a real
implementation. This folder maintains a sliding window of the last n
transitions per environment and emits folded transitions

    (s_t, a_t, R_t^{(m)} = sum_{k<m} gamma^k r_{t+k}, s_{t+m}, done, disc)

with ``disc = gamma^m * (1 - done)`` baked in, so the learner's Bellman
backup is simply ``R + disc * Z(s')`` regardless of n, terminal truncation,
or partial tails at episode end:

  - a full window emits the oldest entry with m = n,
  - termination (``done``) flushes every pending entry with done=1, disc=0,
  - time-limit truncation flushes with done=0, disc=gamma^m so the value
    bootstraps (a semantic the reference conflates by treating
    ``info['is_success']`` as done, ``main.py:148``).
"""

from __future__ import annotations

import numpy as np

from d4pg_tpu.replay.uniform import TransitionBatch


class NStepFolder:
    def __init__(
        self, n: int, gamma: float, num_envs: int, obs_dim: int | tuple,
        act_dim: int, obs_dtype=None,
    ):
        assert n >= 1
        self.n = int(n)
        self.gamma = float(gamma)
        self.num_envs = int(num_envs)
        obs_shape = (obs_dim,) if np.isscalar(obs_dim) else tuple(obs_dim)
        if obs_dtype is None:
            obs_dtype = np.float32 if len(obs_shape) == 1 else np.uint8
        self._obs_shape = obs_shape
        self._obs = np.zeros((num_envs, n, *obs_shape), obs_dtype)
        self._act = np.zeros((num_envs, n, act_dim), np.float32)
        self._rew = np.zeros((num_envs, n), np.float32)
        self._count = np.zeros(num_envs, np.int64)
        self._pow = self.gamma ** np.arange(n, dtype=np.float32)

    def reset(self) -> None:
        """Drop all pending window entries (call when the envs reset outside
        the folder's view — e.g. a new acting cycle after a hard pool reset;
        stale entries would otherwise be stitched across the boundary)."""
        self._count[:] = 0

    def _fold_tail(self, e: int, next_obs_e: np.ndarray, done: float, out: list):
        """Emit all pending entries of env e against next_obs_e."""
        c = int(self._count[e])
        for j in range(c):
            m = c - j
            reward = float(np.dot(self._rew[e, j:c], self._pow[:m]))
            disc = 0.0 if done else self.gamma**m
            out.append(
                (
                    self._obs[e, j].copy(),
                    self._act[e, j].copy(),
                    reward,
                    next_obs_e.copy(),
                    done,
                    disc,
                )
            )
        self._count[e] = 0

    def step(
        self,
        obs: np.ndarray,
        action: np.ndarray,
        reward: np.ndarray,
        next_obs: np.ndarray,
        done: np.ndarray,
        truncated: np.ndarray | None = None,
    ) -> TransitionBatch:
        """Feed one vector-env step ([E, ...] arrays); returns the folded
        transitions ready for the buffer (possibly 0 rows)."""
        e_ids = np.arange(self.num_envs)
        done = np.asarray(done, bool)
        truncated = (
            np.zeros(self.num_envs, bool) if truncated is None else np.asarray(truncated, bool)
        )
        # insert current transition into each env's window
        c = self._count
        self._obs[e_ids, c] = obs
        self._act[e_ids, c] = action
        self._rew[e_ids, c] = reward
        self._count += 1

        rows: list[tuple] = []
        # ordinary full-window emission for live envs
        live_full = (~done) & (~truncated) & (self._count == self.n)
        for e in np.nonzero(live_full)[0]:
            reward_n = float(np.dot(self._rew[e], self._pow))
            rows.append(
                (
                    self._obs[e, 0].copy(),
                    self._act[e, 0].copy(),
                    reward_n,
                    next_obs[e].copy(),
                    0.0,
                    self.gamma**self.n,
                )
            )
            # slide the window left by one
            self._obs[e, :-1] = self._obs[e, 1:]
            self._act[e, :-1] = self._act[e, 1:]
            self._rew[e, :-1] = self._rew[e, 1:]
            self._count[e] = self.n - 1
        # episode boundaries flush everything pending
        for e in np.nonzero(done)[0]:
            self._fold_tail(e, next_obs[e], done=1.0, out=rows)
        for e in np.nonzero(truncated & ~done)[0]:
            self._fold_tail(e, next_obs[e], done=0.0, out=rows)

        if not rows:
            z = np.zeros((0,), np.float32)
            return TransitionBatch(
                obs=np.zeros((0, *self._obs_shape), self._obs.dtype),
                action=np.zeros((0, self._act.shape[-1]), np.float32),
                reward=z,
                next_obs=np.zeros((0, *self._obs_shape), self._obs.dtype),
                done=z,
                discount=z,
            )
        obs_a, act_a, rew_a, nxt_a, dn_a, dc_a = zip(*rows)
        return TransitionBatch(
            obs=np.stack(obs_a),
            action=np.stack(act_a),
            reward=np.asarray(rew_a, np.float32),
            next_obs=np.stack(nxt_a),
            done=np.asarray(dn_a, np.float32),
            discount=np.asarray(dc_a, np.float32),
        )
