"""Sample-on-ingest: PER sampling fused into the sharded receive path.

The host-side latency between a wire frame and a grad step is the
ingest -> insert -> sample -> fetch round trip: the commit thread inserts
rows under the buffer lock, a learner replica later re-acquires the same
lock to walk the sum tree, gather rows and compute IS weights, and under
N replicas those walks contend (the PR-10 host-sample path). This module
collapses the round trip into one pipelined pass, the way "In-Network
Experience Sampling" (PAPERS.md, arXiv 2110.13506) rides sampling on the
transport and "Accelerated Methods for Deep RL" (arXiv 1803.02811) deals
whole sampled blocks rather than rows:

  - :class:`ShardSlicePerTrees` keeps the PER sum/min tree as S
    contiguous per-shard slices plus a tiny top tree over the slice
    roots. Same pairwise reduction structure as one flat
    ``segment_tree.SumTree`` over the full capacity, so totals, mins and
    the inverse-CDF descent are BITWISE identical to the single tree —
    the merge is structural, not a cumsum (float addition is not
    associative; re-bracketing would break the bitwise oracle).
  - :class:`SampleDealer` is driven by the commit thread — the owner of
    global ticket order. Inside the commit's existing buffer-lock window
    it mirrors each insert into the slice trees, settles the priority
    write-back queues, and deals ready-to-train blocks (rows + IS
    weights + indices + generations) drawn from its own seeded stream —
    bitwise the same stream a host ``sample_chunk`` loop would draw.
    Blocks are published into bounded per-replica rings
    (``staging.DealtBlockRing``) AFTER every lock is released.
  - Priority write-back from grad steps is a generation-fenced queue:
    replicas enqueue under the ``sampler`` tier only (ZERO buffer-lock
    acquisitions on the replica sample path); the owning ingest shard's
    worker drains its slices' queues, so every tree write still has a
    single writer under the tier discipline (``core.locking``:
    buffer > shard > sampler > ring).

Determinism contract (the tier-1 bitwise oracle): with the same seed,
the same insert order and the same write-back order, the dealer's blocks
(indices, weights, dtypes) equal the legacy host path —
``buffer.add`` + ``update_priorities`` + ``sample_chunk`` — exactly.
Draws that cannot be dealt (ring full, paused, warmup) are SKIPPED
before touching the RNG, so backpressure never desynchronizes the
stream.
"""

from __future__ import annotations

import time
from collections import deque
from typing import NamedTuple

import numpy as np

from d4pg_tpu.core.locking import TieredLock
from d4pg_tpu.obs import trace as obs_trace
from d4pg_tpu.obs.registry import REGISTRY
from d4pg_tpu.replay.schedule import SharedBetaSchedule
from d4pg_tpu.replay.segment_tree import next_pow2

# Write-back fencing keeps a bounded memory of dead (shed / tombstoned /
# generation-fenced) ticket seqs for the audit cross-check; past the
# bound the oldest are forgotten — the invariant itself is structural
# (dead tickets never insert rows), the audit only witnesses it.
_DEAD_SEQ_BOUND = 4096


class ShardSlicePerTrees:
    """PER sum+min trees partitioned into per-shard slices of the slot
    space, merged by a top tree over the slice roots.

    Slot space ``[0, capacity)`` (capacity rounded to a power of two) is
    split into ``n_slices`` (rounded likewise, clamped to capacity)
    contiguous slices of ``slice_cap`` leaves; slice ``j`` covers slots
    ``[j * slice_cap, (j+1) * slice_cap)``, so the ring-order inserts a
    given ingest shard commits land in a dense run of its own slice —
    the single-writer unit the write-back drain is organized around.

    Every aggregate is the same pairwise reduction a single
    ``segment_tree.SumTree`` over the full capacity computes: a slice
    tree's internal nodes ARE that tree's nodes below the slice-root
    level, and the top tree's internal nodes ARE its nodes above. Since
    the operand values and the reduction bracketing are identical,
    ``total``/``min``/``find_prefixsum`` are bitwise-equal to the single
    tree (pinned by the tier-1 merge property test across K slices,
    including all-zero-priority slices).

    That bitwise identity is also a license to delegate: when the native
    C++ trees are loadable, ``backend='auto'`` backs the whole structure
    with one flat ``NativePerTrees`` — legal because slice == flat is
    pinned by the merge property test (``backend='numpy'``) and flat ==
    native by ``tests/test_native.py``, so every observable value is the
    same by transitivity. It matters on the hot path: the dealer draws
    INSIDE the commit thread's buffer-lock window, and the numpy slice
    walk costs ~6-20x the native calls per deal (measured ~0.45 ms vs
    ~0.07 ms a block), which is the difference between the dealer
    stretching every commit and disappearing into it. The slice
    partition itself (``slice_of``-by-range, the write-back drain
    ownership) is index arithmetic and works over either backing.
    """

    def __init__(self, capacity: int, n_slices: int,
                 backend: str = "auto", dtype=np.float64):
        self.capacity = next_pow2(int(capacity))
        self.n_slices = min(next_pow2(max(1, int(n_slices))), self.capacity)
        self.slice_cap = self.capacity // self.n_slices
        self._top_levels = int(np.log2(self.n_slices))
        self._slice_levels = int(np.log2(self.slice_cap))
        self._stride = 2 * self.slice_cap
        if backend not in ("auto", "numpy"):
            raise ValueError(f"unknown ShardSlicePerTrees backend "
                             f"{backend!r} (want 'auto' or 'numpy')")
        # float32 mode is the DEVICE-TWIN: every leaf value, aggregate
        # and descent compare rounds exactly like the float32 device
        # trees (device_per.PerTrees) — f32 add/sub are correctly-rounded
        # IEEE ops, identical between numpy and XLA — so the twin's
        # sampled slots are bitwise the device descent's. The native C++
        # backing is float64-only and is bypassed in this mode.
        self.dtype = np.dtype(dtype)
        if self.dtype not in (np.dtype(np.float64), np.dtype(np.float32)):
            raise ValueError("ShardSlicePerTrees dtype must be float64 "
                             f"or float32, got {self.dtype}")
        if self.dtype == np.float32:
            backend = "numpy"
        self._native_cls = None
        if backend == "auto":
            try:
                from d4pg_tpu.replay.native import NativePerTrees, load_native
                if load_native() is not None:
                    self._native_cls = NativePerTrees
            except Exception:  # pragma: no cover - loader failure = fallback
                self._native_cls = None
        self._native = None
        self.reset()

    def reset(self) -> None:
        if self._native_cls is not None:
            # a fresh native tree IS the empty state (sum leaves 0, min
            # leaves +inf) — pt_new is cheaper than writing every leaf
            self._native = self._native_cls(self.capacity)
            return
        s = self.n_slices
        self._sum = np.zeros((s, self._stride), self.dtype)
        self._min = np.full((s, self._stride), np.inf, self.dtype)
        self._top = np.zeros(2 * s, self.dtype)
        self._top_min = np.full(2 * s, np.inf, self.dtype)

    def set(self, idx: np.ndarray, values: np.ndarray) -> None:
        """Batched leaf assignment + ancestor repair, the `_Tree.set`
        scheme per slice plus a top-tree lift for the touched slices."""
        if self._native is not None:
            self._native.set(idx, values)
            return
        idx = np.asarray(idx, np.int64).ravel()
        values = np.asarray(values, self.dtype).ravel()
        sl = idx // self.slice_cap
        node = (idx % self.slice_cap) + self.slice_cap
        self._sum[sl, node] = values
        self._min[sl, node] = values
        # unique (slice, parent) pairs as combined keys: all leaves sit
        # at the same depth, so parents stay level-aligned across slices
        # and one halving per iteration repairs one level everywhere
        comb = np.unique(sl * self._stride + (node >> 1))
        while True:
            sp, p = comb // self._stride, comb % self._stride
            if p[0] < 1:
                break
            left = p << 1
            self._sum[sp, p] = np.add(self._sum[sp, left],
                                      self._sum[sp, left | 1])
            self._min[sp, p] = np.minimum(self._min[sp, left],
                                          self._min[sp, left | 1])
            if p[0] == 1:
                break
            comb = np.unique(sp * self._stride + (p >> 1))
        touched = np.unique(sl)
        self._top[self.n_slices + touched] = self._sum[touched, 1]
        self._top_min[self.n_slices + touched] = self._min[touched, 1]
        parent = np.unique((self.n_slices + touched) >> 1)
        while parent[0] >= 1:
            left = parent << 1
            self._top[parent] = np.add(self._top[left], self._top[left | 1])
            self._top_min[parent] = np.minimum(self._top_min[left],
                                               self._top_min[left | 1])
            parent = np.unique(parent >> 1)
            if parent[0] == 0:
                break

    def get(self, idx: np.ndarray) -> np.ndarray:
        if self._native is not None:
            return self._native.get(np.asarray(idx, np.int64))
        idx = np.asarray(idx, np.int64)
        return self._sum[idx // self.slice_cap,
                         (idx % self.slice_cap) + self.slice_cap]

    def total(self) -> float:
        if self._native is not None:
            return self._native.sum()
        return float(self._top[1])

    def min(self) -> float:
        if self._native is not None:
            return self._native.min()
        return float(self._top_min[1])

    def slice_totals(self) -> np.ndarray:
        """Per-slice priority mass — diagnostic only (under the native
        backing this is a leaf gather + float sum, not the tree's exact
        bracketing)."""
        if self._native is not None:
            leaves = self._native.get(np.arange(self.capacity))
            return leaves.reshape(self.n_slices, -1).sum(axis=1)
        return self._sum[:, 1].copy()

    def find_prefixsum(self, prefix: np.ndarray) -> np.ndarray:
        """Batched inverse-CDF, two-phase lock-step descent: log2(S)
        steps through the top tree pick the slice, log2(slice_cap) steps
        through the slice trees (fancy-indexed across the batch) pick
        the leaf. Each step is the exact compare-subtract of
        ``SumTree.find_prefixsum`` over the exact same node values, so
        the returned slots match the single tree bitwise."""
        if self._native is not None:
            return self._native.find_prefixsum(prefix)
        # descend in the tree's own dtype: in float32 (device-twin) mode
        # every compare/subtract rounds exactly like the device descent
        p = np.asarray(prefix, self.dtype).copy()
        node = np.ones_like(p, dtype=np.int64)
        for _ in range(self._top_levels):
            left = node << 1
            left_sum = self._top[left]
            go_right = p >= left_sum
            p = np.where(go_right, p - left_sum, p)
            node = np.where(go_right, left | 1, left)
        sl = node - self.n_slices
        node = np.ones_like(p, dtype=np.int64)
        for _ in range(self._slice_levels):
            left = node << 1
            left_sum = self._sum[sl, left]
            go_right = p >= left_sum
            p = np.where(go_right, p - left_sum, p)
            node = np.where(go_right, left | 1, left)
        return sl * self.slice_cap + (node - self.slice_cap)


class DealtBlock(NamedTuple):
    """One ready-to-train unit: K stacked proportional samples with their
    IS weights, slot indices and sample-time generations (the write-back
    fence), plus the anneal step/beta they were weighted at and the trace
    id of the newest constituent frame (the ``deal`` span parent)."""

    batches: object  # TransitionBatch, arrays [K, B, ...]
    weights: np.ndarray  # [K, B] float32
    idx: np.ndarray  # [K, B] int64
    gen: np.ndarray  # [K, B] int64
    beta: float
    step: int
    tid: int  # 0 when no constituent frame was traced
    deal_seq: int


class SampleDealer:
    """The commit thread's sampled-block dealer.

    Single-writer discipline: the slice trees, the generation mirror,
    ``max_priority``, the RNG and the write-back queues all live under
    ONE ``sampler``-tier lock. Writers are the commit thread (insert
    mirror + settle + draw, reached while it already holds the buffer
    lock — a legal buffer(40) -> sampler(15) descent) and the shard
    workers draining their own slices' write-back queues (top-level
    acquire). Replicas only ever ENQUEUE write-backs — sampler tier
    only, which is what makes the replica sample path buffer-lock-free.

    ``ingest_and_deal`` must be called with the buffer lock held (it
    reads ``buffer.size`` and gathers rows); ``publish`` must be called
    after the buffer lock is released (it takes ring locks and stamps
    the ``deal`` trace span; never while holding the sampler tier, so no
    sampler -> ring edge exists at all).
    """

    def __init__(self, capacity: int, rings, *, n_shards: int, k: int,
                 batch_size: int, alpha: float = 0.6,
                 beta_schedule: SharedBetaSchedule | None = None,
                 min_size: int = 1, seed: int = 0, ring_capacity: int = 4,
                 max_deals_per_tick: int = 1, audit: bool = False,
                 scheme: str = "legacy"):
        if scheme not in ("legacy", "device"):
            raise ValueError(f"unknown SampleDealer scheme {scheme!r} "
                             "(want 'legacy' or 'device')")
        # scheme='device' is the DEVICE-TWIN oracle (tests only): float32
        # trees + the device stratification ((i + u) * total / B from
        # unit uniforms) + the shared jitted weight transform — every
        # draw is bitwise what replay/device_sampler.DeviceSampleDealer
        # produces from the same seed. Both schemes consume exactly B
        # doubles of the seeded stream per strata draw, so pause/resume
        # lockstep works across schemes unchanged.
        self.scheme = scheme
        self._sampler_lock = TieredLock("sampler")
        self._trees = ShardSlicePerTrees(
            capacity, n_shards,
            dtype=np.float32 if scheme == "device" else np.float64)
        self._n_shards = max(1, int(n_shards))
        self._rings = list(rings)
        self.k = int(k)
        self.batch_size = int(batch_size)
        self.alpha = float(alpha)
        self.min_size = max(1, int(min_size))
        self.ring_capacity = int(ring_capacity)
        # Deal budget per tick, per ring. The dealer runs INSIDE the
        # commit thread's buffer-lock window, so refilling a whole
        # ring's room in one tick (capacity x ~0.5 ms/draw) stalls the
        # ordered merge behind a multi-ms deal burst — measured as a
        # ~5 ms p50 bump on every commit-side stage at N=64. One block
        # per tick keeps the critical-section extension bounded by a
        # single draw; the ring's depth is the slack that absorbs the
        # commit/consume cadence mismatch instead.
        self.max_deals_per_tick = max(1, int(max_deals_per_tick))
        self._beta = beta_schedule or SharedBetaSchedule()
        # Same default_rng construction as ReplayBuffer: seed the dealer
        # with the buffer's seed and its draws replay the exact stream a
        # host sample_chunk loop over that buffer would consume — the
        # stream's identity is owned by the buffer, not the dealer.
        self._rng = np.random.default_rng(seed)  # jaxlint: stream-owner=ReplayBuffer._rng
        cap = self._trees.capacity
        self.max_priority = 1.0
        self._size = 0
        self._gen = np.zeros(cap, np.int64)  # jaxlint: guarded-by=_sampler_lock
        self._src_seq = np.full(cap, -1, np.int64)
        self._tid_of = np.zeros(cap, np.uint64)  # trace ids are u64 on the wire
        self._ins_seq = np.zeros(cap, np.int64)
        self._ins_counter = 0
        self._last_tid = 0  # newest insert's trace id (device deal span)
        self._wb = [deque() for _ in range(self._trees.n_slices)]
        self._wb_depth = 0
        self._wb_lag = REGISTRY.histogram("sampler.writeback_lag_ms")
        self._paused = False
        self._audit = bool(audit)
        self._dead: set = set()
        self._dead_fifo: deque = deque()
        self._deal_seq = 0
        self.dealt_blocks = 0
        self.dealt_rows = 0
        self.deals_skipped_full = 0
        self.deals_dropped = 0
        self.writeback_dropped_stale = 0
        self.dealt_dead_tickets = 0
        self.deal_busy_s = 0.0
        REGISTRY.register_provider("sampler", self.sampler_stats)

    @property
    def rings(self):
        """The per-replica dealt rings, replica-indexed (read-only view —
        ``ReplayService.attach_dealer`` wires their demand kicks)."""
        return tuple(self._rings)

    def set_pacing(self, max_deals_per_tick: int) -> None:
        """Live-adjust the per-tick deal budget (elastic actuator).

        Taken under the sampler lock so a mid-tick deal loop reads one
        coherent value; the budget bounds how far each commit's critical
        section is extended by drawing, so the autoscaler halves it when
        the ingest plane is the bottleneck and restores it when idle."""
        with self._sampler_lock:
            self.max_deals_per_tick = max(1, int(max_deals_per_tick))

    # -- commit-thread side (buffer lock held) ------------------------------
    def ingest_and_deal(self, inserts, buffer) -> list:
        """Mirror a commit's inserts, settle pending write-backs, then
        deal up to ``max_deals_per_tick`` blocks into every ring with
        room. Caller (the commit
        thread) HOLDS the buffer lock; rows are gathered here so the
        whole insert+sample+fetch pass costs the one lock window the
        commit already owned. Returns ``[(ring_index, DealtBlock)]`` for
        :meth:`publish` once the buffer lock is released. An empty
        ``inserts`` list is the idle top-up tick."""
        t0 = time.monotonic()
        dealt: list = []
        with self._sampler_lock:
            for idx, seq, tid in inserts:
                idx = np.asarray(idx, np.int64)
                self._gen[idx] += 1
                self._src_seq[idx] = -1 if seq is None else int(seq)
                self._tid_of[idx] = 0 if tid is None else int(tid)
                self._ins_counter += 1
                self._ins_seq[idx] = self._ins_counter
                if tid:
                    self._last_tid = int(tid)
                self._apply_insert_locked(idx)
            self._post_ingest_locked(buffer)
            self._size = int(buffer.size)
            # settle-then-draw inside one critical section: every draw
            # sees all write-backs queued before this tick, mirroring the
            # legacy update_priorities -> sample_chunk order
            self._settle_locked()
            if not self._paused and self._size >= self.min_size:
                for ri, ring in enumerate(self._rings):
                    room = ring.room()
                    if room == 0:
                        # skipped BEFORE any RNG use: backpressure must
                        # not desynchronize the sample stream (idle
                        # top-up ticks skip silently — only a commit
                        # that found no room is a missed deal)
                        if inserts:
                            self.deals_skipped_full += 1
                        continue
                    for _ in range(min(room, self.max_deals_per_tick)):
                        blk = self._draw_block_locked(buffer)
                        if blk is None:
                            break
                        dealt.append((ri, blk))
            self.deal_busy_s += time.monotonic() - t0
        return dealt

    def _apply_insert_locked(self, idx: np.ndarray) -> None:
        """Land one insert's priorities in the trees this dealer reads.
        Host dealer: mirror into the slice trees at the entry priority.
        The device dealer overrides this to a no-op — its priorities land
        in the DEVICE trees via the fused commit (``_post_ingest_locked``
        drains the buffer), not in a host mirror."""
        p = self.max_priority ** self.alpha
        self._trees.set(idx, np.full(len(idx), p))

    def _post_ingest_locked(self, buffer) -> None:
        """Hook between the insert mirror and the settle: the device
        dealer lands every staged row on the device here (same lock
        window as the ``buffer.add`` calls, which is what makes slot
        pre-assignment order equal commit order)."""

    def publish(self, dealt) -> None:
        """Push dealt blocks into their rings and stamp each block's
        ``deal`` span on its newest constituent frame's trace. Called
        with NO locks held; ring pushes cannot fail for capacity (room
        was reserved under the sampler lock and only this thread
        pushes), only for a concurrently closed ring."""
        for ri, blk in dealt:
            if blk.tid:
                obs_trace.RECORDER.record_span(blk.tid, "deal")
            if not self._rings[ri].offer(blk):
                with self._sampler_lock:
                    self.deals_dropped += 1

    def _draw_block_locked(self, buffer):
        """One K-chunk draw, bitwise the legacy host path:
        ``weight_base`` + ``sample_chunk`` over the merged trees."""
        total = self._trees.total()
        if total <= 0.0:
            return None
        size = self._size
        z = self._trees.min() / total * size  # PrioritizedReplayBuffer.weight_base
        t = self._beta.current_step()
        beta = self._beta.beta_at(t)
        idx = np.stack([self._sample_idx_locked(size) for _ in range(self.k)])
        if self.scheme == "device":
            # the SAME compiled float32 transform the device dealer
            # dispatches (device_per.block_weights_jitted): float32 ``**``
            # differs by 1 ulp between numpy and XLA, so sharing the
            # compiled artifact is the only way the oracle's weight
            # comparison can be exact rather than approximate
            from d4pg_tpu.replay import device_per as dper

            w = np.asarray(dper.block_weights_jitted(
                np.float32(total), np.float32(self._trees.min()),
                self._trees.get(idx).astype(np.float32),
                np.float32(beta), np.int32(size)))
        else:
            max_weight = z ** (-beta)
            w = []
            for i in range(self.k):
                p = self._trees.get(idx[i]) / total
                w.append(((p * size) ** (-beta)
                          / max_weight).astype(np.float32))
            w = np.stack(w)
        gen = self._gen[idx].copy()
        if self._audit and self._dead:
            hits = {int(s) for s in self._src_seq[idx.ravel()]} & self._dead
            self.dealt_dead_tickets += len(hits)
        flat = idx.ravel()
        tid = int(self._tid_of[flat[int(np.argmax(self._ins_seq[flat]))]])
        self._beta.advance(self.k)
        self._deal_seq += 1
        self.dealt_blocks += 1
        self.dealt_rows += self.k * self.batch_size
        return DealtBlock(buffer.gather(idx), w, idx, gen,
                          beta, t, tid, self._deal_seq)

    def _sample_idx_locked(self, size: int) -> np.ndarray:
        if self.scheme == "device":
            # device stratification from unit uniforms, float32 end to
            # end — numpy's f32 add/div/mul round exactly like XLA's, so
            # these masses (and the f32 descent they feed) are bitwise
            # the device deal dispatch's (device_per.strata_mass). The
            # stream cost is B doubles, same as the legacy draw.
            b = self.batch_size
            u = self._rng.uniform(0.0, 1.0, b).astype(np.float32)
            total = np.float32(self._trees.total())
            mass = (np.arange(b, dtype=np.float32) + u) * (
                total / np.float32(b))
            idx = self._trees.find_prefixsum(mass)
            return np.minimum(idx, max(size - 1, 0))
        # PrioritizedReplayBuffer.sample_idx, stratified scheme, verbatim
        total = self._trees.total()
        bounds = np.linspace(0.0, total, self.batch_size + 1)
        mass = self._rng.uniform(bounds[:-1], bounds[1:])
        idx = self._trees.find_prefixsum(mass)
        return np.minimum(idx, max(size - 1, 0))

    # -- replica side (sampler tier ONLY — never the buffer lock) -----------
    def queue_writeback(self, idx: np.ndarray, priorities: np.ndarray,
                        generation: np.ndarray) -> None:
        """Enqueue a grad step's TD priorities for the owning shards to
        apply. Generation-fenced at settle time; raw priorities travel,
        ``** alpha`` happens at the single writer."""
        idx = np.asarray(idx, np.int64).ravel()
        pri = np.asarray(priorities, np.float64).ravel()
        assert (pri > 0).all(), "priorities must be positive"
        gen = np.asarray(generation, np.int64).ravel()
        now = time.monotonic()
        sl = idx // self._trees.slice_cap
        with self._sampler_lock:
            for j in np.unique(sl):
                m = sl == j
                self._wb[j].append((idx[m], pri[m], gen[m], now))
                self._wb_depth += 1

    # -- shard-worker side --------------------------------------------------
    def drain_writebacks_for_shard(self, shard_idx: int) -> None:
        """Settle the write-back queues of the slices shard ``shard_idx``
        owns (slice j belongs to shard j mod n_shards). Called by the
        shard's worker thread at top level — the sum-tree write stays
        with its owner. Near-free when idle (unlocked depth probe,
        benign race under the GIL)."""
        if self._wb_depth == 0:
            return
        with self._sampler_lock:
            self._settle_locked(owner=int(shard_idx) % self._n_shards)

    def _settle_locked(self, owner: int | None = None) -> None:
        for j, q in enumerate(self._wb):
            if owner is not None and j % self._n_shards != owner:
                continue
            while q:
                idx, pri, gen, t_enq = q.popleft()
                self._wb_depth -= 1
                self._wb_lag.observe(1e3 * (time.monotonic() - t_enq))
                live = self._gen[idx] == gen
                if not live.all():
                    self.writeback_dropped_stale += int((~live).sum())
                    idx, pri = idx[live], pri[live]
                if len(idx) == 0:
                    continue
                # PrioritizedReplayBuffer.update_priorities, verbatim
                self._trees.set(idx, pri ** self.alpha)
                self.max_priority = max(self.max_priority, float(pri.max()))

    # -- lifecycle ----------------------------------------------------------
    def mark_dead_seqs(self, seqs) -> None:
        """Record shed/tombstoned/fenced ticket seqs for the audit
        cross-check (chaos pins ``dealt_dead_tickets == 0``)."""
        if not self._audit:
            return
        with self._sampler_lock:
            for s in seqs:
                s = int(s)
                if s in self._dead:
                    continue
                self._dead.add(s)
                self._dead_fifo.append(s)
                while len(self._dead_fifo) > _DEAD_SEQ_BOUND:
                    self._dead.discard(self._dead_fifo.popleft())

    def clear_rings(self) -> int:
        """Drop every queued block (restore: blocks dealt against the
        pre-restore generation must not train). Ring locks only — never
        called under the sampler tier."""
        return sum(r.clear() for r in self._rings)

    def pause_dealing(self) -> None:
        """Stop drawing (inserts and settles continue). With no draws
        there is no RNG use, so pause/resume is how the bitwise oracle
        runs the dealer in lockstep with its legacy twin."""
        with self._sampler_lock:
            self._paused = True

    def resume_dealing(self) -> None:
        with self._sampler_lock:
            self._paused = False

    def resync(self, buffer) -> None:
        """Re-derive the dealer's PER state from the buffer (attach /
        checkpoint restore). Caller holds the buffer lock. Pending
        write-backs are dropped — their generations are fenced by the
        restore's generation bump anyway."""
        with self._sampler_lock:
            self._trees.reset()
            self._size = int(buffer.size)
            self.max_priority = float(buffer.max_priority)
            self._gen = np.asarray(buffer.generation).copy()
            self._src_seq.fill(-1)
            self._tid_of.fill(0)
            self._ins_seq.fill(0)
            self._last_tid = 0
            if self._size:
                live = np.arange(self._size)
                # leaves already hold priority ** alpha (state_dict note)
                self._trees.set(live, np.asarray(buffer._trees.get(live)))
            for q in self._wb:
                q.clear()
            self._wb_depth = 0

    def sampler_stats(self) -> dict:
        """Registry provider: the ``sampler`` block."""
        with self._sampler_lock:
            d = {
                "dealt_blocks": self.dealt_blocks,
                "dealt_rows": self.dealt_rows,
                "dealer_queue_depth": self._wb_depth,
                "deals_skipped_full": self.deals_skipped_full,
                "deals_dropped": self.deals_dropped,
                "writeback_dropped_stale": self.writeback_dropped_stale,
                "dealt_dead_tickets": self.dealt_dead_tickets,
                "deal_busy_s": self.deal_busy_s,
                "paused": self._paused,
                "size": self._size,
                "max_priority": self.max_priority,
                "n_slices": self._trees.n_slices,
            }
        d["writeback_lag_ms"] = self._wb_lag.snapshot_dict()
        d["ring_depths"] = [r.depth() for r in self._rings]
        d["ring_capacity"] = self.ring_capacity
        return d

    def close(self) -> None:
        REGISTRY.unregister_provider("sampler", self.sampler_stats)
        for r in self._rings:
            r.close()
