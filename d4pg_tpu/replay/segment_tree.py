"""Array-backed segment trees with fully vectorized batched operations.

Parity: the reference's ``SegmentTree`` / ``SumSegmentTree`` /
``MinSegmentTree`` (``prioritized_replay_memory.py:33-162``, OpenAI-baselines
lineage). The reference walks the tree one element at a time in Python
(``find_prefixsum_idx`` at ``:143-148`` is a per-sample pointer chase —
SURVEY.md flags it as the throughput hazard for a TPU learner). Here:

  - the tree is one flat numpy array of size ``2 * capacity`` (node 1 is the
    root; leaf i lives at ``capacity + i``),
  - ``set`` updates B leaves at once, then repairs ancestors level-by-level
    on the *unique* touched parents — O(B log N) numpy kernel calls total,
  - ``find_prefixsum`` descends all B queries in lock-step: log2(N) vector
    steps, each a single compare/where over the batch.

An optional C++ native backend (``d4pg_tpu/replay/_native``) implements the
same interface for very large batch/capacity; see ``native.py``.
"""

from __future__ import annotations

import numpy as np


def next_pow2(n: int) -> int:
    """Smallest power of two >= n — the shared capacity/bucket rounding
    used by the host trees, the device ring's insert buckets and the
    device trees."""
    p = 1
    while p < n:
        p <<= 1
    return p


class _Tree:
    """Shared machinery; subclasses define the reduction."""

    _neutral: float
    _op = None  # np ufunc

    def __init__(self, capacity: int):
        self.capacity = next_pow2(int(capacity))
        self._levels = int(np.log2(self.capacity))
        self.tree = np.full(2 * self.capacity, self._neutral, np.float64)

    def set(self, idx: np.ndarray, values: np.ndarray) -> None:
        """Batched leaf assignment + ancestor repair."""
        idx = np.asarray(idx, np.int64)
        node = idx + self.capacity
        self.tree[node] = values
        parent = np.unique(node >> 1)
        while parent[0] >= 1:
            left = parent << 1
            self.tree[parent] = self._op(self.tree[left], self.tree[left | 1])
            parent = np.unique(parent >> 1)
            if parent[0] == 0:
                break

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self.tree[np.asarray(idx, np.int64) + self.capacity]

    @property
    def root(self) -> float:
        return float(self.tree[1])


class SumTree(_Tree):
    _neutral = 0.0
    _op = staticmethod(np.add)

    def sum(self) -> float:
        return self.root

    def find_prefixsum(self, prefix: np.ndarray) -> np.ndarray:
        """Batched inverse-CDF: for each p, the smallest leaf i such that
        ``sum(leaves[:i+1]) > p``. Vectorized lock-step descent — the
        reference's ``find_prefixsum_idx`` (``prioritized_replay_memory.py:
        126-149``) for a whole batch in log2(N) numpy steps."""
        p = np.asarray(prefix, np.float64).copy()
        node = np.ones_like(p, dtype=np.int64)  # root
        for _ in range(self._levels):
            left = node << 1
            left_sum = self.tree[left]
            go_right = p >= left_sum
            p = np.where(go_right, p - left_sum, p)
            node = np.where(go_right, left | 1, left)
        return node - self.capacity


class MinTree(_Tree):
    _neutral = np.inf
    _op = staticmethod(np.minimum)

    def min(self) -> float:
        return self.root
