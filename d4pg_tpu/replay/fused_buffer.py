"""Replay buffer for the fused device path: ring + PER trees in HBM.

Companion to ``learner/fused.py``. Ownership model (the part that makes
cross-thread donation safe): ``add`` — called from the ReplayService
drain thread under the buffer lock — only STAGES host rows; every device
mutation (ring scatter, tree insert, and the fused chunk's tree
write-back) happens on the learner thread, which is the single owner of
the ``trees``/storage handles. ``drain()`` flushes staged rows at chunk
boundaries, so inserts take effect between chunks — the same semantics
the host-PER path gets from its buffer lock, without the learner ever
blocking on actor ingest.

The generation guard the host path needs (``prioritized.py`` — a sampled
slot overwritten before its priority lands) is structurally unnecessary
here: priorities are written INSIDE the chunk, and inserts only happen
between chunks on the same thread.

Reference scope covered: ``prioritized_replay_memory.py:224-335``
(priority lifecycle) + ``replay_memory.py:14-80`` (ring), relocated to
the accelerator.
"""

from __future__ import annotations

import numpy as np

from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.device_ring import DeviceStore
from d4pg_tpu.replay.segment_tree import next_pow2
from d4pg_tpu.replay.uniform import TransitionBatch


class FusedDeviceReplay:
    """Fixed-capacity device ring + (optionally) device PER trees."""

    def __init__(
        self,
        capacity: int,
        obs_dim: int | tuple,
        act_dim: int,
        alpha: float = 0.6,
        prioritized: bool = True,
        obs_dtype=None,
        device=None,
    ):
        self.capacity = int(capacity)
        obs_shape = (obs_dim,) if np.isscalar(obs_dim) else tuple(obs_dim)
        if obs_dtype is None:
            obs_dtype = np.float32 if len(obs_shape) == 1 else np.uint8
        self._store = DeviceStore(self.capacity, obs_shape, act_dim,
                                  obs_dtype, device=device)
        self.prioritized = bool(prioritized)
        self.alpha = float(alpha)
        self.trees = dper.init(self.capacity) if prioritized else None
        self.size = 0
        self.head = 0
        self._staged: list[TransitionBatch] = []
        self._staged_rows = 0

    # -- ingest side (any thread, under the service's buffer lock) ---------
    def add(self, batch: TransitionBatch) -> None:
        """Stage host rows; cheap (no device work, no jit dispatch).

        Staging is bounded at ~ring capacity: if the learner pauses (long
        eval, checkpoint) while actors keep streaming, the oldest staged
        batches are dropped — they would only be overwritten by the next
        drain anyway, and an unbounded backlog could otherwise OOM the
        host (the host-buffer path is bounded at ring capacity too)."""
        n = batch.obs.shape[0]
        if n == 0:
            return
        if n > self.capacity:
            raise ValueError(f"batch of {n} exceeds capacity {self.capacity}")
        self._staged.append(
            TransitionBatch(*[np.asarray(v) for v in batch]))
        self._staged_rows += n
        while (self._staged_rows - self._staged[0].obs.shape[0]
               >= self.capacity):
            self._staged_rows -= self._staged.pop(0).obs.shape[0]

    def __len__(self) -> int:
        # staged rows count toward warmup gates — they WILL be trained on
        # (drained before the next chunk)
        return min(self.size + self._staged_rows, self.capacity)

    # -- learner side (single owner of the device handles) -----------------
    @property
    def storage(self) -> TransitionBatch:
        return self._store.arrays

    def drain(self) -> int:
        """Flush staged rows to the device (ring scatter + tree insert at
        ``max_priority ** alpha``). Learner thread only. Returns rows
        flushed."""
        if not self._staged:
            return 0
        batch = (self._staged[0] if len(self._staged) == 1 else
                 TransitionBatch(*[
                     np.concatenate([np.asarray(b[f]) for b in self._staged])
                     for f in range(len(self._staged[0]))]))
        self._staged.clear()
        self._staged_rows = 0
        n = batch.obs.shape[0]
        if n > self.capacity:
            # more staged than the ring holds: older rows would only be
            # overwritten — and duplicate slot indices in one scatter have
            # an unspecified winner, so keep exactly the newest `capacity`
            self.head = int((self.head + (n - self.capacity)) % self.capacity)
            batch = TransitionBatch(*[v[-self.capacity:] for v in batch])
            n = self.capacity
        idx = ((self.head + np.arange(n)) % self.capacity).astype(np.int32)
        self._store.write(idx, batch)
        if self.trees is not None:
            m = next_pow2(n)
            if m != n:
                # pad by repeating live slots: duplicate writes of the same
                # value are harmless to the trees (see device_per.insert)
                idx = np.concatenate([idx, np.full(m - n, idx[0], np.int32)])
            self.trees = dper.insert_jitted(self.trees, idx, self.alpha)
        self.head = int((self.head + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))
        return n

    def state_dict(self) -> dict:
        """Ring + tree state as host numpy for checkpointing. Learner
        thread only (drains staged rows first so nothing is lost)."""
        import jax

        from d4pg_tpu.replay.uniform import pack_rows

        self.drain()
        rows = jax.device_get(
            TransitionBatch(*[arr[:self.size] for arr in self.storage]))
        d = pack_rows(rows, self.head, self.size, self.capacity)
        if self.trees is not None:
            cap = self.trees.capacity
            d["leaf_priorities"] = np.asarray(
                self.trees.sum_tree[cap:cap + self.size])
            d["max_priority"] = float(self.trees.max_priority)
        return d

    def load_state_dict(self, d: dict) -> None:
        import jax.numpy as jnp

        from d4pg_tpu.replay.uniform import unpack_rows

        batch, head, size = unpack_rows(d, self.capacity)
        if batch is not None:
            self._store.write(np.arange(size, dtype=np.int32), batch)
        self.size = size
        self.head = head
        if self.trees is not None:
            trees = dper.init(self.capacity)
            if size:
                trees = dper.set_leaves_jitted(
                    trees, jnp.arange(size),
                    jnp.asarray(d["leaf_priorities"], jnp.float32))
            self.trees = trees._replace(
                max_priority=jnp.float32(d.get("max_priority", 1.0)))
