"""Replay buffer for the fused device path: ring + PER trees in HBM.

Companion to ``learner/fused.py``. Ownership model (the part that makes
cross-thread donation safe): ``add`` — called from the ReplayService
drain thread under the buffer lock — only STAGES host rows; every device
mutation (ring write, tree insert, and the fused chunk's tree write-back)
happens on the learner thread, which is the single owner of the
``trees``/storage handles. Staged rows take effect between chunks — the
same semantics the host-PER path gets from its buffer lock, without the
learner ever blocking on actor ingest.

Ingest fast path (the batched block drain; docs/architecture.md "Ingest
plane"): ``add`` copies rows column-major into a PREALLOCATED host
staging ring (no per-drain ``np.concatenate``, no per-row device work).
The learner moves a block with exactly two calls:

  - ``stage_block()`` — ONE ``jax.device_put`` of a fixed-shape
    [block_rows] frame (the H2D transfer; async under dispatch, so it
    overlaps the in-flight fused chunk's compute),
  - ``commit_staged()`` — ONE jitted dispatch fusing the two-slice ring
    write (``device_ring.block_write``) with the PER tree insert at
    ``max_priority ** alpha``; storage and trees are donated.

``drain()`` loops stage+commit until the staging ring is empty (cycle
boundaries, checkpointing); the overlapped schedule in
``learner/pipeline.IngestOverlap`` interleaves the two calls with fused
chunks so steady state pays ≤ 1 explicit H2D per chunk. ``drain_per_row``
keeps the old one-dispatch-per-row path as the measured baseline and the
bitwise-equivalence oracle (tests/test_ingest.py, bench.py).

The generation guard the host path needs (``prioritized.py`` — a sampled
slot overwritten before its priority lands) is structurally unnecessary
here: priorities are written INSIDE the chunk, and inserts only happen
between chunks on the same thread.

Reference scope covered: ``prioritized_replay_memory.py:224-335``
(priority lifecycle) + ``replay_memory.py:14-80`` (ring), relocated to
the accelerator.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from d4pg_tpu.obs.registry import REGISTRY
from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.device_ring import DeviceStore, block_write
from d4pg_tpu.replay.uniform import TransitionBatch


class HostStagingRing:
    """Preallocated column-major host staging for fixed-shape block frames.

    One contiguous buffer per transition field, ``n_blocks * block_rows``
    rows plus a ``block_rows`` scratch tail so the next frame is ALWAYS a
    contiguous in-bounds [block_rows] view (a partial or boundary-capped
    frame just carries a smaller valid count ``n``; rows past ``n`` are
    stale scratch masked out on device). ``push`` is slice assignment —
    the only host copy a row ever pays — and ``frame`` is zero-copy.

    Bounded like the list staging it replaces: when producers outrun the
    learner by more than the ring, the OLDEST staged rows are dropped
    (they would only be overwritten by the next drains anyway).

    Reuse discipline: a popped frame's rows are rewritten only after the
    write pointer laps the ring (≥ ``(n_blocks - 1) * block_rows`` newer
    rows), which keeps them intact for the duration of the in-flight
    ``device_put`` even on backends that complete H2D asynchronously.
    """

    def __init__(self, specs, block_rows: int, n_blocks: int):
        self.block_rows = int(block_rows)
        self.n_blocks = max(2, int(n_blocks))
        self.size = self.block_rows * self.n_blocks
        self._arrays = [
            np.zeros((self.size + self.block_rows, *shape), dtype)
            for shape, dtype in specs
        ]
        self._r = 0  # absolute rows consumed
        self._w = 0  # absolute rows written

    def __len__(self) -> int:
        return self._w - self._r

    def push(self, batch: TransitionBatch) -> None:
        n = batch.obs.shape[0]
        if n > self.size:  # keep only the newest ring-full
            batch = TransitionBatch(*[np.asarray(v)[-self.size:]
                                      for v in batch])
            n = self.size
        off = self._w % self.size
        first = min(n, self.size - off)
        for dst, src in zip(self._arrays, batch):
            src = np.asarray(src)
            dst[off:off + first] = src[:first]
            if first < n:
                dst[:n - first] = src[first:]
        self._w += n
        if self._w - self._r > self.size:
            self._r = self._w - self.size  # drop oldest

    def frame(self) -> tuple[TransitionBatch, int]:
        """Next pending frame as fixed-shape [block_rows] views + its
        valid row count (0 when empty). Capped at the ring boundary so
        the views stay contiguous."""
        off = self._r % self.size
        n = min(self._w - self._r, self.block_rows, self.size - off)
        views = TransitionBatch(*[a[off:off + self.block_rows]
                                  for a in self._arrays])
        return views, n

    def pop(self, n: int) -> None:
        self._r += n

    def take(self, n: int) -> list[TransitionBatch]:
        """Pop the ``n`` oldest staged rows as one or two per-field view
        batches (two when the run wraps the ring boundary). Zero-copy;
        the views are only valid until the writer laps the ring — the
        multi-ring merge copies them onward immediately
        (``staging.MultiRingStaging``)."""
        n = min(n, len(self))
        if n <= 0:
            return []
        off = self._r % self.size
        first = min(n, self.size - off)
        out = [TransitionBatch(*[a[off:off + first] for a in self._arrays])]
        if first < n:
            out.append(TransitionBatch(*[a[:n - first]
                                         for a in self._arrays]))
        self._r += n
        return out


class FusedDeviceReplay:
    """Fixed-capacity device ring + (optionally) device PER trees."""

    def __init__(
        self,
        capacity: int,
        obs_dim: int | tuple,
        act_dim: int,
        alpha: float = 0.6,
        prioritized: bool = True,
        obs_dtype=None,
        device=None,
        block_rows: int | None = None,
        staging_blocks: int = 8,
        ingest_shards: int = 1,
        gen_tracked: bool = False,
    ):
        self.capacity = int(capacity)
        obs_shape = (obs_dim,) if np.isscalar(obs_dim) else tuple(obs_dim)
        if obs_dtype is None:
            obs_dtype = np.float32 if len(obs_shape) == 1 else np.uint8
        self.block_rows = int(block_rows if block_rows is not None
                              else min(4096, self.capacity))
        self._device = device
        self._store = DeviceStore(self.capacity, obs_shape, act_dim,
                                  obs_dtype, device=device,
                                  block_rows=self.block_rows)
        self.prioritized = bool(prioritized)
        self.alpha = float(alpha)
        self.trees = dper.init(self.capacity) if prioritized else None
        self.size = 0
        self.head = 0
        # Generation-tracked mode (the device-dealt sample plane,
        # replay/device_sampler.DeviceSampleDealer): ``add`` pre-assigns
        # and returns slot indices (the dealer drains every staged row to
        # the device inside the same buffer-lock window, so assignment
        # order IS commit order), a host int64 generation mirror fences
        # priority write-backs, and the fused commit additionally bumps a
        # device int32 generation array so the deal dispatch can snapshot
        # sampled generations without a host sync. Tree VALUES stay
        # host-computed (``p_ins = max_priority ** alpha`` in float64,
        # cast float32): float32 ``**`` is not bitwise portable between
        # numpy and XLA, and keeping the pow on the host is what makes
        # the device trees bitwise-equal to the float32 host twin oracle.
        self.gen_tracked = bool(gen_tracked)
        if self.gen_tracked:
            if not self.prioritized:
                raise ValueError("gen_tracked needs prioritized=True "
                                 "(it exists for the PER dealt plane)")
            if int(ingest_shards) > 1:
                raise ValueError(
                    "gen_tracked needs ingest_shards=1: direct-staged "
                    "shard rows bypass add(), which owns slot assignment")
            import jax.numpy as jnp

            self.max_priority = 1.0
            self.generation = np.zeros(self.capacity, np.int64)
            self.gen = jnp.zeros(self.capacity, jnp.int32)
            self._next_slot = 0
        obs_dtype = np.dtype(obs_dtype)
        # staging covers ~one ring (small buffers) capped at
        # ``staging_blocks`` blocks (big ones): deeper backlogs would only
        # be overwritten by later drains
        n_blocks = max(2, min(int(staging_blocks),
                              -(-self.capacity // self.block_rows)))
        specs = [(obs_shape, obs_dtype), ((act_dim,), np.float32),
                 ((), np.float32), (obs_shape, obs_dtype), ((), np.float32),
                 ((), np.float32)]
        self.ingest_shards = max(1, int(ingest_shards))
        if self.ingest_shards > 1:
            # sharded ingest plane: K workers stage concurrently into
            # private rings; the merge hands the SAME fixed-shape frame
            # stream to stage_block/commit_staged (staging.MultiRingStaging)
            from d4pg_tpu.replay.staging import MultiRingStaging

            self._staging = MultiRingStaging(specs, self.block_rows,
                                             n_blocks, self.ingest_shards)
        else:
            self._staging = HostStagingRing(specs, self.block_rows, n_blocks)
        self._inflight: tuple[TransitionBatch, int] | None = None
        self._commit = self._make_commit()

    def _make_commit(self):
        import jax
        import jax.numpy as jnp

        capacity, block, alpha = self.capacity, self.block_rows, self.alpha
        write = partial(block_write, capacity=capacity, block_rows=block)

        if not self.prioritized:
            return jax.jit(write, donate_argnums=(0,))

        if self.gen_tracked:
            from d4pg_tpu.replay.segment_tree import next_pow2

            # pads park at the TREE capacity (>= ring capacity): dropped
            # by set_leaves' idx < capacity guard AND out of bounds for
            # the [capacity] generation array, so one pad value silences
            # both scatters. (The non-tracked path's repeat-the-first-
            # slot pad would bump that slot's generation spuriously.)
            padcap = next_pow2(capacity)

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def commit_tracked(storage, trees, gen, frame, start, n,
                               p_ins, max_pri):
                storage = write(storage, frame, start, n)
                row = jax.lax.iota(jnp.int32, block)
                idx = jnp.where(row < n, (start + row) % capacity, padcap)
                # p_ins is max_priority ** alpha computed on the HOST
                # (float64 pow, cast f32) — see the gen_tracked note in
                # __init__; the trees only ever see host-rounded values
                trees = dper.set_leaves(
                    trees, idx, jnp.full((block,), p_ins, jnp.float32))
                trees = trees._replace(max_priority=max_pri)
                gen = gen.at[idx].add(1, mode="drop")
                return storage, trees, gen

            return commit_tracked

        @partial(jax.jit, donate_argnums=(0, 1))
        def commit(storage, trees, frame, start, n):
            storage = write(storage, frame, start, n)
            row = jax.lax.iota(jnp.int32, block)
            # pad rows repeat the first live slot: duplicate writes of the
            # same value are harmless to the trees (see device_per.insert)
            idx = jnp.where(row < n, (start + row) % capacity,
                            start % capacity)
            trees = dper.insert(trees, idx, alpha)
            return storage, trees

        return commit

    # -- ingest side (any thread, under the service's buffer lock) ---------
    def add(self, batch: TransitionBatch):
        """Stage host rows into the preallocated column-major staging ring;
        cheap (slice copies — no device work, no jit dispatch). Staging is
        bounded: if the learner pauses (long eval, checkpoint) while actors
        keep streaming, the oldest staged rows are dropped — they would
        only be overwritten by the next drain anyway, and an unbounded
        backlog could otherwise OOM the host.

        In ``gen_tracked`` mode ``add`` also PRE-ASSIGNS the rows' ring
        slots (returned as the insert indices the dealer mirrors) and
        bumps their host generations. Assignment order is commit order
        because the device dealer drains the staging ring inside the
        same buffer-lock window as this call — enforced by refusing the
        silent oldest-drop that would desynchronize slots from rows."""
        n = batch.obs.shape[0]
        if n == 0:
            return np.empty(0, np.int64) if self.gen_tracked else None
        if self.gen_tracked:
            if len(self._staging) + n > self._staging.size:
                raise RuntimeError(
                    "gen_tracked staging overflow: the dealer must drain "
                    "every add within its buffer-lock window (backlog "
                    f"{len(self._staging)} + {n} > {self._staging.size})")
            slots = (self._next_slot + np.arange(n)) % self.capacity
            self._next_slot = int((self._next_slot + n) % self.capacity)
            self.generation[slots] += 1
            self._staging.push(batch)
            return slots
        if self.ingest_shards > 1:
            self._staging.push(batch, shard=0)
        else:
            self._staging.push(batch)
        return None

    def add_sharded(self, batch: TransitionBatch, shard: int,
                    ticket: int | None = None) -> None:
        """Stage host rows into shard ``shard``'s private ring — the
        concurrent half of the sharded ingest plane. Safe WITHOUT the
        service buffer lock: each ring has a single pushing worker and
        its own leaf lock against the learner's merge (the shard worker
        call site in ``ReplayService._worker``). ``ticket`` orders the
        merge; per-shard tickets must ascend (the admission seq does)."""
        if batch.obs.shape[0] == 0:
            return
        if self.ingest_shards > 1:
            self._staging.push(batch, shard=shard, ticket=ticket)
        else:
            self._staging.push(batch)

    def __len__(self) -> int:
        # staged + in-flight rows count toward warmup gates — they WILL be
        # trained on (drained before the next chunk)
        inflight = self._inflight[1] if self._inflight is not None else 0
        return min(self.size + len(self._staging) + inflight, self.capacity)

    # -- learner side (single owner of the device handles) -----------------
    @property
    def storage(self) -> TransitionBatch:
        return self._store.arrays

    # Every shipped caller of the three mutating learner-side entry
    # points below reaches them through ReplayService.ingest_stage/
    # ingest_commit/drain_device/load_replay_state, i.e. UNDER the
    # service's buffer lock; the guarded-by annotations declare that
    # caller contract to the unguarded-shared-write lock-graph rule
    # (bench.py drives the buffer directly, single-threaded).
    def stage_block(self) -> int:  # jaxlint: guarded-by=_buffer_lock
        """Start the H2D transfer of ONE pending block frame (a single
        ``jax.device_put`` of the fixed-shape [block_rows] views) — the
        only explicit transfer the ingest plane makes. No-op while a frame
        is already in flight (the double-buffer depth is one: block t+1
        stages while chunk t computes). Returns rows staged."""
        if self._inflight is not None:
            return 0
        views, n = self._staging.frame()
        if n == 0:
            return 0
        import jax

        frame = (jax.device_put(views, self._device)
                 if self._device is not None else jax.device_put(views))
        self._staging.pop(n)
        self._inflight = (frame, n)
        # one registry inc per BLOCK (never per row): the unified ledger
        # of the fused plane's H2D traffic (obs/registry)
        REGISTRY.counter("fused.rows_staged").inc(n)
        return n

    def commit_staged(self) -> int:  # jaxlint: guarded-by=_buffer_lock
        """Land the in-flight frame: ONE jitted dispatch fusing the
        two-slice ring write with the PER tree insert (storage and trees
        donated). Learner thread only. Returns rows committed."""
        if self._inflight is None:
            return 0
        frame, n = self._inflight
        self._inflight = None
        start = np.int32(self.head)
        if self.gen_tracked:
            # host-f64 pow, f32 cast: the trees only see host-rounded
            # values (bitwise twin contract — see __init__)
            p_ins = np.float32(self.max_priority ** self.alpha)
            storage, self.trees, self.gen = self._commit(
                self._store.arrays, self.trees, self.gen, frame, start,
                np.int32(n), p_ins, np.float32(self.max_priority))
        elif self.trees is not None:
            storage, self.trees = self._commit(
                self._store.arrays, self.trees, frame, start, np.int32(n))
        else:
            storage = self._commit(self._store.arrays, frame, start,
                                   np.int32(n))
        self._store.swap_arrays(storage)
        self.head = int((self.head + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))
        REGISTRY.counter("fused.rows_committed").inc(n)
        REGISTRY.counter("fused.blocks_committed").inc()
        return n

    # priority write-back for the dealt plane: reached from the device
    # dealer's settle inside the commit thread's buffer-lock window
    def apply_priorities(self, idx, p_alpha) -> None:  # jaxlint: guarded-by=_buffer_lock
        """Scatter settled write-back priorities (already ``** alpha``,
        float32) into the device trees: ONE jitted dispatch, trees
        donated (commit thread is the single owner). ``idx`` rows equal
        to the TREE capacity are pads and are dropped — the dealer pads
        to fixed buckets so steady state never recompiles."""
        self.trees = dper.set_leaves_jitted(self.trees, idx, p_alpha)

    def drain(self) -> int:
        """Flush ALL staged rows to the device (stage + commit per block
        until the staging ring is empty). Learner thread only; used at
        cycle boundaries and before checkpoint snapshots. The overlapped
        per-chunk schedule calls ``stage_block``/``commit_staged``
        directly (learner/pipeline.IngestOverlap)."""
        total = self.commit_staged()
        while self.stage_block():
            total += self.commit_staged()
        return total

    def drain_per_row(self) -> int:
        """The pre-block reference drain: one scatter dispatch + one tree
        insert PER ROW. Kept as the measured baseline for
        ``bench.py``'s ``ingest_rows_per_sec`` speedup claim and as the
        bitwise-equivalence oracle for the block path (the block drain
        must land exactly these bytes and priorities). Not used by any
        shipped loop."""
        total = self.commit_staged()  # a device-staged frame goes block-wise
        while True:
            frame, n = self._staging.frame()
            if n == 0:
                break
            self._staging.pop(n)
            for i in range(int(n)):
                idx = np.array([self.head], np.int32)
                row = TransitionBatch(*[np.asarray(v)[i:i + 1]
                                        for v in frame])
                # this IS the per-row anti-pattern (one H2D-carrying
                # dispatch per transition), preserved as baseline/oracle
                self._store.write(idx, row)
                if self.trees is not None:
                    self.trees = dper.insert_jitted(self.trees, idx,
                                                    self.alpha)
                self.head = int((self.head + 1) % self.capacity)
                self.size = int(min(self.size + 1, self.capacity))
            total += int(n)
        return total

    def state_dict(self) -> dict:
        """Ring + tree state as host numpy for checkpointing. Learner
        thread only (drains staged rows first so nothing is lost)."""
        import jax

        from d4pg_tpu.replay.uniform import pack_rows

        self.drain()
        rows = jax.device_get(
            TransitionBatch(*[arr[:self.size] for arr in self.storage]))
        d = pack_rows(rows, self.head, self.size, self.capacity)
        if self.trees is not None:
            cap = self.trees.capacity
            d["leaf_priorities"] = np.asarray(
                self.trees.sum_tree[cap:cap + self.size])
            # gen-tracked: the HOST scalar is authoritative (write-back
            # settles raise it between commits; the device copy only
            # refreshes at the next commit dispatch)
            d["max_priority"] = (float(self.max_priority)
                                 if self.gen_tracked
                                 else float(self.trees.max_priority))
        return d

    def snapshot(self) -> dict:
        """Crash-recovery cut: ``state_dict`` (the drain inside it
        collapses every staging ring head into the device ring, so the
        cut has NO in-flight rows) plus the staging plane's ticket floor
        when sharded — everything a fresh buffer needs to resume bitwise
        at this point. Learner thread only, like ``state_dict``."""
        d = self.state_dict()
        stg = getattr(self._staging, "snapshot", None)
        if stg is not None:
            d["staging"] = stg()
        return d

    def restore(self, d: dict) -> None:
        """Load a ``snapshot`` cut into this (fresh) buffer. Same caller
        contract as ``load_state_dict``: reached under the service's
        buffer lock (or single-threaded, e.g. the bench oracle)."""
        self.load_state_dict(d)
        stg = getattr(self._staging, "restore", None)
        if stg is not None and "staging" in d:
            stg(d["staging"])

    # restore mutates ring+tree state: reached via ReplayService.
    # load_replay_state under the buffer lock, like the paths above
    def load_state_dict(self, d: dict) -> None:  # jaxlint: guarded-by=_buffer_lock
        import jax.numpy as jnp

        from d4pg_tpu.replay.uniform import unpack_rows

        batch, head, size = unpack_rows(d, self.capacity)
        if batch is not None:
            self._store.write(np.arange(size, dtype=np.int32), batch)
        self.size = size
        self.head = head
        if self.trees is not None:
            trees = dper.init(self.capacity)
            if size:
                trees = dper.set_leaves_jitted(
                    trees, jnp.arange(size),
                    jnp.asarray(d["leaf_priorities"], jnp.float32))
            self.trees = trees._replace(
                max_priority=jnp.float32(d.get("max_priority", 1.0)))
        if self.gen_tracked:
            # restore opens a fresh generation epoch: live rows at 1,
            # everything else 0, host mirror and device copy in lockstep
            # — any block dealt against the pre-restore state carries
            # generations that no longer match and is fenced at settle
            self.max_priority = float(d.get("max_priority", 1.0))
            self._next_slot = self.head
            self.generation = np.zeros(self.capacity, np.int64)
            self.generation[:self.size] = 1
            self.gen = jnp.asarray(self.generation, jnp.int32)
