"""Annealing schedules.

Parity: the reference's ``LinearSchedule`` (``prioritized_replay_memory.py:
5-29``), used for PER beta annealing 0.4 -> 1.0 over 100k steps
(``ddpg.py:82-86``). The reference's schedule is *stateful* — ``value()``
increments an internal counter on every call (``:25-29``), which couples the
annealing rate to how often anyone asks. Here the schedule is a pure function
of an explicit step ``t`` (the learner's step counter), which is also what
lets it live inside checkpointed train state and stay exact across resume.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class LinearSchedule:
    schedule_timesteps: int
    final_p: float
    initial_p: float = 1.0

    def value(self, t: int | float):
        """Linear interpolation initial_p -> final_p, clamped after T."""
        frac = min(float(t) / float(self.schedule_timesteps), 1.0)
        return self.initial_p + frac * (self.final_p - self.initial_p)
