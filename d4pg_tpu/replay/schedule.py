"""Annealing schedules.

Parity: the reference's ``LinearSchedule`` (``prioritized_replay_memory.py:
5-29``), used for PER beta annealing 0.4 -> 1.0 over 100k steps
(``ddpg.py:82-86``). The reference's schedule is *stateful* — ``value()``
increments an internal counter on every call (``:25-29``), which couples the
annealing rate to how often anyone asks. Here the schedule is a pure function
of an explicit step ``t`` (the learner's step counter), which is also what
lets it live inside checkpointed train state and stay exact across resume.
"""

from __future__ import annotations

import dataclasses
import itertools


@dataclasses.dataclass(frozen=True)
class LinearSchedule:
    schedule_timesteps: int
    final_p: float
    initial_p: float = 1.0

    def value(self, t: int | float):
        """Linear interpolation initial_p -> final_p, clamped after T."""
        frac = min(float(t) / float(self.schedule_timesteps), 1.0)
        return self.initial_p + frac * (self.final_p - self.initial_p)


class SharedBetaSchedule:
    """One PER-beta anneal clock shared by every sampler in the process.

    The PR-10 defect this fixes: with N learner replicas each replica
    annealed beta off its OWN ``steps_done``, so two replicas at the same
    global training step could hand different IS-weight exponents to the
    same buffer — the anneal rate scaled with N and the weights stopped
    being a function of training progress.

    Design: the only shared mutable state is an ``itertools.count`` step
    source (``next()`` is a single bytecode under CPython's GIL, so
    claiming ticks is lock-free and never double-counts), and
    :meth:`beta_at` is a PURE function of an explicit step — two callers
    that hold the same ``t`` compute bit-identical beta no matter how
    their claims interleave. ``completed()`` is an advisory progress
    snapshot (benign read race; purity of ``beta_at`` is what the
    concurrency regression test pins, not snapshot freshness).
    """

    def __init__(self, beta0: float = 0.4, beta_steps: int = 100_000,
                 start_step: int = 0):
        self.beta0 = float(beta0)
        self.beta_steps = int(beta_steps)
        self._steps = itertools.count(int(start_step))
        self._completed = int(start_step)  # advisory, monotone-ish

    def current_step(self) -> int:
        """Claim-free read of the current global step: the value the next
        claimer WOULD get. Callers snapshot this once per chunk and feed
        it back to :meth:`beta_at` so beta is constant within the chunk
        (exactly the legacy single-replica ``_beta`` behavior). Named
        uniquely on purpose: ``step`` would name-collide with
        ``WeightStore.step`` in the lint lock graph's call resolution."""
        return self._completed

    def beta_at(self, t: int) -> float:
        """Pure linear anneal beta0 -> 1.0 over ``beta_steps`` — the same
        expression ``LearnerReplica._beta`` used, so single-replica runs
        stay bitwise identical."""
        frac = min(1.0, t / max(1, self.beta_steps))
        return self.beta0 + (1.0 - self.beta0) * frac

    def advance(self, n: int) -> int:
        """Consume ``n`` anneal ticks; returns the first claimed tick.
        GIL-atomic per tick — concurrent replicas never claim the same
        tick twice and the clock never runs backwards."""
        first = next(self._steps)
        for _ in range(int(n) - 1):
            next(self._steps)
        self._completed = first + int(n)
        return first
