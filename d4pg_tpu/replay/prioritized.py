"""Prioritized experience replay with vectorized proportional sampling.

Parity: the reference's ``PrioritizedReplayBuffer``
(``prioritized_replay_memory.py:224-335``):

  - new transitions enter with priority ``max_priority ** alpha`` (``:251-256``),
  - proportional sampling by inverse-CDF over the sum tree (``:258-265``),
  - importance-sampling weights ``(p_i * N) ** -beta`` normalized by the max
    weight, computed from the min tree (``:299-313``),
  - ``update_priorities`` writes ``priority ** alpha`` into both trees and
    tracks the running max (``:315-335``).

Differences: all operations are batched numpy (or the C++ native sampler,
``backend='native'`` / ``native/per_trees.cpp``); sampling segments the
total mass into B strata (one uniform draw per stratum), which is the
standard variance-reduction refinement of the reference's B independent
uniform draws (``:263-264``) — set ``stratified=False`` for the
reference's exact scheme.
"""

from __future__ import annotations

import numpy as np

from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch


class _NumpyPerTrees:
    """Sum+min tree pair behind the combined interface the buffer uses
    (the native backend implements the same one in C++)."""

    def __init__(self, capacity: int):
        self._sum_tree = SumTree(capacity)
        self._min_tree = MinTree(capacity)
        self.capacity = self._sum_tree.capacity

    def set(self, idx: np.ndarray, values: np.ndarray) -> None:
        self._sum_tree.set(idx, values)
        self._min_tree.set(idx, values)

    def sum(self) -> float:
        return self._sum_tree.sum()

    def min(self) -> float:
        return self._min_tree.min()

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self._sum_tree.get(idx)

    def find_prefixsum(self, prefix: np.ndarray) -> np.ndarray:
        return self._sum_tree.find_prefixsum(prefix)


def _make_trees(capacity: int, backend: str):
    if backend not in ("auto", "numpy", "native"):
        raise ValueError(f"unknown PER backend {backend!r}")
    if backend in ("auto", "native"):
        try:
            from d4pg_tpu.replay.native import NativePerTrees

            return NativePerTrees(capacity)
        except (RuntimeError, OSError):
            if backend == "native":
                raise
    return _NumpyPerTrees(capacity)


class PrioritizedReplayBuffer(ReplayBuffer):
    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        act_dim: int,
        alpha: float = 0.6,
        seed: int = 0,
        stratified: bool = True,
        backend: str = "auto",
        obs_dtype=None,
        storage: str = "host",
        device=None,
    ):
        super().__init__(capacity, obs_dim, act_dim, seed=seed,
                         obs_dtype=obs_dtype, storage=storage, device=device)
        assert alpha >= 0
        self.alpha = float(alpha)
        self.stratified = bool(stratified)
        self._trees = _make_trees(self.capacity, backend)
        self.max_priority = 1.0
        # Per-slot write generation: with async actors, a slot sampled by
        # the learner can be overwritten by the drain thread before the TD
        # error comes back; a generation captured at sample time lets
        # update_priorities drop those writes instead of stamping a stale
        # priority onto a brand-new transition.
        self.generation = np.zeros(self.capacity, np.int64)

    def add(self, batch: TransitionBatch) -> np.ndarray:
        idx = super().add(batch)
        self.generation[idx] += 1
        p = self.max_priority**self.alpha
        self._trees.set(idx, np.full(len(idx), p))
        return idx

    def sample_idx(self, batch_size: int) -> np.ndarray:
        if self.size == 0:
            raise ValueError("cannot sample from an empty buffer")
        total = self._trees.sum()
        if self.stratified:
            bounds = np.linspace(0.0, total, batch_size + 1)
            mass = self._rng.uniform(bounds[:-1], bounds[1:])
        else:
            mass = self._rng.uniform(0.0, total, size=batch_size)
        idx = self._trees.find_prefixsum(mass)
        # guard: prefix just at/over the total can land on an unwritten leaf
        return np.minimum(idx, max(self.size - 1, 0))

    def weight_base(self) -> float:
        """``z = (p_min / total) * N`` — the scalar whose ``z ** -beta`` is
        the max IS weight. Multi-host sharded replay allgather-mins this
        across hosts so every shard normalizes by the same global max
        weight (per-host normalizers would scale gradient contributions
        inconsistently across hosts)."""
        total = self._trees.sum()
        return float(self._trees.min() / total * self.size)

    def is_weights(
        self, idx: np.ndarray, beta: float,
        weight_base: float | None = None,
    ) -> np.ndarray:
        """(p_i * N)^-beta / max_weight, max via the min tree
        (``prioritized_replay_memory.py:299-311``). ``weight_base``
        overrides the local ``z`` (see :meth:`weight_base`)."""
        assert beta > 0
        total = self._trees.sum()
        z = self.weight_base() if weight_base is None else weight_base
        max_weight = z ** (-beta)
        p = self._trees.get(idx) / total
        return ((p * self.size) ** (-beta) / max_weight).astype(np.float32)

    def sample(
        self, batch_size: int, beta: float = 0.4,
        weight_base: float | None = None,
    ) -> tuple[TransitionBatch, np.ndarray, np.ndarray]:
        """Returns (batch, is_weights, idx); idx feeds update_priorities."""
        idx = self.sample_idx(batch_size)
        return self.gather(idx), self.is_weights(idx, beta, weight_base), idx

    def sample_chunk(
        self, k: int, batch_size: int, beta: float = 0.4,
        weight_base: float | None = None,
    ) -> tuple[TransitionBatch, np.ndarray, np.ndarray]:
        """K stacked proportional samples in ONE storage gather: (batches
        [K, B, ...], weights [K, B], idx [K, B]). Tree walks and IS weights
        stay on the host; with device storage only the idx array crosses."""
        idx = np.stack([self.sample_idx(batch_size) for _ in range(k)])
        w = np.stack([self.is_weights(idx[i], beta, weight_base)
                      for i in range(k)])
        return self.gather(idx), w.astype(np.float32), idx

    def state_dict(self) -> dict:
        d = super().state_dict()
        # leaves already hold priority ** alpha; restore writes them back
        # verbatim (only live slots — unwritten min-tree leaves must stay
        # at the +inf neutral or p_min collapses to 0)
        d["leaf_priorities"] = np.asarray(
            self._trees.get(np.arange(self.size)))
        d["max_priority"] = self.max_priority
        d["generation"] = self.generation.copy()
        return d

    def load_state_dict(self, d: dict) -> None:
        super().load_state_dict(d)
        if self.size:
            self._trees.set(np.arange(self.size), d["leaf_priorities"])
        self.max_priority = float(d["max_priority"])
        self.generation = np.asarray(d["generation"]).copy()

    def update_priorities(
        self,
        idx: np.ndarray,
        priorities: np.ndarray,
        generation: np.ndarray | None = None,
    ) -> None:
        """Write ``priority ** alpha`` into the trees
        (``prioritized_replay_memory.py:315-335``). When ``generation``
        (captured at sample time) is given, entries whose slot has since
        been overwritten are dropped."""
        priorities = np.asarray(priorities, np.float64)
        assert (priorities > 0).all(), "priorities must be positive"
        if generation is not None:
            live = self.generation[idx] == generation
            if not live.all():
                idx, priorities = idx[live], priorities[live]
            if len(idx) == 0:
                return
        self._trees.set(idx, priorities**self.alpha)
        self.max_priority = max(self.max_priority, float(priorities.max()))
