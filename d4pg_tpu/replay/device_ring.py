"""Device-resident replay storage: the transition ring lives in HBM.

TPU-native redesign of the replay data path (no reference equivalent — the
reference's buffers are per-process Python lists, ``replay_memory.py:14-19``,
``prioritized_replay_memory.py:164-222``): host<->device bandwidth, not
FLOPs, bounds a tunneled/PCIe-attached learner, and shipping every sampled
batch from host RAM costs O(batch bytes) per dispatch (25MB/chunk at
Humanoid sizes). With the ring in HBM the host keeps only the PER trees and
picks INDICES; the device gathers rows locally:

  - per-dispatch H2D drops to the [K, B] int32 index array (~16KB),
  - inserts stream the actor batches once (they must cross anyway),
  - the gathered chunk is already on device for the scanned update.

Inserts are padded up to power-of-two buckets so XLA compiles a handful of
scatter shapes instead of one per batch size; pad rows carry index ==
capacity and are dropped by the scatter (``mode='drop'``).
"""

from __future__ import annotations

from functools import partial

import numpy as np

from d4pg_tpu.replay.segment_tree import next_pow2 as _bucket
from d4pg_tpu.replay.uniform import TransitionBatch


class DeviceStore:
    """Fixed-capacity transition storage on an accelerator device.

    Same write/read interface as the host numpy storage inside
    ``ReplayBuffer``; ``read`` accepts [B] or [K, B] index arrays and
    returns device arrays (zero host copies).
    """

    def __init__(
        self,
        capacity: int,
        obs_shape: tuple,
        act_dim: int,
        obs_dtype,
        device=None,
    ):
        import jax
        import jax.numpy as jnp

        self.capacity = int(capacity)
        storage = TransitionBatch(
            obs=jnp.zeros((capacity, *obs_shape), obs_dtype),
            action=jnp.zeros((capacity, act_dim), jnp.float32),
            reward=jnp.zeros((capacity,), jnp.float32),
            next_obs=jnp.zeros((capacity, *obs_shape), obs_dtype),
            done=jnp.zeros((capacity,), jnp.float32),
            discount=jnp.zeros((capacity,), jnp.float32),
        )
        self._storage = (
            jax.device_put(storage, device) if device is not None else
            jax.device_put(storage)
        )

        @partial(jax.jit, donate_argnums=(0,))
        def _insert(storage, idx, batch):
            return TransitionBatch(*[
                arr.at[idx].set(val.astype(arr.dtype), mode="drop")
                for arr, val in zip(storage, batch)
            ])

        @jax.jit
        def _gather(storage, idx):
            return TransitionBatch(*[arr[idx] for arr in storage])

        self._insert = _insert
        self._gather = _gather

    @property
    def arrays(self) -> TransitionBatch:
        """The raw [capacity, ...] device arrays (read-only input to the
        fused learner path, ``learner/fused.py``)."""
        return self._storage

    def write(self, idx: np.ndarray, batch: TransitionBatch) -> None:
        n = len(idx)
        m = _bucket(n)
        if m != n:
            pad = m - n
            # pad index == capacity -> out of bounds -> dropped by the scatter
            idx = np.concatenate(
                [idx, np.full(pad, self.capacity, idx.dtype)])
            batch = TransitionBatch(*[
                np.concatenate([np.asarray(v),
                                np.zeros((pad, *np.asarray(v).shape[1:]),
                                         np.asarray(v).dtype)])
                for v in batch
            ])
        self._storage = self._insert(
            self._storage, np.asarray(idx, np.int32), batch)

    def read(self, idx: np.ndarray) -> TransitionBatch:
        """Gather rows on device; idx [B] or [K, B] (host or device ints)."""
        return self._gather(self._storage, np.asarray(idx, np.int32))
