"""Device-resident replay storage: the transition ring lives in HBM.

TPU-native redesign of the replay data path (no reference equivalent — the
reference's buffers are per-process Python lists, ``replay_memory.py:14-19``,
``prioritized_replay_memory.py:164-222``): host<->device bandwidth, not
FLOPs, bounds a tunneled/PCIe-attached learner, and shipping every sampled
batch from host RAM costs O(batch bytes) per dispatch (25MB/chunk at
Humanoid sizes). With the ring in HBM the host keeps only the PER trees and
picks INDICES; the device gathers rows locally:

  - per-dispatch H2D drops to the [K, B] int32 index array (~16KB),
  - inserts stream the actor batches once (they must cross anyway),
  - the gathered chunk is already on device for the scanned update.

Two write paths:

  - ``write``: scatter by explicit index array (padded up to power-of-two
    buckets so XLA compiles a handful of scatter shapes; pad rows carry an
    out-of-bounds index and are dropped by ``mode='drop'``). Used for
    checkpoint restore and as the per-row reference path.
  - ``write_block``: the ingest fast path — ONE fixed-shape [block_rows]
    frame lands with a single dispatch built from two dynamic-slice
    updates (no scatter). The ring carries ``block_rows`` shadow rows past
    ``capacity``: the block is blended in contiguously at ``start`` (rows
    past the ring end spill into the shadow), then the spilled tail is
    mirrored into the ring head — wraparound as a second masked slice
    instead of a modular scatter. Partial blocks mask by ``n``; the shape
    is static, so steady-state ingest never recompiles.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from d4pg_tpu.replay.segment_tree import next_pow2 as _bucket
from d4pg_tpu.replay.uniform import TransitionBatch


def block_write(storage: TransitionBatch, frame: TransitionBatch,
                start, n, *, capacity: int, block_rows: int):
    """Pure two-slice block landing (see module docstring): blend a
    [block_rows] ``frame`` into the ring at ``start`` (first dynamic
    slice), then mirror the wrapped spill from the shadow tail into the
    head (second slice). ``n`` masks partial frames. Shared by the
    DeviceStore jit and the fused commit in ``replay/fused_buffer.py``
    (which fuses it with the PER tree insert into ONE dispatch)."""
    import jax
    import jax.numpy as jnp

    row = jax.lax.iota(jnp.int32, block_rows)
    wrapped = jnp.maximum(start + n - capacity, 0)

    def upd(arr, val):
        mask = (row < n).reshape((block_rows,) + (1,) * (arr.ndim - 1))
        cur = jax.lax.dynamic_slice_in_dim(arr, start, block_rows)
        arr = jax.lax.dynamic_update_slice_in_dim(
            arr, jnp.where(mask, val.astype(arr.dtype), cur), start, 0)
        # wraparound: rows that spilled past `capacity` also belong at the
        # ring head — static-position tail/head slices, so the whole write
        # is two dynamic_update_slices, no scatter
        tail = jax.lax.dynamic_slice_in_dim(arr, capacity, block_rows)
        head = jax.lax.dynamic_slice_in_dim(arr, 0, block_rows)
        hmask = (row < wrapped).reshape((block_rows,) + (1,) * (arr.ndim - 1))
        return jax.lax.dynamic_update_slice_in_dim(
            arr, jnp.where(hmask, tail, head), 0, 0)

    return TransitionBatch(*[upd(arr, val) for arr, val in zip(storage, frame)])


class DeviceStore:
    """Fixed-capacity transition storage on an accelerator device.

    Same write/read interface as the host numpy storage inside
    ``ReplayBuffer``; ``read`` accepts [B] or [K, B] index arrays and
    returns device arrays (zero host copies). ``block_rows > 0``
    additionally compiles the two-slice block writer (and allocates that
    many shadow rows — consumers must index only ``[0, capacity)``, which
    every sampler already does).
    """

    def __init__(
        self,
        capacity: int,
        obs_shape: tuple,
        act_dim: int,
        obs_dtype,
        device=None,
        block_rows: int = 0,
    ):
        import jax
        import jax.numpy as jnp

        self.capacity = int(capacity)
        self.block_rows = int(block_rows)
        if self.block_rows > self.capacity:
            raise ValueError(
                f"block_rows {block_rows} exceeds capacity {capacity}")
        # shadow rows past the ring end absorb a block's wraparound spill
        # (mirrored into the head by write_block); index `rows` is the one
        # guaranteed-out-of-bounds scatter-drop index either way
        rows = self.capacity + self.block_rows
        self._rows = rows
        storage = TransitionBatch(
            obs=jnp.zeros((rows, *obs_shape), obs_dtype),
            action=jnp.zeros((rows, act_dim), jnp.float32),
            reward=jnp.zeros((rows,), jnp.float32),
            next_obs=jnp.zeros((rows, *obs_shape), obs_dtype),
            done=jnp.zeros((rows,), jnp.float32),
            discount=jnp.zeros((rows,), jnp.float32),
        )
        self._storage = (
            jax.device_put(storage, device) if device is not None else
            jax.device_put(storage)
        )

        @partial(jax.jit, donate_argnums=(0,))
        def _insert(storage, idx, batch):
            return TransitionBatch(*[
                arr.at[idx].set(val.astype(arr.dtype), mode="drop")
                for arr, val in zip(storage, batch)
            ])

        @jax.jit
        def _gather(storage, idx):
            return TransitionBatch(*[arr[idx] for arr in storage])

        self._insert = _insert
        self._gather = _gather
        self._write_block = (
            self._make_write_block() if self.block_rows else None)

    def _make_write_block(self):
        import jax

        return jax.jit(
            partial(block_write, capacity=self.capacity,
                    block_rows=self.block_rows),
            donate_argnums=(0,))

    @property
    def arrays(self) -> TransitionBatch:
        """The raw [capacity (+ shadow), ...] device arrays (read-only
        input to the fused learner path, ``learner/fused.py``; samplers
        index only ``[0, capacity)``)."""
        return self._storage

    def write(self, idx: np.ndarray, batch: TransitionBatch) -> None:
        n = len(idx)
        m = _bucket(n)
        if m != n:
            pad = m - n
            # pad index == total rows -> out of bounds -> dropped
            idx = np.concatenate(
                [idx, np.full(pad, self._rows, idx.dtype)])
            batch = TransitionBatch(*[
                np.concatenate([np.asarray(v),
                                np.zeros((pad, *np.asarray(v).shape[1:]),
                                         np.asarray(v).dtype)])
                for v in batch
            ])
        self._storage = self._insert(
            self._storage, np.asarray(idx, np.int32), batch)

    def write_block(self, start: int, frame: TransitionBatch, n: int) -> None:
        """Land ``n`` valid rows of a fixed-shape [block_rows] ``frame``
        at ring position ``start`` in ONE dispatch (see module docstring).
        ``frame`` may already live on device (staged by an earlier
        ``device_put``) — the dispatch then moves no row bytes at all."""
        if self._write_block is None:
            raise RuntimeError("DeviceStore built without block_rows")
        self._storage = self._write_block(
            self._storage, frame, np.int32(start), np.int32(n))

    def swap_arrays(self, storage: TransitionBatch) -> None:
        """Adopt updated storage handles (the fused commit in
        ``replay/fused_buffer.py`` runs the block write inside its own
        dispatch, fused with the tree insert, and hands the result back)."""
        self._storage = storage

    def read(self, idx: np.ndarray) -> TransitionBatch:
        """Gather rows on device; idx [B] or [K, B] (host or device ints)."""
        return self._gather(self._storage, np.asarray(idx, np.int32))
