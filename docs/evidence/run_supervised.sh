#!/bin/bash
# Crash-tolerant run supervisor for long evidence runs on this image.
#
# The in-process dm_control renderer (Mesa swrast on this EGL-less VM)
# can GPF the whole training process (see docs/evidence/dmc-pixels/
# README.md, round 4) — a native-library hazard, not a framework bug.
# This wrapper turns such a crash into a resume: every segment runs
# with --resume 1 against the same run dir, so a restart continues
# from the latest Orbax checkpoint + step-stamped replay sidecar.
#
# Usage: run_supervised.sh <max_restarts> <logfile> -- <train args...>
# Stops when the training process exits 0 (run complete) or the
# restart budget is exhausted (persistently failing config).
set -u
if [ $# -lt 3 ]; then
  echo "usage: run_supervised.sh <max_restarts> <logfile> -- <train args...>" >&2
  exit 2
fi
MAX=$1; LOG=$2; shift 2
[ "$1" = "--" ] && shift
case " $* " in
  *" --resume 1 "*|*" --resume=1 "*|*" --resume 1"|*" --resume=1") ;;
  *)
    # without --resume 1 every restart would silently reinitialize the
    # run and the log would splice unrelated curves — the one invariant
    # this supervisor exists to uphold ('--resume 0' is just as wrong
    # as omitting it)
    echo "run_supervised.sh: train args must include '--resume 1'" >&2
    exit 2 ;;
esac
n=0
while true; do
  python -m d4pg_tpu.train "$@" >>"$LOG" 2>&1
  code=$?
  if [ $code -eq 0 ]; then echo "[supervisor] run complete" >>"$LOG"; exit 0; fi
  n=$((n+1))
  if [ $n -gt "$MAX" ]; then
    echo "[supervisor] exit $code; restart budget ($MAX) exhausted" >>"$LOG"
    exit $code
  fi
  echo "[supervisor] exit $code; restart $n/$MAX in 10s" >>"$LOG"
  sleep 10
done
