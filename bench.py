"""Benchmark: D4PG learner grad-steps/sec on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The HEADLINE value is the END-TO-END learner rate — PER sample (native
sum-tree backend) -> host->device staging -> K-step scanned update ->
priority write-back, i.e. everything the shipped training loop does per
grad step (``ddpg.py:200-255`` is the reference scope: sample, nets,
projection, optimizer, priorities). ``device_only`` reports the pure
device rate of the scanned update on a pre-staged batch for comparison.

The config is the north star from BASELINE.md: Humanoid-v4-sized D4PG
(obs 376, act 17, batch 256, 51 atoms, 256-wide MLPs). ``vs_baseline`` is
measured against the reference implementation's achievable update rate:
the reference's train step is host-bound — its categorical projection
runs a per-atom Python/NumPy loop on the host (``ddpg.py:142-185``) plus
four network passes and optimizer steps in torch on CPU (the reference
never uses CUDA; ``utils.py:5`` is a comment). BASELINE.json publishes no
numbers, so the baseline figure here is measured fresh each run with an
equivalent torch-CPU step when torch is available, else a recorded
constant.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


from d4pg_tpu.probe import describe, ensure_backend

BATCH = 256
OBS_DIM, ACT_DIM = 376, 17  # Humanoid-v4 (BASELINE.md config #3)
N_ATOMS = 51
STEPS = 320
# torch-CPU reference measurement recorded on this image (2026-07-29,
# measured by bench_reference_torch_cpu below); fallback when the live
# measurement is unavailable.
RECORDED_BASELINE_SPS = 39.6
# fused-learner median from the newest committed accelerator artifact
# (BENCH_r05); the denominator for the host-side tracing-overhead bound
# in bench_fleet_latency (a live TPU capture would refresh it).
RECORDED_FUSED_STEPS_PER_SEC = 152_630.0


def _bench_config():
    """THE benchmark model shape, shared by every path below AND by the
    MFU numerator — measuring throughput of one shape and FLOPs of
    another would silently corrupt the MFU."""
    from d4pg_tpu.learner import D4PGConfig

    return D4PGConfig(obs_dim=OBS_DIM, act_dim=ACT_DIM, v_min=0.0,
                      v_max=800.0, n_atoms=N_ATOMS, hidden=(256, 256, 256),
                      compute_dtype="bfloat16")


def _random_batch(rng, prefix: tuple):
    """A TransitionBatch of random rows with leading dims ``prefix``."""
    from d4pg_tpu.replay.uniform import TransitionBatch

    return TransitionBatch(
        obs=rng.standard_normal((*prefix, OBS_DIM)).astype(np.float32),
        action=rng.uniform(-1, 1, (*prefix, ACT_DIM)).astype(np.float32),
        reward=rng.standard_normal(prefix).astype(np.float32),
        next_obs=rng.standard_normal((*prefix, OBS_DIM)).astype(np.float32),
        done=np.zeros(prefix, np.float32),
        discount=np.full(prefix, 0.99, np.float32),
    )


def _fill(buffer, capacity: int, rng, drain: bool = False) -> None:
    chunk = 4096
    for _ in range(capacity // chunk):
        buffer.add(_random_batch(rng, (chunk,)))
        if drain:
            buffer.drain()


def bench_tpu(k: int = 16, repeats: int = 5) -> list[float]:
    """Learner grad-steps/sec with the production K-updates-per-dispatch
    path (``make_multi_update``; the single-dispatch step is dispatch-bound
    at ~4k steps/sec on this chip). Returns ``repeats`` independent
    timed-window rates from ONE warm process: the device-only path has no
    host round trips, so any spread across these windows is chip-side
    (clock/contention/window placement) — the attribution the ROADMAP
    perf-variance item asks for (41k→54.6k across captures)."""
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.learner import init_state, make_multi_update

    config = _bench_config()
    state = init_state(config, jax.random.key(0))
    update = make_multi_update(config, donate=True, use_is_weights=True)

    rng = np.random.default_rng(0)
    batch = jax.device_put(_random_batch(rng, (k, BATCH)))
    weights = jax.device_put(jnp.ones((k, BATCH), jnp.float32))

    # warmup/compile
    state, metrics = update(state, batch, weights)
    jax.block_until_ready(metrics["critic_loss"])

    n_dispatch = max(1, STEPS // k)
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(n_dispatch):
            state, metrics = update(state, batch, weights)
        jax.block_until_ready(metrics["critic_loss"])
        rates.append(n_dispatch * k / (time.perf_counter() - t0))
    return rates


def bench_end_to_end(k: int = 16, capacity: int = 200_000,
                     steps: int = 640) -> float:
    """End-to-end learner grad-steps/sec: PER sample + H2D staging + K-step
    scanned update + priority write-back, through the SAME ``ChunkPipeline``
    ``train.py`` ships (the host samples chunk t+1 while the device runs
    chunk t; priorities land with staleness <= 2K)."""
    import jax
    from d4pg_tpu.learner import init_state, make_multi_update
    from d4pg_tpu.learner.pipeline import ChunkPipeline
    from d4pg_tpu.replay import LinearSchedule, PrioritizedReplayBuffer

    config = _bench_config()
    state = init_state(config, jax.random.key(0))
    update = make_multi_update(config, donate=True, use_is_weights=True)
    # shipped default (train.py 'auto'): ring in HBM on an accelerator,
    # so a dispatch ships [K, B] indices instead of [K, B, 376] rows
    storage = "device" if jax.default_backend() != "cpu" else "host"
    buffer = PrioritizedReplayBuffer(capacity, OBS_DIM, ACT_DIM, alpha=0.6,
                                     storage=storage)
    beta = LinearSchedule(100_000, 1.0, 0.4)
    _fill(buffer, capacity, np.random.default_rng(0))

    lstep = 0

    def sample_chunk():
        batches, w, idx = buffer.sample_chunk(k, BATCH, beta=beta.value(lstep))
        return (batches, w), idx

    def write_back(idx_list, td):
        for i, idx in enumerate(idx_list):
            buffer.update_priorities(idx, td[i])

    def on_chunk(_state):
        nonlocal lstep
        lstep += k

    pipeline = ChunkPipeline(update, sample_chunk, write_back=write_back)

    state, m = pipeline.run(state, 2, on_chunk=on_chunk)  # warmup/compile
    jax.block_until_ready(m["critic_loss"])
    n_dispatch = max(1, steps // k)
    t0 = time.perf_counter()
    state, m = pipeline.run(state, n_dispatch, on_chunk=on_chunk)
    dt = time.perf_counter() - t0
    return n_dispatch * k / dt


def bench_fused(k: int = 40, capacity: int = 200_000,
                steps: int = 1600, repeats: int = 5) -> list[float]:
    """End-to-end learner rate through the FUSED path (the shipped default
    on device storage, ``learner/fused.py``): PER trees + transition ring
    both in HBM; stratified sample, gather, K-step update and priority
    write-back all inside one scanned dispatch. Zero per-chunk host round
    trips, zero priority staleness — at K=1 these are exactly the
    reference's per-step semantics (``ddpg.py:200-255``) executed on
    device.

    Returns ``repeats`` independent timed-window rates (VERDICT r4 #3: a
    single capture moved 2.5x run-to-run with tunnel health; the headline
    must carry its own spread) plus the steady-state sentinel counts: the
    timed windows run under ``RecompileSentinel`` (which ASSERTS zero XLA
    compilations after the warmup dispatch — a silent recompile would turn
    the headline number into compilation-time measurement) and
    ``TransferSentinel`` (explicit host<->device transfers; the fused
    path's claim is that steady state makes none), and the
    ``ReshardSentinel`` count of resharding collectives (all-to-all /
    collective-permute) in the compiled HLO of the fused dispatch — the
    dynamic twin of the ``sharding-spec-drift`` lint family, asserted
    zero."""
    import jax

    from d4pg_tpu.io.profiling import (
        RecompileSentinel,
        ReshardSentinel,
        TransferSentinel,
    )
    from d4pg_tpu.learner import init_state
    from d4pg_tpu.learner.fused import make_fused_chunk
    from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

    config = _bench_config()
    state = init_state(config, jax.random.key(0))
    buffer = FusedDeviceReplay(capacity, OBS_DIM, ACT_DIM, alpha=0.6)
    _fill(buffer, capacity, np.random.default_rng(0), drain=True)
    fn = make_fused_chunk(config, k=k, batch_size=BATCH, prioritized=True,
                          alpha=0.6, donate=True)

    state, buffer.trees, m = fn(state, buffer.trees, buffer.storage,
                                buffer.size)  # warmup/compile
    jax.block_until_ready(m["critic_loss"])
    # lower() never executes (so donated buffers survive): scan the HLO
    # the warm cache will replay for resharding copies before timing it
    reshards = ReshardSentinel()
    reshards.inspect(fn, state, buffer.trees, buffer.storage, buffer.size)
    reshards.assert_clean("bench_fused compiled dispatch")
    n_dispatch = max(1, steps // k)
    rates = []
    with RecompileSentinel() as recompiles, TransferSentinel() as transfers:
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(n_dispatch):
                state, buffer.trees, m = fn(state, buffer.trees,
                                            buffer.storage, buffer.size)
            jax.block_until_ready(m["critic_loss"])
            rates.append(n_dispatch * k / (time.perf_counter() - t0))
    recompiles.assert_clean("bench_fused steady-state loop")
    return (rates, recompiles.compilations, transfers.total,
            reshards.steady_state_reshards)


def bench_ingest(capacity: int = 200_000, block_rows: int = 4096,
                 rows: int = 65_536, per_row_rows: int = 1024) -> dict:
    """Ingest-plane throughput (rows/sec): the vectorized block drain
    (solo), the old one-dispatch-per-row drain it replaced (the measured
    baseline for the ≥10x claim), and the block drain OVERLAPPED with
    fused chunks — the shipped schedule (``learner/pipeline.IngestOverlap``:
    commit block t, dispatch chunk t, device_put block t+1 under chunk
    t's compute) — with the ≤ 1 explicit-H2D-per-chunk invariant checked
    by ``TransferSentinel`` and zero steady-state recompiles asserted."""
    import jax

    from d4pg_tpu.io.profiling import RecompileSentinel, TransferSentinel
    from d4pg_tpu.learner import init_state
    from d4pg_tpu.learner.fused import make_fused_chunk
    from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

    rng = np.random.default_rng(0)
    feed = _random_batch(rng, (block_rows,))  # reused: ingest cost, not rng

    def fresh():
        buf = FusedDeviceReplay(capacity, OBS_DIM, ACT_DIM, alpha=0.6,
                                block_rows=block_rows)
        buf.add(feed)
        buf.drain()  # warm the stage/commit compile
        jax.block_until_ready(buf.storage.obs)
        return buf

    # -- solo block drain --------------------------------------------------
    buf = fresh()
    n_blocks = max(1, rows // block_rows)
    t0 = time.perf_counter()
    drained = 0
    for _ in range(n_blocks):
        buf.add(feed)
        drained += buf.drain()
    jax.block_until_ready(buf.storage.obs)
    solo = drained / (time.perf_counter() - t0)

    # -- per-row baseline (the path this PR removed from the hot loop) -----
    buf = fresh()
    small = _random_batch(rng, (8,))
    buf.add(small)
    buf.drain_per_row()  # warm the 1-row write/insert compiles
    buf.add(_random_batch(rng, (per_row_rows,)))
    t0 = time.perf_counter()
    n_rows = buf.drain_per_row()
    jax.block_until_ready(buf.storage.obs)
    per_row = n_rows / (time.perf_counter() - t0)

    # -- concurrent with the fused chunk (the shipped overlap schedule) ----
    k, steps = 40, 800
    config = _bench_config()
    state = init_state(config, jax.random.key(0))
    buf = fresh()
    _fill(buf, capacity, rng, drain=True)
    fn = make_fused_chunk(config, k=k, batch_size=BATCH, prioritized=True,
                          alpha=0.6, donate=True)
    state, buf.trees, m = fn(state, buf.trees, buf.storage, buf.size)
    jax.block_until_ready(m["critic_loss"])
    buf.add(feed)
    buf.stage_block()  # prime the double buffer
    n_dispatch = max(1, steps // k)
    committed = 0
    with RecompileSentinel() as rec, TransferSentinel() as tr:
        t0 = time.perf_counter()
        for _ in range(n_dispatch):
            committed += buf.commit_staged()
            state, buf.trees, m = fn(state, buf.trees, buf.storage,
                                     buf.size)
            buf.add(feed)  # actors keep streaming
            buf.stage_block()  # H2D overlaps the in-flight chunk
        jax.block_until_ready(m["critic_loss"])
        dt = time.perf_counter() - t0
    rec.assert_clean("bench_ingest concurrent loop")
    assert tr.h2d <= n_dispatch + 1, (
        f"{tr.h2d} explicit H2D over {n_dispatch} chunks breaks the "
        "<=1-per-chunk invariant")

    # -- ingest-stage latency block (obs plane) ----------------------------
    # per-block stage (ONE device_put) and commit (ONE jitted dispatch)
    # latencies as histograms, plus the measured registry overhead the
    # unified counters add per row (they inc per BLOCK, so the per-row
    # cost is inc_ns * incs_per_block / block_rows — reported against
    # the measured per-row ingest budget).
    from d4pg_tpu.obs.registry import REGISTRY, percentile_summary

    stage_ms, commit_ms = [], []
    buf = fresh()
    for _ in range(32):
        buf.add(feed)
        while True:
            t0 = time.perf_counter()
            n_staged = buf.stage_block()
            stage_ms.append(1e3 * (time.perf_counter() - t0))
            if not n_staged:
                stage_ms.pop()  # empty probe, not a stage
                break
            t0 = time.perf_counter()
            buf.commit_staged()
            commit_ms.append(1e3 * (time.perf_counter() - t0))
    jax.block_until_ready(buf.storage.obs)
    c = REGISTRY.counter("bench.calibration")
    t0 = time.perf_counter()
    for _ in range(100_000):
        c.inc()
    inc_ns = 1e9 * (time.perf_counter() - t0) / 100_000
    incs_per_block = 4  # staging push + fused staged/committed/blocks
    row_budget_ns = 1e9 / solo if solo else None
    overhead_pct = (round(100.0 * inc_ns * incs_per_block
                          / (block_rows * row_budget_ns), 4)
                    if row_budget_ns else None)
    latency = {
        "unit": "ms",
        "stages": {
            "stage_block": percentile_summary(stage_ms),
            "commit_staged": percentile_summary(commit_ms),
        },
        "registry_inc_ns": round(inc_ns, 1),
        "registry_overhead_pct": overhead_pct,
    }

    # -- device-dealt sample path (descent fused behind the commit) --------
    # The gen-tracked ring + DeviceSampleDealer: every ingest tick
    # stages ONE block (the only explicit H2D), commits priorities +
    # generations in the one jitted dispatch, then runs the stratified
    # descent ON DEVICE and deals device-resident blocks. Sentinels pin
    # the tentpole claims: zero steady-state recompiles, zero
    # sampled-row H2D (every explicit put is a staged frame), and zero
    # resharding collectives in the compiled deal dispatch.
    from d4pg_tpu.io.profiling import ReshardSentinel
    from d4pg_tpu.replay.device_sampler import DeviceSampleDealer
    from d4pg_tpu.replay.staging import DeviceDealtBlockRing

    ring = DeviceDealtBlockRing(8)
    dbuf = FusedDeviceReplay(capacity, OBS_DIM, ACT_DIM, alpha=0.6,
                             block_rows=block_rows, gen_tracked=True)
    dealer = DeviceSampleDealer(capacity, [ring], k=8, batch_size=BATCH,
                                min_size=BATCH, seed=0,
                                max_deals_per_tick=2)
    dealer.resync(dbuf)

    def ingest_tick(seq: int) -> None:
        slots = dbuf.add(feed)
        dealer.publish(dealer.ingest_and_deal([(slots, seq, None)], dbuf))

    ingest_tick(1)  # warm stage/commit/deal compiles
    while ring.pop(timeout=0) is not None:
        pass
    deal_rounds, dealt_blocks, dealt_rows = 24, 0, 0
    with RecompileSentinel() as drec, TransferSentinel() as dtr:
        t0 = time.perf_counter()
        for i in range(deal_rounds):
            ingest_tick(i + 2)
            while True:
                block = ring.pop(timeout=0)
                if block is None:
                    break
                dealt_blocks += 1
                dealt_rows += int(block.idx.shape[0] * block.idx.shape[1])
        jax.block_until_ready(dbuf.trees.sum_tree)
        ddt = time.perf_counter() - t0
    drec.assert_clean("bench_ingest device-dealt loop")
    # every explicit H2D must be a staged actor frame; the sample path
    # itself moves NO rows host->device (gathers stay device-resident)
    assert dtr.h2d <= deal_rounds, (
        f"{dtr.h2d} explicit H2D over {deal_rounds} ingest ticks — the "
        "device sample path must only pay the staged-frame puts")
    resh = ReshardSentinel()
    u = np.zeros((dealer.k, dealer.batch_size), np.float32)
    resh.inspect(dealer.deal_fn, dbuf.storage, dbuf.trees.sum_tree,
                 dbuf.trees.min_tree, dbuf.gen, u, np.int32(dbuf.size))
    resh.assert_clean("device deal dispatch")
    device_dealt = {
        "arm": dealer.arm,
        "blocks_dealt": dealt_blocks,
        "dealt_rows_per_sec": round(dealt_rows / ddt, 1) if ddt else None,
        "sampled_row_h2d": 0,
        "h2d_per_ingest": round(dtr.h2d / deal_rounds, 3),
        "steady_state_recompiles": drec.compilations,
        "deal_reshard_collectives": resh.steady_state_reshards,
    }
    return {
        "solo": round(solo, 1),
        "concurrent": round(committed / dt, 1),
        "per_row_baseline": round(per_row, 1),
        "speedup_vs_per_row": round(solo / per_row, 1) if per_row else None,
        "concurrent_grad_steps_per_sec": round(n_dispatch * k / dt, 2),
        "block_rows": block_rows,
        "h2d_per_chunk": round(tr.h2d / n_dispatch, 3),
        "steady_state_recompiles": rec.compilations,
        "latency": latency,
        "device_dealt": device_dealt,
    }


def bench_fleet_latency(n_actors: int = 64, duration_s: float = 10.0,
                        seed: int = 0, chaos=None,
                        rows_per_sec: float = 60.0) -> dict:
    """The wire-to-grad latency block (docs/architecture.md
    "Observability plane"): a seeded N>=64 chaos run over the sharded
    (K=2, v2 raw) plane with trace sampling at the default rate —
    per-stage latency histograms p50/p95/p99 with end-to-end
    wire-to-grad as the headline — plus the measured tracing overhead:

      - an identical untraced twin run (same seed, same chaos script)
        prices the rows/s loss of sampling + span recording + the
        concurrent consumer lane against the plane's throughput,
      - a host microbench of the per-chunk learner hook (mark_grad +
        two registry incs) bounds the fused-steps/s loss: the hook is
        the ONLY code tracing adds to the fused learner loop, so
        loss <= hook_ns / (K * per-step budget at the recorded
        BENCH_r05 rate).
    """
    from d4pg_tpu.fleet.chaos import ChaosConfig
    from d4pg_tpu.fleet.harness import FleetConfig, FleetHarness
    from d4pg_tpu.fleet.sweep import default_chaos
    from d4pg_tpu.obs.registry import REGISTRY
    from d4pg_tpu.obs.trace import DEFAULT_SAMPLE, RECORDER

    chaos = default_chaos(seed) if chaos is None else chaos
    if not isinstance(chaos, ChaosConfig):
        chaos = ChaosConfig(seed=seed)

    def run(sample: float) -> dict:
        cfg = FleetConfig(n_actors=n_actors, duration_s=duration_s,
                          rows_per_sec=rows_per_sec, ingest_shards=2,
                          chaos=chaos, trace_sample=sample)
        return FleetHarness(cfg).run()

    traced = run(DEFAULT_SAMPLE)
    untraced = run(0.0)
    rps_t, rps_u = traced["rows_per_sec"], untraced["rows_per_sec"]
    # per-chunk learner hook: mark_grad on an idle recorder + the two
    # registry incs the fused commit path pays per block
    RECORDER.disable()
    c = REGISTRY.counter("bench.calibration")
    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        RECORDER.mark_grad()
        c.inc()
        c.inc()
    hook_ns = 1e9 * (time.perf_counter() - t0) / reps
    # fused plane: K=40 steps/chunk at the recorded BENCH_r05 median —
    # the hook runs once per chunk, so its per-step share is hook/K
    k = 40
    step_budget_ns = 1e9 / RECORDED_FUSED_STEPS_PER_SEC
    fused_loss_pct = round(100.0 * (hook_ns / k) / step_budget_ns, 4)
    block = dict(traced["latency"] or {})
    block["overhead"] = {
        "rows_per_sec_traced": rps_t,
        "rows_per_sec_untraced": rps_u,
        "rows_loss_pct": (round(100.0 * (rps_u - rps_t) / rps_u, 2)
                          if rps_u else None),
        "hook_ns_per_chunk": round(hook_ns, 1),
        "fused_steps_loss_pct_bound": fused_loss_pct,
        "sample_rate": DEFAULT_SAMPLE,
    }
    block["n_actors"] = n_actors
    block["ingest_shards"] = 2
    block["frames_traced"] = traced["frames_traced"]
    block["seed"] = chaos.seed
    return block


def bench_fleet(ns=(8, 32, 64, 128, 256), duration_s: float = 10.0,
                seed: int = 0, chaos: bool = True,
                shard_ks=(1, 2, 4), shard_rows_per_sec: float = 60.0) -> dict:
    """Fleet fan-out sweep (``d4pg_tpu/fleet``): rows/s into ONE replay
    service from N throttled chaos-wrapped sender lanes over real TCP,
    N up to the BASELINE-mandated 256, with p50/p99 send latency, counted
    drops (chaos / backpressure / receiver sheds), retry and eviction/
    re-admission counts, and crash→recovery times. Pure host+TCP plane —
    no accelerator involved — so it runs identically everywhere.

    The artifact carries TWO sweeps: the N sweep at K=1 (continuity with
    PR 3's numbers) and the ``ingest_shards`` sweep K ∈ ``shard_ks`` at
    N=max(ns) with offered load raised to ``shard_rows_per_sec`` per lane
    so the RECEIVER saturates — rows/s-per-shard, scaling efficiency and
    the margin over the old ~5,200 rows/s single-core ceiling are
    recorded per K. Every row also carries a ``locks`` block (the
    ``core/locking.py`` tier sentinels run armed through the whole
    sweep): per-tier acquisitions/contended/wait_ns/max_hold_ns and the
    hierarchy-violation count — must be 0 in every committed artifact —
    and the shard-sweep scaling table rolls the waits up as
    ``lock_wait_ms`` per K, so a multi-core K-sweep can attribute flat
    scaling to lock contention instead of guessing. Invoked standalone
    as ``python bench.py --fleet`` (persists the artifact under
    docs/evidence/fleet/)."""
    from d4pg_tpu.fleet.chaos import ChaosConfig
    from d4pg_tpu.fleet.sweep import (
        default_chaos,
        run_elastic,
        run_learners,
        run_recovery,
        run_sampler,
        run_serving,
        run_sweep,
        run_weights,
        shard_sweep,
    )

    cc = default_chaos(seed) if chaos else ChaosConfig(seed=seed)
    artifact = run_sweep(ns=ns, duration_s=duration_s, chaos=cc)
    artifact["shard_sweep"] = shard_sweep(
        ks=shard_ks, n_actors=max(ns), duration_s=duration_s,
        rows_per_sec=shard_rows_per_sec, chaos=cc)
    for row in artifact["shard_sweep"]["sweep"]:
        row.pop("chaos_log", None)
    # wire-to-grad latency block: per-stage histograms from a seeded
    # N>=64 chaos run + measured tracing overhead (tier-1 schema-checked
    # in tests/test_obs.py so later PRs can't silently drop it)
    artifact["latency"] = bench_fleet_latency(
        n_actors=max(64, min(ns)), duration_s=duration_s, seed=seed,
        chaos=cc, rows_per_sec=shard_rows_per_sec)
    # crash-recovery block: one service_chaos run (N>=64, K=2, full fault
    # set + two seeded learner kills) — MTTR, fence/loss ledger, restart
    # counts — plus the deterministic bitwise restore-vs-oracle probe.
    # Schema-checked in tier-1 (tests/test_recovery.py) like the latency
    # block, so later PRs can't silently drop it.
    artifact["recovery"] = run_recovery(
        n_actors=max(64, min(ns)), duration_s=duration_s,
        ingest_shards=2, seed=seed)
    # weight-broadcast block: one weight-chaos run (N>=64 pullers over a
    # depth-2 relay tree, torn/stale injection, a relay crash and a
    # learner kill at generation+1) — snapshots/s, delta hit-rate,
    # pull->publish staleness percentiles, and the three run-gating
    # oracles (accepted-frames ledger, trace orphans, lock hierarchy).
    # Schema-checked in tier-1 (tests/test_weight_plane.py) like the
    # latency and recovery blocks.
    artifact["weights"] = run_weights(
        n_pullers=max(64, min(ns)), relay_depth=2,
        duration_s=duration_s, seed=seed, learner_kills=1)
    # multi-learner block: updates/s vs replica count (kill-free rows
    # with staleness percentiles + correction-clip rate per N), then one
    # learner-chaos run at N=4 with seeded replica kills — replayed
    # in-flight frames must bounce off the dead epoch and the published
    # (generation, version) ledger must never rewind. Schema-checked in
    # tier-1 (tests/test_learner_plane.py) like the blocks above.
    artifact["learners"] = run_learners(
        ns=(1, 2, 4), duration_s=min(duration_s, 4.0), seed=seed,
        replica_kills=2)
    # serving block: actions/s vs lane count through the continuous-
    # batching PolicyInferenceServer, the batched-vs-unbatched pair at
    # equal lane count (the headline ratio — absolute rates are one-core
    # conservative), and one server-kill + torn-response chaos row with
    # MTTR. Schema-checked in tier-1 (tests/test_serving.py) like the
    # blocks above.
    artifact["serving"] = run_serving(
        lane_counts=(1, 2, 4), duration_s=min(duration_s, 4.0),
        seed=seed, server_kills=1)
    # sample-on-ingest block: the dealer-vs-host A/B pair (wire_to_grad
    # p95 each arm, buffer-lock acquisitions on the consume path — the
    # dealer arm's pinned 0 by construction) + one dealer chaos row at
    # N=64 (consumer kills + ring clears, shed pressure, stale-gen frame
    # injection) gated by 0 deadlocks/violations/orphans/dealt dead
    # tickets. Schema-checked in tier-1 (tests/test_sampler.py) like the
    # blocks above.
    artifact["sampler"] = run_sampler(
        n_actors=max(64, min(ns)), duration_s=min(duration_s, 6.0),
        seed=seed, learner_kills=2, stale_frames=8)
    # elastic block: the flash-crowd autoscaler-on/off A/B drill at equal
    # seeded offered load (fleet/elastic_chaos.py) — serving SLO breaches
    # and ingest shed rows per arm (the autoscaler arm must be strictly
    # better on BOTH), per-class shed attribution, the scaling-decision
    # ledger with its bit-identical replay oracle, and the offered-load
    # determinism probe. Safe in this parent: run_serving above already
    # initialized the single-core CPU backend this block shares.
    # Schema-checked in tier-1 (tests/test_elastic.py) like the blocks
    # above.
    artifact["elastic"] = run_elastic(seed=seed)
    # mesh-learners block: the socket-vs-collective aggregation A/B at
    # equal offered load (fleet/mesh_ab.py) — updates/s each arm and
    # per-round aggregation latency p50/p95 per replica count. The only
    # fleet block that needs a JAX backend, so it runs in a child
    # process with virtual devices; this parent stays accelerator-free.
    # Schema-checked in tier-1 (tests/test_mesh_replicas.py).
    artifact["mesh_learners"] = _run_mesh_learners_child(seed)
    return artifact


def _run_mesh_learners_child(seed: int) -> dict:
    """Run the mesh_learners A/B in a child with 8 virtual CPU devices
    (the fleet parent keeps JAX uninitialized by design). A failed child
    returns an error stub instead of sinking the whole artifact — the
    schema gate on the committed artifact still catches it."""
    import subprocess

    env = dict(os.environ)
    env["D4PG_BENCH_MESH_CHILD"] = "1"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8".strip())
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-learners",
             f"--seed={seed}"],
            env=env, capture_output=True, text=True, timeout=1800)
    except subprocess.TimeoutExpired:
        return {"metric": "fleet_mesh_learners", "schema": 1,
                "error": "child timed out"}
    if proc.returncode != 0:
        return {"metric": "fleet_mesh_learners", "schema": 1,
                "error": (proc.stderr or proc.stdout)[-2000:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_projection_variants(k: int = 40, steps: int = 1600) -> dict | None:
    """K-scan update rate per --projection implementation (einsum / pallas
    / pallas_ce) at the bench shape — the measurement backing the
    projection-kernel story in README. Runs under ``make_multi_update``
    (VERDICT r4 #4: the single-dispatch path measures the ~1-3 ms tunnel
    dispatch, which swamps the ~15 us kernel; under the K-scan the kernels
    are the denominator, so variant deltas exceed noise). Accelerator
    only: interpret-mode emulation on CPU measures the emulator."""
    import jax

    if jax.default_backend() != "tpu":
        # only the TPU backend runs the actual kernels: CPU would measure
        # the interpret-mode emulator, and any other backend silently
        # falls back to einsum (three identical numbers masquerading as
        # three kernels — worse than no measurement)
        return None

    from d4pg_tpu.learner import init_state, make_multi_update

    rng = np.random.default_rng(0)
    batch = jax.device_put(_random_batch(rng, (k, BATCH)))
    w = jax.device_put(np.ones((k, BATCH), np.float32))
    n_dispatch = max(1, steps // k)
    out = {}
    import dataclasses

    for proj in ("einsum", "pallas", "pallas_ce"):
        config = dataclasses.replace(_bench_config(), projection=proj)
        state = init_state(config, jax.random.key(0))
        update = make_multi_update(config, donate=True, use_is_weights=True)
        state, metrics = update(state, batch, w)  # warmup/compile
        jax.block_until_ready(metrics["critic_loss"])
        t0 = time.perf_counter()
        for _ in range(n_dispatch):
            state, metrics = update(state, batch, w)
        jax.block_until_ready(metrics["critic_loss"])
        out[proj] = round(n_dispatch * k / (time.perf_counter() - t0), 2)
    return out


def model_flops_per_step() -> float | None:
    """XLA-reported FLOPs of ONE update step at the bench shape (B=256,
    Humanoid-sized nets) — the MFU numerator. Uses the compiler's own cost
    analysis of the jitted single-step update (all four network passes,
    both backward passes, projection, Adam, soft target updates), the same
    convention as model-FLOPs-based LLM MFU: replay machinery around the
    update does not count as model compute."""
    import jax

    from d4pg_tpu.learner import init_state, make_update

    config = _bench_config()
    state = init_state(config, jax.random.key(0))
    update = make_update(config, donate=False, use_is_weights=True)
    batch = _random_batch(np.random.default_rng(0), (BATCH,))
    w = np.ones((BATCH,), np.float32)
    try:
        compiled = update.lower(state, batch, w).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        flops = float(ca["flops"])
        return flops if flops > 0 else None
    except Exception:
        return None


# bf16 peak FLOPs/sec by TPU generation (public numbers); MFU is only
# emitted when the device kind maps to one of these.
_PEAK_BF16 = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v6", 918e12), ("trillium", 918e12), ("v4", 275e12), ("v3", 123e12),
)


def peak_flops_per_sec() -> float | None:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None


def bench_reference_torch_cpu(steps: int = 20) -> float | None:
    """Measure an equivalent-shape reference-style step in torch on CPU:
    4 MLP passes + host-side numpy categorical projection + 2 Adam steps,
    mirroring the reference's ``DDPG.train`` data path (SURVEY.md S2)."""
    try:
        import torch
    except Exception:
        return None
    torch.manual_seed(0)

    def mlp(in_dim, out_dim):
        return torch.nn.Sequential(
            torch.nn.Linear(in_dim, 256), torch.nn.ReLU(),
            torch.nn.Linear(256, 256), torch.nn.ReLU(),
            torch.nn.Linear(256, 256), torch.nn.ReLU(),
            torch.nn.Linear(256, out_dim),
        )

    actor, actor_t = mlp(OBS_DIM, ACT_DIM), mlp(OBS_DIM, ACT_DIM)
    critic, critic_t = (mlp(OBS_DIM + ACT_DIM, N_ATOMS),
                        mlp(OBS_DIM + ACT_DIM, N_ATOMS))
    opt_a = torch.optim.Adam(actor.parameters(), lr=1e-3, betas=(0.9, 0.9))
    opt_c = torch.optim.Adam(critic.parameters(), lr=1e-3, betas=(0.9, 0.9))

    obs = torch.randn(BATCH, OBS_DIM)
    act = torch.rand(BATCH, ACT_DIM) * 2 - 1
    # seeded component stream, not numpy's ambient global (jaxlint 22):
    # the torch baseline must replay bit-for-bit like every other arm
    rew = np.random.default_rng(0).standard_normal(BATCH).astype(np.float64)
    v_min, v_max = 0.0, 800.0
    delta = (v_max - v_min) / (N_ATOMS - 1)
    bins = np.linspace(v_min, v_max, N_ATOMS)

    def step():
        with torch.no_grad():
            ta = torch.tanh(actor_t(obs))
            tz = torch.softmax(critic_t(torch.cat([obs, ta], -1)), -1).numpy()
        # reference-style per-atom host projection loop (ddpg.py:142-185)
        proj = np.zeros_like(tz)
        for j in range(N_ATOMS):
            tzj = np.clip(rew + 0.99 * bins[j], v_min, v_max)
            b = (tzj - v_min) / delta
            l, u = np.floor(b).astype(int), np.ceil(b).astype(int)
            eq = l == u
            np.add.at(proj, (np.arange(BATCH), l),
                      tz[:, j] * np.where(eq, 1.0, u - b))
            np.add.at(proj, (np.arange(BATCH), u),
                      tz[:, j] * np.where(eq, 0.0, b - l))
        proj_t = torch.as_tensor(proj, dtype=torch.float32)
        q = torch.softmax(critic(torch.cat([obs, act], -1)), -1)
        loss_c = -(proj_t * torch.log(q + 1e-10)).sum(-1).mean()
        opt_c.zero_grad(); loss_c.backward(); opt_c.step()
        a = torch.tanh(actor(obs))
        qa = torch.softmax(critic(torch.cat([obs, a], -1)), -1)
        loss_a = -(qa * torch.as_tensor(bins, dtype=torch.float32)).sum(-1).mean()
        opt_a.zero_grad(); loss_a.backward(); opt_a.step()

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(steps):
        step()
    return steps / (time.perf_counter() - t0)


def bench_reference_host_projection_ceiling(steps: int = 50) -> float | None:
    """Upper bound on the REFERENCE's learner rate on ANY accelerator.

    The reference's categorical projection runs as a per-atom Python/NumPy
    loop on the HOST (``ddpg.py:142-185``, called every train step at
    ``ddpg.py:214``) — no GPU can overlap it away since the loss consumes
    its output. So reference-on-A100 <= 1000 / (host projection ms) regard-
    less of how fast the A100 runs the MLPs. This measured ceiling is what
    BASELINE.md's ">=10x single-A100" north star is evidenced against
    (VERDICT r4 #5: no A100 figure exists anywhere; this makes the bar
    falsifiable with hardware this repo can touch)."""
    rng = np.random.default_rng(0)
    tz = rng.random((BATCH, N_ATOMS)); tz /= tz.sum(-1, keepdims=True)
    rew = rng.standard_normal(BATCH).astype(np.float64)
    v_min, v_max = 0.0, 800.0
    delta = (v_max - v_min) / (N_ATOMS - 1)
    bins = np.linspace(v_min, v_max, N_ATOMS)

    def project():
        proj = np.zeros_like(tz)
        for j in range(N_ATOMS):
            tzj = np.clip(rew + 0.99 * bins[j], v_min, v_max)
            b = (tzj - v_min) / delta
            l, u = np.floor(b).astype(int), np.ceil(b).astype(int)
            eq = l == u
            np.add.at(proj, (np.arange(BATCH), l),
                      tz[:, j] * np.where(eq, 1.0, u - b))
            np.add.at(proj, (np.arange(BATCH), u),
                      tz[:, j] * np.where(eq, 0.0, b - l))
        return proj

    project()  # warm numpy caches
    t0 = time.perf_counter()
    for _ in range(steps):
        project()
    return steps / (time.perf_counter() - t0)


def bench_sharded_overhead(shard_counts=(1, 2, 4, 8), k: int = 8,
                           capacity_per_shard: int = 8192,
                           steps: int = 64) -> dict:
    """Per-step cost of the replay-sharded fused path vs single-device
    fused (VERDICT r2 #8): what the ``shard_map`` sampling prologue +
    ``lax.pmin`` global IS-weight normalizer + per-shard priority
    write-back cost per step as the mesh widens.

    Runs on whatever devices are visible; the committed table uses 8
    VIRTUAL CPU devices (``xla_force_host_platform_device_count``), which
    prices dispatch structure and collective count honestly but NOT real
    ICI latency — labeled as such where the numbers are reported.
    """
    import jax

    from d4pg_tpu.learner import init_state
    from d4pg_tpu.learner.fused import make_sharded_fused_chunk
    from d4pg_tpu.parallel.mesh import MeshSpec, make_mesh
    from d4pg_tpu.replay.sharded_per import ShardedFusedReplay

    config = _bench_config()
    rng = np.random.default_rng(0)
    results = {}
    for n in shard_counts:
        if n > len(jax.devices()):
            continue
        mesh = make_mesh(MeshSpec(data_parallel=n),
                         devices=jax.devices()[:n])
        capacity = capacity_per_shard * n
        buf = ShardedFusedReplay(capacity, OBS_DIM, ACT_DIM, mesh,
                                 alpha=0.6)
        _fill(buf, capacity, rng, drain=True)
        state = init_state(config, jax.random.key(0))
        fn = make_sharded_fused_chunk(config, mesh, k=k, batch_size=BATCH,
                                      alpha=0.6, donate=False)
        state, trees, m = fn(state, buf.trees, buf.storage, buf.size)
        jax.block_until_ready(m["critic_loss"])  # warmup/compile
        n_dispatch = max(1, steps // k)
        t0 = time.perf_counter()
        for _ in range(n_dispatch):
            state, trees, m = fn(state, trees, buf.storage, buf.size)
        jax.block_until_ready(m["critic_loss"])
        dt = time.perf_counter() - t0
        results[str(n)] = {
            "steps_per_sec": round(n_dispatch * k / dt, 2),
            "ms_per_step": round(1e3 * dt / (n_dispatch * k), 3),
        }
    one = results.get("1", {}).get("ms_per_step")
    for n, row in results.items():
        if one:
            row["overhead_vs_1shard"] = round(row["ms_per_step"] / one, 2)
    return results


def main():
    if "--mesh-learners" in sys.argv:
        # needs its own process like --sharded-overhead: the virtual
        # device count must be fixed BEFORE backend init
        if os.environ.get("D4PG_BENCH_MESH_CHILD") != "1":
            import subprocess

            env = dict(os.environ)
            env["D4PG_BENCH_MESH_CHILD"] = "1"
            flags = env.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count=8".strip()
                )
            raise SystemExit(subprocess.call(
                [sys.executable, os.path.abspath(__file__)]
                + [a for a in sys.argv[1:]], env=env,
            ))
        import jax

        jax.config.update("jax_platforms", "cpu")
        from d4pg_tpu.fleet.sweep import run_mesh_learners

        seed = 0
        for a in sys.argv[1:]:
            if a.startswith("--seed="):
                seed = int(a.split("=", 1)[1])
        print(json.dumps(run_mesh_learners(seed=seed)))
        return
    if "--fleet" in sys.argv:
        # host+TCP only — keep jax/accelerator entirely out of the picture
        # (256 sender threads + a receiver need the core, not a backend)
        artifact = bench_fleet()
        evidence = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "docs", "evidence", "fleet")
        os.makedirs(evidence, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        # pid suffix: same-second writers (two bench invocations, a CI
        # matrix) get distinct names while lexical order stays
        # chronological; prune keeps the evidence tree bounded (newest 8
        # fleet artifacts — flight dumps have their own retention)
        from d4pg_tpu.obs.flight import prune_artifacts

        with open(os.path.join(
                evidence, f"fleet_{stamp}_{os.getpid():07d}.json"), "w") as f:
            json.dump(artifact, f, indent=2)
        prune_artifacts(evidence, "fleet_",
                        int(os.environ.get("D4PG_FLEET_KEEP", "8")))
        # the elastic block also lands standalone under evidence/elastic/
        # (docs/README table + tests/test_elastic.py read it without
        # parsing the full fleet artifact), same stamp+pid+prune scheme
        if "elastic" in artifact:
            elastic_dir = os.path.join(
                os.path.dirname(evidence), "elastic")
            os.makedirs(elastic_dir, exist_ok=True)
            with open(os.path.join(
                    elastic_dir,
                    f"elastic_{stamp}_{os.getpid():07d}.json"), "w") as f:
                json.dump(artifact["elastic"], f, indent=2)
            prune_artifacts(elastic_dir, "elastic_",
                            int(os.environ.get("D4PG_FLEET_KEEP", "8")))
        print(json.dumps(artifact))
        return
    if "--sharded-overhead" in sys.argv:
        # needs its own process: the device count must be fixed BEFORE
        # backend init, so re-exec with virtual CPU devices unless the
        # caller already set them up
        if os.environ.get("D4PG_BENCH_SHARDED_CHILD") != "1":
            import subprocess

            env = dict(os.environ)
            env["D4PG_BENCH_SHARDED_CHILD"] = "1"
            flags = env.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count=8".strip()
                )
            raise SystemExit(subprocess.call(
                [sys.executable, os.path.abspath(__file__),
                 "--sharded-overhead"], env=env,
            ))
        import jax

        jax.config.update("jax_platforms", "cpu")
        out = {
            "metric": "sharded_replay_overhead",
            "unit": "ms/step",
            "backend": "virtual-cpu-devices",
            "shards": bench_sharded_overhead(),
        }
        print(json.dumps(out))
        return

    backend = ensure_backend(timeout=180.0)
    # resolve every '--X auto' arbitration surface the way train.py
    # does (ops/autotune.py: measured on TPU, static elsewhere); the
    # decisions land in the ONE schema-versioned 'autotune' block below
    from d4pg_tpu.ops.autotune import (autotune_block, select_projection,
                                       select_sampler)

    select_projection(
        "auto", batch_size=BATCH, v_min=0.0, v_max=800.0, n_atoms=N_ATOMS)
    select_sampler("auto", capacity=200_000, k=8, batch_size=BATCH)
    device_only_rates = bench_tpu()
    device_only = float(np.median(device_only_rates))
    (fused_rates, fused_recompiles, fused_transfers,
     fused_reshards) = bench_fused()
    fused = float(np.median(fused_rates))
    host_pipeline = bench_end_to_end()
    ingest = bench_ingest()
    baseline = bench_reference_torch_cpu() or RECORDED_BASELINE_SPS
    flops = model_flops_per_step()
    peak = peak_flops_per_sec() if backend == "accel" else None
    proj_variants = bench_projection_variants() if backend == "accel" else None
    out = {
        "metric": "learner_grad_steps_per_sec_end_to_end",
        # value = MEDIAN of the repeated fused windows (comparable across
        # BENCH_rN); min/max/repeats carry the spread (VERDICT r4 #3)
        "value": round(fused, 2),
        "unit": "steps/sec",
        "vs_baseline": round(fused / baseline, 2),
        "min": round(min(fused_rates), 2),
        "max": round(max(fused_rates), 2),
        "repeats": [round(r, 2) for r in fused_rates],
        # device-only spread across repeated same-process windows: there
        # are NO host round trips in this path, so min/max/stddev here
        # bound the CHIP-side variance source (clock/contention/window
        # placement) separately from the tunnel/host noise the fused
        # repeats carry (ROADMAP perf-variance item: 41k→54.6k across
        # captures needed attribution)
        "device_only": round(device_only, 2),
        "device_only_spread": {
            "min": round(min(device_only_rates), 2),
            "max": round(max(device_only_rates), 2),
            "stddev": round(float(np.std(device_only_rates)), 2),
            "spread_pct": round(
                100.0 * (max(device_only_rates) - min(device_only_rates))
                / max(device_only_rates), 1),
            "repeats": [round(r, 2) for r in device_only_rates],
        },
        # sentinel counts over ALL timed fused windows (repeats x
        # n_dispatch dispatches): both must be 0, and bench_fused already
        # asserts the recompile count — a nonzero here means the rates
        # above timed the compiler/PCIe, not the learner
        "steady_state_recompiles": fused_recompiles,
        "steady_state_explicit_transfers": fused_transfers,
        # resharding collectives (all-to-all/collective-permute) in the
        # compiled HLO of the fused dispatch — ReshardSentinel, the
        # dynamic twin of the sharding-spec-drift lint family; asserted 0
        "steady_state_reshards": fused_reshards,
        "host_pipeline_e2e": round(host_pipeline, 2),
        # ingest plane (rows/sec): block drain solo + overlapped with the
        # fused chunk, vs the old per-row drain; h2d_per_chunk must be
        # <= 1 (TransferSentinel-checked in bench_ingest)
        "ingest_rows_per_sec": ingest,
        # every '--X auto' arbitration decision on this chip/shape, one
        # schema-versioned block (projection AND sampler — ops/autotune.
        # autotune_block); replaces the old ad-hoc projection_autotune key
        "autotune": autotune_block(),
        "baseline_torch_cpu": round(baseline, 2),
        # host-projection-bound ceiling of the reference on ANY GPU —
        # the measurable stand-in for the ">=10x single-A100" north star
        "ref_any_gpu_ceiling": round(
            bench_reference_host_projection_ceiling() or 0, 2) or None,
        "model_flops_per_step": flops,
        # model-FLOPs MFU of the headline fused rate: rate x per-step
        # FLOPs / chip peak (bf16). Null off-accelerator or on unknown
        # device kinds. D4PG at B=256/256-wide MLPs is latency-bound, not
        # FLOP-bound, so single-digit percentages are expected and fine —
        # the number exists to say so quantitatively (VERDICT r2 #2).
        "mfu": (round(flops * fused / peak, 4) if flops and peak else None),
        "mfu_range": ([round(flops * min(fused_rates) / peak, 4),
                       round(flops * max(fused_rates) / peak, 4)]
                      if flops and peak else None),
    }
    if proj_variants is not None:
        # K-scan update rate per --projection impl (einsum / pallas /
        # pallas_ce) with dispatch amortized — the measurement behind
        # README's projection-kernel story
        out["projection_variants"] = proj_variants
    if backend != "accel":
        out["note"] = (f"{describe(backend)}; measured on the CPU backend — "
                       "TPU numbers are ~3 orders higher (see README "
                       "Performance)")
    else:
        # a live accelerator measurement is rare under the wedge-prone
        # tunnel: persist the raw artifact so the claim is reproducible
        # evidence (VERDICT r2 #1)
        evidence = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "docs", "evidence", "bench")
        os.makedirs(evidence, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        with open(os.path.join(evidence, f"bench_accel_{stamp}.json"),
                  "w") as f:
            json.dump({**out, "device_kind": _device_kind()}, f, indent=2)
    print(json.dumps(out))


def _device_kind() -> str:
    import jax

    return jax.devices()[0].device_kind


if __name__ == "__main__":
    main()
