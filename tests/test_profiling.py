"""Runtime perf sentinels (d4pg_tpu/io/profiling.py).

The recompile sentinel must trip on a deliberately-recompiling function
(fresh shape every call — the classic unstable-signature bug) and stay
silent over a steady-state jitted loop; the transfer sentinel must count
explicit host<->device crossings and restore jax's entry points on exit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.io.profiling import (
    RecompileError, RecompileSentinel, StepTimer, TransferSentinel,
)


def test_recompile_sentinel_trips_on_shape_churn():
    f = jax.jit(lambda x: (x * 2.0).sum())
    f(jnp.ones(4))  # warmup
    with RecompileSentinel() as sentinel:
        for n in range(5, 8):  # new shape every call -> new compilation
            f(jnp.ones(n))
    assert sentinel.compilations > 0
    with pytest.raises(RecompileError, match="XLA compilation"):
        sentinel.assert_clean("shape-churn loop")


def test_recompile_sentinel_clean_on_stable_loop():
    f = jax.jit(lambda x: (x * 3.0).sum())
    f(jnp.ones(16))  # warmup
    with RecompileSentinel() as sentinel:
        for _ in range(10):
            f(jnp.ones(16))
    sentinel.assert_clean()
    assert sentinel.compilations == 0


def test_recompile_sentinel_ignores_outside_region():
    f = jax.jit(lambda x: x + 1.0)
    with RecompileSentinel() as sentinel:
        pass  # nothing compiled inside the bracket
    f(jnp.ones(33))  # compilation AFTER exit must not count
    assert sentinel.compilations == 0
    sentinel.assert_clean()


def test_transfer_sentinel_counts_and_restores():
    orig_put, orig_get = jax.device_put, jax.device_get
    with TransferSentinel() as t:
        x = jax.device_put(np.ones(8, np.float32))
        jax.device_get(x)
        jax.device_put(np.zeros(2))
    assert (t.h2d, t.d2h, t.total) == (2, 1, 3)
    assert jax.device_put is orig_put and jax.device_get is orig_get


def test_transfer_sentinel_zero_for_on_device_work():
    f = jax.jit(lambda x: x * 2)
    x = jax.device_put(np.ones(8, np.float32))
    f(x)  # warmup outside the bracket
    with TransferSentinel() as t:
        y = f(x)
        y = f(y)
    assert t.total == 0


def test_step_timer_rate():
    timer = StepTimer(alpha=0.5)
    assert timer.stop(10) is None  # stop without start: no measurement
    timer.start()
    rate = timer.stop(100)
    assert rate is not None and rate > 0
