"""Mesh-native learner replicas (marker ``mesh``): the collective-merge
engine of ``learner/mesh_replicas.py`` against its two oracles —

1. N=1 through the mesh-native path is BITWISE the legacy FusedLoop:
   same pure ``fused_chunk_step`` under a singleton-axis ``shard_map``,
   merge as a Python-static identity (no arithmetic).
2. N-replica collective merges match the host-thread ``Aggregator`` on
   the same seeded stream: async (IMPACT lag-weighted fold) and sync
   (N-way average — float64 on the host, widest-available on device, so
   tolerance-grade, rtol 1e-6).

Plus the version-stream contract: merged rounds publish a monotone
version sequence through the same ``WeightStore`` the socket path uses.
"""

from __future__ import annotations

import glob
import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.learner import D4PGConfig, init_state
from d4pg_tpu.learner.aggregator import Aggregator
from d4pg_tpu.learner.loop import FusedLoop
from d4pg_tpu.learner.mesh_replicas import MeshReplicaGroup
from d4pg_tpu.learner.replica import PARAM_FIELDS, LearnerReplica, params_of
from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay
from d4pg_tpu.replay.uniform import TransitionBatch

pytestmark = pytest.mark.mesh

OBS, ACT, N_ROWS, STEPS = 5, 2, 96, 4


def _config():
    return D4PGConfig(obs_dim=OBS, act_dim=ACT, v_min=-10, v_max=10,
                      n_atoms=11, hidden=(16, 16))


def _batch(rng):
    return TransitionBatch(
        obs=rng.standard_normal((N_ROWS, OBS)).astype(np.float32),
        action=rng.uniform(-1, 1, (N_ROWS, ACT)).astype(np.float32),
        reward=rng.standard_normal(N_ROWS).astype(np.float32),
        next_obs=rng.standard_normal((N_ROWS, OBS)).astype(np.float32),
        done=np.zeros(N_ROWS, np.float32),
        discount=np.full(N_ROWS, 0.99, np.float32))


def _fill(batch):
    buf = FusedDeviceReplay(N_ROWS, OBS, ACT, alpha=0.6)
    buf.add(batch)
    buf.drain()
    return buf


def _replica_states(config, n):
    """train.py's replica construction: identical nets, decorrelated
    keys (replica 0 keeps the original chain)."""
    base = init_state(config, jax.random.key(0))
    states = []
    for i in range(n):
        # per-replica leaf copies: updates donate their input state, and
        # donated leaves shared between replicas would be deleted under
        # each other (the same guard train.py applies)
        rstate = jax.tree_util.tree_map(jnp.copy, base)
        if i:
            rstate = rstate._replace(key=jax.random.fold_in(rstate.key, i))
        states.append(rstate)
    return states


# ------------------------------------------------- N=1 bitwise oracle --

def test_n1_mesh_path_bitwise_equals_legacy_loop(rng):
    """ONE replica through the mesh-native engine — stacked state,
    shard_map'd chunk, collective-merge round — must land bit-for-bit
    the state the legacy fused loop produces."""
    config = _config()
    batch = _batch(rng)

    legacy = FusedLoop(config, _fill(batch), k=2, batch_size=8)
    legacy_state, _ = legacy.run(init_state(config, jax.random.key(0)),
                                 STEPS)

    group = MeshReplicaGroup(
        config, _replica_states(config, 1), k=2, batch_size=8)
    group.load(_fill(batch))
    group.run_round(STEPS)

    mesh_state = group.state_slice(0)
    for f in PARAM_FIELDS:
        a = jax.device_get(getattr(legacy_state, f))
        b = jax.device_get(getattr(mesh_state, f))
        jax.tree_util.tree_map(np.testing.assert_array_equal, a, b)
    np.testing.assert_array_equal(jax.device_get(legacy_state.step),
                                  jax.device_get(mesh_state.step))
    # and the merged tree IS the replica's params (identity merge)
    merged = group.merged_params()
    for f in PARAM_FIELDS:
        jax.tree_util.tree_map(
            np.testing.assert_array_equal,
            merged[f], jax.device_get(getattr(legacy_state, f)))
    group.close()


# --------------------------------------- N>1 vs the host aggregator ----

def _legacy_trees(config, batch, n):
    """Ground-truth per-replica streams: n independent legacy FusedLoops
    over identically-filled buffers, from the SAME decorrelated initial
    states train.py builds — the trees a round of thread replicas would
    submit."""
    states = _replica_states(config, n)
    trees = []
    for i in range(n):
        loop = FusedLoop(config, _fill(batch), k=2, batch_size=8)
        state, _ = loop.run(states[i], STEPS)
        trees.append(params_of(state))
    return trees


def _host_merge(trees, mode, clip=8.0):
    """The socket-path ground truth: a real host Aggregator receiving
    one round-synchronous round — every replica pulled the version-0
    basis, so replica i's submission arrives at lag i (async) or joins
    the N-way barrier (sync)."""
    agg = Aggregator(WeightStore(), mode=mode, clip=clip)
    epochs = [agg.register(i) for i in range(len(trees))]
    if mode == "sync":
        threads = [
            threading.Thread(
                target=agg.submit, args=(i, epochs[i], trees[i], 0),
                kwargs={"step": STEPS}, daemon=True)
            for i in range(len(trees))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
    else:
        for i, tree in enumerate(trees):
            res = agg.submit(i, epochs[i], tree, 0, step=STEPS)
            assert res["status"] == "applied" and res["lag"] == i
    _v, merged = agg.current()
    agg.close()
    return merged


def _mesh_round(config, batch, mode, n=2, clip=8.0):
    group = MeshReplicaGroup(
        config, _replica_states(config, n), k=2, batch_size=8,
        mode=mode, clip=clip)
    group.load(_fill(batch))
    group.run_round(STEPS)
    merged = group.merged_params()
    per_replica = [
        {f: jax.device_get(getattr(group.state_slice(i), f))
         for f in PARAM_FIELDS} for i in range(n)]
    group.close()
    return merged, per_replica


def _assert_tree_close(a, b, rtol):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=0)


def test_per_replica_streams_match_legacy_loops(rng):
    """Before any merge semantics: replica i's trained params under the
    mesh engine must equal an independent legacy FusedLoop run from the
    same initial state over the same fill — BITWISE. This isolates the
    engine from the merge in the comparisons below. (The adoption step
    after the merge would perturb the stacked state, so the mesh side
    reads its per-replica slices before merging.)"""
    config = _config()
    batch = _batch(rng)
    legacy = _legacy_trees(config, batch, 2)

    group = MeshReplicaGroup(
        config, _replica_states(config, 2), k=2, batch_size=8)
    group.load(_fill(batch))
    group._fused_steps(STEPS)  # engine only — no merge/adopt yet
    for i, want in enumerate(legacy):
        got = {f: jax.device_get(getattr(group.state_slice(i), f))
               for f in PARAM_FIELDS}
        jax.tree_util.tree_map(np.testing.assert_array_equal, want, got)
    group.close()


def test_sync_collective_average_matches_host_aggregator(rng):
    """Sync mode: the on-device N-way average vs the host's float64
    averaging barrier, same seeded stream — within float64-grade
    tolerance (the device sums in the widest dtype it has)."""
    config = _config()
    batch = _batch(rng)
    host_merged = _host_merge(_legacy_trees(config, batch, 2), "sync")
    mesh_merged, _ = _mesh_round(config, batch, "sync")
    _assert_tree_close(host_merged, mesh_merged, rtol=1e-6)


def test_async_collective_fold_matches_host_aggregator(rng):
    """Async mode: the collective fold (adopt replica 0, blend replica i
    at w = max(1/(1+i), 1/clip)) vs the host aggregator receiving the
    same round-synchronous submissions in replica order."""
    config = _config()
    batch = _batch(rng)
    host_merged = _host_merge(_legacy_trees(config, batch, 3), "async")
    mesh_merged, _ = _mesh_round(config, batch, "async", n=3)
    _assert_tree_close(host_merged, mesh_merged, rtol=1e-6)


# ------------------------------------------------- version stream ------

def test_merge_rounds_publish_monotone_versions(rng):
    config = _config()
    store = WeightStore()
    group = MeshReplicaGroup(
        config, _replica_states(config, 2), k=2, batch_size=8,
        mode="async", store=store,
        extract=lambda tree: tree["actor_params"])
    group.load(_fill(_batch(rng)))
    for _ in range(3):
        group.run_round(2)
    assert group.versions == sorted(group.versions)
    assert len(group.versions) == 3
    # the store's latest pull is the last merged actor tree
    version, params = store.get()
    assert version == group.versions[-1]
    merged = group.merged_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        params, merged["actor_params"])
    group.close()


# ------------------------------------------------- guards --------------

def test_bad_mode_and_clip_rejected():
    config = _config()
    with pytest.raises(ValueError):
        MeshReplicaGroup(config, _replica_states(config, 1), k=2,
                         batch_size=8, mode="hogwild")
    with pytest.raises(ValueError):
        MeshReplicaGroup(config, _replica_states(config, 1), k=2,
                         batch_size=8, clip=0.5)


def test_run_round_before_load_raises():
    config = _config()
    group = MeshReplicaGroup(config, _replica_states(config, 1), k=2,
                             batch_size=8)
    with pytest.raises(RuntimeError):
        group.run_round(2)


# ------------------------------------------------- artifact gate -------

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.obs
def test_fleet_artifact_mesh_learners_schema():
    """The newest committed fleet artifact must carry the mesh_learners
    block: the socket-vs-collective aggregation A/B at equal offered
    load per replica count, with updates/s on BOTH arms and per-round
    aggregation latency percentiles — the measurement attributing the
    mesh-native transport's win. A later PR that drops it fails tier-1
    here."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "fleet", "fleet_*.json")))
    assert arts, "no committed fleet artifact"
    with open(arts[-1]) as f:
        artifact = json.load(f)
    blk = artifact.get("mesh_learners")
    assert blk, "newest fleet artifact lost its mesh_learners block"
    assert blk["metric"] == "fleet_mesh_learners" and blk["schema"] == 1
    assert "error" not in blk, blk.get("error")
    assert blk["sweep"], "mesh_learners sweep is empty"
    for row in blk["sweep"]:
        assert row["metric"] == "mesh_learners_ab" and row["schema"] == 1
        assert row["n_replicas"] >= 1
        for arm in ("socket", "collective"):
            assert row[arm]["updates_per_sec"] > 0
            assert row[arm]["agg_latency_s"]["p50"] is not None
            assert row[arm]["agg_latency_s"]["p95"] is not None
        # both arms ran the SAME offered load — that's what makes the
        # comparison an attribution, not a vibe
        assert row["load"]["rounds"] > 0
        assert row["load"]["steps_per_round"] > 0
