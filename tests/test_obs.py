"""Observability plane (d4pg_tpu/obs): wire-to-grad trace spans, the
unified metrics registry, and the chaos flight recorder.

Tier-1 scope (marker ``obs``): registry consistency + provider
lifecycle, sink-crash containment in the metrics bus, the v2 codec's
trace header extension (round trip + eternal backward compatibility),
span propagation across the K-shard ordered merge under chaos
(monotone sequences, zero orphans, shed frames terminate), the
flight-recorder postmortem on an injected lock-hierarchy violation,
and the bench-artifact ``latency`` schema gate.
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from d4pg_tpu.obs import flight as obs_flight
from d4pg_tpu.obs import trace as obs_trace
from d4pg_tpu.obs.registry import REGISTRY, MetricsRegistry
from d4pg_tpu.replay.uniform import TransitionBatch

pytestmark = pytest.mark.obs

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _batch(rng, n, obs_dim=6, act_dim=2):
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )


# ------------------------------------------------------------ registry ----

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    c = reg.counter("a.rows")
    assert reg.counter("a.rows") is c  # get-or-create is idempotent
    c.inc()
    c.inc(41)
    reg.gauge("a.rate").set(3.5)
    h = reg.histogram("a.lat")
    for v in (1.0, 2.0, 3.0, 100.0):
        h.observe(v)
    out = reg.export()
    assert out["counters"]["a.rows"] == 42
    assert out["gauges"]["a.rate"] == 3.5
    lat = out["histograms"]["a.lat"]
    assert lat["n"] == 4 and lat["p50"] == 2.5 and lat["p99"] > 90.0
    reg.reset_metrics()
    assert reg.export()["counters"]["a.rows"] == 0


def test_registry_provider_consistent_snapshot_and_weakref():
    reg = MetricsRegistry()

    class Owner:
        def __init__(self):
            self._mu = threading.Lock()
            self.n = 7

        def stats(self):
            with self._mu:  # the provider reads under its OWNING lock
                return {"n": self.n}

    o = Owner()
    reg.register_provider("owner", o.stats)
    assert reg.export()["owner"] == {"n": 7}
    # a dying owner drops out of export instead of leaking or raising
    del o
    assert "owner" not in reg.export()


def test_registry_unregister_only_evicts_own_slot():
    reg = MetricsRegistry()

    class Owner:
        def __init__(self, n):
            self.n = n

        def stats(self):
            return {"n": self.n}

    old, new = Owner(1), Owner(2)
    reg.register_provider("svc", old.stats)
    reg.register_provider("svc", new.stats)  # last-registered wins
    reg.unregister_provider("svc", old.stats)  # stale close: must NOT evict
    assert reg.export()["svc"] == {"n": 2}
    reg.unregister_provider("svc", new.stats)
    assert "svc" not in reg.export()


def test_registry_crashing_provider_contained():
    reg = MetricsRegistry()

    def bad():
        raise RuntimeError("boom")

    reg.register_provider("bad", bad)
    out = reg.export()
    assert "boom" in out["bad"]["provider_error"]


# --------------------------------------------- metrics-bus containment ----

def test_metrics_bus_poisoned_sink_disabled_not_fatal(capsys):
    from d4pg_tpu.io.metrics import MetricsBus

    class Poisoned:
        writes = 0

        def write(self, step, metrics):
            Poisoned.writes += 1
            raise IOError("disk full")

        def close(self):
            raise IOError("still broken")

    class Good:
        def __init__(self):
            self.rows = []

        def write(self, step, metrics):
            self.rows.append((step, dict(metrics)))

        def close(self):
            self.closed = True

    fails0 = REGISTRY.counter("metrics_bus.sink_failures").value
    good = Good()
    bus = MetricsBus(sinks=[Poisoned(), good])
    for step in range(3):
        bus.log(step, {"x": 1.0})  # must not raise
    # poisoned sink fired once, got disabled, the good sink kept logging
    assert Poisoned.writes == 1
    assert [s for s, _ in good.rows] == [0, 1, 2]
    bus.close()  # poisoned close contained too
    assert good.closed
    # every failure counted in the unified registry (write + close)
    assert REGISTRY.counter("metrics_bus.sink_failures").value == fails0 + 2
    assert "disabled" in capsys.readouterr().out


# ------------------------------------------------- v2 trace extension -----

def test_raw_codec_trace_extension_roundtrip(rng):
    from d4pg_tpu.distributed.transport import (
        decode_raw, encode_raw, raw_frame_meta, raw_frame_meta_ex)

    b = _batch(rng, 5)
    plain = encode_raw("a0", b, count_env_steps=False)[8:]  # strip frame hdr
    traced = encode_raw("a0", b, count_env_steps=False,
                        trace=(0xDEADBEEF, 123.456))[8:]
    # extension costs exactly 16 bytes and decodes to identical columns
    assert len(traced) == len(plain) + 16
    for enc in (plain, traced):
        aid, got, count = decode_raw(enc)
        assert aid == "a0" and count is False
        np.testing.assert_array_equal(got.obs, b.obs)
        np.testing.assert_array_equal(got.discount, b.discount)
    # header-only meta surfaces the trace without touching columns
    assert raw_frame_meta_ex(plain)[3] is None
    tid, ts = raw_frame_meta_ex(traced)[3]
    assert tid == 0xDEADBEEF and ts == pytest.approx(123.456)
    # the 3-tuple compatibility view is unchanged either way
    assert raw_frame_meta(traced) == ("a0", 5, False)


def test_trace_ids_unique_across_salts():
    a = {obs_trace.new_trace_id(1) for _ in range(100)}
    b = {obs_trace.new_trace_id(2) for _ in range(100)}
    assert len(a) == len(b) == 100 and not (a & b)


# ------------------------------------------------------ trace recorder ----

def test_trace_recorder_spans_and_latency_block():
    rec = obs_trace.TraceRecorder()
    rec.enable(0.5)
    t0 = time.monotonic()
    rec.begin(1, t0)
    for stage in ("admission", "decode", "stage", "merge"):
        rec.record_span(1, stage)
    rec.mark_committed([1])
    assert rec.orphans() == []  # commit is terminal
    rec.mark_grad()
    rec.begin(2, t0)
    rec.record_span(2, "admission")
    assert rec.orphans() == [2]  # admitted, not yet terminated
    rec.terminal_shed(2)
    assert rec.orphans() == []
    block = rec.latency_block()
    assert block["sample_rate"] == 0.5
    assert block["completed"] == 1 and block["shed"] == 1
    assert block["wire_to_grad"]["n"] == 1
    assert block["stages"]["commit_to_grad"]["n"] == 1
    # stage order sanity inside the one completed trace
    spans = rec.span_table()[1]
    order = [spans[s] for s in
             ("send", "admission", "decode", "stage", "merge", "commit",
              "grad")]
    assert order == sorted(order)


def test_trace_recorder_bounded_and_disabled_noop():
    rec = obs_trace.TraceRecorder(max_traces=4)
    rec.enable(1.0)
    for tid in range(4):
        rec.begin(tid, 0.0)  # all live (no terminal): table is full
    rec.begin(99, 0.0)
    assert rec.overflow == 1 and 99 not in rec.span_table()
    rec.terminal_shed(0)  # now one record is evictable
    rec.begin(100, 0.0)
    assert 100 in rec.span_table() and 0 not in rec.span_table()
    rec.disable()
    rec.begin(101, 0.0)
    assert 101 not in rec.span_table()  # disabled recorder records nothing


# ------------------------------ K-shard propagation under chaos (sat.) ----

def test_trace_propagation_k2_merge_under_chaos():
    """Every sampled trace crossing the K=2 sharded ordered merge under
    the full chaos mix must keep a monotone span sequence (admission <=
    decode <= stage <= merge <= commit) and terminate — shed frames get
    terminal ``shed`` spans, nothing leaks (zero orphans)."""
    from d4pg_tpu.fleet import ChaosConfig, FleetConfig, FleetHarness

    chaos = ChaosConfig(
        drop_prob=0.1, delay_prob=0.2, delay_min_s=0.001, delay_max_s=0.005,
        crash_prob=0.05, restart_delay_s=0.3,
        receiver_stall_s=0.1, stall_every_s=0.4, seed=7)
    cfg = FleetConfig(
        n_actors=8, max_ticks=12, rows_per_sec=400.0, block_rows=16,
        obs_dim=24, act_dim=4, capacity=20_000, heartbeat_timeout=0.5,
        evict_every_s=0.1, send_timeout=0.5, chaos=chaos,
        ingest_shards=2, trace_sample=1.0)
    result = FleetHarness(cfg).run()
    assert result["deadlocks"] == 0
    assert result["frames_traced"] > 20  # sampling actually ran
    lat = result["latency"]
    assert lat is not None and lat["orphans"] == 0
    table = obs_trace.RECORDER.span_table()
    assert len(table) == result["frames_traced"] >= lat["completed"] > 0
    ordered_stages = ("send", "admission", "decode", "stage", "merge",
                      "commit", "grad")
    completed = shed = 0
    for tid, spans in table.items():
        terminal = [t for t in ("commit", "grad", "shed") if t in spans]
        assert terminal, f"trace {tid} leaked with spans {sorted(spans)}"
        if "shed" in spans:
            shed += 1
            continue
        completed += 1
        # committed traces crossed EVERY stage, in monotone order
        ts = [spans[s] for s in ordered_stages if s in spans]
        assert len(ts) >= 6
        assert ts == sorted(ts), f"non-monotone spans for {tid}: {spans}"
    assert completed == lat["completed"] and shed == lat["shed"]


def test_trace_tombstoned_frames_get_terminal_shed_spans(rng):
    """Deterministic tombstone coverage: undecodable-but-admissible v2
    frames (good header, truncated columns) are admitted with a trace,
    tombstoned by the shard worker, and must end in a terminal ``shed``
    span — never an orphan — while interleaved valid frames commit."""
    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.distributed.transport import encode_raw
    from d4pg_tpu.replay.uniform import ReplayBuffer

    obs_trace.RECORDER.reset()
    obs_trace.RECORDER.enable(1.0)
    svc = ReplayService(ReplayBuffer(10_000, 6, 2), num_ingest_shards=2)
    good_tids, bad_tids = [], []
    try:
        for i in range(12):
            tid = obs_trace.new_trace_id(3)
            frame = encode_raw(f"lane-{i % 2}", _batch(rng, 4),
                               trace=(tid, time.monotonic()))[8:]
            if i % 3 == 2:
                frame = frame[:-7]  # truncate mid-column: decode raises
                bad_tids.append(tid)
            else:
                good_tids.append(tid)
            assert svc.add_payload(frame, shard=i % 2, codec="raw")
        svc.flush(timeout=10.0)
        table = obs_trace.RECORDER.span_table()
        for tid in bad_tids:
            assert "shed" in table[tid], table[tid]
            assert "commit" not in table[tid]
        for tid in good_tids:
            assert "commit" in table[tid], table[tid]
        assert obs_trace.RECORDER.orphans() == []
        assert svc.ingest_stats()["decode_errors"] == len(bad_tids)
    finally:
        obs_trace.RECORDER.disable()
        svc.close()


def test_trace_shed_frames_get_terminal_spans(rng):
    """Deterministic watermark-shed coverage: with the workers frozen,
    admissions past the shed watermark evict the oldest queued frames —
    each evicted trace must get its terminal ``shed`` span at eviction
    time (the zero-leak contract), not linger half-recorded."""
    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.distributed.transport import encode_raw
    from d4pg_tpu.replay.uniform import ReplayBuffer

    obs_trace.RECORDER.reset()
    obs_trace.RECORDER.enable(1.0)
    svc = ReplayService(ReplayBuffer(10_000, 6, 2), ingest_capacity=4,
                        shed_watermark=0.5, num_ingest_shards=2)
    # freeze the plane: workers and commit exit, admissions still run
    svc._stop.set()
    for w in svc._workers:
        w.join(timeout=5.0)
    svc._commit_thread.join(timeout=5.0)
    tids = []
    for i in range(6):  # shard 0 only; shed_at = 2 -> 4 evictions
        tid = obs_trace.new_trace_id(4)
        tids.append(tid)
        frame = encode_raw("lane-0", _batch(rng, 4),
                           trace=(tid, time.monotonic()))[8:]
        assert svc.add_payload(frame, shard=0, codec="raw")
    table = obs_trace.RECORDER.span_table()
    shed = [tid for tid in tids if "shed" in table[tid]]
    queued = [tid for tid in tids if "shed" not in table[tid]]
    assert len(shed) == 4 and len(queued) == 2  # oldest evicted, FIFO
    assert shed == tids[:4]
    for tid in shed:
        assert "admission" in table[tid]  # admitted first, then evicted
    stats = svc.ingest_stats()
    assert stats["sheds"] == 4 and stats["shed_rows"] == 16
    obs_trace.RECORDER.disable()
    with svc._lock:
        svc._pending = 0  # frozen plane: skip close()'s flush deadline


# ----------------------------------------------------- flight recorder ----

@pytest.mark.failflow
def test_contained_crash_counts_and_flight_records():
    """The shared thread-top-frame containment helper: one counter bump
    on ``threads.contained_crashes`` plus one flight event carrying the
    role and the exception — the breadcrumb every wrapped plane thread
    leaves instead of dying silently."""
    from d4pg_tpu.obs.containment import contained_crash

    ctr = REGISTRY.counter("threads.contained_crashes")
    before = ctr.value
    obs_flight.RECORDER.reset()
    contained_crash("test.lane", ValueError("boom"))
    assert ctr.value == before + 1
    events = [e for e in obs_flight.RECORDER.events()
              if e["kind"] == "thread_crash_contained"]
    assert events and events[-1]["role"] == "test.lane"
    assert events[-1]["error"] == "ValueError: boom"


def test_flight_recorder_ring_bounded_and_dump(tmp_path):
    rec = obs_flight.FlightRecorder(maxlen=8)
    for i in range(20):
        rec.record("tick", i=i)
    assert len(rec) == 8
    events = rec.events()
    assert [e["i"] for e in events] == list(range(12, 20))  # newest kept
    assert all(e["kind"] == "tick" and "t" in e and "seq" in e
               for e in events)
    path = rec.dump(str(tmp_path), "unit test!", extra={"n": 1})
    with open(path) as f:
        d = json.load(f)
    assert d["reason"] == "unit test!" and d["n_events"] == 8
    assert d["context"] == {"n": 1}
    assert [e["i"] for e in d["events"]] == list(range(12, 20))


def test_flight_dump_on_injected_lock_violation(tmp_path):
    """Acceptance bar: an injected lock-hierarchy violation (record
    mode) during a chaos smoke produces a flight-recorder dump that
    contains the violation event AND the >=32 events preceding it."""
    from d4pg_tpu.core import locking
    from d4pg_tpu.fleet import ChaosConfig, FleetConfig, FleetHarness

    chaos = ChaosConfig(
        drop_prob=0.1, delay_prob=0.2, delay_min_s=0.001, delay_max_s=0.005,
        crash_prob=0.05, restart_delay_s=0.3, seed=7)
    cfg = FleetConfig(
        n_actors=8, max_ticks=16, rows_per_sec=400.0, block_rows=16,
        obs_dim=24, act_dim=4, capacity=20_000, heartbeat_timeout=0.5,
        evict_every_s=0.1, send_timeout=0.5, chaos=chaos,
        flight_dir=str(tmp_path))

    obs_flight.RECORDER.reset()  # stale events must not trip the gate

    def inject():
        # wait until THIS run armed record mode and produced a preamble
        # of ring events, then commit the PR-4 wedge shape: a
        # service-tier acquisition under a shard-tier hold (record
        # mode: counted, not raised)
        deadline = time.monotonic() + 20.0
        while ((not locking.debug_enabled()
                or len(obs_flight.RECORDER) < 40)
               and time.monotonic() < deadline):
            time.sleep(0.01)
        leaf = locking.TieredLock("shard")
        outer = locking.TieredLock("service")
        with leaf:
            with outer:
                pass

    t = threading.Thread(target=inject, daemon=True)
    t.start()
    result = FleetHarness(cfg).run()
    t.join(timeout=25.0)
    assert result["locks"]["hierarchy_violations"] == 1
    assert result["deadlocks"] == 0
    dump = result["flight_dump"]
    assert dump is not None and os.path.exists(dump)
    with open(dump) as f:
        d = json.load(f)
    assert d["reason"] == "hierarchy_violation"
    kinds = [e["kind"] for e in d["events"]]
    assert "lock_violation" in kinds
    idx = kinds.index("lock_violation")
    assert idx >= 32, f"only {idx} events precede the violation"
    assert "acquiring 'service'" in d["events"][idx]["msg"]
    # the preamble is real plane activity, not padding
    assert kinds.count("admit") >= 32


def test_clean_smoke_produces_no_dump(tmp_path):
    from d4pg_tpu.fleet import ChaosConfig, FleetConfig, FleetHarness

    cfg = FleetConfig(
        n_actors=2, max_ticks=4, rows_per_sec=400.0, block_rows=16,
        obs_dim=24, act_dim=4, capacity=20_000, heartbeat_timeout=0.5,
        evict_every_s=0.1, send_timeout=0.5, chaos=ChaosConfig(seed=1),
        flight_dir=str(tmp_path))
    result = FleetHarness(cfg).run()
    assert result["deadlocks"] == 0
    assert result["flight_dump"] is None
    assert glob.glob(os.path.join(str(tmp_path), "*.json")) == []


# ------------------------------------------ bench-artifact schema gate ----

_LATENCY_STAGES = ("wire_to_admission", "admission_to_decode",
                   "decode_to_stage", "stage_to_merge", "merge_to_commit",
                   "commit_to_grad", "wire_to_commit", "wire_to_grad")
_OVERHEAD_KEYS = {"rows_per_sec_traced", "rows_per_sec_untraced",
                  "rows_loss_pct", "hook_ns_per_chunk",
                  "fused_steps_loss_pct_bound", "sample_rate"}


def test_fleet_artifact_latency_schema():
    """The newest committed ``docs/evidence/fleet`` artifact must carry
    the ``latency`` block with per-stage p50/p95/p99 histograms, the
    end-to-end wire-to-grad series, the sampling rate, and the measured
    tracing-overhead figures — a later PR that drops any of it fails
    tier-1 here instead of silently shipping a blind artifact."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "fleet", "fleet_*.json")))
    assert arts, "no committed fleet artifact"
    with open(arts[-1]) as f:  # stamp-named: lexical order = newest last
        artifact = json.load(f)
    lat = artifact.get("latency")
    assert lat, "newest fleet artifact lost its latency block"
    assert lat["sample_rate"] > 0
    assert lat["n_traces"] > 0 and lat["orphans"] == 0
    for stage in _LATENCY_STAGES:
        h = lat["stages"][stage]
        assert {"p50", "p95", "p99", "n"} <= set(h), stage
    assert lat["wire_to_grad"]["n"] > 0
    assert _OVERHEAD_KEYS <= set(lat["overhead"])
    # the acceptance bound: <= 2% throughput loss at the default rate
    assert lat["overhead"]["rows_loss_pct"] is not None
    assert lat["overhead"]["rows_loss_pct"] <= 2.0
    assert lat["overhead"]["fused_steps_loss_pct_bound"] <= 2.0
    # the shard-sweep scaling table carries stage attribution next to
    # lock_wait_ms on every traced (K>=2) row
    for row in artifact["shard_sweep"]["scaling"]:
        assert "stage_ms" in row and "lock_wait_ms" in row
        if row["ingest_shards"] > 1:
            assert row["stage_ms"] is not None
            assert "wire_to_commit" in row["stage_ms"]


# ------------------------------------------------- registry end-to-end ----

def test_registry_export_covers_live_planes():
    """One export() answers for every plane at once: the lock provider
    is always present, a live ReplayService's ingest snapshot appears
    under 'ingest' and drops out after close()."""
    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.replay.uniform import ReplayBuffer

    svc = ReplayService(ReplayBuffer(1000, 6, 2), num_ingest_shards=2)
    try:
        rng = np.random.default_rng(0)
        svc.add(_batch(rng, 8), actor_id="a0", shard=0)
        svc.flush()
        out = REGISTRY.export()
        assert out["locks"]["hierarchy_violations"] >= 0
        assert out["ingest"]["rows_committed"] >= 8
        assert out["ingest"]["num_ingest_shards"] == 2
        assert out["counters"]["ingest.rows_committed"] >= 8
    finally:
        svc.close()
    assert "ingest" not in REGISTRY.export()


def test_registry_export_covers_weight_plane():
    """The weight plane registers an aggregate 'weights' provider: the
    block is always present (module-lifetime registration, mirroring
    'locks'), counts live servers, and folds per-server frame/byte/
    oracle tallies plus the staleness histogram."""
    from d4pg_tpu.distributed.weight_plane import WeightPlaneServer
    from d4pg_tpu.distributed.weights import WeightStore

    base = REGISTRY.export()["weights"]
    assert "staleness_ms" in base
    store = WeightStore()
    srv = WeightPlaneServer(store)
    try:
        out = REGISTRY.export()["weights"]
        assert out["servers"] >= base.get("servers", 0) + 1
        assert "snapshots_built" in out
        assert "delta_hit_rate" in out
    finally:
        srv.close()


def test_registry_export_covers_serving_plane():
    """A live PolicyInferenceServer registers the 'serving' provider
    (queue depth, batch occupancy/latency histograms, the staleness-SLA
    pair) and unregisters it on close — per-instance lifetime, like
    'ingest', not module-lifetime like 'weights'."""
    from d4pg_tpu.distributed.weights import WeightStore
    from d4pg_tpu.learner.state import D4PGConfig
    from d4pg_tpu.serving import PolicyInferenceServer

    cfg = D4PGConfig(obs_dim=4, act_dim=2, n_atoms=11, hidden=(16,))
    srv = PolicyInferenceServer(cfg, WeightStore())
    try:
        out = REGISTRY.export()["serving"]
        assert out["queue_depth"] == 0
        assert out["sla_staleness_s"] == srv.sla_staleness_s
        for block in ("batch_occupancy", "batch_rows", "latency_ms"):
            assert "p95" in out[block]
        for counter in ("requests", "batches", "adoptions",
                        "fenced_rejected", "sla_breaches"):
            assert counter in out
    finally:
        srv.close()
    assert "serving" not in REGISTRY.export()
