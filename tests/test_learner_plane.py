"""Multi-learner-plane tests (marker ``learner``): the aggregator's
staleness-corrected merge (learner/aggregator.py), the replica→aggregator
wire protocol (distributed/update_plane.py), the IngestOverlap
single-consumer contract, the N=1-through-aggregator ⇔ legacy-fused-loop
bitwise oracle, the replica-kill chaos smoke, and the bench-artifact
``learners`` schema gate."""

from __future__ import annotations

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from d4pg_tpu.distributed.transport import ProtocolError
from d4pg_tpu.distributed.update_plane import (
    AggregatorServer,
    UpdateClient,
    decode_update,
    encode_update,
    update_frame_meta,
)
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.learner.aggregator import Aggregator
from d4pg_tpu.obs.registry import REGISTRY

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.learner


def _params(rng, scale=1.0):
    return {"w": (scale * rng.standard_normal((4, 3))).astype(np.float32),
            "b": (scale * rng.standard_normal(3)).astype(np.float32)}


def _agg(mode="async", clip=8.0, **kw):
    return Aggregator(WeightStore(), mode=mode, clip=clip, **kw)


# ------------------------------------------------- aggregator: modes ----

def test_bad_mode_and_clip_rejected():
    with pytest.raises(ValueError):
        _agg(mode="hogwild")
    with pytest.raises(ValueError):
        # clip < 1 would weight stale updates ABOVE fresh ones
        _agg(clip=0.5)


def test_lag0_adopted_wholesale_bitwise(rng):
    """A fresh submission (lag 0) IS the next aggregate — the exact
    identity fast-path, not a float blend that happens to be close."""
    agg = _agg()
    epoch = agg.register(0, params=_params(rng))
    sub = _params(rng)
    basis_version, _ = agg.basis(0)
    res = agg.submit(0, epoch, sub, basis_version)
    assert res == {"status": "applied", "version": 1, "lag": 0,
                   "weight": 1.0, "clipped": False}
    _v, cur = agg.current()
    for k in sub:
        np.testing.assert_array_equal(cur[k], sub[k])
    agg.close()


def test_stale_correction_arithmetic(rng):
    """lag=1 applies params + 0.5*(new - params) leaf-wise,
    dtype-preserving."""
    agg = _agg()
    e0 = agg.register(0, params=_params(rng))
    e1 = agg.register(1)
    b1, _ = agg.basis(1)                      # replica 1 pulls at v0
    agg.submit(0, e0, _params(rng), agg.basis(0)[0])  # v1: r1 now stale
    _v, before = agg.current()
    before = {k: v.copy() for k, v in before.items()}
    sub = _params(rng)
    res = agg.submit(1, e1, sub, b1)
    assert res["status"] == "applied" and res["lag"] == 1
    assert res["weight"] == pytest.approx(0.5) and not res["clipped"]
    _v, cur = agg.current()
    for k in sub:
        expect = (before[k]
                  + np.float32(0.5) * (sub[k] - before[k])).astype(np.float32)
        np.testing.assert_array_equal(cur[k], expect)
        assert cur[k].dtype == np.float32
    agg.close()


def test_clip_floor_bounds_very_stale_updates(rng):
    """raw 1/(1+lag) below 1/clip engages the floor: a very stale but
    live replica keeps a bounded vote, and the engagement is counted."""
    agg = _agg(clip=2.0)
    e0 = agg.register(0, params=_params(rng))
    e1 = agg.register(1)
    b1, _ = agg.basis(1)
    for _ in range(5):                        # drive replica 1's lag to 5
        agg.submit(0, e0, _params(rng), agg.basis(0)[0])
    res = agg.submit(1, e1, _params(rng), b1)
    assert res["status"] == "applied" and res["lag"] == 5
    assert res["weight"] == pytest.approx(0.5)   # floored at 1/clip
    assert res["clipped"] is True
    snap = agg._snapshot()
    assert snap["clip_rate"] == pytest.approx(1 / 6, abs=1e-4)
    assert snap["replicas"]["1"]["lag"] == 5
    agg.close()


def test_basis_never_reserves_own_submission(rng):
    """The sole replica must never re-adopt its own round-tripped params
    — the precondition of the N=1 bitwise oracle."""
    agg = _agg()
    epoch = agg.register(0, params=_params(rng))
    v, basis = agg.basis(0)
    assert v == 0 and basis is None           # nothing newer than its own
    agg.submit(0, epoch, _params(rng), v)
    v, basis = agg.basis(0)
    assert v == 1 and basis is None           # its OWN submit: still None
    e1 = agg.register(1)
    agg.submit(1, e1, _params(rng), agg.basis(1)[0])
    v, basis = agg.basis(0)
    assert v == 2 and basis is not None       # someone else advanced it
    agg.close()


def test_future_basis_is_a_protocol_breach(rng):
    agg = _agg()
    epoch = agg.register(0, params=_params(rng))
    res = agg.submit(0, epoch, _params(rng), basis_version=7)
    assert res["status"] == "fenced" and res["lag"] == -7
    agg.close()


# ------------------------------------------------- aggregator: sync ----

def test_sync_barrier_averages_in_float64(rng):
    agg = _agg(mode="sync")
    e0 = agg.register(0, params=_params(rng))
    e1 = agg.register(1)
    a, b = _params(rng), _params(rng)
    results = {}

    def worker(rid, epoch, sub):
        results[rid] = agg.submit(rid, epoch, sub, agg.basis(rid)[0])

    t = threading.Thread(target=worker, args=(0, e0, a), daemon=True)
    t.start()
    time.sleep(0.1)                           # r0 parked on the barrier
    worker(1, e1, b)
    t.join(timeout=5.0)
    assert not t.is_alive()
    for rid in (0, 1):
        assert results[rid]["status"] == "applied"
        assert results[rid]["weight"] == pytest.approx(0.5)
        assert results[rid]["version"] == 1   # ONE publish for the round
    _v, cur = agg.current()
    for k in a:
        expect = ((a[k].astype(np.float64) + b[k].astype(np.float64))
                  / 2).astype(np.float32)
        np.testing.assert_array_equal(cur[k], expect)
    agg.close()


def test_sync_fence_releases_survivors_sole_contributor_exact(rng):
    """A replica killed mid-round is dropped from the barrier; the
    survivor completes as sole contributor and is adopted EXACTLY."""
    agg = _agg(mode="sync")
    e0 = agg.register(0, params=_params(rng))
    agg.register(1)
    sub = _params(rng)
    results = {}

    def worker():
        results[0] = agg.submit(0, e0, sub, agg.basis(0)[0])

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    time.sleep(0.1)
    agg.fence_replica(1)                      # the kill unwedges the round
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert results[0]["status"] == "applied"
    _v, cur = agg.current()
    for k in sub:
        np.testing.assert_array_equal(cur[k], sub[k])
    agg.close()


# ------------------------------------------- aggregator: fencing -------

def test_epoch_and_generation_fencing(rng):
    agg = _agg()
    epoch = agg.register(0, params=_params(rng))
    assert agg.live_epoch(0) == epoch
    agg.fence_replica(0)
    assert agg.live_epoch(0) is None
    res = agg.submit(0, epoch, _params(rng), 0)   # dead-epoch arrival
    assert res["status"] == "fenced"
    epoch2 = agg.register(0)                      # respawn: next epoch
    assert epoch2 == epoch + 1
    res = agg.submit(0, epoch2, _params(rng), agg.basis(0)[0],
                     generation=99)               # wrong store generation
    assert res["status"] == "fenced"
    assert agg.counters()["fenced"] == 2
    assert agg.counters()["applied"] == 0
    agg.close()


def test_ledger_monotone_across_fences(rng):
    agg = _agg()
    epoch = agg.register(0, params=_params(rng))
    for _ in range(3):
        agg.submit(0, epoch, _params(rng), agg.basis(0)[0])
        agg.fence_replica(0)
        epoch = agg.register(0)
    ledger = agg.ledger()
    assert [v for _g, v in ledger] == [1, 2, 3]
    assert agg.ledger_monotone() is True
    agg.close()


@pytest.mark.obs
def test_obs_learner_provider_exported(rng):
    """The aggregator's ``learner`` provider rides the registry export —
    the per-replica lag / clip-rate surface the chaos report reads."""
    agg = _agg()
    epoch = agg.register(0, params=_params(rng))
    agg.submit(0, epoch, _params(rng), agg.basis(0)[0])
    snap = REGISTRY.export().get("learner")
    assert snap is not None
    assert snap["mode"] == "async" and snap["version"] == 1
    assert snap["live_replicas"] == 1 and snap["applied"] == 1
    assert snap["replicas"]["0"]["submits"] == 1
    assert snap["staleness"]["count"] == 1
    agg.close()
    assert "learner" not in REGISTRY.export()


# ------------------------------------------------- wire protocol -------

def test_update_frame_roundtrip_and_header_only_meta(rng):
    params = _params(rng)
    frame = encode_update(params, replica_id=3, epoch=2, generation=1,
                          basis_version=17, step=40, trace_id=99)
    meta = update_frame_meta(frame)           # header-only: no payload read
    assert (meta["replica_id"], meta["epoch"], meta["generation"]) == (3, 2, 1)
    assert (meta["basis_version"], meta["step"]) == (17, 40)
    assert meta["trace_id"] == 99 and meta["codec"] == "f32"
    meta2, decoded = decode_update(frame)
    assert meta2["crc"] == meta["crc"]
    for k in params:
        np.testing.assert_array_equal(decoded[k], params[k])  # f32: bitwise


def test_update_frame_quantized_codecs(rng):
    params = _params(rng)
    for codec, atol in (("bf16", 0.05), ("int8", 0.05)):
        frame = encode_update(params, replica_id=0, epoch=1, generation=0,
                              basis_version=0, codec=codec)
        _meta, decoded = decode_update(frame)
        for k in params:
            assert decoded[k].dtype == np.float32
            np.testing.assert_allclose(decoded[k], params[k], atol=atol)


def test_torn_payload_detected_never_merged(rng):
    frame = bytearray(encode_update(_params(rng), replica_id=0, epoch=1,
                                    generation=0, basis_version=0))
    frame[-1] ^= 0xFF
    with pytest.raises(ProtocolError):
        decode_update(bytes(frame))
    update_frame_meta(bytes(frame))           # header path stays oblivious


def test_update_plane_tcp_e2e_and_zero_decode_fence(rng):
    """Submit over a real socket, then fence the replica and replay its
    genuinely in-flight frame: it must bounce off the HEADER check
    (fenced_header, payload never decoded) and the version not move."""
    agg = _agg()
    server = AggregatorServer(agg)
    client = UpdateClient("127.0.0.1", server.port)
    try:
        epoch = agg.register(0, params=_params(rng))
        res = client.submit(0, epoch, _params(rng), agg.basis(0)[0],
                            generation=agg._store.generation)
        assert res["status"] == "applied" and res["version"] == 1
        assert res["lag"] == 0 and res["weight"] == pytest.approx(1.0)
        torn = bytearray(client.last_frame)
        torn[-1] ^= 0xFF
        assert client.submit_frame(bytes(torn))["status"] == "torn"
        agg.fence_replica(0)
        version_before = agg.version
        replay = client.submit_frame(client.last_frame)
        assert replay["status"] == "fenced"
        assert agg.version == version_before
        stats = server.stats()
        assert stats["fenced_header"] == 1 and stats["torn"] == 1
        assert stats["applied"] == 1
    finally:
        client.close()
        server.close()
        agg.close()


# ------------------------------------- IngestOverlap single consumer ---

class _FakeService:
    """Just the surface IngestOverlap dispatches into."""

    def __init__(self):
        self.gate = threading.Event()
        self.gate.set()
        self.commits = 0

    def ingest_commit(self):
        self.gate.wait()
        self.commits += 1
        return 1

    def ingest_stage(self):
        return 0

    def drain_device(self):
        return 0


def test_ingest_overlap_second_consumer_raises():
    from d4pg_tpu.learner.pipeline import IngestDispatchError, IngestOverlap

    svc = _FakeService()
    first = IngestOverlap(svc)
    with pytest.raises(IngestDispatchError):
        IngestOverlap(svc)                    # live second owner: loud
    first.release()
    second = IngestOverlap(svc)               # explicit handoff: fine
    assert second.commit() == 1
    with pytest.raises(IngestDispatchError):
        first.commit()                        # ownership moved away
    second.release()
    second.release()                          # idempotent


def test_ingest_overlap_concurrent_dispatch_raises():
    from d4pg_tpu.learner.pipeline import IngestDispatchError, IngestOverlap

    svc = _FakeService()
    overlap = IngestOverlap(svc)
    svc.gate.clear()                          # park the first dispatch
    t = threading.Thread(target=overlap.commit, daemon=True)
    t.start()
    time.sleep(0.1)
    with pytest.raises(IngestDispatchError):
        overlap.stage()                       # the second-replica shape
    svc.gate.set()
    t.join(timeout=5.0)
    assert svc.commits == 1
    overlap.release()


# ------------------------------------------------- N=1 bitwise oracle --

def test_n1_through_aggregator_bitwise_equals_legacy_loop(rng):
    """ONE replica driving the extracted FusedLoop through the
    aggregator must land bit-for-bit the state the legacy fused loop
    produces — the merge plane at N=1 is the identity, exactly."""
    import jax

    from d4pg_tpu.learner import D4PGConfig, init_state
    from d4pg_tpu.learner.loop import FusedLoop
    from d4pg_tpu.learner.replica import PARAM_FIELDS, LearnerReplica
    from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay
    from d4pg_tpu.replay.uniform import TransitionBatch

    OBS, ACT, N, STEPS = 5, 2, 96, 4
    config = D4PGConfig(obs_dim=OBS, act_dim=ACT, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(16, 16))
    batch = TransitionBatch(
        obs=rng.standard_normal((N, OBS)).astype(np.float32),
        action=rng.uniform(-1, 1, (N, ACT)).astype(np.float32),
        reward=rng.standard_normal(N).astype(np.float32),
        next_obs=rng.standard_normal((N, OBS)).astype(np.float32),
        done=np.zeros(N, np.float32),
        discount=np.full(N, 0.99, np.float32))

    def fill():
        buf = FusedDeviceReplay(N, OBS, ACT, alpha=0.6)
        buf.add(batch)
        buf.drain()
        return buf

    # legacy: the extracted loop driven directly
    legacy = FusedLoop(config, fill(), k=2, batch_size=8)
    legacy_state, _ = legacy.run(init_state(config, jax.random.key(0)), STEPS)

    # replica: SAME loop, but basis/submit through a real aggregator
    agg = _agg()
    rep = LearnerReplica(0, config, agg, init_state(config, jax.random.key(0)),
                         k=2, batch_size=8, buffer=fill())
    res = rep.run_round(STEPS)
    assert res["status"] == "applied" and res["lag"] == 0

    for f in PARAM_FIELDS:
        a = jax.device_get(getattr(legacy_state, f))
        b = jax.device_get(getattr(rep.state, f))
        jax.tree_util.tree_map(np.testing.assert_array_equal, a, b)
    np.testing.assert_array_equal(jax.device_get(legacy_state.step),
                                  jax.device_get(rep.state.step))
    # and the aggregate IS the submitted tree (lag-0 wholesale adopt)
    _v, cur = agg.current()
    jax.tree_util.tree_map(
        np.testing.assert_array_equal,
        {f: jax.device_get(cur[f]) for f in PARAM_FIELDS},
        {f: jax.device_get(getattr(legacy_state, f)) for f in PARAM_FIELDS})
    rep.close()
    agg.close()


# ------------------------------------------------- chaos smoke ---------

@pytest.mark.fleet
def test_learner_chaos_smoke():
    """A small replica-kill run must pass all four gating oracles — the
    full-size version is the bench artifact's ``learners`` chaos row."""
    from d4pg_tpu.fleet.learner_chaos import (
        LearnerChaosConfig,
        run_learner_chaos,
    )

    from d4pg_tpu.obs.registry import REGISTRY

    crashes0 = REGISTRY.counter("threads.contained_crashes").value
    rep = run_learner_chaos(LearnerChaosConfig(
        n_replicas=2, duration_s=1.5, replica_kills=1, seed=3))
    assert rep["replica_kills"] == 1
    # chaos is injected through narrow, expected-error paths; the broad
    # top-frame containments must never fire during a clean run
    assert REGISTRY.counter("threads.contained_crashes").value == crashes0
    assert rep["replayed_fenced"] == rep["replayed_inflight"]
    assert rep["updates_applied"] > 0 and rep["updates_per_sec"] > 0
    assert rep["torn"]["detected"] == rep["torn"]["injected"]
    assert rep["ledger"]["monotone"] is True
    assert rep["hierarchy_violations"] == 0
    assert rep["trace"]["orphans"] == 0
    assert rep["lane_errors"] == 0


# ------------------------------------------------- artifact gate -------

@pytest.mark.obs
def test_fleet_artifact_learners_schema():
    """The newest committed fleet artifact must carry the learners
    block: updates/s vs replica count (kill-free rows) plus one chaos
    run with >=1 replica kill, every replayed in-flight frame fenced,
    a never-rewinding ledger, 0 hierarchy violations, 0 trace orphans —
    a later PR that drops any of it fails tier-1 here."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "fleet", "fleet_*.json")))
    assert arts, "no committed fleet artifact"
    with open(arts[-1]) as f:
        artifact = json.load(f)
    blk = artifact.get("learners")
    assert blk, "newest fleet artifact lost its learners block"
    assert blk["metric"] == "fleet_learners" and blk["schema"] == 1
    assert [row["n_replicas"] for row in blk["sweep"]] == [1, 2, 4]
    for row in blk["sweep"]:
        assert row["updates_per_sec"] > 0
        assert row["staleness"]["p95"] is not None
        assert row["ledger_monotone"] is True
        assert row["trace_orphans"] == 0
        assert row["hierarchy_violations"] == 0
    chaos = blk["chaos"]
    assert chaos["metric"] == "learner_chaos" and chaos["schema"] == 1
    assert chaos["replica_kills"] >= 1
    assert chaos["replayed_fenced"] == chaos["replayed_inflight"]
    assert chaos["torn"]["detected"] == chaos["torn"]["injected"]
    assert chaos["updates_per_sec"] > 0
    assert chaos["ledger"]["monotone"] is True
    assert chaos["hierarchy_violations"] == 0
    assert chaos["trace"]["orphans"] == 0
