"""Device-resident replay storage (replay/device_ring.py): equivalence with
host storage across wraparound and padded inserts, chunk gathers, and the
train() path with --replay_storage device (exercised on the CPU backend —
the storage API is identical across platforms)."""

import numpy as np
import pytest

from d4pg_tpu.replay import PrioritizedReplayBuffer, ReplayBuffer
from d4pg_tpu.replay.uniform import TransitionBatch


def _batch(rng, n, obs_dim=4, act_dim=2):
    done = (rng.random(n) < 0.2).astype(np.float32)
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=done,
        discount=(0.99 * (1.0 - done)).astype(np.float32),
    )


def test_device_store_matches_host_across_wraparound(rng):
    cap = 32
    host = ReplayBuffer(cap, 4, 2, storage="host")
    dev = ReplayBuffer(cap, 4, 2, storage="device")
    # odd batch sizes force pad buckets; total exceeds capacity -> wrap
    for n in (3, 5, 8, 7, 16, 11):
        b = _batch(rng, n)
        idx_h = host.add(b)
        idx_d = dev.add(b)
        np.testing.assert_array_equal(idx_h, idx_d)
    assert host.size == dev.size == cap
    idx = np.arange(cap)
    h, d = host.gather(idx), dev.gather(idx)
    for name, hv, dv in zip(TransitionBatch._fields, h, d):
        np.testing.assert_allclose(np.asarray(dv), hv, err_msg=name)


def test_device_store_chunk_gather_shape(rng):
    buf = ReplayBuffer(64, 4, 2, storage="device")
    buf.add(_batch(rng, 64))
    batches, w, idx = buf.sample_chunk(3, 8)
    assert w is None and idx.shape == (3, 8)
    assert batches.obs.shape == (3, 8, 4)
    assert batches.reward.shape == (3, 8)
    # rows really come from storage
    direct = buf.gather(idx[1])
    np.testing.assert_allclose(np.asarray(batches.obs[1]),
                               np.asarray(direct.obs))


def test_per_device_storage_roundtrip(rng):
    buf = PrioritizedReplayBuffer(128, 4, 2, alpha=0.6, storage="device")
    buf.add(_batch(rng, 100))
    batches, w, idx = buf.sample_chunk(2, 16, beta=0.5)
    assert batches.obs.shape == (2, 16, 4) and w.shape == (2, 16)
    buf.update_priorities(idx[0], np.full(16, 2.0))
    buf.update_priorities(idx[1], np.full(16, 0.5))
    b2, w2, i2 = buf.sample(8, beta=0.5)
    assert np.asarray(b2.obs).shape == (8, 4)


@pytest.mark.parametrize("fused", ["on", "off"])
def test_train_with_device_storage(tmp_path, fused):
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=16,
        eval_trials=1, batch_size=16, memory_size=2000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, replay_storage="device", fused_replay=fused,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
    assert "avg_test_reward" in metrics
