"""Analysis-layer tests: EWMA math, run logger persistence, plot CLI."""

import json
import os

import numpy as np
import pytest

from d4pg_tpu.analysis import RunLogger, ewma, load_returns_csv, plot_runs


def test_ewma_constant_series():
    x = np.full(10, 3.0)
    np.testing.assert_allclose(ewma(x), x, rtol=1e-12)


def test_ewma_matches_reference_formulation():
    """Bias-corrected EWMA equals the reference's scaling-matrix form
    (plots/plots.py:8-21): y_t = sum_k a^(t-k) (1-a) x_k / (1 - a^(t+1))."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal(50)
    a = 0.95
    t = np.arange(50)
    ref = np.array([
        np.sum(a ** (ti - t[: ti + 1]) * (1 - a) * x[: ti + 1]) / (1 - a ** (ti + 1))
        for ti in t
    ])
    np.testing.assert_allclose(ewma(x, a), ref, rtol=1e-10)


def test_ewma_empty():
    assert ewma(np.array([])).shape == (0,)


def test_run_logger_roundtrip(tmp_path):
    path = str(tmp_path / "log.jsonl")
    log = RunLogger(path, "runA")
    log.log("return", 1, -10.0)
    log.log("return", 2, -5.0)
    log.log("loss", 1, 3.0)
    log.close()
    log2 = RunLogger(path, "runB")  # append-only: same file, second run
    log2.log("return", 1, -20.0)
    log2.close()
    runs = RunLogger.load(path)
    assert runs["runA"]["return"] == [(1, -10.0), (2, -5.0)]
    assert runs["runA"]["loss"] == [(1, 3.0)]
    assert runs["runB"]["return"] == [(1, -20.0)]


def test_load_returns_csv_skips_malformed(tmp_path):
    p = tmp_path / "returns.csv"
    p.write_text("step,avg\n1,-10.5\nbad,row\n2,-9.0\n")
    steps, rets = load_returns_csv(str(p))
    np.testing.assert_array_equal(steps, [1, 2])
    np.testing.assert_array_equal(rets, [-10.5, -9.0])


def test_plot_runs_writes_png(tmp_path):
    out = str(tmp_path / "out.png")
    steps = np.arange(20)
    path = plot_runs(
        {"a": (steps, -100 + steps.astype(float)),
         "b": (steps, -120 + 2 * steps.astype(float))},
        out_path=out,
    )
    assert os.path.exists(path) and os.path.getsize(path) > 1000
