"""Transport failure semantics + replay-service degradation/recovery.

The contracts the fleet plane leans on, pinned one by one:
  - a timed-out send under ``drop_on_timeout`` returns False, never raises;
  - the retry loop honors its bounded-attempt invariant (``max_retries``
    caps reconnects even when the time budget is generous);
  - a frame that survives a retry arrives BITWISE identical (the encoded
    bytes are retried verbatim, not re-encoded);
  - an evicted actor that resumes heartbeating is re-admitted, not counted
    dead forever (the ``dead_actors`` regression);
  - the shed watermark drops the OLDEST queued batch, counts it, and never
    blocks the caller.
"""

import threading
import time

import numpy as np
import pytest

from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.transport import (
    CoalescingSender,
    TransitionReceiver,
    TransitionSender,
)
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch


def _batch(n=8, obs_dim=4, act_dim=2, seed=0):
    rng = np.random.default_rng(seed)
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.standard_normal((n, act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )


def _drain_sender_into_dead_peer(sender, batch, tries=10):
    """Send until the broken pipe is observed (TCP lets the first write
    after a silent peer death land in the kernel buffer)."""
    for _ in range(tries):
        if not sender.send(batch):
            return False
    return True


def test_send_timeout_returns_false_not_raise():
    """drop_on_timeout: exhausting the time budget returns False and
    counts the frame, instead of raising ConnectionError."""
    received = []
    recv = TransitionReceiver(lambda b, aid, c: received.append(b),
                              host="127.0.0.1")
    sender = TransitionSender("127.0.0.1", recv.port, actor_id="t",
                              retry_timeout=0.4, drop_on_timeout=True,
                              backoff_base=0.05)
    assert sender.send(_batch()) is True
    recv.close()  # learner dies
    time.sleep(0.75)  # past the dying listener's teardown grace window
    t0 = time.monotonic()
    ok = _drain_sender_into_dead_peer(sender, _batch())  # no raise
    assert ok is False
    assert time.monotonic() - t0 < 10.0
    assert sender.frames_dropped >= 1
    sender.close()


def test_bounded_retry_attempts_invariant():
    """max_retries caps reconnect attempts per call even under a generous
    time budget: the call returns (False) after exactly that many."""
    recv = TransitionReceiver(lambda b, aid, c: None, host="127.0.0.1")
    sender = TransitionSender("127.0.0.1", recv.port, actor_id="t",
                              retry_timeout=30.0, max_retries=3,
                              drop_on_timeout=True, backoff_base=0.05)
    recv.close()
    time.sleep(0.75)  # past the dying listener's teardown grace window
    retries0 = sender.retries
    t0 = time.monotonic()
    assert _drain_sender_into_dead_peer(sender, _batch()) is False
    elapsed = time.monotonic() - t0
    # the failing call burned exactly max_retries reconnect attempts, and
    # returned long before the 30 s time budget
    assert sender.retries - retries0 == 3
    assert elapsed < 10.0
    # the invariant holds per call: another send spends another 3
    assert sender.send(_batch()) is False
    assert sender.retries - retries0 == 6
    sender.close()


def test_retry_preserves_payload_bitwise():
    """A frame delivered after the learner restarts is bitwise the frame
    that was first attempted: same rows, same dtypes, same actor id."""
    got: list = []
    recv = TransitionReceiver(lambda b, aid, c: got.append((aid, b)),
                              host="127.0.0.1")
    port = recv.port
    sender = TransitionSender("127.0.0.1", port, actor_id="bitwise-7",
                              retry_timeout=20.0, backoff_base=0.05)
    sender.send(_batch(seed=1))
    deadline = time.monotonic() + 5.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(got) == 1
    got.clear()

    recv.close()  # learner dies mid-run
    template = _batch(seed=42)
    done = threading.Event()
    results: list[bool] = []

    def late_sends():
        # early post-death writes can vanish into the kernel buffer or the
        # dying listener's backlog; keep sending the SAME frame until one
        # delivery lands at the RESTARTED receiver
        deadline = time.monotonic() + 15.0
        while not got and time.monotonic() < deadline:
            results.append(sender.send(template))
            time.sleep(0.05)
        done.set()

    t = threading.Thread(target=late_sends, daemon=True)
    t.start()
    time.sleep(0.7)  # past the dead listener's teardown window
    recv2 = TransitionReceiver(lambda b, aid, c: got.append((aid, b)),
                               host="127.0.0.1", port=port)  # restart
    assert done.wait(timeout=20.0)
    deadline = time.monotonic() + 5.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got, "no frame delivered to the restarted receiver"
    assert sender.retries >= 1, "delivery did not traverse a retry"
    aid, delivered = got[0]
    assert aid == "bitwise-7"
    for sent_col, recv_col in zip(template, delivered):
        assert recv_col.dtype == sent_col.dtype
        np.testing.assert_array_equal(recv_col, sent_col)  # bitwise
    sender.close()
    recv2.close()


def test_coalescing_sender_sheds_and_shrinks_on_backpressure():
    """The fleet-sender degradation: a timed-out block is shed (counted in
    dropped_rows) and the adaptive target snaps back to min_block."""
    recv = TransitionReceiver(lambda b, aid, c: None, host="127.0.0.1")
    sender = CoalescingSender("127.0.0.1", recv.port, actor_id="c",
                              retry_timeout=0.3, max_retries=2,
                              drop_on_timeout=True, backoff_base=0.05,
                              min_block=4, max_block=64,
                              flush_interval=1e9)
    assert sender.send(_batch(4)) is True  # fills exactly min_block: ships
    recv.close()
    time.sleep(0.75)  # past the dying listener's teardown grace window
    ok = True
    for _ in range(10):  # first post-death writes may land in the buffer
        ok = sender.send(_batch(4))
        if not ok:
            break
    assert ok is False
    assert sender.dropped_rows >= 4
    assert sender._target == sender._min_block
    assert sender.delivered_rows >= 4
    sender.close()


def test_evicted_actor_readmitted_on_heartbeat():
    """Regression (ISSUE 3 satellite): eviction is not a death sentence.
    An evicted actor that heartbeats again must leave dead_actors() and
    be counted as a re-admission with a recovery interval."""
    svc = ReplayService(ReplayBuffer(100, 4, 2), heartbeat_timeout=0.05)
    svc.heartbeat("a0")
    time.sleep(0.1)
    assert svc.dead_actors() == ["a0"]
    assert svc.evict_dead() == ["a0"]
    assert svc.evicted_actors() == ["a0"]
    # evicted and silent: STILL counted dead (eviction must not hide it)
    assert svc.dead_actors() == ["a0"]
    assert svc.evict_dead() == []  # idempotent between state changes

    svc.heartbeat("a0")  # the actor comes back
    assert svc.dead_actors() == []
    assert svc.evicted_actors() == []
    stats = svc.ingest_stats()
    assert stats["evictions"] == 1
    assert stats["readmissions"] == 1
    assert len(stats["recovery_s"]) == 1 and stats["recovery_s"][0] > 0
    svc.close()


def test_evicted_actor_readmitted_by_streaming():
    """add() heartbeats, so a restarted actor re-admits itself with its
    first delivered batch — no separate control channel needed."""
    svc = ReplayService(ReplayBuffer(100, 4, 2), heartbeat_timeout=0.05)
    svc.add(_batch(), actor_id="a1")
    time.sleep(0.1)
    svc.evict_dead()
    assert svc.dead_actors() == ["a1"]
    svc.add(_batch(), actor_id="a1")  # the restarted actor streams again
    assert svc.dead_actors() == []
    assert svc.ingest_stats()["readmissions"] == 1
    svc.flush()
    assert len(svc) == 16
    svc.close()


class _SlowBuffer:
    """ReplayBuffer veneer whose inserts take forever — forces the ingest
    queue to back up so the shed path is exercised deterministically."""

    def __init__(self, inner: ReplayBuffer, delay_s: float):
        self._inner = inner
        self._delay_s = delay_s
        self.inserted_batches = 0

    def add(self, batch):
        time.sleep(self._delay_s)
        self.inserted_batches += 1
        return self._inner.add(batch)

    def __len__(self):
        return len(self._inner)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_shed_watermark_drops_oldest_counted_never_blocks():
    slow = _SlowBuffer(ReplayBuffer(10_000, 4, 2), delay_s=0.05)
    svc = ReplayService(slow, ingest_capacity=4, shed_watermark=0.5)
    t0 = time.monotonic()
    for i in range(12):
        # never blocks, always True — the watermark sheds instead
        assert svc.add(_batch(seed=i), actor_id="a0", block=False) is True
    assert time.monotonic() - t0 < 1.0  # 12 adds never waited on inserts
    svc.flush(timeout=10.0)
    stats = svc.ingest_stats()
    assert stats["sheds"] > 0
    assert stats["shed_rows"] == 8 * stats["sheds"]
    # conservation: every accepted batch was inserted or counted shed
    assert slow.inserted_batches + stats["sheds"] == 12
    # env_steps counts INSERTED rows only — shed rows never inflate it
    assert svc.env_steps == 8 * slow.inserted_batches
    assert stats["pending"] == 0
    svc.close()


def test_shed_disabled_keeps_block_contract():
    """Without a watermark the pre-fleet contract holds: a full queue
    returns False on the non-blocking path (no silent shedding)."""
    slow = _SlowBuffer(ReplayBuffer(10_000, 4, 2), delay_s=0.05)
    svc = ReplayService(slow, ingest_capacity=2)
    results = [svc.add(_batch(seed=i), actor_id="a0", block=False,
                       timeout=0.01) for i in range(10)]
    assert False in results  # backpressure surfaced, not absorbed
    assert svc.ingest_stats()["sheds"] == 0
    svc.flush(timeout=10.0)
    svc.close()


def test_shed_watermark_at_k2_per_shard_counted_never_blocks():
    """Regression at K=2 (ISSUE 4 satellite): the shed watermark is a
    PER-SHARD contract — each shard sheds its own oldest, counts it
    under its own lock, and the service totals close the conservation
    equation exactly as at K=1."""
    slow = _SlowBuffer(ReplayBuffer(10_000, 4, 2), delay_s=0.05)
    svc = ReplayService(slow, ingest_capacity=4, shed_watermark=0.5,
                        num_ingest_shards=2)
    t0 = time.monotonic()
    for i in range(12):
        # never blocks, always True — the watermark sheds instead
        assert svc.add(_batch(seed=i), actor_id=f"a{i % 2}",
                       block=False, shard=i % 2) is True
    assert time.monotonic() - t0 < 1.0
    svc.flush(timeout=10.0)
    stats = svc.ingest_stats()
    assert stats["sheds"] > 0
    assert stats["shed_rows"] == 8 * stats["sheds"]
    # conservation: every accepted batch was committed or counted shed
    assert slow.inserted_batches + stats["sheds"] == 12
    assert svc.env_steps == 8 * slow.inserted_batches
    assert stats["pending"] == 0
    assert stats["order_breaks"] == 0
    # the per-shard ledgers sum to the service totals
    per = stats["per_shard"]
    assert len(per) == 2
    assert sum(p["sheds"] for p in per) == stats["sheds"]
    assert sum(p["rows_in"] for p in per) == 12 * 8
    svc.close()


def test_crash_readmission_at_k2():
    """Regression at K=2: eviction/re-admission bookkeeping is global
    across shards — an actor owned by shard 1 that dies and later
    streams through shard 0 is re-admitted, not double-counted."""
    svc = ReplayService(ReplayBuffer(100, 4, 2), heartbeat_timeout=0.05,
                        num_ingest_shards=2)
    svc.add(_batch(), actor_id="a1", shard=1)
    time.sleep(0.1)
    assert svc.evict_dead() == ["a1"]
    assert svc.dead_actors() == ["a1"]
    svc.add(_batch(), actor_id="a1", shard=0)  # restarts on another shard
    assert svc.dead_actors() == []
    stats = svc.ingest_stats()
    assert stats["evictions"] == 1 and stats["readmissions"] == 1
    assert len(stats["recovery_s"]) == 1 and stats["recovery_s"][0] > 0
    svc.flush()
    assert len(svc) == 16
    svc.close()


def test_raw_codec_bitwise_matches_npz():
    """The v2 raw frame must decode to exactly what the npz frame does:
    same arrays, dtypes, actor id and count flag — it is a wire-format
    change, not a semantic one."""
    from d4pg_tpu.distributed.transport import (
        _HEADER, _decode, _encode, decode_raw, encode_raw, raw_frame_meta)

    batch = _batch(n=16, seed=9)
    for count in (True, False):
        raw = encode_raw("actor-x", batch, count)[_HEADER.size:]
        npz = _encode("actor-x", batch, count)[_HEADER.size:]
        aid_r, got_r, cnt_r = decode_raw(raw)
        aid_n, got_n, cnt_n = _decode(npz)
        assert aid_r == aid_n == "actor-x"
        assert cnt_r == cnt_n == count
        for r, n in zip(got_r, got_n):
            assert r.dtype == n.dtype
            np.testing.assert_array_equal(r, n)
        # the header-only metadata path (zero-decode admission) agrees
        assert raw_frame_meta(raw) == ("actor-x", 16, count)


def test_payload_decode_error_tombstoned_not_wedged():
    """A corrupt raw payload admitted to a shard must be counted
    (decode_errors) and tombstoned — later frames still commit in order
    instead of the merge wedging behind the dead ticket."""
    from d4pg_tpu.distributed.transport import _HEADER, encode_raw

    svc = ReplayService(ReplayBuffer(1000, 4, 2), num_ingest_shards=2,
                        shed_watermark=0.9)
    good = encode_raw("a0", _batch(), True)[_HEADER.size:]
    # intact header (admission metadata parses fine) but truncated
    # columns: the failure surfaces at WORKER decode, after admission
    corrupt = good[:-50]
    assert svc.add_payload(good, shard=0, codec="raw") is True
    assert svc.add_payload(corrupt, shard=1, codec="raw") is True
    assert svc.add_payload(good, shard=1, codec="raw") is True
    svc.flush(timeout=10.0)
    stats = svc.ingest_stats()
    assert svc.env_steps == 16  # both good frames landed
    assert stats["decode_errors"] >= 1
    assert stats["pending"] == 0
    svc.close()


def test_sender_backoff_jitter_seeded_reproducible():
    """Seeded backoff jitter draws an identical schedule — the fleet
    harness's reproducibility reaches into the retry path."""
    recv = TransitionReceiver(lambda b, aid, c: None, host="127.0.0.1")

    def failing_schedule(seed):
        s = TransitionSender("127.0.0.1", recv.port, actor_id="j",
                             retry_timeout=1.0, max_retries=2,
                             drop_on_timeout=True, backoff_base=0.01,
                             backoff_seed=seed)
        draws = [float(s._backoff_rng.random()) for _ in range(8)]
        s.close()
        return draws

    assert failing_schedule(5) == failing_schedule(5)
    assert failing_schedule(5) != failing_schedule(6)
    recv.close()


def test_add_payload_without_watermark_blocks_not_drops():
    """REVIEW regression (high): with NO shed watermark (train.py's
    default wiring) a full ingest shard must give the sharded receiver
    the same blocking backpressure the K=1 path has — a learner stall
    must never silently discard frames off add_payload."""
    from d4pg_tpu.distributed.transport import _HEADER, encode_raw

    slow = _SlowBuffer(ReplayBuffer(10_000, 4, 2), delay_s=0.01)
    svc = ReplayService(slow, ingest_capacity=2, num_ingest_shards=2)
    frames = [encode_raw(f"a{i % 2}", _batch(seed=i), True)[_HEADER.size:]
              for i in range(16)]
    # far past per-shard capacity: pre-fix, the non-blocking admission
    # returned False on a full deque and the frame vanished uncounted
    results = [svc.add_payload(f, shard=i % 2, codec="raw")
               for i, f in enumerate(frames)]
    assert all(results)  # blocking admission absorbed the burst
    svc.flush(timeout=10.0)
    stats = svc.ingest_stats()
    assert svc.env_steps == 16 * 8  # every frame landed
    assert stats["sheds"] == 0
    assert stats["admit_fails"] == 0
    assert stats["pending"] == 0
    svc.close()


def test_stale_ticket_below_merge_floor_discarded_not_wedged():
    """REVIEW regression (medium): a ticket the order-break valve
    skipped past (worker held its group through the grace) later lands
    at the head of its shard's outbox with seq < the merge floor. It
    must be discarded and counted — not left as a forever-unpoppable
    head that gates the shard's worker and wedges flush()/close()."""
    import itertools

    svc = ReplayService(ReplayBuffer(1000, 4, 2), num_ingest_shards=2)
    b = _batch()
    with svc._lock:
        svc._pending += 2
    with svc._commit_cond:
        svc._next_seq = 5  # the valve already advanced past ticket 3
        svc._seq = itertools.count(6)
        svc._out[0].append((3, "a0", b, 8, True, None))  # the late ticket
        svc._out[1].append((5, "a1", b, 8, True, None))  # current floor head
        svc._commit_cond.notify_all()
    svc.flush(timeout=5.0)
    stats = svc.ingest_stats()
    assert stats["pending"] == 0  # flush drained — no wedge
    assert stats["order_breaks"] >= 1  # the discard was counted
    assert svc.env_steps == 8  # only the floor ticket committed
    assert len(svc) == 8
    with svc._commit_cond:
        assert not svc._out[0]  # the stale head is gone, worker ungated
    svc.close()


def test_order_break_valve_prunes_stale_tombstones(monkeypatch):
    """REVIEW regression (low): when the safety valve advances the merge
    floor, tombstones below it can never be consumed by the equality
    walk — they must be pruned, not accumulate for the service
    lifetime."""
    import itertools

    import d4pg_tpu.distributed.replay_service as rs

    monkeypatch.setattr(rs, "_ORDER_GRACE_S", 0.2)
    svc = ReplayService(ReplayBuffer(1000, 4, 2), num_ingest_shards=2)
    b = _batch()
    with svc._lock:
        svc._pending += 1
    with svc._commit_cond:
        svc._skip.update({1, 2})  # tombstones below the coming jump
        svc._seq = itertools.count(8)
        svc._out[0].append((7, "a0", b, 8, True, None))  # tickets 0-6 vanished
        svc._commit_cond.notify_all()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and svc.env_steps < 8:
        time.sleep(0.02)
    assert svc.env_steps == 8  # the valve skipped ahead and committed
    stats = svc.ingest_stats()
    assert stats["order_breaks"] >= 1
    assert stats["pending"] == 0
    with svc._commit_cond:
        assert not svc._skip  # pruned at the jump, not grown forever
    svc.close()


def test_corrupt_v2_frame_drops_connection_without_thread_crash():
    """REVIEW regression (low): a well-framed but hostile v2 payload
    raises struct.error/UnicodeDecodeError (not ProtocolError) out of
    decode_raw; the unsharded serve loop must drop the connection
    silently — not die with an unhandled-exception traceback — and keep
    serving new connections."""
    import socket as socket_mod

    from d4pg_tpu.distributed.transport import _HEADER, _MAGIC_RAW

    crashes = []
    orig_hook = threading.excepthook
    threading.excepthook = lambda a: crashes.append(a)
    try:
        received = []
        recv = TransitionReceiver(lambda b, aid, c: received.append(b),
                                  host="127.0.0.1")
        c = socket_mod.create_connection(("127.0.0.1", recv.port))
        # valid frame header; body parses as count=255, actor-id length
        # 255 and then UnicodeDecodeError on the \xff actor-id bytes
        garbage = b"\xff" * 64
        c.sendall(_HEADER.pack(_MAGIC_RAW, len(garbage)) + garbage)
        c.settimeout(5.0)
        assert c.recv(1) == b""  # server dropped the connection...
        c.close()
        # ...and the plane still serves: a fresh sender lands a frame
        sender = TransitionSender("127.0.0.1", recv.port, actor_id="ok")
        assert sender.send(_batch()) is True
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not received:
            time.sleep(0.02)
        assert len(received) == 1
        assert not crashes  # serve thread exited cleanly, no traceback
        sender.close()
        recv.close()
    finally:
        threading.excepthook = orig_hook
