"""Categorical projection vs an independent NumPy oracle.

The oracle re-implements the projection spec defined by the reference's two
impls (``ddpg.py:122-140`` and ``:142-185``): per-atom Bellman map, clip to
support, linear interpolation of mass between floor/ceil bins, terminal
transitions collapsing to a delta at clip(r).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from d4pg_tpu.core import CategoricalSupport, categorical_projection
from d4pg_tpu.core.losses import expected_q


def oracle_projection(v_min, v_max, n_atoms, probs, rewards, discounts):
    """Straightforward per-sample, per-atom scatter projection (numpy)."""
    delta = (v_max - v_min) / (n_atoms - 1)
    atoms = v_min + delta * np.arange(n_atoms)
    out = np.zeros_like(probs)
    b_size = probs.shape[0]
    for i in range(b_size):
        for a in range(n_atoms):
            tz = np.clip(rewards[i] + discounts[i] * atoms[a], v_min, v_max)
            b = (tz - v_min) / delta
            l, u = int(np.floor(b)), int(np.ceil(b))
            if l == u:
                out[i, l] += probs[i, a]
            else:
                out[i, l] += probs[i, a] * (u - b)
                out[i, u] += probs[i, a] * (b - l)
    return out


@pytest.fixture
def support():
    return CategoricalSupport(v_min=-10.0, v_max=10.0, n_atoms=51)


def random_dist(rng, shape):
    p = rng.random(shape)
    return p / p.sum(axis=-1, keepdims=True)


def test_matches_oracle(rng, support):
    b = 37
    probs = random_dist(rng, (b, support.n_atoms)).astype(np.float32)
    rewards = rng.normal(0, 5, b).astype(np.float32)
    dones = (rng.random(b) < 0.3).astype(np.float32)
    discounts = (0.99**3) * (1.0 - dones)

    got = np.asarray(categorical_projection(support, probs, rewards, discounts))
    want = oracle_projection(
        support.v_min, support.v_max, support.n_atoms, probs, rewards, discounts
    )
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_rows_sum_to_one(rng, support):
    probs = random_dist(rng, (64, support.n_atoms))
    rewards = rng.normal(0, 20, 64)  # many hit the clip boundaries
    discounts = np.full(64, 0.99)
    got = np.asarray(categorical_projection(support, probs, rewards, discounts))
    np.testing.assert_allclose(got.sum(-1), 1.0, atol=1e-6)
    assert (got >= -1e-7).all()


def test_terminal_collapses_to_delta_at_reward(support):
    """discount=0 must reproduce the reference's terminal overwrite
    (``ddpg.py:165-181``): a delta (or two-bin interpolation) at clip(r)."""
    probs = np.full((3, support.n_atoms), 1.0 / support.n_atoms)
    rewards = np.array([0.0, -10.0, 3.1])  # exact bin, clip edge, fractional
    discounts = np.zeros(3)
    got = np.asarray(categorical_projection(support, probs, rewards, discounts))
    atoms = np.asarray(support.atoms)
    np.testing.assert_allclose(got.sum(-1), 1.0, atol=1e-6)
    # projected mean must equal clip(r)
    np.testing.assert_allclose((got * atoms).sum(-1), rewards, atol=1e-5)
    # exact-bin cases are pure deltas
    assert got[0, 25] == pytest.approx(1.0)
    assert got[1, 0] == pytest.approx(1.0)


def test_identity_when_reward_zero_discount_one(rng, support):
    """r=0, discount=1 leaves distributions unchanged."""
    probs = random_dist(rng, (8, support.n_atoms))
    got = np.asarray(
        categorical_projection(support, probs, np.zeros(8), np.ones(8))
    )
    np.testing.assert_allclose(got, probs, atol=1e-6)


def test_mean_contraction(rng, support):
    """Projected mean ~= r + gamma^n * E[Z] when no clipping occurs."""
    probs = random_dist(rng, (16, support.n_atoms))
    rewards = rng.normal(0, 0.5, 16)
    discounts = np.full(16, 0.5)
    got = categorical_projection(support, jnp.asarray(probs), rewards, discounts)
    want = rewards + discounts * np.asarray(
        expected_q(support, jnp.asarray(probs))
    )
    # small interpolation error is expected (projection is not mean-exact
    # once mass is redistributed, but with these scales it's tight)
    np.testing.assert_allclose(np.asarray(expected_q(support, got)), want, atol=0.05)
