"""Flax model shape/init/semantics tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.models import (
    Actor,
    CategoricalCritic,
    MixtureOfGaussianCritic,
    PixelActor,
    PixelCategoricalCritic,
)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def test_actor_shapes_and_bounds(key):
    m = Actor(act_dim=6)
    obs = jax.random.normal(key, (32, 17))
    params = m.init(key, obs)
    a = m.apply(params, obs)
    assert a.shape == (32, 6)
    assert (np.abs(np.asarray(a)) <= 1.0).all()  # tanh-bounded


def test_actor_hidden_structure(key):
    """Three ReLU'd hidden layers of width 256 + output head (SURVEY §7:
    the reference's missing-activation quirk is intentionally not kept)."""
    m = Actor(act_dim=2)
    params = m.init(key, jnp.zeros((1, 3)))["params"]
    assert set(params) == {"fc1", "fc2", "fc3", "out"}
    assert params["fc1"]["kernel"].shape == (3, 256)
    assert params["out"]["kernel"].shape == (256, 2)
    # fan-in init: std ~ 1/sqrt(fan_in)
    k = np.asarray(params["fc2"]["kernel"])
    assert k.std() == pytest.approx(1.0 / np.sqrt(256), rel=0.15)
    assert np.asarray(params["out"]["kernel"]).std() == pytest.approx(3e-3, rel=0.2)


def test_categorical_critic_probs_and_logits(key):
    m = CategoricalCritic(n_atoms=51)
    obs = jax.random.normal(key, (8, 11))
    act = jax.random.normal(key, (8, 3))
    params = m.init(key, obs, act)
    p = m.apply(params, obs, act)
    assert p.shape == (8, 51)
    np.testing.assert_allclose(np.asarray(p).sum(-1), 1.0, rtol=1e-5)
    logits = m.apply(params, obs, act, return_logits=True)
    np.testing.assert_allclose(
        np.asarray(jax.nn.softmax(logits, -1)), np.asarray(p), rtol=1e-5
    )


def test_critic_action_enters_second_layer(key):
    """Action concatenated after the first layer (``models.py:80``): the
    fc2 kernel's input width is hidden + act_dim."""
    m = CategoricalCritic(n_atoms=11)
    params = m.init(key, jnp.zeros((1, 5)), jnp.zeros((1, 4)))["params"]
    torso = params["torso"]
    assert torso["fc1"]["kernel"].shape == (5, 256)
    assert torso["fc2"]["kernel"].shape == (256 + 4, 256)


def test_mog_critic_outputs_valid_mixture(key):
    m = MixtureOfGaussianCritic(n_components=5)
    obs = jax.random.normal(key, (4, 7))
    act = jax.random.normal(key, (4, 2))
    params = m.init(key, obs, act)
    out = m.apply(params, obs, act)
    assert out.means.shape == (4, 5)
    np.testing.assert_allclose(
        np.exp(np.asarray(out.log_weights)).sum(-1), 1.0, rtol=1e-4
    )
    assert (np.asarray(out.stds) > 0).all()


def test_pixel_models(key):
    px = jax.random.randint(key, (2, 84, 84, 3), 0, 255, dtype=jnp.uint8)
    actor = PixelActor(act_dim=6)
    p = actor.init(key, px)
    a = actor.apply(p, px)
    assert a.shape == (2, 6)
    critic = PixelCategoricalCritic(n_atoms=51)
    pc = critic.init(key, px, a)
    z = critic.apply(pc, px, a)
    assert z.shape == (2, 51)
    np.testing.assert_allclose(np.asarray(z).sum(-1), 1.0, rtol=1e-4)


def test_actor_jits_with_static_shapes(key):
    m = Actor(act_dim=3)
    obs = jnp.zeros((16, 9))
    params = m.init(key, obs)
    f = jax.jit(lambda p, o: m.apply(p, o))
    out = f(params, obs)
    assert out.shape == (16, 3)
