"""jaxlint rule-family fixtures: each rule must fire on a known-bad snippet
and stay silent on the known-good variant, plus suppression/CLI plumbing.

The fixtures deliberately contain the hazards the rules hunt — none of
this code is ever executed, only parsed.
"""

import textwrap

import pytest

from d4pg_tpu.lint import RULES, lint_source
from d4pg_tpu.lint.__main__ import main as lint_main


def findings(src, rule=None):
    res = lint_source(textwrap.dedent(src), "fixture.py")
    assert not res.errors, res.errors
    out = res.findings
    return [f for f in out if f.rule == rule] if rule else out


# ---------------------------------------------------------------- R1 ------

def test_prng_key_reuse_fires():
    out = findings("""
        import jax

        def sample(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
        """, "prng-key-reuse")
    assert len(out) == 1
    assert "'key'" in out[0].message and out[0].line == 6


def test_prng_key_reuse_across_loop_iterations():
    # consumed every iteration, never re-split: same randomness each time
    out = findings("""
        import jax

        def rollout(key, xs):
            outs = []
            for x in xs:
                outs.append(x + jax.random.normal(key))
            return outs
        """, "prng-key-reuse")
    assert len(out) == 1


def test_prng_key_clean_patterns():
    out = findings("""
        import jax

        def good(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1)
            b = jax.random.uniform(k2)
            return a + b

        def folded(key, n):
            return [jax.random.normal(jax.random.fold_in(key, i))
                    for i in range(n)]

        def loop_rebind(key, xs):
            for x in xs:
                key, sub = jax.random.split(key)
                x = x + jax.random.normal(sub)
            return key

        def branches(key, flag):
            if flag:
                return jax.random.normal(key)
            else:
                return jax.random.uniform(key)

        def numpy_not_keys(mu, sigma):
            import numpy as np
            a = np.random.normal(mu, sigma)
            b = np.random.normal(mu, sigma)
            return a + b
        """, "prng-key-reuse")
    assert out == []


# ---------------------------------------------------------------- R2 ------

def test_host_sync_fires_in_jitted_fn():
    out = findings("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            v = float(x.sum())
            y = np.asarray(x)
            x.block_until_ready()
            return v + x.item()
        """, "host-sync-in-jit")
    assert len(out) == 4


def test_host_sync_fires_in_scan_body():
    out = findings("""
        import jax.lax as lax

        def outer(xs):
            def body(c, x):
                return c + int(x), x
            return lax.scan(body, 0.0, xs)
        """, "host-sync-in-jit")
    assert len(out) == 1


def test_host_sync_clean_outside_trace():
    # identical syncs in plain host code are legitimate
    out = findings("""
        import numpy as np

        def log_metrics(metrics):
            return {k: float(v) for k, v in metrics.items()}

        def to_host(x):
            return np.asarray(x)
        """, "host-sync-in-jit")
    assert out == []


# ---------------------------------------------------------------- R3 ------

def test_recompile_jit_in_loop_and_immediate_call():
    out = findings("""
        import jax

        def train(xs):
            for x in xs:
                y = jax.jit(lambda z: z + 1)(x)
            return y
        """, "recompile-hazard")
    # both hazards on one line: jit-in-loop AND jit(f)(x)
    assert len(out) == 2


def test_recompile_loop_var_as_static_arg():
    out = findings("""
        import jax

        def f(x, n):
            return x * n

        g = jax.jit(f, static_argnums=(1,))

        def run(x):
            for n in range(8):
                x = g(x, n)
            return x
        """, "recompile-hazard")
    assert len(out) == 1 and "loop variable 'n'" in out[0].message


def test_recompile_clean_hoisted_jit():
    out = findings("""
        import jax

        g = jax.jit(lambda z: z + 1)

        def train(xs):
            for x in xs:
                y = g(x)
            return y
        """, "recompile-hazard")
    assert out == []


# ---------------------------------------------------------------- R4 ------

def test_use_after_donation_fires():
    out = findings("""
        import jax

        g = jax.jit(lambda s: s, donate_argnums=(0,))

        def run(state):
            out = g(state)
            print(state)
            return out
        """, "use-after-donation")
    assert len(out) == 1 and "'state'" in out[0].message


def test_donation_clean_on_rebind():
    out = findings("""
        import jax

        g = jax.jit(lambda s: s, donate_argnums=(0,))

        def run(state):
            for _ in range(4):
                state = g(state)
            return state
        """, "use-after-donation")
    assert out == []


# ---------------------------------------------------------------- R5 ------

def test_tracer_leak_fires():
    out = findings("""
        import jax

        acc = []

        @jax.jit
        def leaky(x):
            acc.append(x)
            global last
            last = x
            return x

        class Model:
            @jax.jit
            def fwd(self, x):
                self.cache = x
                return x
        """, "tracer-leak")
    assert len(out) == 3


def test_tracer_leak_clean_local_mutation():
    out = findings("""
        import jax

        @jax.jit
        def fine(x):
            parts = []
            parts.append(x)
            table = {}
            table["x"] = x
            return parts[0] + table["x"]
        """, "tracer-leak")
    assert out == []


# ----------------------------------------------------- suppressions -------

def test_inline_suppression():
    res = lint_source(textwrap.dedent("""
        import jax

        def sample(key):
            a = jax.random.normal(key)
            b = jax.random.normal(key)  # jaxlint: disable=prng-key-reuse
            return a + b
        """), "fixture.py")
    assert res.findings == [] and len(res.suppressed) == 1
    assert res.clean


def test_file_wide_suppression():
    res = lint_source(textwrap.dedent("""
        # jaxlint: disable-file=prng-key-reuse
        import jax

        def sample(key):
            return jax.random.normal(key) + jax.random.normal(key)
        """), "fixture.py")
    assert res.findings == [] and len(res.suppressed) == 1


def test_suppression_is_rule_specific():
    res = lint_source(textwrap.dedent("""
        import jax

        def sample(key):
            a = jax.random.normal(key)
            b = jax.random.normal(key)  # jaxlint: disable=tracer-leak
            return a + b
        """), "fixture.py")
    assert len(res.findings) == 1


# -------------------------------------------------------------- CLI -------

def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "def f(key):\n"
        "    return jax.random.normal(key) + jax.random.uniform(key)\n")
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint_main([str(bad)]) == 1
    assert "prng-key-reuse" in capsys.readouterr().out
    assert lint_main([str(good)]) == 0
    assert lint_main(["--list-rules"]) == 0
    assert lint_main([str(bad), "--rules", "tracer-leak"]) == 0
    assert lint_main([str(bad), "--rules", "no-such-rule"]) == 2


def test_rule_catalog_covers_all_families():
    assert set(RULES) == {
        "prng-key-reuse", "host-sync-in-jit", "recompile-hazard",
        "use-after-donation", "tracer-leak", "device-put-in-loop",
        "host-time-in-jit", "lock-order", "sharding-rule-bypass",
        "lock-cycle", "unguarded-shared-write", "wire-magic-registry",
        "codec-asymmetry", "unchecked-frame", "flag-bit-collision",
        "thread-crash-containment", "span-terminal-missing",
        "ledger-conservation", "collective-axis-unbound",
        "sharding-spec-drift", "donation-alias",
        "rng-ambient-stream", "rng-stream-thread-escape",
        "rng-draw-count-drift",
    }
    assert RULES["sharding-rule-bypass"].scope == "module"
    # the lock-graph and wire-graph families analyze whole programs,
    # not single modules
    assert RULES["lock-cycle"].scope == "program"
    assert RULES["unguarded-shared-write"].scope == "program"
    for rule in ("wire-magic-registry", "codec-asymmetry",
                 "unchecked-frame", "flag-bit-collision",
                 "thread-crash-containment", "span-terminal-missing",
                 "ledger-conservation", "collective-axis-unbound",
                 "sharding-spec-drift", "donation-alias",
                 "rng-ambient-stream", "rng-stream-thread-escape",
                 "rng-draw-count-drift"):
        assert RULES[rule].scope == "program"
    assert RULES["lock-order"].scope == "module"


# ---------------------------------------------------------------- R7 ------

def test_lock_order_fires_on_buffer_lock_under_shard_cond():
    out = findings("""
        class Service:
            def bad(self, shard, batch):
                with shard.cond:
                    with self._buffer_lock:
                        self.buffer.add(batch)
        """, "lock-order")
    assert len(out) == 1
    assert "'cond'" in out[0].message


def test_lock_order_fires_on_acquire_and_ring_locks():
    out = findings("""
        class Staging:
            def bad(self, i):
                with self._ring_locks[i]:
                    self._lock.acquire()
                    try:
                        self.n += 1
                    finally:
                        self._lock.release()
        """, "lock-order")
    assert len(out) == 1


def test_lock_order_clean_patterns():
    # sequential (non-nested) acquisition and leaf-last nesting are the
    # documented discipline — neither may fire
    out = findings("""
        class Service:
            def good(self, shard, batch):
                with shard.cond:
                    shard.q.append(batch)
                with self._buffer_lock:
                    self.buffer.add(batch)
                with self._lock:
                    self.pending -= 1

            def also_good(self, shard):
                with self._buffer_lock:
                    with shard.cond:
                        return len(shard.q)

            def new_scope_resets(self, shard):
                with shard.cond:
                    def helper(self):
                        with self._buffer_lock:
                            return 1  # different thread's scope
                    return helper
        """, "lock-order")
    assert out == []


def test_device_put_in_loop_fires():
    out = findings("""
        import jax

        def drain(rows):
            for row in rows:
                jax.device_put(row)

        def drain_while(rows):
            while rows:
                x = jax.device_put(rows.pop())
        """, "device-put-in-loop")
    assert len(out) == 2


def test_device_put_in_loop_clean_patterns():
    out = findings("""
        import jax

        def block_drain(rows):
            block = stack(rows)
            return jax.device_put(block)  # one transfer, outside any loop

        def other_scope(rows):
            for row in rows:
                # nested function is its own scope; defining it in a loop
                # is not a per-iteration transfer
                def put():
                    return jax.device_put(row)
            return put

        def not_jax(rows, stager):
            for row in rows:
                stager.device_put(row)  # some other object's method
        """, "device-put-in-loop")
    assert out == []


def test_syntax_error_reported_not_raised(tmp_path):
    res = lint_source("def broken(:\n", "broken.py")
    assert res.errors and not res.clean


# ------------------------------------------- R10: host-time-in-jit --------

def test_host_time_in_jit_fires_on_clock_reads():
    out = findings("""
        import time
        from time import perf_counter
        import jax

        @jax.jit
        def step(x):
            t0 = time.time()
            t1 = time.perf_counter()
            t2 = perf_counter()
            return x * (t1 - t0) + t2
        """, "host-time-in-jit")
    assert len(out) == 3
    assert "trace time" in out[0].message.lower() \
        or "TRACE time" in out[0].message


def test_host_time_in_jit_fires_transitively_and_on_obs_calls():
    # update_step is only REACHED from a jitted wrapper — the taint must
    # propagate; registry/span calls are host side effects that fire
    # once at trace time and never again
    out = findings("""
        import time
        import jax
        from d4pg_tpu.obs.trace import RECORDER

        def update_step(state, batch):
            RECORDER.record_span(1, "grad")
            REGISTRY.counter("steps").inc()
            return state, time.monotonic()

        update = jax.jit(lambda s, b: update_step(s, b))
        """, "host-time-in-jit")
    assert len(out) == 3


def test_host_time_in_jit_clean_patterns():
    out = findings("""
        import time
        import jax

        def host_loop(update, state, batch):
            # clock reads at the DISPATCH site are the correct pattern
            t0 = time.perf_counter()
            state, m = update(state, batch)
            return state, time.perf_counter() - t0

        @jax.jit
        def step(x, t_wall):
            # timestamps threaded in as arguments are real data
            return x * t_wall

        def bare_time_not_claimed(time):
            # a user-defined callable named `time` is not the module
            return time()
        """, "host-time-in-jit")
    assert out == []


def test_host_time_in_jit_suppressible():
    res = lint_source(textwrap.dedent("""
        import time
        import jax

        @jax.jit
        def step(x):
            # trace-time stamp is INTENTIONAL here: compile-era marker
            t = time.time()  # jaxlint: disable=host-time-in-jit
            return x
        """), "fixture.py")
    assert [f for f in res.findings if f.rule == "host-time-in-jit"] == []
    assert any(f.rule == "host-time-in-jit" for f in res.suppressed)


# ------------------------------------- R15: sharding-rule-bypass ----------

def test_sharding_rule_bypass_fires_on_direct_and_aliased_ctors():
    out = findings("""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(mesh, x):
            sh = NamedSharding(mesh, P("data"))
            qualified = jax.sharding.PartitionSpec(None, "model")
            return jax.device_put(x, sh), qualified
        """, "sharding-rule-bypass")
    assert len(out) == 3
    assert all("partition-rule" in f.message for f in out)


def test_sharding_rule_bypass_fires_on_partition_ps_realias():
    # calling through a re-alias of the core's own PS export is the
    # same bypass: the spec skips the rule table
    out = findings("""
        from d4pg_tpu.parallel import partition

        P = partition.PS

        def spec_for(name):
            return P("data") if name else partition.PS()
        """, "sharding-rule-bypass")
    assert len(out) == 2


def test_sharding_rule_bypass_clean_through_rule_core():
    # resolving layouts THROUGH the core (factories + rule matching) and
    # merely importing Mesh / annotating with PS are all fine
    out = findings("""
        from jax.sharding import Mesh

        from d4pg_tpu.parallel import partition

        def place(mesh, tree, x):
            sh = partition.batch_sharding(mesh)
            specs = partition.match_partition_rules(
                partition.D4PG_RULES, tree)
            return sh, specs, partition.sharding(mesh, "data")
        """, "sharding-rule-bypass")
    assert out == []


def test_sharding_rule_bypass_exempts_partition_core():
    res = lint_source(textwrap.dedent("""
        from jax.sharding import NamedSharding, PartitionSpec

        def spec(*axes):
            return PartitionSpec(*axes)

        def sharding(mesh, *axes):
            return NamedSharding(mesh, spec(*axes))
        """), "d4pg_tpu/parallel/partition.py")
    assert [f for f in res.findings
            if f.rule == "sharding-rule-bypass"] == []


def test_sharding_rule_bypass_suppressible():
    res = lint_source(textwrap.dedent("""
        from jax.sharding import PartitionSpec

        def exotic(mesh):
            # layout experiment outside the table on purpose (bench-only)
            return PartitionSpec("data")  # jaxlint: disable=sharding-rule-bypass
        """), "fixture.py")
    assert [f for f in res.findings
            if f.rule == "sharding-rule-bypass"] == []
    assert any(f.rule == "sharding-rule-bypass" for f in res.suppressed)


# ------------------------------------------------- R8: lock-cycle ---------

def test_lock_cycle_fires_on_cross_function_abba():
    """The shape the syntactic lock-order rule CANNOT see: each function
    nests correctly in isolation; the ABBA cycle only exists through the
    call edges (worker holds the shard cond into a helper that takes the
    merge cond; the committer holds the merge cond into a helper that
    takes the shard cond)."""
    out = findings("""
        class Service:
            def worker(self, shard):
                with shard.cond:
                    self._hand_off(shard)

            def _hand_off(self, shard):
                with self._commit_cond:
                    self._commit_cond.notify_all()

            def committer(self, shard):
                with self._commit_cond:
                    self._drain_one(shard)

            def _drain_one(self, shard):
                with shard.cond:
                    return shard.q.popleft()
        """, "lock-cycle")
    assert len(out) == 1
    assert "cond" in out[0].message and "_commit_cond" in out[0].message
    assert "deadlock" in out[0].message


def test_lock_cycle_fires_on_direct_abba():
    out = findings("""
        class S:
            def a(self):
                with self._ring_locks[0]:
                    with self._buffer_lock:
                        pass

            def b(self):
                with self._buffer_lock:
                    with self._ring_locks[1]:
                        pass
        """, "lock-cycle")
    assert len(out) == 1


def test_lock_cycle_clean_on_consistent_order():
    """Hierarchy-consistent nesting — even deep through calls — must not
    fire: every path acquires in one global order."""
    out = findings("""
        class Service:
            def committer(self, shard):
                with self._buffer_lock:
                    self._insert(shard)

            def _insert(self, shard):
                with shard.ring_lock:
                    shard.rows.clear()

            def sampler(self):
                with self._buffer_lock:
                    with self._ring_locks[0]:
                        return 1

            def sequential(self, shard):
                with shard.cond:
                    shard.q.clear()
                with self._buffer_lock:
                    return 2
        """, "lock-cycle")
    assert out == []


def test_lock_cycle_merge_wedge_regression():
    """Acceptance bar: re-introducing the PR-4 merge-wedge DISCIPLINE
    REVERT — the shard worker waiting on merge-inbox state while still
    holding its shard condition — is caught statically even though the
    commit-cond acquisition is a call away (the runtime twin of this
    regression lives in test_locking.py::test_merge_wedge_shape_is_caught
    on the real service objects)."""
    out = findings("""
        class ReplayService:
            def _worker(self, s):
                with s.cond:
                    items = self._pop_coalesced(s)
                    self._wait_for_inbox(s)   # REVERTED: was outside s.cond
                    return items

            def _wait_for_inbox(self, s):
                with self._commit_cond:
                    while self._out[s.idx]:
                        self._commit_cond.wait(0.1)

            def _commit_loop(self):
                with self._commit_cond:
                    group = self._pop_ready()
                for s in self._shards:
                    self._settle(s)

            def _pop_ready(self):
                return list(self._out)

            def _settle(self, s):
                with s.cond:
                    s.cond.notify_all()
        """)
    cyc = [f for f in out if f.rule == "lock-cycle"]
    assert len(cyc) == 1
    assert "cond" in cyc[0].message and "_commit_cond" in cyc[0].message


# ------------------------------------- R9: unguarded-shared-write ---------

def test_unguarded_write_fires_on_naked_counter():
    """A genuine unguarded counter: every other access takes the lock;
    the hot-path increment skips it."""
    out = findings("""
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self.rows = 0

            def bump(self, n):
                with self._lock:
                    self.rows += n

            def snapshot(self):
                with self._lock:
                    return {"rows": self.rows}

            def fast_path(self, n):
                self.rows += n   # racy read-modify-write
        """, "unguarded-shared-write")
    assert len(out) == 1
    assert "'rows'" in out[0].message and "'_lock'" in out[0].message
    assert "guarded-by" in out[0].message


def test_unguarded_write_satisfied_by_annotation():
    """`# jaxlint: guarded-by=<lock>` declares the caller-holds-it
    contract (line-level or def-level) and satisfies the checker."""
    out = findings("""
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self.rows = 0

            def bump(self, n):
                with self._lock:
                    self.rows += n

            def snapshot(self):
                with self._lock:
                    return {"rows": self.rows}

            def _bump_locked(self, n):  # jaxlint: guarded-by=_lock
                self.rows += n

            def line_level(self, n):
                self.rows += n  # jaxlint: guarded-by=_lock
        """, "unguarded-shared-write")
    assert out == []


def test_unguarded_write_inherits_caller_lock():
    """A helper whose EVERY call site holds the lock is guarded by
    inheritance — no annotation needed (the _pop_ready pattern: writes
    under the commit condition held by the caller)."""
    out = findings("""
        import threading

        class Merge:
            def __init__(self):
                self._commit_cond = threading.Condition()
                self.order_breaks = 0

            def loop(self):
                with self._commit_cond:
                    self._pop_ready()

            def valve(self):
                with self._commit_cond:
                    self._pop_ready()
                    self.order_breaks += 1

            def _pop_ready(self):
                self.order_breaks += 1
        """, "unguarded-shared-write")
    assert out == []


def test_unguarded_write_silent_without_majority():
    """Single-writer attributes read without the lock everywhere are NOT
    lock-owned — inference must stay silent rather than guess."""
    out = findings("""
        import threading

        class Ring:
            def __init__(self):
                self._lock = threading.Lock()
                self.head = 0

            def write(self):
                with self._lock:
                    self.head += 1

            def reader_a(self):
                return self.head

            def reader_b(self):
                return self.head + 1
        """, "unguarded-shared-write")
    assert out == []


def test_lock_graph_cli_mode(tmp_path, capsys):
    """`--locks` prints the graph artifact; exit 1 iff a cycle exists."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        class S:
            def a(self):
                with self._ring_locks[0]:
                    with self._buffer_lock:
                        pass

            def b(self):
                with self._buffer_lock:
                    with self._ring_locks[1]:
                        pass
        """))
    assert lint_main(["--locks", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "_buffer_lock" in out and "_ring_locks" in out
    assert "cycles:" in out and "edges" in out

    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent("""
        class S:
            def a(self):
                with self._buffer_lock:
                    with self._ring_locks[0]:
                        pass
        """))
    assert lint_main(["--locks", str(good)]) == 0
    out = capsys.readouterr().out
    assert "cycles: none" in out
    assert "_buffer_lock -> _ring_locks" in out


# ------------------------------------------------- wire families (11-14) --

def test_wire_magic_registry_fires_on_unregistered_magic():
    out = findings("""
        import struct

        def encode(payload):
            return struct.pack("!HI", 0xD412, len(payload)) + payload
        """, "wire-magic-registry")
    assert len(out) == 1
    assert "0xD412" in out[0].message and "absent" in out[0].message


def test_wire_magic_registry_fires_on_private_redeclare():
    out = findings("""
        import struct

        _MAGIC = 0xD4F6  # privately re-declares the ingest-v1 magic

        def encode(payload):
            return struct.pack("!II", _MAGIC, len(payload)) + payload
        """, "wire-magic-registry")
    assert len(out) == 1
    assert "re-declares" in out[0].message
    assert "d4pg_tpu.core.wire" in out[0].message


def test_wire_magic_registry_exempts_seed_literals():
    out = findings("""
        import numpy as np

        def rng(seed, replica):
            ss = np.random.SeedSequence(seed, spawn_key=(0xD4E4, replica))
            return np.random.default_rng(seed ^ 0xD4E3)
        """, "wire-magic-registry")
    assert out == []


def test_wire_magic_registry_fires_on_undeclared_flag_bit():
    out = findings("""
        import struct

        SFLAG_PRIORITY = 0x08  # bit never allocated in the registry

        def check(magic):
            return magic == 0xD4E2
        """, "wire-magic-registry")
    assert len(out) == 1
    assert "flag bit 0x08" in out[0].message


def test_codec_asymmetry_fires_on_format_drift():
    # decoder reads three fields where the ingest header declares two
    out = findings("""
        import struct

        def decode(head):
            if not head:
                return None
            try:
                got, length, extra = struct.unpack("!IIH", head)
            except struct.error:
                return None
            return got == 0xD4F6
        """, "codec-asymmetry")
    assert len(out) == 1
    assert "'!IIH'" in out[0].message and "segment" in out[0].message


def test_codec_asymmetry_fires_on_size_const_drift():
    out = findings("""
        import struct

        HDR = struct.Struct("!II")
        HDR_SIZE = 12  # calcsize says 8
        """, "codec-asymmetry")
    assert len(out) == 1
    assert "HDR_SIZE = 12" in out[0].message and "= 8" in out[0].message


def test_codec_asymmetry_fires_on_argument_count_drift():
    out = findings("""
        import struct

        def greet(gen, extra):
            return struct.pack("!HI", 0xD4FA, gen, extra)
        """, "codec-asymmetry")
    drift = [f for f in out if "2 field(s)" in f.message]
    assert len(drift) == 1
    assert "3 argument(s)" in drift[0].message


def test_codec_asymmetry_fires_on_one_sided_magic():
    out = findings("""
        import struct

        def greet(gen):
            return struct.pack("!HI", 0xD4FA, gen)
        """, "codec-asymmetry")
    assert len(out) == 1
    assert "one-sided" in out[0].message


def test_codec_asymmetry_clean_on_split_reads():
    # weight_plane's idiom: magic read separately, then the remainder of
    # the declared request format — both are contiguous field segments
    out = findings("""
        import struct

        _REQ = struct.Struct("!IqIBB")

        def serve(conn, recv_exact):
            head = recv_exact(conn, 4)
            if head is None:
                return None
            (magic,) = struct.unpack("!I", head)
            if magic != 0xD4FC:
                return None
            rest = recv_exact(conn, _REQ.size - 4)
            have, gen, codec, flags = struct.unpack("!qIBB", rest)
            return have, gen, codec, flags
        """, "codec-asymmetry")
    assert out == []


def test_unchecked_frame_fires_on_naked_recv_unpack():
    out = findings("""
        import struct

        def serve(sock):
            head = sock.recv(64)
            magic, length = struct.unpack("!II", head)
            return sock.recv(length)
        """, "unchecked-frame")
    assert len(out) == 1
    assert "struct.error containment" in out[0].message


def test_unchecked_frame_clean_on_contained_or_exact_read():
    out = findings("""
        import struct

        HDR = struct.Struct("!II")

        def serve_contained(sock):
            head = sock.recv(64)
            try:
                magic, length = struct.unpack("!II", head)
            except struct.error:
                return None
            return magic, length

        def serve_exact(sock):
            head = sock.recv(HDR.size)
            magic, length = HDR.unpack(head)
            return magic, length
        """, "unchecked-frame")
    assert out == []


def test_unchecked_frame_fires_on_parse_before_crc():
    # weights-v2 declares crc32-payload: np.load before any crc32 call
    # on the path is a torn-frame acceptance hazard even when contained
    out = findings("""
        import io
        import struct

        import numpy as np

        def pull(sock):
            head = sock.recv(13)
            magic, kind, crc, length = struct.unpack("!IBII", head)
            if magic != 0xD4FC:
                return None
            payload = sock.recv(length)
            try:
                with np.load(io.BytesIO(payload)) as z:
                    return dict(z)
            except ValueError:
                return None
        """, "unchecked-frame")
    assert len(out) == 1
    assert "crc32" in out[0].message


def test_unchecked_frame_clean_when_crc_checked_first():
    out = findings("""
        import io
        import struct
        import zlib

        import numpy as np

        def pull(sock):
            head = sock.recv(13)
            magic, kind, crc, length = struct.unpack("!IBII", head)
            if magic != 0xD4FC:
                return None
            payload = sock.recv(length)
            if zlib.crc32(payload) != crc:
                return None
            try:
                with np.load(io.BytesIO(payload)) as z:
                    return dict(z)
            except ValueError:
                return None
        """, "unchecked-frame")
    assert out == []


def test_flag_bit_collision_fires_on_registry_conflict():
    out = findings("""
        import struct

        F_TENANT = 0x01  # bit 0 of the serving flag byte is 'trace'

        def check(magic):
            return magic == 0xD4E2
        """, "flag-bit-collision")
    assert len(out) == 1
    assert "already allocated to 'trace'" in out[0].message


def test_flag_bit_collision_fires_on_two_local_claims():
    out = findings("""
        import struct

        F_AAA = 0x08
        FLAG_BBB = 0x08  # same undeclared bit, different meaning

        def check(magic):
            return magic == 0xD4E2
        """, "flag-bit-collision")
    assert len(out) == 1
    assert "FLAG_BBB" in out[0].message and "F_AAA" in out[0].message


def test_flag_bit_collision_clean_on_consistent_mirror():
    # a local alias of a declared bit with a matching meaning is the
    # sanctioned pattern (transport's _F_TRACE before the registry)
    out = findings("""
        import struct

        _F_TRACE = 0x02

        def check(magic):
            return magic == 0xD4F8
        """, "flag-bit-collision")
    assert out == []


def test_wire_cli_mode(tmp_path, capsys):
    """`--wire` prints the registry artifact; exit 1 iff a family fires."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import struct

        def encode(payload):
            return struct.pack("!HI", 0xD412, len(payload)) + payload
        """))
    assert lint_main(["--wire", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "0xD412" in out and "findings:" in out

    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent("""
        import struct

        HDR = struct.Struct("!II")

        def greet(sock, gen):
            sock.sendall(struct.pack("!HI", 0xD4FA, gen))

        def read_greeting(sock):
            head = sock.recv(6)
            try:
                magic, gen = struct.unpack("!HI", head)
            except struct.error:
                return None
            if magic != 0xD4FA:
                return None
            return gen
        """))
    assert lint_main(["--wire", str(good)]) == 0
    out = capsys.readouterr().out
    assert "0xD4FA" in out and "findings: none" in out


# ----------------------------------------- R16-R18 (failgraph) ------------

@pytest.mark.failflow
def test_thread_containment_fires_on_escaping_target():
    out = findings("""
        import threading

        class Plane:
            def start(self):
                self._t = threading.Thread(target=self._serve, daemon=True)
                self._t.start()

            def _serve(self):
                while True:
                    self.handle_one()
        """, "thread-crash-containment")
    assert len(out) == 1
    assert "die silently" in out[0].message


@pytest.mark.failflow
def test_thread_containment_clean_on_caught_and_counted():
    out = findings("""
        import threading

        class Plane:
            def start(self):
                self._t = threading.Thread(target=self._serve, daemon=True)
                self._t.start()

            def _serve(self):
                try:
                    while True:
                        self.handle_one()
                except Exception:
                    self.contained_crashes += 1
        """, "thread-crash-containment")
    assert out == []


@pytest.mark.failflow
def test_thread_containment_fires_on_uncounted_handler():
    out = findings("""
        import threading

        class Plane:
            def start(self):
                self._t = threading.Thread(target=self._serve, daemon=True)
                self._t.start()

            def _serve(self):
                try:
                    while True:
                        self.handle_one()
                except Exception:
                    pass
        """, "thread-crash-containment")
    assert len(out) == 1
    assert "without counting" in out[0].message


@pytest.mark.failflow
def test_thread_containment_fires_on_reraising_handler():
    out = findings("""
        import threading

        class Plane:
            def start(self):
                self._t = threading.Thread(target=self._serve, daemon=True)
                self._t.start()

            def _serve(self):
                try:
                    while True:
                        self.handle_one()
                except Exception:
                    self.contained_crashes += 1
                    raise
        """, "thread-crash-containment")
    assert len(out) == 1
    assert "die silently" in out[0].message


@pytest.mark.failflow
def test_thread_containment_fires_on_unresolvable_target():
    out = findings("""
        import threading

        def launch(lanes):
            for lane in lanes:
                t = threading.Thread(target=lane.run, daemon=True)
                t.start()
        """, "thread-crash-containment")
    assert len(out) == 1
    assert "does not resolve" in out[0].message


@pytest.mark.failflow
def test_thread_containment_contained_by_declaration_satisfies():
    out = findings("""
        import threading

        class Lane:
            def run(self):
                try:
                    self.spin()
                except Exception:
                    self.crashes += 1

        def launch(lanes):
            for lane in lanes:
                t = threading.Thread(target=lane.run, daemon=True)  # jaxlint: contained-by=Lane.run
                t.start()
        """, "thread-crash-containment")
    assert out == []


@pytest.mark.failflow
def test_thread_containment_contained_by_weak_handler_fires():
    out = findings("""
        import threading

        class Lane:
            def run(self):
                self.spin()

        def launch(lanes):
            for lane in lanes:
                t = threading.Thread(target=lane.run, daemon=True)  # jaxlint: contained-by=Lane.run
                t.start()
        """, "thread-crash-containment")
    assert len(out) == 1
    assert "not itself contained-and-counted" in out[0].message


@pytest.mark.failflow
def test_span_terminal_fires_on_raise_path_orphan():
    out = findings("""
        class Plane:
            def handle(self, frame):
                tid = self.next_id()
                TRACE.begin(tid, 0.0)
                payload = self.decode(frame)
                TRACE.mark_committed(tid)
        """, "span-terminal-missing")
    assert len(out) == 1
    assert "orphaned span" in out[0].message


@pytest.mark.failflow
def test_span_terminal_clean_on_exception_edge_shed():
    out = findings("""
        class Plane:
            def handle(self, frame):
                tid = self.next_id()
                TRACE.begin(tid, 0.0)
                try:
                    payload = self.decode(frame)
                except Exception:
                    TRACE.terminal_shed(tid)
                    raise
                TRACE.mark_committed(tid)
        """, "span-terminal-missing")
    assert out == []


@pytest.mark.failflow
def test_span_terminal_clean_on_escrowed_root():
    # the trace id rides the queue entry out of the frame: custody is
    # handed off, not orphaned
    out = findings("""
        class Plane:
            def admit(self, frame):
                tid = self.next_id()
                TRACE.begin(tid, 0.0)
                self.pending[tid] = frame
        """, "span-terminal-missing")
    assert out == []


@pytest.mark.failflow
def test_ledger_fires_on_unaccounted_admission():
    out = findings("""
        class Plane:
            def admit(self, frame):
                self.frames += 1
                payload = self.decode(frame)
                self.apply_update(payload)
        """, "ledger-conservation")
    assert len(out) == 1
    assert "vanish from the ledger" in out[0].message


@pytest.mark.failflow
def test_ledger_clean_on_counted_dispositions():
    out = findings("""
        class Plane:
            def admit(self, frame):
                self.frames += 1
                try:
                    payload = self.decode(frame)
                except Exception:
                    self.torn += 1
                    return
                self.pending.append(payload)
        """, "ledger-conservation")
    assert out == []


@pytest.mark.failflow
def test_fail_cli_mode(tmp_path, capsys):
    """`--fail` prints the exception-flow artifact; exit 1 iff a family
    fires."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading

        class Plane:
            def start(self):
                self._t = threading.Thread(target=self._serve)
                self._t.start()

            def _serve(self):
                self.handle_one()
        """))
    assert lint_main(["--fail", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "thread roles" in out and "finding(s)" in out

    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent("""
        import threading

        class Plane:
            def start(self):
                self._t = threading.Thread(target=self._serve)
                self._t.start()

            def _serve(self):
                try:
                    self.handle_one()
                except Exception:
                    self.contained_crashes += 1
        """))
    assert lint_main(["--fail", str(good)]) == 0
    out = capsys.readouterr().out
    assert "[contained]" in out and "findings: none" in out


# ------------------------------------------------- --json plumbing --------

def _run_json(argv, capsys):
    import json

    rc = lint_main(argv)
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == 1
    assert isinstance(doc["findings"], list)
    assert isinstance(doc["errors"], list)
    return rc, doc


def test_json_default_mode(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        def f(key):
            a = jax.random.normal(key)
            b = jax.random.normal(key)
            return a + b
        """))
    rc, doc = _run_json(["--json", str(bad)], capsys)
    assert rc == 1 and doc["mode"] == "findings"
    assert any(f["rule"] == "prng-key-reuse" for f in doc["findings"])
    f = doc["findings"][0]
    assert set(f) == {"file", "line", "col", "rule", "message", "suppressed"}


def test_json_locks_mode(tmp_path, capsys):
    src = tmp_path / "locks.py"
    src.write_text("x = 1\n")
    rc, doc = _run_json(["--locks", "--json", str(src)], capsys)
    assert rc == 0 and doc["mode"] == "locks"
    assert {"functions", "nodes", "edges", "cycles"} <= set(doc)


def test_json_wire_mode(tmp_path, capsys):
    src = tmp_path / "wire.py"
    src.write_text("x = 1\n")
    rc, doc = _run_json(["--wire", "--json", str(src)], capsys)
    assert rc == 0 and doc["mode"] == "wire"
    assert {"functions", "modules", "magics", "flags"} <= set(doc)


@pytest.mark.failflow
def test_json_fail_mode(tmp_path, capsys):
    src = tmp_path / "fail.py"
    src.write_text(textwrap.dedent("""
        import threading

        class Plane:
            def start(self):
                self._t = threading.Thread(target=self._serve)
                self._t.start()

            def _serve(self):
                self.handle_one()
        """))
    rc, doc = _run_json(["--fail", "--json", str(src)], capsys)
    assert rc == 1 and doc["mode"] == "fail"
    assert {"threads", "spans", "ledger", "handlers"} <= set(doc)
    assert doc["threads"] and doc["threads"][0]["status"] == "escapes"
