"""Device-resident PER trees + fused chunk step (replay/device_per.py,
learner/fused.py, replay/fused_buffer.py) against the host implementations
as oracle (replay/segment_tree.py mirrors the reference's
prioritized_replay_memory.py:33-162)."""

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.learner import D4PGConfig, init_state
from d4pg_tpu.learner.fused import make_fused_chunk
from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay
from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.uniform import TransitionBatch


CAP = 64


def _host_trees(idx, values):
    s, m = SumTree(CAP), MinTree(CAP)
    s.set(idx, values)
    m.set(idx, values)
    return s, m


def test_set_leaves_matches_host_trees(rng):
    idx = rng.choice(CAP, size=40, replace=False)
    vals = rng.integers(1, 100, size=40).astype(np.float64)
    s, m = _host_trees(idx, vals)
    trees = dper.set_leaves(dper.init(CAP), jnp.asarray(idx),
                            jnp.asarray(vals, jnp.float32))
    assert np.isclose(float(trees.sum_tree[1]), s.sum(), rtol=1e-6)
    assert float(trees.min_tree[1]) == m.min()
    got = np.asarray(trees.sum_tree[CAP + idx])
    np.testing.assert_allclose(got, vals, rtol=1e-6)


def test_prefix_sample_matches_host_descent(rng):
    idx = np.arange(CAP)
    vals = rng.integers(1, 50, size=CAP).astype(np.float64)
    s, _ = _host_trees(idx, vals)
    trees = dper.set_leaves(dper.init(CAP), jnp.asarray(idx),
                            jnp.asarray(vals, jnp.float32))
    key = jax.random.key(3)
    B = 32
    got = np.asarray(dper.sample(trees, key, B, jnp.int32(CAP)))
    # replicate the stratified masses with the same uniforms
    u = np.asarray(jax.random.uniform(key, (B,)), np.float64)
    total = float(trees.sum_tree[1])
    mass = (np.arange(B) + u) * (total / B)
    expect = s.find_prefixsum(mass)
    np.testing.assert_array_equal(got, expect)


def test_sample_respects_size_limit(rng):
    # only the first 10 slots are written; samples must stay inside them
    idx = np.arange(10)
    trees = dper.set_leaves(dper.init(CAP), jnp.asarray(idx),
                            jnp.ones(10, jnp.float32))
    got = np.asarray(dper.sample(trees, jax.random.key(0), 64, jnp.int32(10)))
    assert got.min() >= 0 and got.max() < 10


def test_is_weights_matches_host_formula(rng):
    idx = np.arange(CAP)
    vals = rng.uniform(0.1, 5.0, size=CAP)
    trees = dper.set_leaves(dper.init(CAP), jnp.asarray(idx),
                            jnp.asarray(vals, jnp.float32))
    q = rng.choice(CAP, size=16)
    beta, size = 0.7, CAP
    got = np.asarray(dper.is_weights(trees, jnp.asarray(q),
                                     jnp.float32(beta), jnp.int32(size)))
    total = vals.sum()
    p_min = vals.min() / total
    max_w = (p_min * size) ** (-beta)
    expect = ((vals[q] / total * size) ** (-beta)) / max_w
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_set_leaves_pads_are_dropped(rng):
    """Entries with idx >= capacity are pads: a mixed batch only writes
    its valid rows, and a pad-only call is a no-op (both trees, all
    levels — the repair chain must not let parked pads alias real
    nodes)."""
    trees = dper.set_leaves(dper.init(CAP), jnp.arange(8),
                            jnp.full(8, 2.0, jnp.float32))
    mixed = dper.set_leaves(
        trees, jnp.asarray([1, CAP, 3, CAP]),
        jnp.asarray([5.0, 99.0, 7.0, 99.0], jnp.float32))
    assert float(mixed.sum_tree[CAP + 1]) == 5.0
    assert float(mixed.sum_tree[CAP + 3]) == 7.0
    assert float(mixed.sum_tree[1]) == 2.0 * 6 + 5.0 + 7.0
    assert float(mixed.min_tree[1]) == 2.0
    pads_only = dper.set_leaves(
        mixed, jnp.full(4, CAP), jnp.full(4, 123.0, jnp.float32))
    np.testing.assert_array_equal(np.asarray(pads_only.sum_tree),
                                  np.asarray(mixed.sum_tree))


def test_set_leaves_traces_at_production_capacity():
    """The pad sentinel must not overflow int32 at real buffer sizes
    (1M-slot ring -> tree capacity 2^20): trace-only check."""
    cap = 1 << 20
    t = dper.PerTrees(
        jax.ShapeDtypeStruct((2 * cap,), jnp.float32),
        jax.ShapeDtypeStruct((2 * cap,), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32),
    )
    out = jax.eval_shape(
        dper.set_leaves, t,
        jax.ShapeDtypeStruct((256,), jnp.int32),
        jax.ShapeDtypeStruct((256,), jnp.float32))
    assert out.sum_tree.shape == (2 * cap,)


def test_insert_and_update_semantics():
    trees = dper.init(CAP)
    alpha = 0.6
    trees = dper.insert(trees, jnp.arange(8), alpha)
    # new items enter at max_priority ** alpha == 1 (max_priority starts 1)
    np.testing.assert_allclose(np.asarray(trees.sum_tree[CAP:CAP + 8]), 1.0)
    td = jnp.asarray([3.0, -7.0, 0.5, 1.0])
    trees = dper.update_from_td(trees, jnp.asarray([0, 1, 2, 3]), td, alpha)
    expect = (np.abs(np.asarray(td)) + 1e-6) ** alpha
    np.testing.assert_allclose(np.asarray(trees.sum_tree[CAP:CAP + 4]),
                               expect, rtol=1e-5)
    # running max tracks the raw priority, so later inserts inherit it
    assert np.isclose(float(trees.max_priority), 7.0 + 1e-6)
    trees = dper.insert(trees, jnp.asarray([9]), alpha)
    assert np.isclose(float(trees.sum_tree[CAP + 9]),
                      (7.0 + 1e-6) ** alpha, rtol=1e-5)


def test_device_trees_match_host_under_random_op_sequences(rng):
    """Stateful fuzz: a random interleaving of inserts, priority updates
    (with duplicate indices) and prefix-sum queries keeps the device
    trees in lock-step with the host numpy trees (the reference-parity
    oracle). Duplicate-update batches are made value-consistent so the
    unspecified-winner freedom cannot cause a legitimate divergence."""
    s_host, m_host = SumTree(CAP), MinTree(CAP)
    trees = dper.init(CAP)
    live = 0
    for step in range(30):
        if rng.integers(2) == 0 or live == 0:  # insert a block of new slots
            n = int(rng.integers(1, 9))
            idx = (np.arange(live, live + n) % CAP)
            live = min(live + n, CAP)
            p = float(np.asarray(trees.max_priority)) ** 0.6
            s_host.set(idx, np.full(n, p))
            m_host.set(idx, np.full(n, p))
            trees = dper.insert(trees, jnp.asarray(idx), 0.6)
        else:  # priority update with possible duplicates
            n = int(rng.integers(1, 9))
            idx = rng.integers(0, live, size=n)
            vals = rng.uniform(0.5, 4.0, size=len(np.unique(idx)))
            # same value for every duplicate of a slot
            lut = dict(zip(np.unique(idx), vals))
            pr = np.array([lut[i] for i in idx])
            s_host.set(idx, pr**0.6)
            m_host.set(idx, pr**0.6)
            trees = dper.set_leaves(trees, jnp.asarray(idx),
                                    jnp.asarray(pr**0.6, jnp.float32))
        np.testing.assert_allclose(float(trees.sum_tree[1]), s_host.sum(),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(trees.min_tree[1]), m_host.min(),
                                   rtol=1e-6)
        leaf_idx = np.arange(live)
        np.testing.assert_allclose(
            np.asarray(trees.sum_tree[CAP + leaf_idx]),
            s_host.get(leaf_idx), rtol=1e-5)
    # final: a batch of prefix queries descends to the same leaves
    mass = rng.uniform(0, s_host.sum() * 0.999, size=64)
    host_leaves = s_host.find_prefixsum(mass)
    # replicate via the device descent on the same masses
    p = jnp.asarray(mass, jnp.float32)
    node = jnp.ones(64, jnp.int32)
    import math
    for _ in range(int(math.log2(CAP))):
        left = node << 1
        ls = trees.sum_tree[left]
        go = p >= ls
        p = jnp.where(go, p - ls, p)
        node = jnp.where(go, left | 1, left)
    dev_leaves = np.asarray(node) - CAP
    # f32 vs f64 partial sums can disagree exactly at a leaf boundary;
    # allow off-by-one-leaf there
    assert (np.abs(dev_leaves - host_leaves) <= 1).all()
    assert (dev_leaves == host_leaves).mean() > 0.9


def test_beta_schedule_matches_host_schedule():
    from d4pg_tpu.replay import LinearSchedule

    host = LinearSchedule(1000, 1.0, 0.4)
    for t in (0, 250, 999, 5000):
        got = float(dper.beta_schedule(jnp.int32(t), 0.4, 1000))
        assert np.isclose(got, host.value(t), atol=1e-6)


def _fill_storage(rng, cap, obs_dim, act_dim):
    return TransitionBatch(
        obs=jnp.asarray(rng.standard_normal((cap, obs_dim)), jnp.float32),
        action=jnp.asarray(rng.uniform(-1, 1, (cap, act_dim)), jnp.float32),
        reward=jnp.asarray(rng.standard_normal(cap), jnp.float32),
        next_obs=jnp.asarray(rng.standard_normal((cap, obs_dim)), jnp.float32),
        done=jnp.zeros(cap, jnp.float32),
        discount=jnp.full(cap, 0.99, jnp.float32),
    )


def test_fused_chunk_per_step_and_priorities(rng):
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10, n_atoms=11,
                        hidden=(16, 16, 16))
    state = init_state(config, jax.random.key(0))
    storage = _fill_storage(rng, CAP, 4, 2)
    trees = dper.insert(dper.init(CAP), jnp.arange(CAP), 0.6)
    fn = make_fused_chunk(config, k=1, batch_size=8, prioritized=True,
                          alpha=0.6, donate=False)
    state2, trees2, m = fn(state, trees, storage, CAP)
    assert int(state2.step) == int(state.step) + 1
    # with k=1 no resampling can overwrite: leaf at each sampled idx must
    # equal (|td| + eps) ** alpha (last write wins for duplicates)
    idx = np.asarray(m["idx"][0])
    td = np.asarray(m["td_error"][0])
    expect = (np.abs(td) + 1e-6) ** 0.6
    leaf = np.asarray(trees2.sum_tree[CAP + idx])
    for slot in np.unique(idx):
        cands = expect[idx == slot]
        assert np.any(np.isclose(leaf[idx == slot][0], cands, rtol=1e-4))


def test_fused_chunk_multi_step_advances_and_is_deterministic(rng):
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10, n_atoms=11,
                        hidden=(16, 16, 16))
    state = init_state(config, jax.random.key(0))
    storage = _fill_storage(rng, CAP, 4, 2)
    trees = dper.insert(dper.init(CAP), jnp.arange(CAP), 0.6)
    fn = make_fused_chunk(config, k=5, batch_size=8, donate=False)
    s1, t1, m1 = fn(state, trees, storage, CAP)
    s2, t2, m2 = fn(state, trees, storage, CAP)
    assert int(s1.step) == 5
    assert m1["critic_loss"].shape == (5,)
    np.testing.assert_array_equal(np.asarray(m1["idx"]), np.asarray(m2["idx"]))
    np.testing.assert_array_equal(np.asarray(t1.sum_tree),
                                  np.asarray(t2.sum_tree))
    assert np.isfinite(float(m1["critic_loss"][-1]))


def test_fused_chunk_mog_critic(rng):
    """The fused chunk composes with the mixture-of-Gaussians critic (the
    reference's empty stub, implemented for real): MoG TD errors feed the
    in-scan priority write-back like the categorical path."""
    config = D4PGConfig(obs_dim=4, act_dim=2, critic_family="mog",
                        n_components=3, hidden=(16, 16), mog_samples=8)
    state = init_state(config, jax.random.key(0))
    storage = _fill_storage(rng, CAP, 4, 2)
    trees = dper.insert(dper.init(CAP), jnp.arange(CAP), 0.6)
    fn = make_fused_chunk(config, k=2, batch_size=8, donate=False)
    s1, t1, m = fn(state, trees, storage, CAP)
    assert int(s1.step) == 2
    assert np.isfinite(np.asarray(m["critic_loss"])).all()
    assert not np.allclose(np.asarray(t1.sum_tree), np.asarray(trees.sum_tree))


def test_fused_chunk_uniform_variant(rng):
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10, n_atoms=11,
                        hidden=(16, 16, 16))
    state = init_state(config, jax.random.key(0))
    storage = _fill_storage(rng, CAP, 4, 2)
    fn = make_fused_chunk(config, k=3, batch_size=8, prioritized=False,
                          donate=False)
    state2, m = fn(state, storage, jnp.int32(CAP))
    assert int(state2.step) == 3
    idx = np.asarray(m["idx"])
    assert idx.min() >= 0 and idx.max() < CAP


def test_fused_buffer_drain_overflow_keeps_newest(rng):
    """Staging more rows than the ring holds must keep exactly the newest
    ``capacity`` (the block drain lands rows sequentially; older slots
    are overwritten in order, never scatter-raced)."""
    buf = FusedDeviceReplay(CAP, 1, 1, prioritized=False)
    rows = np.arange(100, dtype=np.float32)[:, None]
    for lo in (0, 40):
        n = 60 if lo == 40 else 40
        r = rows[lo:lo + n]
        buf.add(TransitionBatch(
            obs=r, action=np.zeros((n, 1), np.float32),
            reward=r[:, 0], next_obs=r,
            done=np.zeros(n, np.float32),
            discount=np.ones(n, np.float32)))
    assert buf.drain() == 100  # all staged rows land (block-sequential)
    assert buf.size == CAP and buf.head == 100 % CAP
    got = np.sort(np.asarray(buf.storage.reward[:CAP]))
    np.testing.assert_array_equal(got, np.arange(100 - CAP, 100))


def test_fused_buffer_staging_is_bounded(rng):
    """Ingest while the learner is paused must not grow without bound:
    the preallocated staging ring drops the OLDEST rows under backlog
    (the next drains would overwrite them anyway), and drain still lands
    the newest rows."""
    buf = FusedDeviceReplay(CAP, 1, 1, prioritized=False)
    for i in range(20):  # 20 batches x 10 rows >> capacity 64
        r = np.full((10, 1), float(i), np.float32)
        buf.add(TransitionBatch(
            obs=r, action=np.zeros((10, 1), np.float32), reward=r[:, 0],
            next_obs=r, done=np.zeros(10, np.float32),
            discount=np.ones(10, np.float32)))
    assert len(buf._staging) <= buf._staging.size  # preallocated bound
    assert buf._staging.size <= 2 * CAP  # stays O(capacity)
    buf.drain()
    assert buf.size == CAP
    # the newest batches survived
    assert float(np.asarray(buf.storage.reward[:CAP]).max()) == 19.0


def test_train_fused_uniform_async(tmp_path):
    """End-to-end train() through the fused path with uniform replay and
    async actors (decoupled loop + remainder chunks: 18 = 8 + 8 + 2)."""
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=18,
        eval_trials=1, batch_size=16, memory_size=2000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, replay_storage="device", fused_replay="on",
        prioritized_replay=False, async_actors=True,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])


def test_train_fused_her_goal_env(tmp_path):
    """HER relabels stream through the fused device buffer like ordinary
    rows (goal-conditioned obs, success-based dones)."""
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="fake-goal", her=True, max_steps=10, warmup=80, n_epochs=1,
        n_cycles=2, episodes_per_cycle=2, train_steps_per_cycle=8,
        eval_trials=1, batch_size=16, memory_size=2000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, replay_storage="device", fused_replay="on",
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
    assert "success_rate" in metrics


def test_fused_buffer_stage_drain(rng):
    buf = FusedDeviceReplay(CAP, 4, 2, alpha=0.6)
    batch = TransitionBatch(
        obs=rng.standard_normal((10, 4)).astype(np.float32),
        action=rng.uniform(-1, 1, (10, 2)).astype(np.float32),
        reward=rng.standard_normal(10).astype(np.float32),
        next_obs=rng.standard_normal((10, 4)).astype(np.float32),
        done=np.zeros(10, np.float32),
        discount=np.full(10, 0.99, np.float32),
    )
    buf.add(batch)
    assert len(buf) == 10 and buf.size == 0  # staged counts toward warmup
    n = buf.drain()
    assert n == 10 and buf.size == 10 and len(buf) == 10
    # tree mass: 10 live slots at max_priority**alpha == 1 (pad writes are
    # duplicates of slot 0, not extra mass)
    assert np.isclose(float(buf.trees.sum_tree[1]), 10.0)
    got = np.asarray(buf.storage.obs[:10])
    np.testing.assert_allclose(got, batch.obs, rtol=1e-6)
    # ring wrap: 60 more rows wrap over capacity 64
    big = TransitionBatch(*[np.repeat(np.asarray(v), 6, axis=0)
                            for v in batch])
    buf.add(big)
    buf.drain()
    assert buf.size == CAP and buf.head == (10 + 60) % CAP
