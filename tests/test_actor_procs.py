"""Spawned local actor processes (train.py --actor_procs): real
process-level parallelism over the TCP plane, replacing the reference's
mp.Process fan-out (main.py:399-405)."""

import os

import numpy as np

import pytest

pytestmark = pytest.mark.slow


def test_train_with_spawned_actor_processes(tmp_path):
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=8,
        eval_trials=1, batch_size=16, memory_size=5000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, n_workers=0, actor_procs=1,
        async_actors=True,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
    # all data arrived from the spawned process over TCP
    assert metrics["env_steps"] >= 100


def test_spawned_actor_process_respawned_on_death(tmp_path, capfd):
    """VERDICT r2 #7: a dead --actor_procs child must be respawned by the
    supervisor (same identity/config — actors are stateless), and actor
    liveness must reach the metrics bus as ``dead_actors``."""
    import glob
    import multiprocessing as mp
    import threading
    import time

    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=10, episodes_per_cycle=1, train_steps_per_cycle=8,
        updates_per_dispatch=4, eval_trials=1, batch_size=16,
        memory_size=5000, log_dir=str(tmp_path), hidden=(16, 16),
        n_atoms=11, v_min=-5.0, v_max=0.0, n_workers=0, actor_procs=1,
        async_actors=True,
    )
    result: dict = {}
    t = threading.Thread(target=lambda: result.update(train(cfg)), daemon=True)
    t.start()
    # past warmup and into the cycle loop: the csv sink has logged a row
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        csvs = glob.glob(str(tmp_path / "exp_*" / "returns.csv"))
        if csvs and os.path.getsize(csvs[0]) > 0 and mp.active_children():
            break
        time.sleep(0.2)
    kids = mp.active_children()
    assert kids, "spawned actor process not found"
    kids[0].terminate()  # kill the actor mid-run
    t.join(timeout=600)
    assert not t.is_alive()
    out = capfd.readouterr().out
    assert "supervisor: restarting actor process 0" in out
    assert "dead_actors" in result
    assert np.isfinite(result["critic_loss"])
