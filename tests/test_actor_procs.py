"""Spawned local actor processes (train.py --actor_procs): real
process-level parallelism over the TCP plane, replacing the reference's
mp.Process fan-out (main.py:399-405)."""

import numpy as np


def test_train_with_spawned_actor_processes(tmp_path):
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=8,
        eval_trials=1, batch_size=16, memory_size=5000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, n_workers=0, actor_procs=1,
        async_actors=True,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
    # all data arrived from the spawned process over TCP
    assert metrics["env_steps"] >= 100
