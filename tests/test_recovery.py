"""Crash-recovery plane: durable snapshots, generation fencing, chaos.

The contracts pinned here, one by one:
  - ``ReplayService.snapshot()/restore()`` round-trips the host buffer
    BITWISE into a fresh service (columns, PER state, write head, seq
    floor) and bumps the generation past the snapshot's;
  - the generation fence: a raw frame stamped with a pre-restart
    generation is accepted-but-fenced (declared loss, never a duplicate),
    while current-generation and non-opted-in legacy frames commit;
  - the checkpoint sidecar refuses torn/corrupt bytes loudly
    (``SnapshotCorruptError``), loads legacy bare pickles, and the
    train-level loader degrades to learner-only instead of poisoning the
    buffer;
  - the learner-kill chaos harness survives seeded mid-run service kills
    with zero deadlocks/hierarchy violations and reports MTTR + fence
    accounting + the reconnect-storm spread;
  - the deterministic recovery probe's post-restore oracle is bitwise;
  - flight-dump retention is bounded, collision-free, and never touches
    the fleet artifacts beside it;
  - the newest committed fleet artifact carries the recovery block
    (the schema gate — a later PR that drops it fails tier-1 here).
"""

import dataclasses
import glob
import json
import os
import pickle

import numpy as np
import pytest

import d4pg_tpu
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.transport import (
    TransitionReceiver,
    TransitionSender,
)
from d4pg_tpu.io.checkpoint import (
    SnapshotCorruptError,
    load_replay_sidecar,
    replay_sidecar_path,
    save_replay_sidecar,
)
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch

PACKAGE_DIR = os.path.dirname(os.path.abspath(d4pg_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)


def _batch(n=8, obs_dim=6, act_dim=2, seed=0):
    rng = np.random.default_rng(seed)
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.standard_normal((n, act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )


def _wait_for(pred, timeout=5.0):
    """send() returns once bytes hit the socket; admission happens on
    the receiver's connection thread — poll the service-side effect."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def _bitwise(x, y) -> bool:
    if isinstance(x, dict):
        return (isinstance(y, dict) and x.keys() == y.keys()
                and all(_bitwise(x[k], y[k]) for k in x))
    if isinstance(x, (list, tuple)):
        return (isinstance(y, (list, tuple)) and len(x) == len(y)
                and all(_bitwise(a, b) for a, b in zip(x, y)))
    xa, ya = np.asarray(x), np.asarray(y)
    return xa.dtype == ya.dtype and bool(np.array_equal(xa, ya))


# ------------------------------------------------ snapshot / restore ----

@pytest.mark.recovery
def test_snapshot_restore_roundtrip_bitwise():
    """A snapshot restored into a FRESH service reproduces the buffer
    bitwise and carries the cut's env-step/commit accounting."""
    a = ReplayService(ReplayBuffer(1024, 6, 2))
    try:
        for i in range(5):
            a.add(_batch(seed=i), actor_id="rt")
        a.flush()
        snap = a.snapshot()
        a_state = a.replay_state()
        a_steps = a.env_steps
    finally:
        a.close()
    assert snap["env_steps"] == a_steps and a_steps == 40

    b = ReplayService(ReplayBuffer(1024, 6, 2))
    try:
        b.restore(snap)
        assert _bitwise(b.replay_state(), a_state)
        assert b.env_steps == a_steps
        # the restored incarnation serves a LATER generation than the cut
        assert b.generation > int(snap["generation"])
        # and keeps committing cleanly past the restored floor
        b.add(_batch(seed=99), actor_id="rt")
        b.flush()
        assert b.env_steps == a_steps + 8
    finally:
        b.close()


@pytest.mark.recovery
def test_restore_rejects_snapshot_without_buffer():
    svc = ReplayService(ReplayBuffer(256, 6, 2))
    try:
        with pytest.raises(ValueError):
            svc.restore({"schema": 1, "env_steps": 0})
    finally:
        svc.close()


@pytest.mark.recovery
def test_restore_never_rewinds_generation():
    """A STALE snapshot (older generation than the constructor floor)
    must not rewind the serving id — rewinding would un-fence a prior
    incarnation's retried frames into silent duplicates."""
    a = ReplayService(ReplayBuffer(256, 6, 2))
    try:
        a.add(_batch(seed=1), actor_id="g")
        a.flush()
        snap = a.snapshot()  # generation 0
    finally:
        a.close()
    b = ReplayService(ReplayBuffer(256, 6, 2), generation=7)
    try:
        b.restore(snap)
        assert b.generation == 7  # max(floor, snap+1), not snap+1 == 1
    finally:
        b.close()


# ------------------------------------------------- generation fence ----

@pytest.mark.recovery
def test_generation_fence_end_to_end_tcp():
    """A sender greeted with a PRE-restart generation has its raw frames
    fenced by a later-generation service: send() succeeds (declared
    loss, not an error), zero rows commit, and the fence ledger counts
    frame + rows."""
    svc = ReplayService(ReplayBuffer(1024, 6, 2), generation=1)
    recv = TransitionReceiver(
        lambda b, aid, c: None, host="127.0.0.1",
        on_payload=lambda p, shard, codec: svc.add_payload(p, shard, codec),
        generation=0)  # the dead incarnation's greeting
    sender = TransitionSender("127.0.0.1", recv.port, actor_id="stale",
                              codec="raw", expect_generation=True,
                              retry_timeout=5.0)
    try:
        assert sender.send(_batch(seed=3)) is True
        assert sender.generation == 0  # learned from the greeting
        assert _wait_for(
            lambda: svc.ingest_stats()["fenced_frames"] == 1)
        svc.flush()
        stats = svc.ingest_stats()
        assert stats["fenced_frames"] == 1
        assert stats["fenced_rows"] == 8
        assert svc.env_steps == 0  # nothing committed — and no duplicate
    finally:
        sender.close()
        recv.close()
        svc.close()


@pytest.mark.recovery
def test_current_generation_frames_commit():
    """The same opt-in wiring at the CURRENT generation commits rows
    normally — the fence only bites pre-restart stamps."""
    svc = ReplayService(ReplayBuffer(1024, 6, 2), generation=2)
    recv = TransitionReceiver(
        lambda b, aid, c: None, host="127.0.0.1",
        on_payload=lambda p, shard, codec: svc.add_payload(p, shard, codec),
        generation=(lambda: svc.generation))
    sender = TransitionSender("127.0.0.1", recv.port, actor_id="live",
                              codec="raw", expect_generation=True,
                              retry_timeout=5.0)
    try:
        assert sender.send(_batch(seed=4)) is True
        assert sender.generation == 2
        assert _wait_for(lambda: svc.env_steps == 8)
        stats = svc.ingest_stats()
        assert stats["fenced_frames"] == 0
    finally:
        sender.close()
        recv.close()
        svc.close()


@pytest.mark.recovery
def test_legacy_sender_unaffected_by_greeting():
    """A sender that does NOT opt in ignores the greeting bytes and its
    unstamped frames are never fenced — the wire upgrade is additive."""
    svc = ReplayService(ReplayBuffer(1024, 6, 2), generation=5)
    recv = TransitionReceiver(
        lambda b, aid, c: None, host="127.0.0.1",
        on_payload=lambda p, shard, codec: svc.add_payload(p, shard, codec),
        generation=(lambda: svc.generation))
    sender = TransitionSender("127.0.0.1", recv.port, actor_id="legacy",
                              codec="raw", retry_timeout=5.0)
    try:
        assert sender.send(_batch(seed=5)) is True
        assert _wait_for(lambda: svc.env_steps == 8)
        assert svc.ingest_stats()["fenced_frames"] == 0
    finally:
        sender.close()
        recv.close()
        svc.close()


# ------------------------------------------------ checkpoint sidecar ----

def _snap_fixture():
    return {"schema": 1, "env_steps": 17,
            "buffer": {"obs": np.arange(12, dtype=np.float32)}}


@pytest.mark.recovery
def test_sidecar_roundtrip(tmp_path):
    run_dir = str(tmp_path)
    save_replay_sidecar(run_dir, 0, 42, _snap_fixture())
    loaded = load_replay_sidecar(run_dir, 0)
    assert loaded is not None
    snap, step = loaded
    assert step == 42
    assert _bitwise(snap, _snap_fixture())


@pytest.mark.recovery
def test_sidecar_missing_returns_none(tmp_path):
    assert load_replay_sidecar(str(tmp_path), 3) is None


@pytest.mark.recovery
def test_sidecar_corrupt_rejected(tmp_path):
    """A flipped payload byte, a truncated header, and an unknown
    version are all refused with SnapshotCorruptError — never fed to
    load_state_dict."""
    run_dir = str(tmp_path)
    path = save_replay_sidecar(run_dir, 0, 7, _snap_fixture())
    blob = bytearray(open(path, "rb").read())

    torn = bytearray(blob)
    torn[-1] ^= 0xFF  # bit rot in the pickle body
    open(path, "wb").write(bytes(torn))
    with pytest.raises(SnapshotCorruptError):
        load_replay_sidecar(run_dir, 0)

    open(path, "wb").write(bytes(blob[:6]))  # torn mid-header
    with pytest.raises(SnapshotCorruptError):
        load_replay_sidecar(run_dir, 0)

    versioned = bytearray(blob)
    versioned[4] = 250  # unknown format version
    open(path, "wb").write(bytes(versioned))
    with pytest.raises(SnapshotCorruptError):
        load_replay_sidecar(run_dir, 0)


@pytest.mark.recovery
def test_sidecar_legacy_bare_pickle_loads(tmp_path):
    """Pre-CRC sidecars (bare pickle, no magic frame) still load — the
    integrity frame is additive, not a format break."""
    run_dir = str(tmp_path)
    with open(replay_sidecar_path(run_dir, 0), "wb") as f:
        pickle.dump({"step": 9, "snap": _snap_fixture()}, f)
    loaded = load_replay_sidecar(run_dir, 0)
    assert loaded is not None
    snap, step = loaded
    assert step == 9 and _bitwise(snap, _snap_fixture())


@pytest.mark.recovery
def test_train_loader_degrades_to_learner_only(tmp_path, capsys):
    """The train-level loader turns a corrupt sidecar into a LOUD
    learner-only resume: (None, -1) plus the refusal diagnostic."""
    from d4pg_tpu.train import _load_host_replay

    run_dir = str(tmp_path)
    path = save_replay_sidecar(run_dir, 0, 7, _snap_fixture())
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    snap, step = _load_host_replay(run_dir, 0, 7)
    assert snap is None and step == -1
    out = capsys.readouterr().out
    assert "corrupt" in out and "learner-only" in out

    # a sidecar AHEAD of the restored state is refused the same way
    save_replay_sidecar(run_dir, 0, 100, _snap_fixture())
    snap, step = _load_host_replay(run_dir, 0, 7)
    assert snap is None and step == -1
    assert "AHEAD" in capsys.readouterr().out

    # a slightly-STALE sidecar is accepted with a warning
    save_replay_sidecar(run_dir, 0, 5, _snap_fixture())
    snap, step = _load_host_replay(run_dir, 0, 7)
    assert snap is not None and step == 5
    assert "behind the restored state" in capsys.readouterr().out


# ------------------------------------------------- learner-kill chaos ----

@pytest.mark.recovery
@pytest.mark.fleet
def test_service_chaos_smoke():
    """A small seeded fleet survives mid-run service kills: the
    supervisor restores from the latest snapshot, actors re-handshake,
    and the run ends with zero deadlocks/hierarchy violations and a
    populated recovery block (MTTR, fence ledger, storm spread)."""
    from d4pg_tpu.fleet import FleetConfig, FleetHarness
    from d4pg_tpu.fleet.sweep import default_service_chaos

    cfg = FleetConfig(
        n_actors=6, duration_s=6.0, rows_per_sec=60.0, block_rows=16,
        obs_dim=12, act_dim=3, capacity=40_000, ingest_shards=2,
        codec="raw", send_timeout=0.5,
        chaos=default_service_chaos(seed=11, duration_s=6.0),
    )
    result = FleetHarness(cfg).run()
    sc = result["service_chaos"]
    assert sc is not None
    assert sc["kills"] >= 1
    assert sc["restarts"] >= 1
    assert sc["failed_restarts"] == 0
    assert sc["final_generation"] == sc["kills"]
    assert sc["snapshots"] >= 1
    assert sc["mttr_s"]["n"] == sc["restarts"]
    assert sc["mttr_s"]["max_s"] < 30.0
    assert result["deadlocks"] == 0
    assert result["locks"]["hierarchy_violations"] == 0
    # the reconnect-storm guard actually spread the re-handshake wave
    storm = sc["reconnect_storm"]
    assert storm["jitters"] >= 1
    assert storm["distinct"] >= 1
    # rows still flowed after the restarts
    assert result["rows_inserted"] > 0


@pytest.mark.recovery
def test_service_chaos_requires_raw_codec_and_threads():
    """npz frames carry no generation stamp — service chaos over npz
    would re-admit pre-crash retries as silent duplicates, so the
    config refuses the combination outright."""
    from d4pg_tpu.fleet import FleetConfig
    from d4pg_tpu.fleet.sweep import default_service_chaos

    chaos = default_service_chaos(seed=0, duration_s=5.0)
    with pytest.raises(ValueError, match="raw"):
        FleetConfig(n_actors=2, codec="npz", chaos=chaos)
    with pytest.raises(ValueError):
        FleetConfig(n_actors=2, codec="raw", mode="process", chaos=chaos)


@pytest.mark.recovery
def test_kill_schedule_seeded_and_bounded():
    from d4pg_tpu.fleet import ChaosPolicy
    from d4pg_tpu.fleet.sweep import default_service_chaos

    chaos = default_service_chaos(seed=5, duration_s=10.0)
    a = ChaosPolicy(chaos).service_kill_schedule(10.0)
    b = ChaosPolicy(chaos).service_kill_schedule(10.0)
    assert a == b and len(a) == chaos.service_kill_count
    assert all(0.1 <= t < 10.0 for t in a)
    other = dataclasses.replace(chaos, seed=6)
    assert ChaosPolicy(other).service_kill_schedule(10.0) != a


@pytest.mark.recovery
def test_recovery_probe_oracle_bitwise():
    """Kill-and-restore equals an uninterrupted run, modulo the declared
    losses — the acceptance oracle, at probe scale."""
    from d4pg_tpu.fleet.sweep import recovery_probe

    out = recovery_probe(seed=1, blocks=12, block_rows=8, obs_dim=6,
                         act_dim=2, cut=6, lost=2)
    assert out["oracle_bitwise_equal"] is True
    assert out["rows_lost_declared"] == 2 * 8
    assert out["rows_compared"] == (12 - 2) * 8


# ---------------------------------------------------- dump retention ----

@pytest.mark.recovery
def test_flight_dump_retention_and_collision_free(tmp_path):
    """Repeated dumps keep only the newest N flight files, with
    collision-free names, and never touch the fleet artifacts beside
    them."""
    from d4pg_tpu.obs.flight import FlightRecorder

    fleet_art = tmp_path / "fleet_20990101-000000_0000001.json"
    fleet_art.write_text("{}")
    rec = FlightRecorder(maxlen=16, keep_dumps=3)
    rec.record("kill", generation=1)
    paths = [rec.dump(str(tmp_path), "service_kill") for _ in range(7)]
    assert len(set(paths)) == 7  # same-second dumps never collide
    left = sorted(os.path.basename(p) for p in glob.glob(
        str(tmp_path / "flight_*.json")))
    assert len(left) == 3
    # the newest three survived (stamp+seq names sort chronologically)
    assert left == sorted(os.path.basename(p) for p in paths)[-3:]
    assert fleet_art.exists()


@pytest.mark.recovery
def test_prune_artifacts_disabled_and_missing_dir(tmp_path):
    from d4pg_tpu.obs.flight import prune_artifacts

    (tmp_path / "flight_a.json").write_text("{}")
    assert prune_artifacts(str(tmp_path), "flight_", 0) == []
    assert (tmp_path / "flight_a.json").exists()
    assert prune_artifacts(str(tmp_path / "nope"), "flight_", 5) == []


# ------------------------------------------------ lock-plane audit ----

@pytest.mark.recovery
@pytest.mark.lint
def test_snapshot_paths_keep_lock_graph_clean():
    """The snapshot/restore plane must not have added lock-graph edges:
    the whole-program graph stays cycle-free, and no held-while-acquiring
    edge is witnessed inside a snapshot/restore/kill function (their
    acquisitions are strictly sequential by design)."""
    from d4pg_tpu.lint.engine import build_lock_graph

    graph, errors = build_lock_graph([PACKAGE_DIR])
    assert not errors
    assert graph.cycles == []
    offenders = [w for ws in graph.edges.values() for w in ws
                 if any(f"({name})" in w for name in
                        ("snapshot", "restore", "kill"))]
    assert offenders == [], offenders


# ------------------------------------------------- artifact schema ----

@pytest.mark.recovery
@pytest.mark.obs
def test_fleet_artifact_recovery_schema():
    """The newest committed fleet artifact must carry the recovery
    block: the acceptance run's kills/restarts, MTTR, fence ledger,
    reconnect-storm spread, and a TRUE bitwise oracle — a later PR that
    drops any of it fails tier-1 here instead of silently shipping an
    artifact with no recovery story."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "fleet", "fleet_*.json")))
    assert arts, "no committed fleet artifact"
    with open(arts[-1]) as f:  # stamp-named: lexical order = newest last
        artifact = json.load(f)
    rec = artifact.get("recovery")
    assert rec, "newest fleet artifact lost its recovery block"
    assert rec["metric"] == "fleet_recovery" and rec["schema"] == 1
    assert rec["kills"] >= 2  # the acceptance bar: >= 2 mid-run kills
    assert rec["restarts"] >= 1
    assert rec["failed_restarts"] == 0
    assert rec["deadlocks"] == 0
    assert rec["hierarchy_violations"] == 0
    assert rec["mttr_s"]["n"] >= 1 and rec["mttr_s"]["max_s"] is not None
    assert rec["final_generation"] >= 1
    assert rec["rows_fenced"] >= 0 and rec["frames_fenced"] >= 0
    storm = rec["reconnect_storm"]
    assert {"jitters", "distinct", "spread_ms"} <= set(storm)
    oracle = rec["oracle"]
    assert oracle["oracle_bitwise_equal"] is True
    assert oracle["rows_lost_declared"] >= 0
