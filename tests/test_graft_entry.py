"""Driver entry-point hardening (VERDICT r4 #1): ``dryrun_multichip`` is a
virtual-mesh correctness check and must NEVER initialize a non-CPU backend —
the chip can be wedged (hangs init) or libtpu-mismatched (raises at first
dispatch AFTER ``jax.devices()`` succeeds, the MULTICHIP_r04 regression).

Run in a subprocess: backend selection is process-global state, and the
point is to exercise the real driver code path with NO prior CPU pinning
(no conftest config.update active in the child).
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The child deliberately does NOT set JAX_PLATFORMS / pin CPU beforehand:
# dryrun_multichip itself must do the forcing. Afterwards, the set of
# *initialized* backends (xla_bridge's process-global registry) must be
# exactly {cpu} — i.e. the accelerator plugin was never touched, even
# though it stays visible to the process.
_CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count={n}"
os.environ.pop("JAX_PLATFORMS", None)
import __graft_entry__
__graft_entry__.dryrun_multichip({n})
from jax._src import xla_bridge
initialized = set(xla_bridge._backends)
assert initialized == {{"cpu"}}, f"non-CPU backend initialized: {{initialized}}"
print("BACKENDS-OK", sorted(initialized))
"""


@pytest.mark.slow
def test_dryrun_multichip_never_initializes_accelerator():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO
    r = subprocess.run(
        [sys.executable, "-c", _CHILD.format(n=4)],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "BACKENDS-OK ['cpu']" in r.stdout
    assert "dryrun_multichip OK" in r.stdout
    assert "dryrun multihost fused OK" in r.stdout
