"""Tier-1 lint gate: the whole d4pg_tpu package must lint clean.

Every hazard jaxlint can see in this codebase is either fixed or carries
an inline ``# jaxlint: disable=<rule>`` suppression whose comment explains
why the pattern is deliberate. A new finding here means a PR introduced a
throughput/correctness hazard (or a rule regression) — fix the code or
justify a suppression, don't weaken the gate.

Marked ``lint`` so the whole-repo AST pass can be deselected with
``-m "not lint"`` when iterating on unrelated tests.
"""

import os
import subprocess
import sys

import pytest

import d4pg_tpu
from d4pg_tpu.lint import lint_paths

PACKAGE_DIR = os.path.dirname(os.path.abspath(d4pg_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)


@pytest.mark.lint
def test_package_lints_clean():
    result = lint_paths([PACKAGE_DIR])
    msgs = [f.format() for f in result.findings] + result.errors
    assert result.clean, (
        "jaxlint found unsuppressed hazards:\n" + "\n".join(msgs))


@pytest.mark.lint
def test_bench_and_entrypoints_lint_clean():
    """The scripts feeding the headline numbers are held to the same bar."""
    files = [os.path.join(REPO_ROOT, n) for n in ("bench.py",)]
    result = lint_paths([f for f in files if os.path.exists(f)])
    msgs = [f.format() for f in result.findings] + result.errors
    assert result.clean, (
        "jaxlint found unsuppressed hazards:\n" + "\n".join(msgs))


@pytest.mark.lint
def test_suppression_audit():
    """Audit every ``# jaxlint: disable`` in the package + bench.py: each
    must name only REGISTERED rules (a typo'd rule id suppresses nothing
    and rots silently) and carry a justification comment on the flagged
    line's neighborhood (the documented suppression contract — see
    docs/architecture.md "Suppressions"). New packages (e.g. fleet/) ride
    the same audit automatically."""
    import re

    from d4pg_tpu.lint.rules import RULES

    directive = re.compile(r"#\s*jaxlint:\s*disable(?:-file)?=([\w,\- ]+)")
    audited = 0
    problems = []
    files = [os.path.join(REPO_ROOT, "bench.py")]
    for dirpath, _dirs, names in os.walk(PACKAGE_DIR):
        files.extend(os.path.join(dirpath, n) for n in names
                     if n.endswith(".py"))
    for path in files:
        with open(path) as f:
            lines = f.readlines()
        for i, line in enumerate(lines):
            m = directive.search(line)
            # the lint package's own docs/fixtures mention the directive
            # in strings — only audit real trailing-comment suppressions
            if m is None or os.sep + "lint" + os.sep in path:
                continue
            audited += 1
            where = f"{os.path.relpath(path, REPO_ROOT)}:{i + 1}"
            for rule in m.group(1).replace(" ", "").split(","):
                if rule not in RULES:
                    problems.append(f"{where}: unknown rule {rule!r}")
            lo, hi = max(0, i - 3), min(len(lines), i + 2)
            neighborhood = "".join(lines[lo:hi])
            # justification = at least one comment line near the
            # suppression that is NOT itself a directive
            has_comment = any(
                "#" in nl and not directive.search(nl)
                for nl in lines[lo:hi]) or '"""' in neighborhood
            if not has_comment:
                problems.append(f"{where}: suppression without an adjacent "
                                "justification comment")
    assert audited > 0, "audit found no suppressions — regex rot?"
    assert not problems, "\n".join(problems)


@pytest.mark.lint
def test_cli_module_entrypoint():
    """`python -m d4pg_tpu.lint <package>` is the documented interface; it
    must agree with the library API and exit 0 on the repo."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
