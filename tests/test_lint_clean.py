"""Tier-1 lint gate: the whole d4pg_tpu package must lint clean.

Every hazard jaxlint can see in this codebase is either fixed or carries
an inline ``# jaxlint: disable=<rule>`` suppression whose comment explains
why the pattern is deliberate. A new finding here means a PR introduced a
throughput/correctness hazard (or a rule regression) — fix the code or
justify a suppression, don't weaken the gate.

Marked ``lint`` so the whole-repo AST pass can be deselected with
``-m "not lint"`` when iterating on unrelated tests.
"""

import os
import subprocess
import sys

import pytest

import d4pg_tpu
from d4pg_tpu.lint import lint_paths

PACKAGE_DIR = os.path.dirname(os.path.abspath(d4pg_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)


@pytest.mark.lint
def test_package_lints_clean():
    result = lint_paths([PACKAGE_DIR])
    msgs = [f.format() for f in result.findings] + result.errors
    assert result.clean, (
        "jaxlint found unsuppressed hazards:\n" + "\n".join(msgs))


@pytest.mark.lint
def test_bench_and_entrypoints_lint_clean():
    """The scripts feeding the headline numbers are held to the same bar."""
    files = [os.path.join(REPO_ROOT, n) for n in ("bench.py",)]
    result = lint_paths([f for f in files if os.path.exists(f)])
    msgs = [f.format() for f in result.findings] + result.errors
    assert result.clean, (
        "jaxlint found unsuppressed hazards:\n" + "\n".join(msgs))


@pytest.mark.lint
def test_suppression_audit():
    """Audit every ``# jaxlint: disable`` AND ``# jaxlint: guarded-by``
    in the package + bench.py: a disable must name only REGISTERED rules
    (a typo'd rule id suppresses nothing and rots silently), a
    guarded-by must name a lock the whole-program lock graph actually
    knows (a typo'd lock name vouches for nothing), a ``contained-by``
    must name a handler the exception-flow graph resolved AND verified
    contained-and-counted (status ``ok`` — a typo'd or weak handler
    vouches for nothing), an ``axis-bound-by`` must name a binder the
    sharding graph resolved AND verified bound under a shard_map axis
    (status ``ok`` — same bar), a ``stream-owner`` must name a stream
    the rng graph discovered AND verified seeded or SeedSequence-
    branched (status ``ok`` — same bar), and all must carry a
    justification comment on the flagged line's neighborhood (the
    documented contract — see docs/architecture.md "Suppressions").
    New packages (e.g. fleet/) ride the same audit automatically."""
    import re

    from d4pg_tpu.lint.engine import (
        build_fail_graph, build_lock_graph, build_mesh_graph,
        build_rng_graph,
    )
    from d4pg_tpu.lint.lockgraph import _DEFAULT_TIERS
    from d4pg_tpu.lint.rules import RULES

    directive = re.compile(r"#\s*jaxlint:\s*disable(?:-file)?=([\w,\- ]+)")
    guarded = re.compile(r"#\s*jaxlint:\s*guarded-by=([\w,\- ]+)")
    contained = re.compile(r"#\s*jaxlint:\s*contained-by=([\w\.\-,]+)")
    bound = re.compile(r"#\s*jaxlint:\s*axis-bound-by=([\w\.\-,]+)")
    stream_owner = re.compile(r"#\s*jaxlint:\s*stream-owner=([\w\.\-,]+)")
    graph, _errors = build_lock_graph([PACKAGE_DIR])
    known_locks = set(graph.nodes) | set(_DEFAULT_TIERS)
    fail_graph, _errors = build_fail_graph([PACKAGE_DIR])
    mesh_graph, _errors = build_mesh_graph([PACKAGE_DIR])
    rng_graph, _errors = build_rng_graph(
        [PACKAGE_DIR, os.path.join(REPO_ROOT, "bench.py")])
    audited = 0
    problems = []
    files = [os.path.join(REPO_ROOT, "bench.py")]
    for dirpath, _dirs, names in os.walk(PACKAGE_DIR):
        files.extend(os.path.join(dirpath, n) for n in names
                     if n.endswith(".py"))
    for path in files:
        with open(path) as f:
            lines = f.readlines()
        for i, line in enumerate(lines):
            m = directive.search(line)
            g = guarded.search(line)
            c = contained.search(line)
            b = bound.search(line)
            s = stream_owner.search(line)
            # the lint package's own docs/fixtures mention the directives
            # in strings — only audit real trailing-comment annotations
            if (m is None and g is None and c is None and b is None
                    and s is None) \
                    or os.sep + "lint" + os.sep in path:
                continue
            audited += 1
            where = f"{os.path.relpath(path, REPO_ROOT)}:{i + 1}"
            if m is not None:
                for rule in m.group(1).replace(" ", "").split(","):
                    if rule not in RULES:
                        problems.append(f"{where}: unknown rule {rule!r}")
            if g is not None:
                for lock in g.group(1).replace(" ", "").split(","):
                    if lock not in known_locks:
                        problems.append(
                            f"{where}: guarded-by names unknown lock "
                            f"{lock!r} (not in the discovered lock graph)")
            if c is not None:
                for spec in c.group(1).split(","):
                    if fail_graph.handlers.get(spec) != "ok":
                        problems.append(
                            f"{where}: contained-by names handler {spec!r} "
                            f"with audit status "
                            f"{fail_graph.handlers.get(spec)!r} (must "
                            f"resolve to a contained-and-counted frame)")
            if b is not None:
                for spec in b.group(1).split(","):
                    if mesh_graph.handlers.get(spec) != "ok":
                        problems.append(
                            f"{where}: axis-bound-by names binder {spec!r} "
                            f"with audit status "
                            f"{mesh_graph.handlers.get(spec)!r} (must "
                            f"resolve to a shard_map-bound frame)")
            if s is not None:
                for spec in s.group(1).split(","):
                    if rng_graph.handlers.get(spec) != "ok":
                        problems.append(
                            f"{where}: stream-owner names stream {spec!r} "
                            f"with audit status "
                            f"{rng_graph.handlers.get(spec)!r} (must "
                            f"resolve to a discovered seeded/branched "
                            f"component stream)")
            lo, hi = max(0, i - 6), min(len(lines), i + 2)
            neighborhood = "".join(lines[lo:hi])
            # justification = at least one comment line near the
            # annotation that is NOT itself a directive
            has_comment = any(
                "#" in nl and not directive.search(nl)
                and not guarded.search(nl) and not contained.search(nl)
                and not bound.search(nl) and not stream_owner.search(nl)
                for nl in lines[lo:hi]) or '"""' in neighborhood
            if not has_comment:
                problems.append(f"{where}: annotation without an adjacent "
                                "justification comment")
    assert audited > 0, "audit found no suppressions — regex rot?"
    assert not problems, "\n".join(problems)


@pytest.mark.lint
def test_lock_graph_clean_over_package():
    """Tier-1 gate for the concurrency plane: the whole-program lock
    graph over ``d4pg_tpu/`` must contain the declared ingest-plane
    locks, carry NO cycles, and only hierarchy-descending tiered edges
    (``test_package_lints_clean`` already fails on ``lock-cycle``/
    ``unguarded-shared-write`` findings; this pins the graph shape the
    ``--locks`` review artifact prints)."""
    from d4pg_tpu.core.locking import HIERARCHY
    from d4pg_tpu.lint.engine import build_lock_graph
    from d4pg_tpu.lint.lockgraph import _DEFAULT_TIERS, format_graph

    graph, errors = build_lock_graph([PACKAGE_DIR])
    assert not errors, errors
    assert graph.cycles == [], format_graph(graph)
    # the ingest plane's locks are all discovered, with their tier
    # labels, and so are the weight plane's three
    for lock, tier in (("_lock", "service"), ("_buffer_lock", "buffer"),
                       ("_commit_cond", "commit"), ("cond", "shard"),
                       ("_ring_locks", "ring"), ("_relay_lock", "wrelay"),
                       ("_frame_lock", "wserve"), ("_store_lock", "wstore"),
                       ("_replica_lock", "replica"), ("_agg_cond", "agg"),
                       ("_pserve_cond", "pserve")):
        assert lock in graph.nodes, sorted(graph.nodes)
        assert graph.nodes[lock] == tier
    # every edge between tier-labeled locks DESCENDS the hierarchy
    tiers = dict(_DEFAULT_TIERS)
    tiers.update({k: v for k, v in graph.nodes.items() if v})
    for (held, acquired) in graph.edges:
        th = HIERARCHY.get(tiers.get(held, ""))
        tb = HIERARCHY.get(tiers.get(acquired, ""))
        if th is not None and tb is not None and held != acquired:
            # name-identity merges unrelated same-named locks (e.g. the
            # sender-side transport._lock with the service lock), so
            # only leaf-held ascents are hard failures — mirroring the
            # lock-cycle rule's leaf-ascent check
            assert not (th <= HIERARCHY["shard"] and tb >= th), (
                f"leaf ascent {held} -> {acquired}: "
                + str(graph.edges[(held, acquired)]))


@pytest.mark.lint
def test_cli_locks_mode_clean():
    """``python -m d4pg_tpu.lint --locks`` is the review artifact for
    concurrency PRs; it must exit 0 (no cycles) on the repo and print
    the graph."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", "--locks", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "cycles: none" in proc.stdout
    assert "_commit_cond" in proc.stdout


@pytest.mark.lint
def test_cli_module_entrypoint():
    """`python -m d4pg_tpu.lint <package>` is the documented interface; it
    must agree with the library API and exit 0 on the repo."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.lint
def test_wire_graph_clean_over_package():
    """Tier-1 gate for the protocol surface: the whole-program wire graph
    over ``d4pg_tpu/`` must discover every declared magic with at least
    one pack AND one unpack witness, reproduce the declared flag-bit
    map, and carry zero findings."""
    from d4pg_tpu.lint.engine import build_wire_graph
    from d4pg_tpu.lint.wiregraph import format_registry

    graph, errors = build_wire_graph([PACKAGE_DIR])
    assert not errors, errors
    assert graph.findings == [], format_registry(graph)
    from d4pg_tpu.core import wire

    declared_magics = {spec.magic for spec in wire.REGISTRY.values()}
    assert set(graph.magics) == declared_magics, format_registry(graph)
    for magic, e in graph.magics.items():
        assert e["packs"], f"{magic!r}: no pack witness discovered"
        assert e["unpacks"], f"{magic!r}: no unpack witness discovered"
        assert e["plane"] is not None
    # the discovered flag map IS the declared per-plane allocation
    for plane, bits in wire.PLANE_FLAG_BITS.items():
        if bits:
            assert graph.flags.get(plane) == dict(bits), (plane, graph.flags)
        else:
            assert not graph.flags.get(plane), (plane, graph.flags)


@pytest.mark.lint
def test_wire_mirror_matches_declared_registry():
    """The lint package is stdlib-only, so ``wiregraph._DECLARED``
    mirrors ``core.wire.REGISTRY`` instead of importing it. This pin is
    what makes the mirror safe: any drift — a row added, a format
    changed, a flag reallocated, a crc discipline flipped — fails here
    with the exact rows named."""
    from d4pg_tpu.core import wire
    from d4pg_tpu.lint.wiregraph import _DECLARED

    declared = {
        name: (spec.plane, spec.magic, spec.header, spec.crc,
               tuple(sorted(spec.flags)),
               tuple(fmt for _ext_name, fmt in spec.extensions))
        for name, spec in wire.REGISTRY.items()}
    mirrored = {
        row[0]: (row[1], row[2], row[3], row[4],
                 tuple(sorted(row[5])), tuple(row[6]))
        for row in _DECLARED}
    assert mirrored == declared


@pytest.mark.lint
def test_cli_wire_mode_clean():
    """``python -m d4pg_tpu.lint --wire`` is the review artifact for
    protocol PRs; it must exit 0 on the repo, print every declared
    magic, and report no findings."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", "--wire", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "findings: none" in proc.stdout
    for magic in ("0xD4AB", "0xD4E2", "0xD4E3", "0xD4F6", "0xD4F7",
                  "0xD4F8", "0xD4FA", "0xD4FC", "D4RS"):
        assert magic in proc.stdout, proc.stdout
    assert "flag bits:" in proc.stdout


@pytest.mark.lint
@pytest.mark.failflow
def test_fail_graph_clean_over_package():
    """Tier-1 gate for the crash-containment surface: the whole-program
    exception-flow graph over ``d4pg_tpu/`` must show every thread spawn
    contained (or covered by an audited ``contained-by`` declaration),
    every trace begin settled or escrowed, every admission counter
    balanced, and zero findings."""
    from d4pg_tpu.lint.engine import build_fail_graph
    from d4pg_tpu.lint.failgraph import format_failgraph

    graph, errors = build_fail_graph([PACKAGE_DIR])
    assert not errors, errors
    assert graph.findings == [], format_failgraph(graph)
    assert graph.threads, "no thread spawns discovered — walker rot?"
    for site, target, status in graph.threads:
        assert status in ("contained", "no-raise", "contained-by"), (
            site, target, status)
    for site, root, status in graph.spans:
        assert status in ("settled", "escrow"), (site, root, status)
    for site, counter, status in graph.ledger:
        assert status == "balanced", (site, counter, status)
    # the fleet lane spawn's declaration is resolved and verified
    assert graph.handlers.get("ThrottledSender.run") == "ok", graph.handlers
    # the five wire planes' serve/accept loops are all discovered
    discovered = " ".join(t for _s, t, _st in graph.threads)
    for frame in ("TransitionReceiver._accept", "AggregatorServer._serve",
                  "WeightServer._accept", "PolicyInferenceServer._batcher",
                  "ReplayService._commit_loop"):
        assert frame in discovered, discovered


@pytest.mark.lint
@pytest.mark.failflow
def test_cli_fail_mode_clean():
    """``python -m d4pg_tpu.lint --fail`` is the review artifact for
    thread/obs PRs; it must exit 0 on the repo, print the thread-role
    table, and report no findings."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", "--fail", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "findings: none" in proc.stdout
    assert "thread roles" in proc.stdout
    assert "contained-by=ThrottledSender.run [ok]" in proc.stdout


@pytest.mark.lint
def test_cli_json_modes_clean():
    """``python -m d4pg_tpu.lint --all --json`` is the single CI
    entrypoint: ONE schema-1 document carrying the syntactic findings
    AND every graph mode's artifact section (the per-mode ``--json``
    documents are encoded by the same helpers, so gating the merged doc
    gates them all). Must exit clean on the repo."""
    import json

    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", "--all", "--json",
         PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["schema"] == 1 and doc["mode"] == "all", doc
    assert doc["findings"] == [] and doc["errors"] == [], doc
    assert "suppressed" in doc
    sections = {
        "locks": {"functions", "nodes", "edges", "cycles"},
        "wire": {"functions", "modules", "magics", "flags"},
        "fail": {"functions", "modules", "threads", "spans", "ledger",
                 "handlers"},
        "mesh": {"functions", "modules", "axes", "shard_maps",
                 "collectives", "shardings", "donations", "handlers"},
        "rng": {"functions", "modules", "scoped", "streams", "branches",
                "handlers"},
    }
    for section, keys in sections.items():
        sub = doc[section]
        assert sub["findings"] == [] and sub["errors"] == [], (section, sub)
        assert keys <= set(sub), (section, sorted(sub))
    assert doc["locks"]["cycles"] == []
