"""Tier-1 lint gate: the whole d4pg_tpu package must lint clean.

Every hazard jaxlint can see in this codebase is either fixed or carries
an inline ``# jaxlint: disable=<rule>`` suppression whose comment explains
why the pattern is deliberate. A new finding here means a PR introduced a
throughput/correctness hazard (or a rule regression) — fix the code or
justify a suppression, don't weaken the gate.

Marked ``lint`` so the whole-repo AST pass can be deselected with
``-m "not lint"`` when iterating on unrelated tests.
"""

import os
import subprocess
import sys

import pytest

import d4pg_tpu
from d4pg_tpu.lint import lint_paths

PACKAGE_DIR = os.path.dirname(os.path.abspath(d4pg_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)


@pytest.mark.lint
def test_package_lints_clean():
    result = lint_paths([PACKAGE_DIR])
    msgs = [f.format() for f in result.findings] + result.errors
    assert result.clean, (
        "jaxlint found unsuppressed hazards:\n" + "\n".join(msgs))


@pytest.mark.lint
def test_bench_and_entrypoints_lint_clean():
    """The scripts feeding the headline numbers are held to the same bar."""
    files = [os.path.join(REPO_ROOT, n) for n in ("bench.py",)]
    result = lint_paths([f for f in files if os.path.exists(f)])
    msgs = [f.format() for f in result.findings] + result.errors
    assert result.clean, (
        "jaxlint found unsuppressed hazards:\n" + "\n".join(msgs))


@pytest.mark.lint
def test_cli_module_entrypoint():
    """`python -m d4pg_tpu.lint <package>` is the documented interface; it
    must agree with the library API and exit 0 on the repo."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
