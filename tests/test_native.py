"""Native C++ PER-tree backend: equivalence against the numpy oracle.

The numpy segment trees (tested in test_replay.py) are the oracle; the C++
backend (native/per_trees.cpp via ctypes) must agree exactly.

Rebuilding the library: ``make -C native`` from the repo root compiles
``native/per_trees.cpp`` (plain g++, no third-party deps) and installs it
as ``d4pg_tpu/replay/_native/libper_trees.so``. ``load_native()`` runs
that make target automatically on first use; when the toolchain is absent,
the checked-in ``.so`` targets a different platform/ABI, or the load dies
for any other reason, this whole module SKIPS (never errors) and the
numpy backend remains the tested oracle.
"""

import numpy as np
import pytest

from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.uniform import TransitionBatch

try:
    from d4pg_tpu.replay.native import load_native
    native_available = load_native() is not None
except Exception:  # pragma: no cover - platform-specific loader failure
    native_available = False
pytestmark = pytest.mark.skipif(
    not native_available,
    reason="native per_trees library not loadable on this platform "
           "(rebuild with `make -C native`)",
)


def test_native_matches_numpy_trees(rng):
    from d4pg_tpu.replay.native import NativePerTrees

    N = 4096
    nat = NativePerTrees(N)
    s, m = SumTree(N), MinTree(N)
    for _ in range(5):
        idx = rng.integers(0, N, 500)
        vals = rng.random(500) + 1e-6
        nat.set(idx, vals)
        s.set(idx, vals)
        m.set(idx, vals)
        assert nat.sum() == pytest.approx(s.sum(), rel=1e-12)
        assert nat.min() == pytest.approx(m.min(), rel=1e-12)
        mass = rng.uniform(0, s.sum(), 128)
        np.testing.assert_array_equal(nat.find_prefixsum(mass),
                                      s.find_prefixsum(mass))
        probe = rng.integers(0, N, 64)
        np.testing.assert_allclose(nat.get(probe), s.get(probe), rtol=1e-12)


def test_native_set_get_accept_chunk_shaped_indices(rng):
    """[K, B] chunk indices (what ``update_priorities`` receives from the
    K-chunk sample paths) must apply ALL K*B writes, matching the numpy
    trees' fancy-assignment semantics — the C ABI takes an element count,
    and ``len()`` of a 2D array is its outer dim (the silent-drop
    regression the sample-on-ingest bitwise oracle caught)."""
    from d4pg_tpu.replay.native import NativePerTrees

    N = 256
    nat = NativePerTrees(N)
    s = SumTree(N)
    idx = rng.integers(0, N, size=(4, 32))
    vals = rng.random((4, 32)) + 1e-6
    nat.set(idx, vals)
    s.set(idx, vals)
    assert nat.sum() == s.sum()
    np.testing.assert_array_equal(nat.get(idx), s.get(idx))
    assert nat.get(idx).shape == idx.shape
    mass = rng.uniform(0, s.sum(), size=(2, 16))
    np.testing.assert_array_equal(nat.find_prefixsum(mass),
                                  s.find_prefixsum(mass))
    assert nat.find_prefixsum(mass).shape == mass.shape


def test_native_backend_in_buffer(rng):
    """PER buffer behaves identically under both backends (same seed)."""
    def run(backend):
        buf = PrioritizedReplayBuffer(256, 3, 1, alpha=0.6, seed=7,
                                      backend=backend)
        r = np.random.default_rng(1)
        for _ in range(4):
            n = 32
            done = np.zeros(n, np.float32)
            buf.add(TransitionBatch(
                obs=r.standard_normal((n, 3)).astype(np.float32),
                action=r.standard_normal((n, 1)).astype(np.float32),
                reward=r.standard_normal(n).astype(np.float32),
                next_obs=r.standard_normal((n, 3)).astype(np.float32),
                done=done,
                discount=np.full(n, 0.99, np.float32),
            ))
        batch, w, idx = buf.sample(64, beta=0.5)
        buf.update_priorities(idx, r.random(64) + 1e-3)
        batch2, w2, idx2 = buf.sample(64, beta=0.7)
        return idx, w, idx2, w2

    a = run("numpy")
    b = run("native")
    for x, y in zip(a, b):
        np.testing.assert_allclose(x, y, rtol=1e-7)


def test_native_backend_explicit_request_errors_without_lib(monkeypatch):
    """backend='native' must raise (not silently fall back) when the lib is
    unavailable."""
    import d4pg_tpu.replay.native as native_mod

    monkeypatch.setattr(native_mod, "_lib", None)
    monkeypatch.setattr(native_mod, "_loaded", True)
    with pytest.raises(RuntimeError):
        PrioritizedReplayBuffer(64, 3, 1, backend="native")
