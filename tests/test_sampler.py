"""Sample-on-ingest-plane tests (marker ``sampler``): the shard-slice
PER tree merge math (replay/sampler.ShardSlicePerTrees), the dealer's
bitwise block oracle against the legacy host sample path, the
N=1 dealt-replica ⇔ host-replica state oracle, the shared beta anneal
clock (the PR-10 per-caller-anneal regression), write-back generation
fencing, the fenced-frame-never-dealt invariant, the dealer chaos
smoke, the ``sampler`` obs provider + ``deal`` trace span, and the
bench-artifact ``sampler`` schema gate."""

from __future__ import annotations

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from d4pg_tpu.obs.registry import REGISTRY
from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.sampler import SampleDealer, ShardSlicePerTrees
from d4pg_tpu.replay.schedule import SharedBetaSchedule
from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.staging import DealtBlockRing
from d4pg_tpu.replay.uniform import TransitionBatch

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.sampler


def _batch(rng, n, obs_dim=6, act_dim=3):
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32))


# ------------------------------------------- shard-slice tree merge ----

@pytest.mark.parametrize("backend", ["numpy", "auto"])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_slice_merge_bitwise_equals_single_tree(rng, k, backend):
    """Totals, mins and the batched inverse-CDF descent over K partial
    slice trees must equal ONE flat SumTree/MinTree over the same slots
    BITWISE — the merge is structural (same pairwise bracketing), not a
    cumsum, so there is no float tolerance to hide behind.
    ``backend='numpy'`` pins the slice merge math itself; ``'auto'``
    pins that the native-delegated backing (when the lib is loadable —
    it silently falls back to the same numpy path otherwise) observes
    the identical contract."""
    cap = 64
    merged = ShardSlicePerTrees(cap, k, backend=backend)
    s, m = SumTree(cap), MinTree(cap)
    for _ in range(25):
        idx = rng.integers(0, cap, size=int(rng.integers(1, 17)))
        vals = rng.uniform(0.01, 5.0, size=idx.size)
        merged.set(idx, vals)
        s.set(idx, vals)
        m.set(idx, vals)
        assert merged.total() == s.sum()
        assert merged.min() == m.min()
        np.testing.assert_array_equal(merged.get(idx), s.get(idx))
        prefixes = rng.uniform(0.0, s.sum(), size=33)
        np.testing.assert_array_equal(merged.find_prefixsum(prefixes),
                                      s.find_prefixsum(prefixes))


@pytest.mark.parametrize("k", [2, 4])
def test_slice_merge_with_all_zero_priority_slices(rng, k):
    """Slices holding no mass (all-zero priorities — e.g. a shard slice
    nothing has committed into yet) must not perturb the draw: the
    descent lands in live slices exactly where the single tree does."""
    cap = 32
    merged = ShardSlicePerTrees(cap, k, backend="numpy")
    s = SumTree(cap)
    # populate ONLY slice 0's slot range; other slices stay all-zero
    idx = np.arange(cap // k)
    vals = rng.uniform(0.1, 2.0, size=idx.size)
    merged.set(idx, vals)
    s.set(idx, vals)
    assert merged.total() == s.sum()
    prefixes = rng.uniform(0.0, s.sum(), size=50)
    np.testing.assert_array_equal(merged.find_prefixsum(prefixes),
                                  s.find_prefixsum(prefixes))
    # a later write into a previously-zero slice repairs the top tree
    hi = np.arange(cap - cap // k, cap)
    hvals = rng.uniform(0.1, 2.0, size=hi.size)
    merged.set(hi, hvals)
    s.set(hi, hvals)
    assert merged.total() == s.sum()
    prefixes = rng.uniform(0.0, s.sum(), size=50)
    np.testing.assert_array_equal(merged.find_prefixsum(prefixes),
                                  s.find_prefixsum(prefixes))


def test_slice_cap_one_edge(rng):
    """n_slices == capacity: every slice is a single leaf and the top
    tree does all the descent work."""
    cap = 8
    merged = ShardSlicePerTrees(cap, cap, backend="numpy")
    s = SumTree(cap)
    idx = np.arange(cap)
    vals = rng.uniform(0.1, 3.0, size=cap)
    merged.set(idx, vals)
    s.set(idx, vals)
    assert merged.slice_cap == 1
    assert merged.total() == s.sum()
    prefixes = rng.uniform(0.0, s.sum(), size=40)
    np.testing.assert_array_equal(merged.find_prefixsum(prefixes),
                                  s.find_prefixsum(prefixes))


# ------------------------------------------- dealer block oracle -------

def test_dealer_blocks_bitwise_equal_legacy_sample_chunk(rng):
    """Twin seeded setups: the dealer draws through the merged slice
    trees, legacy draws through ``sample_chunk`` on an identically
    filled buffer. Indices, weights, dtypes, beta and the gathered rows
    must match exactly — across priority write-back rounds too (the
    capacity-1 ring makes each dealt block settle its predecessor's
    write-back before drawing, the legacy update-then-sample order)."""
    CAP, K, B, SEED, ROUNDS = 128, 3, 8, 11, 4
    legacy = PrioritizedReplayBuffer(CAP, 6, 3, alpha=0.6, seed=SEED)
    twin = PrioritizedReplayBuffer(CAP, 6, 3, alpha=0.6, seed=SEED)
    ring = DealtBlockRing(capacity=1)
    dealer = SampleDealer(CAP, [ring], n_shards=2, k=K, batch_size=B,
                          alpha=0.6,
                          beta_schedule=SharedBetaSchedule(0.4, 1000),
                          min_size=1, seed=SEED, ring_capacity=1)
    legacy_sched = SharedBetaSchedule(0.4, 1000)

    dealer.pause_dealing()  # paused deals never touch the RNG
    for i in range(3):
        batch = _batch(rng, 48)
        legacy.add(batch)
        dealer.ingest_and_deal([(twin.add(batch), i, None)], twin)
    dealer.resume_dealing()

    for _ in range(ROUNDS):
        dealt = dealer.ingest_and_deal((), twin)  # idle top-up tick
        assert len(dealt) == 1
        dealer.publish(dealt)
        blk = ring.pop(timeout=0)
        assert blk is not None

        lbeta = legacy_sched.beta_at(legacy_sched.current_step())
        lb, lw, lidx = legacy.sample_chunk(K, B, beta=lbeta,
                                           weight_base=legacy.weight_base())
        legacy_sched.advance(K)

        np.testing.assert_array_equal(blk.idx, lidx)
        np.testing.assert_array_equal(blk.weights, lw)
        assert blk.weights.dtype == lw.dtype == np.float32
        assert blk.beta == lbeta
        for a, b in zip(blk.batches, lb):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(blk.gen, legacy.generation[lidx])

        # write back the same TD magnitudes through both paths
        td = np.asarray(rng.uniform(0.05, 3.0, size=lidx.shape))
        legacy.update_priorities(lidx, td, generation=legacy.generation[lidx])
        dealer.queue_writeback(blk.idx, td, blk.gen)
    assert dealer.dealt_blocks == ROUNDS
    dealer.close()


# ------------------------------------------- N=1 replica state oracle --

def test_n1_dealt_replica_bitwise_equals_host_replica(rng):
    """ONE replica consuming dealt blocks must land bit-for-bit the
    state a host-sampled replica reaches over an identically-filled,
    identically-seeded service — run in pause/resume lockstep with a
    capacity-1 ring so each side's priority write-back settles before
    the next draw, exactly the legacy update-then-sample order."""
    import jax

    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.distributed.weights import WeightStore
    from d4pg_tpu.learner import D4PGConfig, init_state
    from d4pg_tpu.learner.aggregator import Aggregator
    from d4pg_tpu.learner.replica import PARAM_FIELDS, LearnerReplica

    OBS, ACT, CAP, K, B, SEED, ROUNDS = 5, 2, 256, 2, 8, 5, 3
    config = D4PGConfig(obs_dim=OBS, act_dim=ACT, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(16, 16))
    blocks = [_batch(rng, 48, OBS, ACT) for _ in range(2)]

    svc_h = ReplayService(
        PrioritizedReplayBuffer(CAP, OBS, ACT, alpha=0.6, seed=SEED))
    svc_d = ReplayService(
        PrioritizedReplayBuffer(CAP, OBS, ACT, alpha=0.6, seed=SEED))
    ring = DealtBlockRing(capacity=1)
    dealer = SampleDealer(CAP, [ring], n_shards=1, k=K, batch_size=B,
                          alpha=0.6,
                          beta_schedule=SharedBetaSchedule(0.4, 1000),
                          min_size=1, seed=SEED, ring_capacity=1)
    dealer.pause_dealing()  # fill first, deal in lockstep below
    svc_d.attach_dealer(dealer)
    for b in blocks:
        svc_h.add(b, actor_id="oracle")
        svc_d.add(b, actor_id="oracle")
    svc_h.flush(timeout=10.0)
    svc_d.flush(timeout=10.0)

    agg_h = Aggregator(WeightStore())
    agg_d = Aggregator(WeightStore())
    rep_h = LearnerReplica(0, config, agg_h,
                           init_state(config, jax.random.key(0)),
                           k=K, batch_size=B, service=svc_h,
                           beta_schedule=SharedBetaSchedule(0.4, 1000))
    rep_d = LearnerReplica(0, config, agg_d,
                           init_state(config, jax.random.key(0)),
                           k=K, batch_size=B, service=svc_d,
                           dealt_ring=ring,
                           beta_schedule=SharedBetaSchedule(0.4, 1000))
    assert rep_h.mode == "host" and rep_d.mode == "dealt"

    def wait_block(timeout=5.0):
        deadline = time.monotonic() + timeout
        while ring.depth() == 0:
            assert time.monotonic() < deadline, "dealer never dealt a block"
            time.sleep(0.01)

    for _ in range(ROUNDS):
        # dealt side: resume -> the commit thread's idle tick settles the
        # previous round's write-back and deals ONE block (ring cap 1) ->
        # pause -> consume it
        dealer.resume_dealing()
        wait_block()
        dealer.pause_dealing()
        rep_d.run_round(K)
        rep_h.run_round(K)

    for f in PARAM_FIELDS:
        a = jax.device_get(getattr(rep_h.state, f))
        b = jax.device_get(getattr(rep_d.state, f))
        jax.tree_util.tree_map(np.testing.assert_array_equal, a, b)
    assert rep_h.steps_done == rep_d.steps_done == ROUNDS * K
    rep_h.close()
    rep_d.close()
    agg_h.close()
    agg_d.close()
    svc_h.close()
    svc_d.close()


# ------------------------------------------- shared beta clock ---------

def test_shared_beta_two_replicas_same_step_same_beta():
    """The PR-10 regression: two replicas sampling concurrently must
    read the IDENTICAL beta at the same global step — the anneal clock
    is shared, not per-caller (which scaled the anneal rate with N)."""
    sched = SharedBetaSchedule(beta0=0.4, beta_steps=1000)
    barrier = threading.Barrier(2)
    out: list = [None, None]

    def reader(i):
        barrier.wait()
        t = sched.current_step()
        out[i] = (t, sched.beta_at(t))

    threads = [threading.Thread(target=reader, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert out[0] == out[1]

    # concurrent claims never double-count: 4 threads x 250 steps
    # advance the clock by exactly 1000, to the anneal ceiling
    def advancer():
        for _ in range(50):
            sched.advance(5)

    threads = [threading.Thread(target=advancer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sched.current_step() == 1000
    assert sched.beta_at(sched.current_step()) == 1.0


def test_shared_beta_matches_legacy_formula():
    sched = SharedBetaSchedule(beta0=0.4, beta_steps=100)
    for t in (0, 1, 50, 100, 250):
        expect = 0.4 + (1.0 - 0.4) * min(1.0, t / 100)
        assert sched.beta_at(t) == expect


# ------------------------------------------- write-back fencing --------

def test_writeback_generation_fence_drops_stale(rng):
    """A write-back whose slot was overwritten between draw and settle
    must be DROPPED, not applied to the new occupant's priority."""
    CAP, K, B = 64, 1, 4
    ring = DealtBlockRing(capacity=2)
    buf = PrioritizedReplayBuffer(CAP, 6, 3, alpha=0.6, seed=0)
    dealer = SampleDealer(CAP, [ring], n_shards=1, k=K, batch_size=B,
                          min_size=1, seed=0, ring_capacity=2)
    idx = buf.add(_batch(rng, 16))
    dealer.publish(dealer.ingest_and_deal([(idx, 0, None)], buf))
    blk = ring.pop(timeout=0)
    assert blk is not None
    # overwrite one drawn slot (its generation bumps), then write back
    victim = int(blk.idx.ravel()[0])
    dealer.ingest_and_deal([(np.array([victim]), 1, None)], buf)
    before = dealer._trees.get(blk.idx.ravel()).copy()
    dealer.queue_writeback(blk.idx, np.full(blk.idx.shape, 9.0), blk.gen)
    dealer.drain_writebacks_for_shard(0)
    after = dealer._trees.get(blk.idx.ravel())
    stale = blk.idx.ravel() == victim
    assert dealer.writeback_dropped_stale == int(stale.sum())
    # the overwritten slot kept its fresh-insert priority...
    np.testing.assert_array_equal(after[stale], before[stale])
    # ...while live slots took the update (9.0 ** alpha)
    if (~stale).any():
        np.testing.assert_array_equal(after[~stale],
                                      np.full(int((~stale).sum()), 9.0**0.6))
    dealer.close()


def test_fenced_frame_never_dealt(rng):
    """A frame stamped with a pre-restart generation fences at admission
    — it inserts no rows, so it is STRUCTURALLY undealable; the audit
    counter stays 0 while fresh frames keep dealing."""
    from d4pg_tpu.distributed import transport
    from d4pg_tpu.distributed.replay_service import ReplayService

    svc = ReplayService(PrioritizedReplayBuffer(256, 6, 3, seed=0),
                        generation=1)
    ring = DealtBlockRing(capacity=2)
    dealer = SampleDealer(256, [ring], n_shards=1, k=1, batch_size=4,
                          min_size=1, seed=0, ring_capacity=2, audit=True)
    svc.attach_dealer(dealer)
    # encode_raw returns length-prefixed wire bytes; admission takes the
    # bare payload the receiver would hand it
    stale = transport.encode_raw("corpse", _batch(rng, 8), True,
                                 generation=0)
    assert svc.add_payload(stale[transport._HEADER.size:],
                           codec="raw") is True  # declared loss, not error
    svc.add(_batch(rng, 16), actor_id="live")
    svc.flush(timeout=10.0)
    deadline = time.monotonic() + 5.0
    while ring.depth() == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    stats = svc.ingest_stats()
    assert stats["fenced_frames"] == 1 and stats["fenced_rows"] == 8
    assert svc.env_steps == 16          # fenced rows never inserted
    assert ring.depth() > 0             # live rows still deal
    assert dealer.dealt_dead_tickets == 0
    svc.close()


# ------------------------------------------- dealt ring ----------------

def test_dealt_ring_capacity_close_and_clear():
    ring = DealtBlockRing(capacity=2)
    assert ring.room() == 2
    assert ring.offer("a") and ring.offer("b")
    assert not ring.offer("c")          # full: unreserved offer fails
    assert ring.depth() == 2 and ring.room() == 0
    assert ring.pop(timeout=0) == "a"
    assert ring.offer("c")
    assert ring.clear() == 2            # respawn path drops the backlog
    assert ring.pop(timeout=0.01) is None
    ring.close()
    assert ring.closed and ring.room() == 0
    assert not ring.offer("d")
    assert ring.pop(timeout=None) is None  # close unblocks a waiting pop


# ------------------------------------------- obs plane -----------------

@pytest.mark.obs
def test_sampler_provider_and_deal_span(rng):
    """The ``sampler`` registry provider must export the dealt counters
    + write-back lag histogram, and a dealt block must commit a ``deal``
    span hanging off its newest constituent frame's committed trace."""
    from d4pg_tpu.obs import trace as obs_trace

    obs_trace.RECORDER.reset()
    obs_trace.RECORDER.enable(sample_rate=1.0)
    REGISTRY.histogram("sampler.writeback_lag_ms").reset()
    ring = DealtBlockRing(capacity=1)
    buf = PrioritizedReplayBuffer(64, 6, 3, alpha=0.6, seed=0)
    dealer = SampleDealer(64, [ring], n_shards=1, k=1, batch_size=4,
                          min_size=1, seed=0, ring_capacity=1)
    tid = 7
    obs_trace.RECORDER.begin(tid, time.monotonic())
    obs_trace.RECORDER.record_span(tid, "admission")
    obs_trace.RECORDER.mark_committed([tid])
    dealt = dealer.ingest_and_deal([(buf.add(_batch(rng, 16)), 0, tid)], buf)
    assert len(dealt) == 1
    dealer.publish(dealt)
    blk = dealt[0][1]
    assert blk.tid == tid
    dealer.queue_writeback(blk.idx, np.full(blk.idx.shape, 1.0), blk.gen)
    dealer.drain_writebacks_for_shard(0)
    obs_trace.RECORDER.mark_grad()
    lat = obs_trace.RECORDER.latency_block()
    assert lat["orphans"] == 0
    assert lat["stages"]["commit_to_deal"]["n"] >= 1
    assert lat["stages"]["deal_to_grad"]["n"] >= 1

    prov = REGISTRY.export()["sampler"]
    assert prov["dealt_blocks"] == 1
    assert prov["dealt_rows"] == 4
    assert prov["dealer_queue_depth"] == 0  # write-back drained
    assert prov["writeback_lag_ms"]["n"] == 1
    assert prov["ring_capacity"] == 1
    assert prov["ring_depths"] == [1]
    dealer.close()
    obs_trace.RECORDER.disable()
    obs_trace.RECORDER.reset()


# ------------------------------------------- chaos smoke ---------------

@pytest.mark.fleet
def test_sampler_chaos_smoke():
    """A small dealer-mode chaos run (consumer kill + stale-generation
    injection under sender chaos) must pass the gating oracles — the
    full-size version is the bench artifact's ``sampler`` chaos row."""
    from d4pg_tpu.fleet.sampler_chaos import (
        SamplerChaosConfig,
        run_sampler_chaos,
    )

    from d4pg_tpu.obs.registry import REGISTRY

    crashes0 = REGISTRY.counter("threads.contained_crashes").value
    rep = run_sampler_chaos(SamplerChaosConfig(
        sample_path="dealer", n_actors=4, duration_s=3.0,
        rows_per_sec=40.0, learner_kills=1, stale_frames=3, seed=3))
    assert rep["deadlocks"] == 0
    # chaos is injected through narrow, expected-error paths; the broad
    # top-frame containments must never fire during a clean run
    assert REGISTRY.counter("threads.contained_crashes").value == crashes0
    assert rep["hierarchy_violations"] == 0
    assert rep["trace_orphans"] == 0
    assert rep["sampler"]["dealt_dead_tickets"] == 0
    assert rep["consumer"]["sample_path_buffer_acqs"] == 0
    assert rep["consumer"]["consumer_kills"] == 1
    assert rep["consumer"]["stale_frames_injected"] == 3
    assert rep["fenced_frames"] == 3
    assert rep["sampler"]["dealt_blocks"] > 0
    assert rep["consumer"]["blocks_consumed"] > 0


# ------------------------------------------- artifact gate -------------

@pytest.mark.obs
def test_fleet_artifact_sampler_schema():
    """The newest committed fleet artifact must carry the sampler block:
    the three-arm A/B sweep — host vs dealer vs device (the on-device
    descent), the dealer/device consume paths pinned at ZERO buffer-lock
    acquisitions, wire-to-grad AND deal-to-grad p95 on every arm — and
    one dealer chaos row passing every gating oracle. A later PR that
    drops any of it fails tier-1 here."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "fleet", "fleet_*.json")))
    assert arts, "no committed fleet artifact"
    with open(arts[-1]) as f:
        artifact = json.load(f)
    blk = artifact.get("sampler")
    assert blk, "newest fleet artifact lost its sampler block"
    assert blk["metric"] == "fleet_sampler" and blk["schema"] == 1
    ab = blk["ab"]
    assert ab["dealer"]["sample_path_buffer_acqs"] == 0
    assert ab["device"]["sample_path_buffer_acqs"] == 0
    assert ab["host"]["sample_path_buffer_acqs"] > 0
    for arm in ("dealer", "host", "device"):
        assert ab[arm]["wire_to_grad_p95_ms"] is not None
        assert "deal_to_grad_p95_ms" in ab[arm]
        assert ab[arm]["blocks_consumed"] > 0
        assert ab[arm]["deadlocks"] == 0
        assert ab[arm]["hierarchy_violations"] == 0
        assert ab[arm]["trace_orphans"] == 0
    for arm in ("dealer", "device"):
        assert ab[arm]["sampler"]["dealt_blocks"] > 0
        assert "wire_to_grad_p95_delta_ms" in ab[arm]
    chaos = blk["chaos"]
    assert chaos["metric"] == "sampler_chaos" and chaos["schema"] == 1
    assert chaos["sample_path"] == "dealer"
    assert chaos["deadlocks"] == 0
    assert chaos["hierarchy_violations"] == 0
    assert chaos["trace_orphans"] == 0
    assert chaos["sampler"]["dealt_dead_tickets"] == 0
    assert chaos["consumer"]["sample_path_buffer_acqs"] == 0
    assert chaos["consumer"]["consumer_kills"] >= 1
    assert chaos["fenced_frames"] >= 1
