"""Sharded data-parallel learner tests on the 8-virtual-device CPU mesh
(SURVEY.md §4: multi-host behavior simulated with 8 local XLA CPU devices).

The key property: the sharded update is EQUIVALENT to the single-device
update on the same global batch — the synchronous replacement for the
reference's racy hogwild scheme has no semantic drift, only layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.learner import D4PGConfig, init_state, make_update
from d4pg_tpu.parallel import (
    MeshSpec,
    make_mesh,
    make_sharded_update,
    replicate_state,
    shard_batch,
)
from d4pg_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from d4pg_tpu.replay.uniform import TransitionBatch

OBS, ACT, B = 4, 2, 64


def _config(**kw):
    base = dict(obs_dim=OBS, act_dim=ACT, v_min=-5.0, v_max=5.0, n_atoms=11,
                hidden=(32, 32, 32))
    base.update(kw)
    return D4PGConfig(**base)


def _batch(rng):
    done = (rng.random(B) < 0.2).astype(np.float32)
    return TransitionBatch(
        obs=rng.standard_normal((B, OBS)).astype(np.float32),
        action=rng.uniform(-1, 1, (B, ACT)).astype(np.float32),
        reward=rng.standard_normal(B).astype(np.float32),
        next_obs=rng.standard_normal((B, OBS)).astype(np.float32),
        done=done,
        discount=(0.99 * (1.0 - done)).astype(np.float32),
    )


def test_mesh_geometry():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    mesh = make_mesh(MeshSpec())
    assert mesh.shape[DATA_AXIS] == 8 and mesh.shape[MODEL_AXIS] == 1
    mesh2 = make_mesh(MeshSpec(data_parallel=4, model_parallel=2))
    assert mesh2.shape[DATA_AXIS] == 4 and mesh2.shape[MODEL_AXIS] == 2
    with pytest.raises(ValueError):
        make_mesh(MeshSpec(data_parallel=3))


def test_batch_sharded_state_replicated(rng):
    config = _config()
    mesh = make_mesh()
    state = replicate_state(init_state(config, jax.random.key(0)), mesh)
    batch = shard_batch(_batch(rng), mesh)
    # batch leading dim split 8 ways; params present on all devices
    assert len(batch.obs.sharding.device_set) == 8
    leaf = jax.tree_util.tree_leaves(state.actor_params)[0]
    assert leaf.sharding.is_fully_replicated


def test_sharded_update_matches_single_device(rng):
    """Bitwise-level equivalence (up to float tolerance) between the sharded
    and single-device update on the same global batch."""
    config = _config()
    batch = _batch(rng)
    w = np.ones((B,), np.float32)

    ref_state = init_state(config, jax.random.key(42))
    ref_update = make_update(config, donate=False)
    ref_next, ref_metrics = ref_update(ref_state, batch, jnp.asarray(w))

    mesh = make_mesh()
    sh_state = replicate_state(init_state(config, jax.random.key(42)), mesh)
    sh_update = make_sharded_update(config, mesh, donate=False)
    sh_next, sh_metrics = sh_update(sh_state, shard_batch(batch, mesh),
                                    shard_batch(jnp.asarray(w), mesh))

    np.testing.assert_allclose(
        float(ref_metrics["critic_loss"]), float(sh_metrics["critic_loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(ref_metrics["td_error"]), np.asarray(sh_metrics["td_error"]),
        rtol=1e-4, atol=1e-5,
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_next.critic_params),
        jax.tree_util.tree_leaves(sh_next.critic_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_sharded_update_multi_step_stability(rng):
    """Several sharded steps run and keep params replicated + finite."""
    config = _config()
    mesh = make_mesh()
    state = replicate_state(init_state(config, jax.random.key(1)), mesh)
    update = make_sharded_update(config, mesh, donate=False, use_is_weights=False)
    for _ in range(3):
        state, metrics = update(state, shard_batch(_batch(rng), mesh))
    leaf = jax.tree_util.tree_leaves(state.actor_params)[0]
    assert leaf.sharding.is_fully_replicated
    assert np.isfinite(float(metrics["critic_loss"]))
    assert int(state.step) == 3


def test_sharded_multi_update_matches_sequential(rng):
    """The production config (VERDICT r1 #3): K scanned updates sharded over
    the data axis == K sequential sharded updates on the same batches."""
    from d4pg_tpu.parallel import make_sharded_multi_update, shard_stacked

    config = _config()
    K = 4
    batches = [_batch(rng) for _ in range(K)]
    w = np.ones((B,), np.float32)

    mesh = make_mesh(MeshSpec(data_parallel=4), devices=jax.devices()[:4])
    seq_state = replicate_state(init_state(config, jax.random.key(7)), mesh)
    seq_update = make_sharded_update(config, mesh, donate=False)
    seq_tds = []
    for b in batches:
        seq_state, m = seq_update(seq_state, shard_batch(b, mesh),
                                  shard_batch(jnp.asarray(w), mesh))
        seq_tds.append(np.asarray(m["td_error"]))

    stacked = TransitionBatch(*[np.stack(x) for x in zip(*batches)])
    multi_state = replicate_state(init_state(config, jax.random.key(7)), mesh)
    multi_update = make_sharded_multi_update(config, mesh, donate=False)
    multi_state, ms = multi_update(
        multi_state,
        shard_stacked(stacked, mesh),
        shard_stacked(jnp.ones((K, B), jnp.float32), mesh),
    )

    assert int(jax.device_get(multi_state.step)) == K
    np.testing.assert_allclose(
        np.asarray(ms["td_error"]), np.stack(seq_tds), rtol=1e-4, atol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(seq_state.critic_params),
        jax.tree_util.tree_leaves(multi_state.critic_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    leaf = jax.tree_util.tree_leaves(multi_state.actor_params)[0]
    assert leaf.sharding.is_fully_replicated


def test_train_mesh_with_updates_per_dispatch(tmp_path):
    """End-to-end train() on a 2-device data mesh WITH K>1 fused dispatch —
    the round-1 degrade path is gone (VERDICT r1 #3)."""
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=5,
        eval_trials=1, batch_size=16, memory_size=2000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, data_parallel=2, updates_per_dispatch=2,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
    assert "avg_test_reward" in metrics


def test_sharded_factories_reject_pallas_projection():
    """pallas_call has no GSPMD partitioning rule; the mesh factories must
    fail loudly instead of compiling a silently-broken sharded kernel."""
    import pytest

    from d4pg_tpu.learner.state import D4PGConfig
    from d4pg_tpu.parallel.data_parallel import (
        make_sharded_multi_update,
        make_sharded_update,
    )
    from d4pg_tpu.parallel.mesh import make_mesh

    config = D4PGConfig(obs_dim=3, act_dim=1, n_atoms=11, hidden=(8,),
                        projection="pallas")
    mesh = make_mesh()
    for factory in (make_sharded_update, make_sharded_multi_update):
        with pytest.raises(ValueError, match="pallas"):
            factory(config, mesh)
