"""Weight-broadcast plane tests (``distributed/weight_plane.py`` +
``fleet/weight_chaos.py``): codecs and their oracle bounds, bitwise
delta reconstruction, the dual-protocol server (v1 pullers + v2
delta/quantized/fenced pullers on one port), single-flight frame
memoization, torn-payload rejection, generation fencing through relays,
the stale-degradation contract, and the bench-artifact weights schema
gate."""

from __future__ import annotations

import glob
import io
import json
import os
import time
import zlib

import numpy as np
import pytest

from d4pg_tpu.distributed.weight_plane import (
    BF16_REL_BOUND,
    CODECS,
    WeightPlaneClient,
    WeightPlaneServer,
    WeightRelay,
    WeightWireChaos,
    bf16_to_f32,
    decode_flat,
    delta_apply,
    delta_encode,
    encode_flat,
    f32_to_bf16,
    quant_error_excess,
)
from d4pg_tpu.distributed.weight_server import WeightClient, WeightServer
from d4pg_tpu.distributed.weights import WeightStore

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.weights


def _params(rng, d=24):
    return {"actor": {"w0": rng.normal(size=(d, d)).astype(np.float32),
                      "b0": rng.normal(size=(d,)).astype(np.float32)},
            "meta": {"count": np.int64(7)}}


def _flat(rng, d=24):
    return {"a/w": rng.normal(size=(d, d)).astype(np.float32),
            "a/b": rng.normal(size=(d,)).astype(np.float32),
            "a/i": np.arange(d, dtype=np.int32),
            "__norm_mean__": rng.normal(size=(4,))}


def _pull_until(client, want_version, timeout=5.0, want_gen=None):
    deadline = time.monotonic() + timeout
    res = None
    while time.monotonic() < deadline:
        got = client.get_if_newer()
        if got is not None:
            res = got
        if (client.version >= want_version
                and (want_gen is None or client.generation == want_gen)):
            return res
        time.sleep(0.02)
    raise AssertionError(
        f"never reached v{want_version} (at v{client.version} "
        f"gen{client.generation})")


# ------------------------------------------------------------ codecs ----

def test_bf16_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(512,)) * 10.0 ** rng.integers(-6, 6, size=512)
         ).astype(np.float32)
    back = bf16_to_f32(f32_to_bf16(x))
    assert np.all(np.abs(back - x) <= BF16_REL_BOUND * np.abs(x) + 1e-40)
    # exactly-representable values survive bitwise
    exact = np.array([0.0, 1.0, -2.5, 0.15625], dtype=np.float32)
    assert bf16_to_f32(f32_to_bf16(exact)).tobytes() == exact.tobytes()


def test_encode_decode_all_codecs_and_oracle():
    rng = np.random.default_rng(1)
    flat = _flat(rng)
    for codec in CODECS:
        enc = encode_flat(flat, codec)
        dec = decode_flat(enc)
        assert dec.keys() == flat.keys()
        # non-f32 and meta tensors travel raw whatever the codec
        assert dec["a/i"].tobytes() == flat["a/i"].tobytes()
        assert dec["__norm_mean__"].tobytes() == flat["__norm_mean__"].tobytes()
        if codec == "f32":
            assert dec["a/w"].tobytes() == flat["a/w"].tobytes()
        # the quantization oracle: every tensor within its declared bound
        assert quant_error_excess(flat, enc) <= 0


def test_int8_zero_tensor_exact():
    enc = encode_flat({"z": np.zeros(8, np.float32)}, "int8")
    assert decode_flat(enc)["z"].tobytes() == np.zeros(8, np.float32).tobytes()


def test_unknown_codec_rejected():
    with pytest.raises(ValueError):
        encode_flat({}, "fp4")
    with pytest.raises(ValueError):
        WeightPlaneClient("127.0.0.1", 1, codec="fp4")


# ------------------------------------------------------------- delta ----

def test_delta_roundtrip_bitwise_all_arms():
    """Every delta arm — __same__, sparse XOR, full tensor, __dropped__,
    new key — reconstructs bitwise."""
    rng = np.random.default_rng(2)
    base = encode_flat(_flat(rng), "f32")
    new_flat = _flat(np.random.default_rng(2))
    new_flat["a/w"][3] += 1.0                      # sparse change
    new_flat["a/b"] = rng.normal(size=(24,)).astype(np.float32)  # full change
    del new_flat["a/i"]                            # dropped
    new_flat["a/new"] = np.ones(3, np.float32)     # added
    new = encode_flat(new_flat, "f32")
    entries = delta_encode(base, new)
    assert "xi:r:a/w" in entries                  # sparse arm taken
    same = json.loads(entries["__same__"].tobytes().decode())
    assert "r:__norm_mean__" in same              # unchanged arm taken
    rebuilt = delta_apply(base, entries)
    assert rebuilt.keys() == new.keys()
    assert all(rebuilt[k].tobytes() == new[k].tobytes() for k in new)


def test_delta_composes_with_quantized_codec():
    """A quantized delta reconstructs bitwise the quantized full frame
    (deltas run over ENCODED bytes, so the oracle stays exact)."""
    rng = np.random.default_rng(3)
    f1 = _flat(rng)
    f2 = {k: v.copy() for k, v in f1.items()}
    f2["a/w"][0] += 0.25
    for codec in ("bf16", "int8"):
        e1, e2 = encode_flat(f1, codec), encode_flat(f2, codec)
        rebuilt = delta_apply(e1, delta_encode(e1, e2))
        assert rebuilt.keys() == e2.keys()
        assert all(rebuilt[k].tobytes() == e2[k].tobytes() for k in e2)


def test_delta_odd_byte_lengths():
    """XOR word padding: dtypes whose nbytes aren't a multiple of 4."""
    b = {"r:x": np.arange(7, dtype=np.uint8), "r:y": np.arange(3).astype(np.float16)}
    n = {"r:x": np.arange(7, dtype=np.uint8) + 1,
         "r:y": (np.arange(3) + 1).astype(np.float16)}
    rebuilt = delta_apply(b, delta_encode(b, n))
    assert all(rebuilt[k].tobytes() == n[k].tobytes() for k in n)
    assert all(rebuilt[k].dtype == n[k].dtype for k in n)


# ------------------------------------------------- server + client ----

def test_full_then_delta_pull_and_memo_single_flight():
    rng = np.random.default_rng(4)
    store = WeightStore()
    srv = WeightPlaneServer(store, window=4)
    try:
        store.publish(_params(rng), step=1, to_host=False)
        clients = [WeightPlaneClient("127.0.0.1", srv.port, codec="f32")
                   for _ in range(4)]
        for c in clients:
            v, params = c.get_if_newer()
            assert v == 1 and params["meta"]["count"] == 7
        stats = srv.weight_stats()
        # 4 pullers, ONE encode + ONE frame build (single-flight memo)
        assert stats["codec_encodes"] == 1
        assert stats["frames_full"] == 4
        store.publish(_params(rng), step=2, to_host=False)
        for c in clients:
            v, _ = c.get_if_newer()
            assert v == 2 and c.counters["delta_frames"] == 1
        assert srv.weight_stats()["frames_delta"] == 4
        for c in clients:
            assert c.get_if_newer() is None  # not newer
            c.close()
    finally:
        srv.close()


def test_quantized_transport_end_to_end():
    rng = np.random.default_rng(5)
    store = WeightStore()
    srv = WeightPlaneServer(store, window=4)
    try:
        p = _params(rng)
        store.publish(p, step=1, to_host=False)
        for codec, tol in (("bf16", BF16_REL_BOUND), ("int8", 1.0 / 127)):
            c = WeightPlaneClient("127.0.0.1", srv.port, codec=codec)
            _, got = c.get_if_newer()
            w, gw = p["actor"]["w0"], got["actor"]["w0"]
            assert np.max(np.abs(gw - w)) <= tol * np.max(np.abs(w)) + 1e-6
            assert got["meta"]["count"] == 7  # non-f32 stays exact
            c.close()
        assert srv.weight_stats()["oracle_quant_failures"] == 0
        assert srv.weight_stats()["oracle_quant_checks"] >= 2
    finally:
        srv.close()


def test_v1_client_against_plane_server():
    """Dual protocol: the legacy WeightClient pulls from the plane
    server unchanged (norm stats piggyback included)."""
    rng = np.random.default_rng(6)
    store = WeightStore()
    srv = WeightPlaneServer(store, window=4)
    try:
        norm = (np.zeros(4), np.ones(4), 5.0)
        store.publish(_params(rng), step=3, to_host=False, norm_stats=norm)
        c = WeightClient("127.0.0.1", srv.port)
        v, params = c.get_if_newer(0)
        assert v == 1 and c.step == 3
        assert c.norm_stats is not None and c.norm_stats[2] == 5.0
        assert c.get_if_newer(v) is None
        c.close()
    finally:
        srv.close()


def test_out_of_window_puller_falls_back_to_full():
    rng = np.random.default_rng(7)
    store = WeightStore()
    srv = WeightPlaneServer(store, window=2)
    try:
        c = WeightPlaneClient("127.0.0.1", srv.port, codec="f32")
        helper = WeightPlaneClient("127.0.0.1", srv.port, codec="f32")
        store.publish(_params(rng), step=1, to_host=False)
        assert c.get_if_newer()[0] == 1
        # the window ingests versions AT SERVE TIME: pull each publish
        # through a helper so v2..v4 enter the window and v1 ages out
        for step in (2, 3, 4):
            store.publish(_params(rng), step=step, to_host=False)
            helper.get_if_newer()
        assert c.get_if_newer()[0] == 4
        assert c.counters["full_frames"] == 2  # base evicted -> full
        assert c.counters["delta_frames"] == 0
        helper.close()
        c.close()
    finally:
        srv.close()


def test_torn_payload_rejected_never_accepted():
    rng = np.random.default_rng(8)
    store = WeightStore()
    chaos = WeightWireChaos(torn_prob=1.0, seed=1)
    srv = WeightPlaneServer(store, chaos=chaos)
    try:
        store.publish(_params(rng), step=1, to_host=False)
        c = WeightPlaneClient("127.0.0.1", srv.port, reconnect_interval=0.01)
        for _ in range(3):
            assert c.get_if_newer() is None
            time.sleep(0.02)
        assert c.counters["torn_rejected"] >= 1
        assert c.counters["accepts"] == 0
        chaos.torn_prob = 0.0     # chaos off -> recovers on stale socket
        assert _pull_until(c, 1)[0] == 1
        c.close()
    finally:
        srv.close()


def test_generation_fence_client_rejects_pre_crash_frame():
    rng = np.random.default_rng(9)
    p = _params(rng)
    store0 = WeightStore(generation=0)
    srv0 = WeightPlaneServer(store0)
    store0.publish(p, step=1, to_host=False)
    store0.publish(p, step=2, to_host=False)
    pre_crash = srv0.latest_full_payload()  # gen0 v2, genuine bytes
    srv0.close()

    store1 = WeightStore(generation=1)
    chaos = WeightWireChaos(stale_prob=1.0, seed=2)
    chaos.stash.append(pre_crash)
    srv1 = WeightPlaneServer(store1, chaos=chaos)
    try:
        store1.publish(p, step=3, to_host=False)  # gen1 v1: version REWINDS
        c = WeightPlaneClient("127.0.0.1", srv1.port)
        c.generation = 1  # has seen gen1 (e.g. via a peer relay)
        assert c.get_if_newer() is None  # injected gen0 v2: fenced
        assert c.counters["fenced_rejected"] == 1
        chaos.stale_prob = 0.0
        res = c.get_if_newer()
        assert res is not None and res[0] == 1
        assert (c.generation, c.version) == (1, 1)
        c.close()
    finally:
        srv1.close()


def test_generation_bump_purges_server_window():
    """The server drops every pre-crash window entry the moment it sees
    a newer generation — a relay can never serve one as current."""
    rng = np.random.default_rng(10)
    store = WeightStore(generation=0)
    srv = WeightPlaneServer(store, window=8)
    try:
        store.publish(_params(rng), step=1, to_host=False)
        c = WeightPlaneClient("127.0.0.1", srv.port)
        assert c.get_if_newer()[0] == 1
        # simulate the relay's restart-adoption: same store jumps a gen
        store.publish_versioned(_params(rng), version=1, step=9, generation=1)
        v, _ = c.get_if_newer()
        assert v == 1 and c.generation == 1
        stats = srv.weight_stats()
        assert stats["window_purged_generations"] == 1
        assert stats["window_len"] == 1  # only the gen-1 entry survives
        c.close()
    finally:
        srv.close()


def test_relay_chain_propagates_and_fences():
    rng = np.random.default_rng(11)
    p = _params(rng)
    store = WeightStore(generation=0)
    srv = WeightPlaneServer(store, window=4)
    r1 = WeightRelay("127.0.0.1", srv.port, poll_interval=0.01, window=4)
    r2 = WeightRelay("127.0.0.1", r1.port, poll_interval=0.01, window=4)
    leaf = WeightPlaneClient("127.0.0.1", r2.port, codec="bf16")
    try:
        store.publish(p, step=1, to_host=False,
                      norm_stats=(np.zeros(4), np.ones(4)))
        res = _pull_until(leaf, 1)
        assert res[0] == 1 and leaf.norm_stats is not None
        # generation bump at the ROOT propagates through both hops and
        # the version rewind is adopted, not fenced, at the leaf
        store.publish_versioned(p, version=1, step=2, generation=1)
        _pull_until(leaf, 1, want_gen=1)
        assert (leaf.generation, leaf.version) == (1, 1)
        assert r1.gen_adoptions >= 1 and r2.gen_adoptions >= 1
    finally:
        leaf.close()
        r2.close()
        r1.close()
        srv.close()


def test_plane_serve_traces_never_orphan():
    from d4pg_tpu.obs.trace import RECORDER

    rng = np.random.default_rng(12)
    store = WeightStore()
    srv = WeightPlaneServer(store)
    RECORDER.reset()
    RECORDER.enable(sample_rate=1.0)
    try:
        store.publish(_params(rng), step=1, to_host=False)
        c = WeightPlaneClient("127.0.0.1", srv.port)
        assert c.get_if_newer()[0] == 1          # commit terminal
        store.publish(_params(rng), step=2, to_host=False)
        # a delta frame against a base THIS client doesn't hold (a
        # desynced/misbehaving server) must be shed, not applied
        with srv._frame_lock:
            srv._refresh_locked()
            payload, _, _ = srv._frame_locked(0, 2, "f32", 1)
        c.version = 0
        assert c._accept(payload) is None        # base-miss -> shed
        assert c.counters["delta_base_misses"] == 1
        assert _pull_until(c, 2)[0] == 2         # full retry commits
        c.close()
        time.sleep(0.2)                          # teardown sweep settles
        assert RECORDER.orphans() == []
    finally:
        RECORDER.disable()
        RECORDER.reset()
        srv.close()


# ------------------------- satellite: v1 degradation + norm piggyback ----

def test_v1_norm_stats_survive_reconnect_and_degradation():
    """Norm-stats piggyback across a server restart: the client keeps
    the last stats while degraded and refreshes them on the new
    incarnation's first frame."""
    rng = np.random.default_rng(13)
    p = _params(rng)
    store = WeightStore()
    srv = WeightServer(store)
    port = srv.port
    store.publish(p, step=1, to_host=False,
                  norm_stats=(np.zeros(3), np.ones(3), 5.0))
    c = WeightClient("127.0.0.1", port, reconnect_interval=0.01)
    v, _ = c.get_if_newer(0)
    assert v == 1 and float(c.norm_stats[2]) == 5.0
    srv.close()
    assert c.get_if_newer(v) is None        # degraded: stale weights
    assert c.norm_stats is not None         # ...and stale stats KEPT
    # restarted server with refreshed stats on the same port
    deadline = time.monotonic() + 10.0
    while True:
        try:
            srv2 = WeightServer(store, port=port)
            break
        except OSError:
            assert time.monotonic() < deadline
            time.sleep(0.05)
    try:
        store.publish(p, step=2, to_host=False,
                      norm_stats=(np.ones(3), np.ones(3), 9.0))
        deadline = time.monotonic() + 5.0
        res = None
        while res is None and time.monotonic() < deadline:
            res = c.get_if_newer(v)
            time.sleep(0.02)
        assert res is not None and res[0] == 2
        assert float(c.norm_stats[2]) == 9.0
        c.close()
    finally:
        srv2.close()


def test_v1_down_timeout_raises_and_flight_events():
    from d4pg_tpu.obs.flight import RECORDER as FLIGHT

    rng = np.random.default_rng(14)
    store = WeightStore()
    srv = WeightServer(store)
    store.publish(_params(rng), step=1, to_host=False)
    c = WeightClient("127.0.0.1", srv.port, down_timeout=0.2,
                     reconnect_interval=0.01)
    assert c.get_if_newer(0)[0] == 1
    srv.close()
    FLIGHT.reset()
    assert c.get_if_newer(1) is None        # enters stale degradation
    kinds = [e["kind"] for e in FLIGHT.events()]
    assert "weight_stale_enter" in kinds
    time.sleep(0.25)
    with pytest.raises(ConnectionError, match="unreachable"):
        c.get_if_newer(1)                   # past down_timeout: raises
    c.close()
    FLIGHT.reset()


def test_v1_frame_memo_single_flight():
    """Satellite 1: N pullers of one version cost ONE flatten+savez."""
    rng = np.random.default_rng(15)
    store = WeightStore()
    srv = WeightServer(store)
    try:
        store.publish(_params(rng), step=1, to_host=False)
        clients = [WeightClient("127.0.0.1", srv.port) for _ in range(5)]
        for c in clients:
            assert c.get_if_newer(0)[0] == 1
        assert srv.frame_encodes == 1
        store.publish(_params(rng), step=2, to_host=False)
        for c in clients:
            assert c.get_if_newer(1)[0] == 2
            c.close()
        assert srv.frame_encodes == 2
    finally:
        srv.close()


# ------------------------------------------------------ chaos + gate ----

@pytest.mark.fleet
def test_weight_chaos_smoke():
    """A small end-to-end chaos run must pass all three gating oracles
    (plus the in-server delta/quant oracles) — the full-size version of
    this run is the bench artifact's weights block."""
    from d4pg_tpu.fleet.weight_chaos import WeightChaosConfig, run_weight_chaos

    from d4pg_tpu.obs.registry import REGISTRY

    crashes0 = REGISTRY.counter("threads.contained_crashes").value
    rep = run_weight_chaos(WeightChaosConfig(
        n_pullers=8, relay_depth=2, duration_s=2.5,
        learner_kills=1, relay_kills=1, seed=3))
    assert rep["learner_kills"] == 1 and rep["final_generation"] == 1
    # chaos is injected through narrow, expected-error paths; the broad
    # top-frame containments must never fire during a clean run
    assert REGISTRY.counter("threads.contained_crashes").value == crashes0
    assert rep["torn"]["accepted"] == 0
    assert rep["ledger"]["monotone"] is True
    assert rep["ledger"]["unpublished_accepted"] == 0
    assert rep["trace"]["orphans"] == 0
    assert rep["hierarchy_violations"] == 0
    assert rep["oracle"]["delta_failures"] == 0
    assert rep["oracle"]["quant_failures"] == 0
    assert rep["frames_delta"] > 0 and rep["frames_full"] > 0
    assert rep["snapshots_per_sec"] > 0


@pytest.mark.obs
def test_fleet_artifact_weights_schema():
    """The newest committed fleet artifact must carry the weights block:
    an N>=64 / relay-depth>=2 / >=1-learner-kill chaos run with
    snapshots/s, delta hit-rate, staleness percentiles, and all three
    oracles clean — a later PR that drops any of it fails tier-1 here."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "fleet", "fleet_*.json")))
    assert arts, "no committed fleet artifact"
    with open(arts[-1]) as f:
        artifact = json.load(f)
    w = artifact.get("weights")
    assert w, "newest fleet artifact lost its weights block"
    assert w["metric"] == "weight_chaos" and w["schema"] == 1
    assert w["n_pullers"] >= 64
    assert w["relay_depth"] >= 2
    assert w["learner_kills"] >= 1 and w["final_generation"] >= 1
    assert w["snapshots_per_sec"] > 0
    assert w["delta_hit_rate"] is not None and 0 < w["delta_hit_rate"] <= 1
    for pct in ("p50", "p95", "p99"):
        assert w["staleness_ms"][pct] is not None
    assert w["torn"]["injected"] >= 1 and w["torn"]["accepted"] == 0
    assert w["hierarchy_violations"] == 0
    assert w["trace"]["orphans"] == 0
    assert w["ledger"]["monotone"] is True
    assert w["ledger"]["unpublished_accepted"] == 0
    assert w["oracle"]["delta_failures"] == 0
    assert w["oracle"]["quant_failures"] == 0
