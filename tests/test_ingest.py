"""Ingest-plane tests: the batched block drain (replay/fused_buffer.py +
device_ring.block_write), the overlapped ≤1-H2D-per-chunk schedule
(learner/pipeline.IngestOverlap), the coalescing transport, and the
projection autotuner policy. The per-row drain the block path replaced is
kept as the bitwise oracle (``drain_per_row``)."""

import threading
import time

import jax
import numpy as np
import pytest

from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.io.profiling import TransferSentinel
from d4pg_tpu.learner import D4PGConfig, init_state
from d4pg_tpu.learner.fused import make_fused_chunk
from d4pg_tpu.learner.pipeline import IngestOverlap
from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay, HostStagingRing
from d4pg_tpu.replay.uniform import TransitionBatch

OBS, ACT = 5, 2


def _batch(rng, n, obs=OBS, act=ACT):
    return TransitionBatch(
        obs=rng.standard_normal((n, obs)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, act)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )


# ------------------------------------------------------- block drain ------

def test_block_drain_bitwise_equals_per_row(rng):
    """Same rows through the block path and the old per-row path must land
    the SAME bytes in the ring and the SAME priorities in the trees."""
    a = FusedDeviceReplay(96, OBS, ACT, block_rows=32)
    b = FusedDeviceReplay(96, OBS, ACT, block_rows=32)
    for n in (33, 64, 7, 100, 128, 5):  # partials, full blocks, > capacity
        batch = _batch(rng, n)
        a.add(batch)
        b.add(batch)
    assert a.drain() == b.drain_per_row()
    assert (a.size, a.head) == (b.size, b.head)
    for f in range(len(a.storage)):
        np.testing.assert_array_equal(
            np.asarray(a.storage[f][:96]), np.asarray(b.storage[f][:96]))
    np.testing.assert_array_equal(np.asarray(a.trees.sum_tree),
                                  np.asarray(b.trees.sum_tree))
    np.testing.assert_array_equal(np.asarray(a.trees.min_tree),
                                  np.asarray(b.trees.min_tree))


def test_block_drain_wraparound_at_capacity_boundary(rng):
    """Blocks that straddle the ring end must wrap exactly (the two-slice
    shadow-mirror path), matching a sequential host oracle."""
    cap = 50
    buf = FusedDeviceReplay(cap, OBS, ACT, prioritized=False, block_rows=16)
    host = np.zeros((cap, OBS), np.float32)
    head = size = 0
    for n in (10, 40, 23, cap, 9, 64):  # 64 > capacity: oldest overwritten
        batch = _batch(rng, n)
        buf.add(batch)
        buf.drain()
        for i in range(n):
            host[head] = batch.obs[i]
            head = (head + 1) % cap
            size = min(size + 1, cap)
    assert (buf.head, buf.size) == (head, size)
    np.testing.assert_array_equal(np.asarray(buf.storage.obs[:cap]), host)


def test_partial_final_block(rng):
    """A drain whose last block is partially filled lands exactly the
    valid rows; the masked scratch rows past ``n`` touch nothing."""
    buf = FusedDeviceReplay(64, OBS, ACT, block_rows=16)
    batch = _batch(rng, 21)  # one full block + 5-row partial
    buf.add(batch)
    assert buf.drain() == 21
    assert (buf.size, buf.head) == (21, 21)
    np.testing.assert_array_equal(np.asarray(buf.storage.obs[:21]), batch.obs)
    # untouched slots stay zero-initialized
    assert not np.asarray(buf.storage.obs[21:64]).any()
    cap = buf.trees.capacity
    leaves = np.asarray(buf.trees.sum_tree[cap:cap + 64])
    assert (leaves[:21] > 0).all() and not leaves[21:].any()


def test_interleaved_drain_and_fused_chunk_preserves_priorities(rng):
    """drain -> chunk -> drain: the chunk's TD write-backs survive the next
    block insert untouched; inserted slots get max_priority ** alpha."""
    config = D4PGConfig(obs_dim=OBS, act_dim=ACT, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(16, 16))
    buf = FusedDeviceReplay(128, OBS, ACT, alpha=0.6, block_rows=32)
    buf.add(_batch(rng, 64))
    buf.drain()
    fn = make_fused_chunk(config, k=2, batch_size=8, alpha=0.6, donate=False)
    state = init_state(config, jax.random.key(0))
    state, buf.trees, m = fn(state, buf.trees, buf.storage, buf.size)
    cap = buf.trees.capacity
    after_chunk = np.asarray(buf.trees.sum_tree[cap:cap + 128])
    head0 = buf.head
    buf.add(_batch(rng, 32))
    assert buf.drain() == 32
    leaves = np.asarray(buf.trees.sum_tree[cap:cap + 128])
    inserted = (head0 + np.arange(32)) % 128
    expected = float(np.asarray(buf.trees.max_priority)) ** 0.6
    np.testing.assert_allclose(leaves[inserted], expected, rtol=1e-6)
    untouched = np.setdiff1d(np.arange(128), inserted)
    np.testing.assert_array_equal(leaves[untouched], after_chunk[untouched])
    # and the chunk still samples fine afterwards
    state, buf.trees, m = fn(state, buf.trees, buf.storage, buf.size)
    assert np.isfinite(np.asarray(m["critic_loss"])).all()


def test_overlap_le_one_h2d_per_chunk(rng):
    """The shipped overlap schedule (commit -> dispatch -> stage) makes at
    most ONE explicit device_put per fused chunk."""
    config = D4PGConfig(obs_dim=OBS, act_dim=ACT, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(16, 16))
    buf = FusedDeviceReplay(256, OBS, ACT, alpha=0.6, block_rows=32)
    service = ReplayService(buf)
    ingest = IngestOverlap(service)
    fn = make_fused_chunk(config, k=2, batch_size=8, alpha=0.6, donate=True)
    state = init_state(config, jax.random.key(0))
    service.add(_batch(rng, 64))
    service.flush()
    ingest.flush()
    state, buf.trees, m = fn(state, buf.trees, buf.storage,
                             buf.size)  # warmup/compile
    n_chunks = 6
    with TransferSentinel() as t:
        for _ in range(n_chunks):
            ingest.commit()
            state, buf.trees, m = fn(state, buf.trees, buf.storage,
                                     buf.size)
            service.add(_batch(rng, 32))
            service.flush()
            ingest.stage()
    assert t.h2d <= n_chunks
    # every staged row is committed or still in flight (the initial 64
    # rode the pre-loop flush, which commits without staging)
    assert ingest.rows_staged == (ingest.rows_committed - 64) + 32
    ingest.flush()
    assert len(buf) == 64 + n_chunks * 32
    service.close()


def test_staging_ring_bounded_drops_oldest(rng):
    ring = HostStagingRing([((OBS,), np.float32), ((ACT,), np.float32),
                            ((), np.float32), ((OBS,), np.float32),
                            ((), np.float32), ((), np.float32)],
                           block_rows=8, n_blocks=2)  # bound: 16 rows
    first, second = _batch(rng, 10), _batch(rng, 10)
    ring.push(first)
    ring.push(second)  # 20 staged > 16: the 4 oldest drop
    assert len(ring) == 16
    frames = []
    while True:
        views, n = ring.frame()
        if n == 0:
            break
        frames.append(views.obs[:n].copy())
        ring.pop(n)
    got = np.concatenate(frames)
    want = np.concatenate([first.obs, second.obs])[-16:]
    np.testing.assert_array_equal(got, want)


# ------------------------------------------- sharded multi-ring staging ---

def test_multi_ring_merge_bitwise_equals_single_ring_and_per_row(rng):
    """The sharded staging plane (K private rings + ticket-ordered merge,
    ``staging.MultiRingStaging``) must land EXACTLY the bytes and
    priorities of the single-ring path AND the per-row oracle — the
    merge-commit reorders nothing at quiescence."""
    a = FusedDeviceReplay(96, OBS, ACT, block_rows=32)
    b = FusedDeviceReplay(96, OBS, ACT, block_rows=32, ingest_shards=2)
    c = FusedDeviceReplay(96, OBS, ACT, block_rows=32, ingest_shards=2)
    for rnd in range(4):  # several rounds; the ring wraps capacity
        for t, n in enumerate((13, 24, 7, 30, 9)):  # stays within staging
            batch = _batch(rng, n)
            ticket = rnd * 10 + t
            a.add(batch)
            b.add_sharded(batch, shard=t % 2, ticket=ticket)
            c.add_sharded(batch, shard=t % 2, ticket=ticket)
        assert a.drain() == b.drain() == c.drain_per_row()
    assert (a.size, a.head) == (b.size, b.head) == (c.size, c.head)
    for f in range(len(a.storage)):
        np.testing.assert_array_equal(
            np.asarray(a.storage[f][:96]), np.asarray(b.storage[f][:96]))
        np.testing.assert_array_equal(
            np.asarray(b.storage[f][:96]), np.asarray(c.storage[f][:96]))
    np.testing.assert_array_equal(np.asarray(a.trees.sum_tree),
                                  np.asarray(b.trees.sum_tree))
    np.testing.assert_array_equal(np.asarray(b.trees.sum_tree),
                                  np.asarray(c.trees.sum_tree))


def test_service_direct_stage_k2_bitwise_equals_k1(rng):
    """End to end through the service: a K=2 ``ReplayService`` over a
    sharded fused buffer engages the direct-stage fast path (workers
    copy rows into their own ring, no buffer lock) and must still land
    the identical device state as the K=1 plane."""
    from d4pg_tpu.obs.registry import REGISTRY

    admitted0 = REGISTRY.counter("ingest.rows_admitted").value
    committed0 = REGISTRY.counter("ingest.rows_committed").value
    f1 = FusedDeviceReplay(256, OBS, ACT, block_rows=32)
    f2 = FusedDeviceReplay(256, OBS, ACT, block_rows=32, ingest_shards=2)
    s1 = ReplayService(f1)
    s2 = ReplayService(f2, num_ingest_shards=2)
    assert s2._direct_stage, "direct-stage fast path must engage"
    batches = [_batch(rng, n) for n in (8, 3, 16, 5, 12, 7, 9, 4)]
    for i, b in enumerate(batches):
        s1.add(b)
        s2.add(b, shard=i % 2)
    s1.flush()
    s2.flush()
    assert s1.drain_device() == s2.drain_device()
    assert s1.env_steps == s2.env_steps
    for f in range(len(f1.storage)):
        np.testing.assert_array_equal(np.asarray(f1.storage[f][:64]),
                                      np.asarray(f2.storage[f][:64]))
    np.testing.assert_array_equal(np.asarray(f1.trees.sum_tree),
                                  np.asarray(f2.trees.sum_tree))
    # counter-total bitwise equivalence (the no-double-count contract):
    # the K=2 service ran every row through add_sharded's direct-stage
    # fast path (staged_rows == 64), but its row LEDGER must be
    # identical to K=1's — rows_committed counts each row once at the
    # ordered commit, never again at staging; naive "rows_in +
    # staged_rows" style aggregation would report the fast path twice.
    st1, st2 = s1.ingest_stats(), s2.ingest_stats()
    assert sum(p["staged_rows"] for p in st2["per_shard"]) == 64
    assert sum(p["staged_rows"] for p in st1["per_shard"]) == 0
    assert st1["rows_committed"] == st2["rows_committed"] == 64
    assert sum(p["rows_in"] for p in st1["per_shard"]) \
        == sum(p["rows_in"] for p in st2["per_shard"]) == 64
    # ...and the process-wide registry ledger agrees: exactly 2x64 rows
    # admitted AND committed across the two services, no fast-path echo
    assert REGISTRY.counter("ingest.rows_admitted").value \
        - admitted0 == 128
    assert REGISTRY.counter("ingest.rows_committed").value \
        - committed0 == 128
    s1.close()
    s2.close()


# -------------------------------------------- transport coalescing --------

def test_coalescing_sender_batches_frames(rng):
    from d4pg_tpu.distributed.transport import (
        CoalescingSender, TransitionReceiver)

    frames: list[tuple[TransitionBatch, bool]] = []
    got = threading.Event()

    def on_batch(batch, actor_id, count):
        frames.append((batch, count))
        got.set()

    recv = TransitionReceiver(on_batch)
    sender = CoalescingSender("127.0.0.1", recv.port, actor_id="c0",
                              min_block=64, max_block=256,
                              flush_interval=60.0)
    sent = [_batch(rng, 10) for _ in range(8)]
    try:
        for b in sent:
            sender.send(b)  # 80 rows: one 64-row flush, 16 left pending
        sender.flush()
        deadline = time.monotonic() + 5.0
        while sum(f[0].obs.shape[0] for f in frames) < 80:
            assert time.monotonic() < deadline, "coalesced rows not delivered"
            time.sleep(0.01)
    finally:
        sender.close()
        recv.close()
    # 8 sends rode in ≤ 3 wire frames (coalesced), rows in order
    assert 1 <= len(frames) <= 3
    got_rows = np.concatenate([np.asarray(f[0].obs) for f in frames])
    np.testing.assert_array_equal(
        got_rows, np.concatenate([b.obs for b in sent]))


def test_coalescing_sender_splits_count_flag(rng):
    """HER relabels (count_env_steps=False) must not merge into a frame
    with real env rows — the flag is frame-granular on the wire."""
    from d4pg_tpu.distributed.transport import (
        CoalescingSender, TransitionReceiver)

    frames = []

    def on_batch(batch, actor_id, count):
        frames.append((batch.obs.shape[0], count))

    recv = TransitionReceiver(on_batch)
    sender = CoalescingSender("127.0.0.1", recv.port, min_block=256,
                              max_block=256, flush_interval=60.0)
    try:
        sender.send(_batch(rng, 5), count_env_steps=True)
        sender.send(_batch(rng, 3), count_env_steps=False)  # forces a flush
        sender.send(_batch(rng, 2), count_env_steps=False)
        sender.flush()
        deadline = time.monotonic() + 5.0
        while sum(n for n, _ in frames) < 10:
            assert time.monotonic() < deadline
            time.sleep(0.01)
    finally:
        sender.close()
        recv.close()
    assert frames == [(5, True), (5, False)]


def test_replay_service_coalesced_ingest_counts_env_steps(rng):
    buf = FusedDeviceReplay(256, OBS, ACT, block_rows=32)
    service = ReplayService(buf)
    for i in range(10):
        service.add(_batch(rng, 7), count_env_steps=(i % 2 == 0))
    service.flush()
    assert service.env_steps == 5 * 7  # only the counted half
    assert len(service) == 70
    service.close()


# ------------------------------------------------- projection autotune ----

def test_autotune_explicit_override_passes_through():
    from d4pg_tpu.ops.autotune import select_projection

    r = select_projection("pallas_ce", batch_size=64, v_min=0, v_max=1,
                          n_atoms=11)
    assert r.selected == "pallas_ce" and "override" in r.reason


def test_autotune_static_policy_off_tpu_and_on_mesh():
    from d4pg_tpu.ops.autotune import select_projection

    r = select_projection("auto", batch_size=64, v_min=0, v_max=1,
                          n_atoms=11)
    assert r.selected == "einsum"  # CPU backend: nothing real to time
    assert r.timings_ms is None
    r = select_projection("auto", batch_size=64, v_min=0, v_max=1,
                          n_atoms=11, mesh=True)
    assert r.selected == "einsum" and "GSPMD" in r.reason


def test_autotune_measured_path_agrees_with_loss_core():
    """The timed micro-kernels themselves must run and pick SOME variant
    (exercised here on CPU where pallas runs interpreted — slow but
    correct; the policy path never does this, it is forced for
    coverage)."""
    from d4pg_tpu.ops.autotune import autotune_projection

    r = autotune_projection(batch_size=8, v_min=0, v_max=1, n_atoms=11,
                            repeats=1, iters=1)
    assert r.selected in ("einsum", "pallas", "pallas_ce")
    assert isinstance(r.timings_ms["einsum"], float)


def test_config_auto_resolves_before_learner_config():
    from d4pg_tpu.config import ExperimentConfig

    cfg = ExperimentConfig(env="point", v_min=-10.0, v_max=10.0)
    assert cfg.projection == "auto"
    config = cfg.learner_config(OBS, ACT)
    assert config.projection in ("einsum", "pallas", "pallas_ce")
