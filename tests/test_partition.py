"""Partition-rule engine: the contracts ISSUE 14 pins.

Scalar leaves are never partitioned, first match wins, unmatched keys
fail loudly with the resolved table, and shard->gather round-trips
bitwise over the 8 virtual devices the suite runs on. These are the
semantics every sharded jit in the framework now inherits from
``parallel/partition.py``, so they get direct coverage rather than
riding along inside the mesh integration tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.parallel import MeshSpec, make_mesh, partition, replica_mesh
from d4pg_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, REPLICA_AXIS

PS = partition.PS

pytestmark = pytest.mark.mesh


def _tree():
    return {
        "encoder": {
            "conv1": {"kernel": np.ones((3, 3, 4, 8), np.float32),
                      "bias": np.ones((8,), np.float32)},
        },
        "fc1": {"kernel": np.ones((8, 16), np.float32),
                "bias": np.ones((16,), np.float32)},
        "step": np.zeros((), np.int32),
        "scale": np.ones((1,), np.float32),
    }


class TestMatching:
    def test_scalar_leaves_never_partitioned(self):
        # Even a catch-all rule that shards everything cannot touch
        # ndim-0 or size-1 leaves: step counters and Adam `count` must
        # stay replicated or the update math breaks.
        rules = ((r".*", PS(DATA_AXIS)),)
        specs = partition.match_partition_rules(rules, _tree())
        assert specs["step"] == PS()
        assert specs["scale"] == PS()
        assert specs["fc1"]["kernel"] == PS(DATA_AXIS)

    def test_first_match_wins(self):
        rules = (
            (r"encoder/conv\d+/kernel", PS(None, None, None, MODEL_AXIS)),
            (r"kernel", PS(DATA_AXIS)),  # would also match conv kernels
            (r".*", PS()),
        )
        specs = partition.match_partition_rules(rules, _tree())
        assert specs["encoder"]["conv1"]["kernel"] == PS(
            None, None, None, MODEL_AXIS)
        assert specs["fc1"]["kernel"] == PS(DATA_AXIS)
        assert specs["fc1"]["bias"] == PS()

    def test_unmatched_key_fails_loudly(self):
        rules = ((r"kernel", PS()),)  # biases match nothing
        with pytest.raises(ValueError) as e:
            partition.match_partition_rules(rules, _tree())
        msg = str(e.value)
        assert "bias" in msg           # the offending leaf's path
        assert "kernel" in msg         # the resolved table is printed

    def test_production_rules_are_total(self):
        # D4PG_RULES must resolve every leaf of a real pixel state —
        # the catch-all guarantees totality, the conv rules claim the
        # model axis.
        from d4pg_tpu.config import ExperimentConfig

        cfg = ExperimentConfig(
            env="pixel-point", share_encoder=True, frame_stack=3,
            augment="shift", augment_pad=1, encoder_width=8,
            batch_size=16, n_atoms=11, hidden=(16, 16),
        ).resolve().learner_config(obs_dim=(8, 8, 9), act_dim=2)
        specs = partition.state_specs(cfg)
        flat: list[tuple[str, PS]] = []
        partition.named_tree_map(
            lambda n, s: flat.append((n, s)) or s, specs)
        by_name = dict(flat)
        assert by_name["actor_params/params/encoder/conv1/kernel"] == PS(
            None, None, None, MODEL_AXIS)
        assert by_name["actor_params/params/encoder/conv1/bias"] == PS(
            MODEL_AXIS)
        # Adam moments mirror the param placement (re.search finds the
        # param path inside the optimizer path).
        assert by_name[
            "actor_opt_state/0/mu/params/encoder/conv1/kernel"] == PS(
            None, None, None, MODEL_AXIS)
        assert by_name["step"] == PS()
        assert by_name["key"] == PS()


class TestNaming:
    def test_named_flat_roundtrip(self):
        params = _tree()
        flat = partition.named_flat(
            {k: v for k, v in params.items() if isinstance(v, dict)})
        assert "encoder/conv1/kernel" in flat
        back = partition.named_unflat(flat)
        assert back["encoder"]["conv1"]["kernel"].shape == (3, 3, 4, 8)

    def test_named_tree_map_handles_namedtuples_and_none(self):
        import collections

        Pair = collections.namedtuple("Pair", ["a", "b"])
        tree = Pair(a={"x": np.ones(3)}, b=(None, [np.zeros(2)]))
        names = partition.tree_names(tree)
        assert names == ["a/x", "b/1/0"]


class TestPlacement:
    def test_shard_gather_bitwise_roundtrip(self):
        # 8 virtual devices (conftest). Random payloads survive a
        # shard->gather cycle bit-for-bit.
        mesh = make_mesh(MeshSpec(data_parallel=4, model_parallel=2))
        rng = np.random.default_rng(0)
        tree = {
            "encoder": {"conv1": {
                "kernel": rng.standard_normal((3, 3, 4, 8)).astype(np.float32),
                "bias": rng.standard_normal((8,)).astype(np.float32)}},
            "fc1": {"kernel": rng.standard_normal((16, 32)).astype(np.float32)},
        }
        shardings = partition.shardings_for(mesh, tree)
        shard_fns, gather_fns = partition.make_shard_and_gather_fns(shardings)
        placed = jax.tree_util.tree_map(lambda f, x: f(x), shard_fns, tree)
        back = jax.tree_util.tree_map(lambda f, x: f(x), gather_fns, placed)
        jax.tree_util.tree_map(np.testing.assert_array_equal, tree, back)
        # and the conv kernel actually landed on the model axis
        k = placed["encoder"]["conv1"]["kernel"]
        assert k.sharding.spec == PS(None, None, None, MODEL_AXIS)

    def test_replica_stack_shardings(self):
        mesh = replica_mesh(2)
        tree = {"fc1": {"kernel": np.ones((4, 4), np.float32)},
                "step": np.zeros((), np.int32)}
        stacked = jax.tree_util.tree_map(
            lambda x: jnp.stack([x, x]), tree)
        placed = jax.device_put(
            stacked, partition.replica_stack_shardings(mesh, tree))
        assert placed["fc1"]["kernel"].sharding.spec == PS(REPLICA_AXIS)
        # scalars become [N]-vectors split over replica — still one
        # value per replica, never partitioned within a replica
        assert placed["step"].sharding.spec == PS(REPLICA_AXIS)

    def test_state_shardings_match_replicate_state(self):
        from d4pg_tpu.config import ExperimentConfig
        from d4pg_tpu.learner.state import init_state
        from d4pg_tpu.parallel import replicate_state

        cfg = ExperimentConfig(
            batch_size=16, n_atoms=11, hidden=(8, 8),
        ).resolve().learner_config(obs_dim=3, act_dim=2)
        st = init_state(cfg, jax.random.key(0))
        mesh = make_mesh(MeshSpec(data_parallel=4, model_parallel=2))
        placed = replicate_state(st, mesh)
        want = partition.state_shardings(cfg, mesh)
        assert placed.actor_params["params"]["fc1"][
            "kernel"].sharding == want.actor_params["params"]["fc1"]["kernel"]
