"""Pallas kernel tests (interpret mode on the CPU backend).

The einsum projection (core/distribution.py, itself oracle-tested against
the reference's per-atom loop in test_projection.py) is the oracle here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.core.distribution import CategoricalSupport, categorical_projection
from d4pg_tpu.ops.projection import projection_pallas


def _rand_dist(rng, b, a):
    p = rng.random((b, a))
    return (p / p.sum(-1, keepdims=True)).astype(np.float32)


@pytest.mark.parametrize("batch", [1, 64, 100])
def test_pallas_projection_matches_einsum(rng, batch):
    sup = CategoricalSupport(-10.0, 0.0, 51)
    p = jnp.asarray(_rand_dist(rng, batch, 51))
    r = jnp.asarray(rng.uniform(-12, 2, batch), jnp.float32)  # incl. out-of-range
    done = rng.random(batch) < 0.3
    d = jnp.asarray((0.99**3) * ~done, jnp.float32)
    ref = categorical_projection(sup, p, r, d)
    out = projection_pallas(sup, p, r, d, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, atol=1e-5)


def test_pallas_projection_terminal_delta(rng):
    """Terminal transitions (discount 0) collapse to a delta at clip(r)."""
    sup = CategoricalSupport(0.0, 10.0, 11)
    p = jnp.asarray(_rand_dist(rng, 8, 11))
    r = jnp.asarray(np.full(8, 5.0), jnp.float32)
    d = jnp.zeros(8, jnp.float32)
    out = np.asarray(projection_pallas(sup, p, r, d, True))
    want = np.zeros((8, 11), np.float32)
    want[:, 5] = 1.0  # atom exactly at 5.0
    np.testing.assert_allclose(out, want, atol=1e-6)


def test_pallas_ce_forward_matches_einsum_ce(rng):
    """Fused projection+cross-entropy == einsum projection then CE."""
    from d4pg_tpu.core.losses import cross_entropy_per_sample
    from d4pg_tpu.ops.projection_ce import projection_ce_pallas

    sup = CategoricalSupport(-10.0, 0.0, 51)
    for batch in (1, 64, 100):
        p = jnp.asarray(_rand_dist(rng, batch, 51))
        q = jnp.asarray(_rand_dist(rng, batch, 51))
        r = jnp.asarray(rng.uniform(-12, 2, batch), jnp.float32)
        done = rng.random(batch) < 0.3
        d = jnp.asarray((0.99**3) * ~done, jnp.float32)
        ref = cross_entropy_per_sample(categorical_projection(sup, p, r, d), q)
        out = projection_ce_pallas(sup, p, r, d, q, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4, rtol=1e-5)


def test_pallas_ce_gradient_matches_stop_gradient_reference(rng):
    """The custom VJP must equal autodiff of CE(stop_gradient(proj), q) —
    the exact gradient convention of learner/update.py's critic loss."""
    from d4pg_tpu.core.losses import cross_entropy_per_sample
    from d4pg_tpu.ops.projection_ce import projection_ce_pallas

    sup = CategoricalSupport(-5.0, 0.0, 31)
    batch = 64
    p = jnp.asarray(_rand_dist(rng, batch, 31))
    q = jnp.asarray(_rand_dist(rng, batch, 31))
    r = jnp.asarray(rng.uniform(-6, 1, batch), jnp.float32)
    d = jnp.asarray(np.full(batch, 0.99), jnp.float32)
    w = jnp.asarray(rng.random(batch), jnp.float32)  # IS-weighted mean

    def ref_loss(q_):
        proj = jax.lax.stop_gradient(categorical_projection(sup, p, r, d))
        return jnp.mean(w * cross_entropy_per_sample(proj, q_))

    def fused_loss(q_):
        return jnp.mean(w * projection_ce_pallas(sup, p, r, d, q_, True))

    g_ref = jax.grad(ref_loss)(q)
    g_fused = jax.grad(fused_loss)(q)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               atol=1e-5, rtol=1e-5)
    # and no gradient leaks through the Bellman operands
    gp = jax.grad(lambda p_: jnp.sum(
        projection_ce_pallas(sup, p_, r, d, q, True)))(p)
    np.testing.assert_array_equal(np.asarray(gp), 0.0)


def test_update_step_pallas_ce_matches_einsum(rng):
    """One full update with --projection pallas_ce equals the einsum path
    (same batch, same seed) to float tolerance."""
    import warnings

    from d4pg_tpu.learner import D4PGConfig, init_state, make_update
    from d4pg_tpu.replay.uniform import TransitionBatch

    b, obs_dim, act_dim = 64, 6, 2
    batch = TransitionBatch(
        obs=rng.standard_normal((b, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (b, act_dim)).astype(np.float32),
        reward=rng.standard_normal(b).astype(np.float32),
        next_obs=rng.standard_normal((b, obs_dim)).astype(np.float32),
        done=np.zeros(b, np.float32),
        discount=np.full(b, 0.99, np.float32),
    )
    weights = np.ones(b, np.float32)
    outs = {}
    for proj in ("einsum", "pallas_ce"):
        config = D4PGConfig(obs_dim=obs_dim, act_dim=act_dim, v_min=-5.0,
                            v_max=0.0, n_atoms=11, hidden=(16, 16),
                            projection=proj)
        state = init_state(config, jax.random.key(0))
        update = make_update(config, donate=False, use_is_weights=True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # interpret-mode warning on CPU
            state, metrics = update(state, batch, weights)
        outs[proj] = (state, metrics)
    np.testing.assert_allclose(
        float(outs["pallas_ce"][1]["critic_loss"]),
        float(outs["einsum"][1]["critic_loss"]), rtol=1e-5)
    for a, b_ in zip(jax.tree_util.tree_leaves(outs["einsum"][0].critic_params),
                     jax.tree_util.tree_leaves(outs["pallas_ce"][0].critic_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-4)


def test_random_shift_properties(rng):
    """DrQ random shift: every output row is a valid crop of its padded
    input, dtype/shape preserved, deterministic per key, varied across
    the batch."""
    from d4pg_tpu.ops.augment import random_shift

    b, h, w, c, pad = 16, 8, 8, 3, 2
    imgs = rng.integers(0, 255, (b, h, w, c), dtype=np.uint8)
    out = np.asarray(random_shift(jax.random.key(0), jnp.asarray(imgs), pad))
    assert out.shape == imgs.shape and out.dtype == np.uint8
    # each row must equal one of the (2*pad+1)^2 crops of its padded self
    offsets_seen = set()
    for i in range(b):
        padded = np.pad(imgs[i], ((pad, pad), (pad, pad), (0, 0)),
                        mode="edge")
        found = None
        for dy in range(2 * pad + 1):
            for dx in range(2 * pad + 1):
                if np.array_equal(out[i], padded[dy:dy + h, dx:dx + w]):
                    found = (dy, dx)
                    break
            if found:
                break
        assert found is not None, f"row {i} is not a crop of its input"
        offsets_seen.add(found)
    assert len(offsets_seen) > 1  # shifts actually vary across the batch
    # deterministic per key
    out2 = np.asarray(random_shift(jax.random.key(0), jnp.asarray(imgs), pad))
    np.testing.assert_array_equal(out, out2)
    # pad=0 is the identity
    np.testing.assert_array_equal(
        np.asarray(random_shift(jax.random.key(1), jnp.asarray(imgs), 0)),
        imgs)


def test_update_step_with_shift_augmentation(rng):
    """--augment shift runs through the full jit'd pixel update: finite
    losses, and the augmented update diverges from the unaugmented one
    (the views differ) while non-pixel configs reject the flag."""
    from d4pg_tpu.learner import D4PGConfig, init_state, make_update
    from d4pg_tpu.replay.uniform import TransitionBatch

    b, hw, ch = 8, 12, 3
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (b, hw, hw, ch), dtype=np.uint8),
        action=rng.uniform(-1, 1, (b, 2)).astype(np.float32),
        reward=rng.standard_normal(b).astype(np.float32),
        next_obs=rng.integers(0, 255, (b, hw, hw, ch), dtype=np.uint8),
        done=np.zeros(b, np.float32),
        discount=np.full(b, 0.99, np.float32),
    )
    losses = {}
    for aug in ("none", "shift"):
        config = D4PGConfig(
            obs_dim=hw * hw * ch, act_dim=2, pixels=True,
            obs_shape=(hw, hw, ch), encoder_channels=(8,) * 4,
            v_min=-5.0, v_max=0.0, n_atoms=11, hidden=(16, 16),
            augment=aug)
        state = init_state(config, jax.random.key(0))
        update = make_update(config, donate=False, use_is_weights=False)
        state, metrics = update(state, batch)
        assert np.isfinite(float(metrics["critic_loss"]))
        losses[aug] = float(metrics["critic_loss"])
    assert losses["none"] != losses["shift"]
    with pytest.raises(ValueError, match="pixel"):
        D4PGConfig(obs_dim=6, act_dim=2, augment="shift")
