"""Pallas kernel tests (interpret mode on the CPU backend).

The einsum projection (core/distribution.py, itself oracle-tested against
the reference's per-atom loop in test_projection.py) is the oracle here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.core.distribution import CategoricalSupport, categorical_projection
from d4pg_tpu.ops.projection import projection_pallas


def _rand_dist(rng, b, a):
    p = rng.random((b, a))
    return (p / p.sum(-1, keepdims=True)).astype(np.float32)


@pytest.mark.parametrize("batch", [1, 64, 100])
def test_pallas_projection_matches_einsum(rng, batch):
    sup = CategoricalSupport(-10.0, 0.0, 51)
    p = jnp.asarray(_rand_dist(rng, batch, 51))
    r = jnp.asarray(rng.uniform(-12, 2, batch), jnp.float32)  # incl. out-of-range
    done = rng.random(batch) < 0.3
    d = jnp.asarray((0.99**3) * ~done, jnp.float32)
    ref = categorical_projection(sup, p, r, d)
    out = projection_pallas(sup, p, r, d, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, atol=1e-5)


def test_pallas_projection_terminal_delta(rng):
    """Terminal transitions (discount 0) collapse to a delta at clip(r)."""
    sup = CategoricalSupport(0.0, 10.0, 11)
    p = jnp.asarray(_rand_dist(rng, 8, 11))
    r = jnp.asarray(np.full(8, 5.0), jnp.float32)
    d = jnp.zeros(8, jnp.float32)
    out = np.asarray(projection_pallas(sup, p, r, d, True))
    want = np.zeros((8, 11), np.float32)
    want[:, 5] = 1.0  # atom exactly at 5.0
    np.testing.assert_allclose(out, want, atol=1e-6)
