"""Replay storage, segment trees, PER, n-step folding, schedules."""

import numpy as np
import pytest

from d4pg_tpu.replay import (
    LinearSchedule,
    MinTree,
    NStepFolder,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    SumTree,
    TransitionBatch,
)


def make_batch(n, obs_dim=3, act_dim=2, seed=0):
    rng = np.random.default_rng(seed)
    return TransitionBatch(
        obs=rng.normal(size=(n, obs_dim)).astype(np.float32),
        action=rng.normal(size=(n, act_dim)).astype(np.float32),
        reward=rng.normal(size=n).astype(np.float32),
        next_obs=rng.normal(size=(n, obs_dim)).astype(np.float32),
        done=(rng.random(n) < 0.2).astype(np.float32),
        discount=rng.random(n).astype(np.float32),
    )


# ---------------- schedules ----------------


def test_linear_schedule_matches_reference_semantics():
    # beta 0.4 -> 1.0 over 100k (ddpg.py:82-86), pure function of t
    s = LinearSchedule(100_000, final_p=1.0, initial_p=0.4)
    assert s.value(0) == pytest.approx(0.4)
    assert s.value(50_000) == pytest.approx(0.7)
    assert s.value(100_000) == pytest.approx(1.0)
    assert s.value(1_000_000) == pytest.approx(1.0)  # clamped


# ---------------- uniform ring ----------------


def test_ring_wraparound_and_sampling():
    buf = ReplayBuffer(capacity=8, obs_dim=3, act_dim=2)
    b1 = make_batch(6, seed=1)
    idx = buf.add(b1)
    assert list(idx) == list(range(6))
    assert len(buf) == 6
    b2 = make_batch(5, seed=2)
    idx2 = buf.add(b2)
    assert list(idx2) == [6, 7, 0, 1, 2]  # wraps
    assert len(buf) == 8
    # overwritten slots hold the new data
    np.testing.assert_array_equal(buf.obs[0], b2.obs[2])
    s = buf.sample(16)
    assert s.obs.shape == (16, 3)
    s2 = buf.sample(8, replace=False)
    assert len(np.unique(s2.reward)) == 8 or len(buf) < 8


def test_empty_sample_raises():
    buf = ReplayBuffer(4, 1, 1)
    with pytest.raises(ValueError):
        buf.sample(2)


# ---------------- segment trees ----------------


def test_sum_tree_matches_numpy(rng):
    t = SumTree(100)  # rounds to 128
    vals = rng.random(100)
    t.set(np.arange(100), vals)
    assert t.sum() == pytest.approx(vals.sum())
    # partial update
    upd_idx = rng.integers(0, 100, 17)
    upd_val = rng.random(17)
    t.set(upd_idx, upd_val)
    vals2 = vals.copy()
    vals2[upd_idx] = upd_val  # note: duplicate idx -> last write wins, same as tree
    # rebuild expected with duplicates resolved in order
    for i, v in zip(upd_idx, upd_val):
        vals[i] = v
    assert t.sum() == pytest.approx(vals.sum())
    np.testing.assert_allclose(t.get(np.arange(100)), vals)


def test_find_prefixsum_inverse_cdf(rng):
    vals = rng.random(64)
    t = SumTree(64)
    t.set(np.arange(64), vals)
    cdf = np.cumsum(vals)
    queries = rng.uniform(0, cdf[-1] - 1e-9, 1000)
    got = t.find_prefixsum(queries)
    want = np.searchsorted(cdf, queries, side="right")
    np.testing.assert_array_equal(got, want)


def test_find_prefixsum_with_zeros():
    t = SumTree(8)
    t.set(np.arange(8), np.array([0.0, 2.0, 0.0, 0.0, 1.0, 0.0, 0.0, 0.0]))
    got = t.find_prefixsum(np.array([0.0, 1.9, 2.0, 2.5]))
    np.testing.assert_array_equal(got, [1, 1, 4, 4])


def test_min_tree(rng):
    vals = rng.random(33) + 0.1
    t = MinTree(33)
    t.set(np.arange(33), vals)
    assert t.min() == pytest.approx(vals.min())
    t.set(np.array([7]), np.array([0.01]))
    assert t.min() == pytest.approx(0.01)


# ---------------- PER ----------------


def test_per_proportional_sampling_statistics():
    buf = PrioritizedReplayBuffer(64, 1, 1, alpha=1.0, seed=3)
    n = 32
    batch = make_batch(n, 1, 1)
    idx = buf.add(batch)
    # give item 5 priority 9x the others -> expect ~9x sample frequency
    pri = np.ones(n)
    pri[5] = 9.0
    buf.update_priorities(idx, pri)
    counts = np.zeros(n)
    for _ in range(300):
        i = buf.sample_idx(64)
        counts += np.bincount(i, minlength=n)
    freq = counts / counts.sum()
    expected_5 = 9.0 / (n - 1 + 9.0)
    assert freq[5] == pytest.approx(expected_5, rel=0.15)


def test_per_is_weights_match_formula():
    buf = PrioritizedReplayBuffer(16, 1, 1, alpha=0.6)
    idx = buf.add(make_batch(8, 1, 1))
    pri = np.arange(1.0, 9.0)
    buf.update_priorities(idx, pri)
    beta = 0.5
    w = buf.is_weights(idx, beta)
    p = pri**0.6
    probs = p / p.sum()
    want = (probs * 8) ** (-beta)
    want = want / ((probs.min() * 8) ** (-beta))
    np.testing.assert_allclose(w, want.astype(np.float32), rtol=1e-5)
    assert w.max() == pytest.approx(1.0)


def test_per_is_weights_global_base_override():
    """Multi-host sharded replay normalizes all shards by one global
    ``z = p_min_frac * N`` (allgather-min of the local bases): the
    override must rescale weights by (z_local / z_global)^beta relative
    to local normalization."""
    buf = PrioritizedReplayBuffer(16, 1, 1, alpha=0.6)
    idx = buf.add(make_batch(8, 1, 1))
    buf.update_priorities(idx, np.arange(1.0, 9.0))
    beta = 0.5
    z_local = buf.weight_base()
    w_local = buf.is_weights(idx, beta)
    z_global = z_local / 4.0  # another shard holds a smaller min priority
    w_global = buf.is_weights(idx, beta, weight_base=z_global)
    np.testing.assert_allclose(
        w_global, w_local * (z_global / z_local) ** beta, rtol=1e-5)
    # and the override is what sample()/sample_chunk() thread through
    _, w_s, i_s = buf.sample(8, beta=beta, weight_base=z_global)
    np.testing.assert_allclose(
        w_s, buf.is_weights(i_s, beta, weight_base=z_global), rtol=1e-6)


def test_per_new_items_get_max_priority():
    buf = PrioritizedReplayBuffer(16, 1, 1, alpha=1.0)
    i1 = buf.add(make_batch(2, 1, 1))
    buf.update_priorities(i1, np.array([10.0, 1.0]))
    i2 = buf.add(make_batch(1, 1, 1, seed=9))
    # new item inherits max_priority (=10)
    assert buf._trees.get(i2)[0] == pytest.approx(10.0)


def test_per_sample_roundtrip():
    buf = PrioritizedReplayBuffer(32, 2, 1)
    buf.add(make_batch(20, 2, 1))
    batch, w, idx = buf.sample(10, beta=0.4)
    assert batch.obs.shape == (10, 2)
    assert w.shape == (10,) and idx.shape == (10,)
    assert (idx < 20).all()
    buf.update_priorities(idx, np.abs(np.random.default_rng(0).normal(size=10)) + 1e-6)


# ---------------- n-step ----------------


def test_nstep_one_step_passthrough():
    f = NStepFolder(n=1, gamma=0.9, num_envs=1, obs_dim=1, act_dim=1)
    out = f.step(
        obs=np.array([[1.0]]),
        action=np.array([[0.5]]),
        reward=np.array([2.0]),
        next_obs=np.array([[1.5]]),
        done=np.array([False]),
    )
    assert out.reward[0] == pytest.approx(2.0)
    assert out.discount[0] == pytest.approx(0.9)
    assert out.done[0] == 0.0


def test_nstep_fold_and_terminal_flush():
    gamma = 0.5
    f = NStepFolder(n=3, gamma=gamma, num_envs=1, obs_dim=1, act_dim=1)

    def step(t, r, done=False):
        return f.step(
            obs=np.array([[float(t)]]),
            action=np.array([[0.0]]),
            reward=np.array([r]),
            next_obs=np.array([[float(t + 1)]]),
            done=np.array([done]),
        )

    assert step(0, 1.0).reward.size == 0  # window filling
    assert step(1, 2.0).reward.size == 0
    out = step(2, 4.0)  # full window: fold r0 + g r1 + g^2 r2
    assert out.reward[0] == pytest.approx(1.0 + 0.5 * 2.0 + 0.25 * 4.0)
    assert out.obs[0, 0] == 0.0 and out.next_obs[0, 0] == 3.0
    assert out.discount[0] == pytest.approx(gamma**3)
    # terminal: flush remaining tail (entries t=1,2 pending + new t=3)
    out = step(3, 8.0, done=True)
    assert out.reward.shape == (3,)
    np.testing.assert_allclose(
        out.reward, [2.0 + 0.5 * 4 + 0.25 * 8, 4 + 0.5 * 8, 8.0]
    )
    assert (out.done == 1.0).all() and (out.discount == 0.0).all()
    # all flushed transitions bootstrap against the terminal next_obs
    assert (out.next_obs == 4.0).all()
    # window resets after terminal
    assert step(0, 1.0).reward.size == 0


def test_nstep_truncation_bootstraps():
    gamma = 0.9
    f = NStepFolder(n=2, gamma=gamma, num_envs=1, obs_dim=1, act_dim=1)
    f.step(
        obs=np.array([[0.0]]), action=np.array([[0.0]]), reward=np.array([1.0]),
        next_obs=np.array([[1.0]]), done=np.array([False]),
    )
    out = f.step(
        obs=np.array([[1.0]]), action=np.array([[0.0]]), reward=np.array([3.0]),
        next_obs=np.array([[2.0]]), done=np.array([False]),
        truncated=np.array([True]),
    )
    # full-window emission AND truncation flush of the remaining tail
    assert out.reward.shape == (2,)
    assert out.reward[0] == pytest.approx(1.0 + gamma * 3.0)
    assert out.discount[0] == pytest.approx(gamma**2)
    assert out.done[0] == 0.0  # truncation is not termination
    assert out.reward[1] == pytest.approx(3.0)
    assert out.discount[1] == pytest.approx(gamma)


def test_nstep_multi_env_independent():
    f = NStepFolder(n=2, gamma=1.0, num_envs=2, obs_dim=1, act_dim=1)
    f.step(
        obs=np.zeros((2, 1)), action=np.zeros((2, 1)),
        reward=np.array([1.0, 10.0]), next_obs=np.ones((2, 1)),
        done=np.array([False, False]),
    )
    out = f.step(
        obs=np.ones((2, 1)), action=np.zeros((2, 1)),
        reward=np.array([2.0, 20.0]), next_obs=np.full((2, 1), 2.0),
        done=np.array([False, True]),
    )
    # env0: folded 2-step (1+2); env1: terminal flush of both entries
    rewards = sorted(out.reward.tolist())
    assert rewards == pytest.approx([3.0, 20.0, 30.0])


def test_nstep_reset_drops_pending_windows():
    """reset() must discard partial windows so nothing is stitched across a
    hard env reset: after reset, the first n-1 steps emit nothing and the
    first emitted transition starts from post-reset data."""
    f = NStepFolder(n=3, gamma=0.9, num_envs=1, obs_dim=1, act_dim=1)
    # two steps of a doomed episode (window partially filled)
    for x in (1.0, 2.0):
        out = f.step(np.array([[x]]), np.array([[x]]), np.array([x]),
                     np.array([[x + 0.5]]), np.array([False]))
        assert out.obs.shape[0] == 0
    f.reset()
    # refill from scratch: exactly n steps until the first emission
    for x in (10.0, 20.0):
        out = f.step(np.array([[x]]), np.array([[x]]), np.array([x]),
                     np.array([[x + 0.5]]), np.array([False]))
        assert out.obs.shape[0] == 0
    out = f.step(np.array([[30.0]]), np.array([[30.0]]), np.array([30.0]),
                 np.array([[30.5]]), np.array([False]))
    assert out.obs.shape[0] == 1
    assert out.obs[0, 0] == pytest.approx(10.0)  # post-reset head, not 1.0
    assert out.reward[0] == pytest.approx(10.0 + 0.9 * 20.0 + 0.81 * 30.0)


def test_per_generation_guard_drops_stale_priority_updates():
    """ADVICE r1: a slot overwritten between sample and write-back must not
    receive the old transition's priority."""
    from d4pg_tpu.replay import PrioritizedReplayBuffer
    from d4pg_tpu.replay.uniform import TransitionBatch

    def batch(n, val):
        return TransitionBatch(
            obs=np.full((n, 2), val, np.float32),
            action=np.zeros((n, 1), np.float32),
            reward=np.zeros(n, np.float32),
            next_obs=np.zeros((n, 2), np.float32),
            done=np.zeros(n, np.float32),
            discount=np.full(n, 0.99, np.float32),
        )

    buf = PrioritizedReplayBuffer(8, 2, 1, alpha=1.0)
    idx0 = buf.add(batch(8, 0.0))
    gen = buf.generation[idx0].copy()
    # ring wraps: slots 0..3 now hold NEW transitions
    buf.add(batch(4, 1.0))
    before = buf._trees.get(np.arange(8)).copy()
    buf.update_priorities(idx0, np.full(8, 100.0), generation=gen)
    after = buf._trees.get(np.arange(8))
    # overwritten slots 0..3 kept their fresh-insert priority...
    np.testing.assert_array_equal(after[:4], before[:4])
    # ...surviving slots 4..7 got the new priority
    np.testing.assert_allclose(after[4:], 100.0)
    # without a generation, all update (legacy semantics)
    buf.update_priorities(np.arange(4), np.full(4, 7.0))
    np.testing.assert_allclose(buf._trees.get(np.arange(4)), 7.0)
