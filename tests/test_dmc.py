"""DM-Control adapter tests (BASELINE.md config #4 plumbing).

Skipped wholesale when dm_control or an offscreen GL backend is missing —
the adapter itself stays importable everywhere (lazy imports).
"""

import numpy as np
import pytest

from d4pg_tpu.envs.dmc import DMControlEnv, parse_dmc_id


def test_parse_dmc_id():
    assert parse_dmc_id("cheetah-run-pixels") == ("cheetah", "run", True)
    assert parse_dmc_id("dmc:cheetah-run-pixels") == ("cheetah", "run", True)
    assert parse_dmc_id("dmc:cartpole-swingup") == ("cartpole", "swingup", False)
    # dotted dm_control task names keep everything after the first dash
    assert parse_dmc_id("dmc:ball_in_cup-catch") == ("ball_in_cup", "catch", False)
    assert parse_dmc_id("Pendulum-v1") is None
    assert parse_dmc_id("HalfCheetah-v4") is None
    assert parse_dmc_id("point") is None


def _dmc_available() -> bool:
    try:
        env = DMControlEnv("cartpole", "swingup", pixels=True, height=16,
                           width=16, action_repeat=2, seed=0)
        obs, _ = env.reset()
        return obs.shape == (16, 16, 3)
    except Exception:
        return False


pixels_ready = pytest.mark.skipif(
    not _dmc_available(), reason="dm_control or offscreen GL unavailable"
)


@pixels_ready
def test_dmc_pixel_env_contract():
    env = DMControlEnv("cartpole", "swingup", pixels=True, height=16,
                       width=16, action_repeat=2, seed=0)
    obs, info = env.reset()
    assert obs.dtype == np.uint8 and obs.shape == (16, 16, 3)
    assert env.observation_space.shape == (16, 16, 3)
    a = np.zeros(env.action_space.shape, np.float32)
    obs2, r, term, trunc, _ = env.step(a)
    assert obs2.shape == (16, 16, 3)
    assert isinstance(r, float)
    assert term is False  # suite tasks end by time limit only
    env.close()


@pixels_ready
def test_dmc_state_env_contract():
    env = DMControlEnv("cartpole", "swingup", pixels=False, seed=0)
    obs, _ = env.reset()
    assert obs.dtype == np.float32 and obs.ndim == 1
    assert env.observation_space.shape == obs.shape
    obs2, r, term, trunc, _ = env.step(
        np.zeros(env.action_space.shape, np.float32)
    )
    assert obs2.shape == obs.shape
    env.close()


@pixels_ready
def test_dmc_action_repeat_sums_reward():
    e1 = DMControlEnv("cartpole", "swingup", pixels=False, action_repeat=1,
                      seed=3)
    e4 = DMControlEnv("cartpole", "swingup", pixels=False, action_repeat=4,
                      seed=3)
    e1.reset(seed=3)
    e4.reset(seed=3)
    a = np.zeros(e1.action_space.shape, np.float32)
    r_sum = sum(e1.step(a)[1] for _ in range(4))
    _, r4, *_ = e4.step(a)
    np.testing.assert_allclose(r4, r_sum, rtol=1e-6)
