"""RNG-provenance static analysis (lint/rnggraph.py, families 22-24 +
the interprocedural prng-key-reuse upgrade) + the DrawLedger runtime
twin.

Fixture halves drive each family on a known-bad snippet and its
known-good variant (parsed, never executed — determinism scope is
entered by giving the fixture a ``fleet/`` path); the package halves
gate the real tree: the rng graph over ``d4pg_tpu/`` + ``bench.py``
must discover streams and branch sites, resolve every declared stream
owner, and carry zero findings, and the ``--rng``/``--all`` CLI
artifacts must exit 0. The runtime half pins DrawLedger semantics
(counting proxy, canonical digest, schedule namespace) and the A/B
equal-seeded-load oracle: two sampler-chaos arms at one seed must
export the same schedule digest.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import d4pg_tpu
from d4pg_tpu.lint import lint_source
from d4pg_tpu.lint.__main__ import main as lint_main
from d4pg_tpu.obs.draw_ledger import LEDGER, SCHEDULE_PREFIX, DrawLedger

pytestmark = pytest.mark.rnglint

PACKAGE_DIR = os.path.dirname(os.path.abspath(d4pg_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)


def findings(src, rule, path="fleet/fixture.py"):
    """Fixtures default to a determinism-scoped path — families 22/24
    only patrol fleet/elastic/replay/obs/analysis code."""
    res = lint_source(textwrap.dedent(src), path)
    assert not res.errors, res.errors
    return [f for f in res.findings if f.rule == rule]


# ------------------------------------ R22 rng-ambient-stream --------------

def test_numpy_module_global_draw_fires():
    out = findings("""
        import numpy as np

        def tick():
            return np.random.randn(4)
        """, "rng-ambient-stream")
    assert len(out) == 1
    assert "hidden module-level global stream" in out[0].message


def test_stdlib_random_draw_fires():
    out = findings("""
        import random

        def jitter():
            return random.random() * 0.1
        """, "rng-ambient-stream")
    assert len(out) == 1
    assert "process-global Random" in out[0].message


def test_unseeded_default_rng_fires():
    out = findings("""
        import numpy as np

        def make():
            rng = np.random.default_rng()
            return rng.random()
        """, "rng-ambient-stream")
    assert len(out) == 1
    assert "unseeded" in out[0].message


def test_wallclock_seed_fires():
    out = findings("""
        import time
        import numpy as np

        def make():
            rng = np.random.default_rng(int(time.time()))
            return rng.random()
        """, "rng-ambient-stream")
    assert len(out) == 1
    assert "wall-clock" in out[0].message


def test_branched_component_stream_clean():
    out = findings("""
        import numpy as np

        def make(seed):
            rng = np.random.default_rng(
                np.random.SeedSequence(seed, spawn_key=(1,)))
            return rng.random()
        """, "rng-ambient-stream")
    assert out == []


def test_ambient_outside_determinism_scope_clean():
    """The same ambient draw in a non-scoped module (no fleet/elastic/
    replay/obs/analysis directory, no chaos/traffic/sampler stem) is
    out of the family's jurisdiction."""
    out = findings("""
        import numpy as np

        def tick():
            return np.random.randn(4)
        """, "rng-ambient-stream", path="util/fixture.py")
    assert out == []


# ------------------------------------ R23 rng-stream-thread-escape --------

_SHARED_STREAM = """
    import threading
    import numpy as np

    class Pump:
        def __init__(self, seed):
            self._rng = np.random.default_rng({ctor})

        def start(self):
            threading.Thread(target=self._send).start()
            threading.Thread(target=self._recv).start()

        def _send(self):
            return self._rng.random()

        def _recv(self):
            return self._rng.random()
    """


def test_shared_stream_across_threads_fires():
    out = findings(_SHARED_STREAM.format(ctor="seed"),
                   "rng-stream-thread-escape")
    assert len(out) == 1
    assert "2 distinct thread-spawn targets" in out[0].message
    assert "Pump._send" in out[0].message and "Pump._recv" in out[0].message


def test_branched_stream_across_threads_clean():
    out = findings(
        _SHARED_STREAM.format(
            ctor="np.random.SeedSequence(seed, spawn_key=(7,))"),
        "rng-stream-thread-escape")
    assert out == []


def test_stream_owner_annotation_satisfies():
    """A caller-owned stream may declare its owner; the declaration is
    audited — the named stream must be a discovered seeded component
    stream."""
    src = _SHARED_STREAM.format(ctor="seed") + """
    class Owner:
        def __init__(self, seed):
            self._rng = np.random.default_rng(
                np.random.SeedSequence(seed, spawn_key=(3,)))
    """
    src = src.replace(
        "self._rng = np.random.default_rng(seed)",
        "self._rng = np.random.default_rng(seed)"
        "  # jaxlint: stream-owner=Owner._rng")
    out = [f for f in lint_source(textwrap.dedent(src),
                                  "fleet/fixture.py").findings
           if f.rule == "rng-stream-thread-escape"]
    assert out == []


def test_stream_owner_unresolved_fires():
    src = _SHARED_STREAM.format(ctor="seed").replace(
        "self._rng = np.random.default_rng(seed)",
        "self._rng = np.random.default_rng(seed)"
        "  # jaxlint: stream-owner=Ghost._rng")
    out = [f for f in lint_source(textwrap.dedent(src),
                                  "fleet/fixture.py").findings
           if f.rule == "rng-stream-thread-escape"]
    assert len(out) == 1
    assert "does not resolve" in out[0].message


# ------------------------------------ R24 rng-draw-count-drift ------------

def test_conditional_draw_then_reuse_fires():
    """The PR-12 desync shape: one branch draws, both paths then share
    the stream — the second draw's offset is path-dependent."""
    out = findings("""
        import numpy as np

        def step(flag, seed):
            rng = np.random.default_rng(seed)
            if flag:
                a = rng.random()
            return rng.random()
        """, "rng-draw-count-drift")
    assert len(out) == 1
    assert "path-dependent" in out[0].message


def test_skip_before_rng_use_idiom_clean():
    """Paths that exit the loop body before the FIRST draw are the
    documented skip idiom: every drawing iteration consumes the same
    fixed count, so the event index stays aligned."""
    out = findings("""
        import numpy as np

        def consume(items, seed):
            rng = np.random.default_rng(seed)
            out = []
            for it in items:
                if it is None:
                    continue
                out.append(rng.random())
            return out
        """, "rng-draw-count-drift")
    assert out == []


def test_per_iteration_drift_fires():
    out = findings("""
        import numpy as np

        def consume(items, seed):
            rng = np.random.default_rng(seed)
            out = []
            for it in items:
                u = rng.random()
                if it > 0:
                    u += rng.random()
                out.append(u)
            return out
        """, "rng-draw-count-drift")
    assert len(out) == 1
    assert "per loop iteration" in out[0].message


def test_fixed_draws_per_event_clean():
    """The sanctioned chaos shape: a fixed draw count per event, fate
    decided from the drawn uniforms afterwards."""
    out = findings("""
        import numpy as np

        def consume(items, seed):
            rng = np.random.default_rng(seed)
            out = []
            for it in items:
                u_a, u_b = rng.random(2)
                if u_a < 0.5:
                    out.append(u_b)
            return out
        """, "rng-draw-count-drift")
    assert out == []


def test_persistent_stream_exit_total_drift_fires():
    """An attr stream outlives the frame: two call paths leaving with
    different nonzero totals desync every later consumer."""
    out = findings("""
        import numpy as np

        class Chaos:
            def __init__(self, seed):
                self._rng = np.random.default_rng(seed)

            def step(self, flag):
                u = self._rng.random()
                if flag:
                    u += self._rng.random()
                return u
        """, "rng-draw-count-drift")
    assert len(out) == 1
    assert "path-dependent total" in out[0].message


# ------------------------------------ interprocedural prng-key-reuse ------

def test_key_reuse_across_call_boundary_fires():
    out = findings("""
        import jax

        def helper(key, shape):
            return jax.random.normal(key, shape)

        def run(key):
            x = helper(key, (4,))
            y = jax.random.normal(key, (4,))
            return x + y
        """, "prng-key-reuse", path="fixture.py")
    assert len(out) == 1
    assert "the callee draws from it" in out[0].message


def test_key_split_across_call_boundary_clean():
    out = findings("""
        import jax

        def helper(key, shape):
            return jax.random.normal(key, shape)

        def run(key):
            k1, k2 = jax.random.split(key)
            x = helper(k1, (4,))
            y = jax.random.normal(k2, (4,))
            return x + y
        """, "prng-key-reuse", path="fixture.py")
    assert out == []


# ------------------------------------ package gates -----------------------

@pytest.mark.lint
def test_rng_graph_clean_over_package():
    """Tier-1 gate for the determinism surface: the whole-program rng
    graph over ``d4pg_tpu/`` + ``bench.py`` must discover the component
    streams and their SeedSequence branch sites, resolve every declared
    stream owner, and carry zero findings."""
    from d4pg_tpu.lint.engine import build_rng_graph
    from d4pg_tpu.lint.rnggraph import format_rnggraph

    graph, errors = build_rng_graph(
        [PACKAGE_DIR, os.path.join(REPO_ROOT, "bench.py")])
    assert not errors, errors
    assert graph.findings == [], format_rnggraph(graph)
    assert graph.streams, "no RNG streams discovered — walker rot?"
    assert graph.branches, "no SeedSequence branch sites — walker rot?"
    assert graph.scoped > 0
    for spec, status in graph.handlers.items():
        assert status == "ok", (spec, status)
    # the ledger-wrapped chaos/traffic streams must stay discoverable
    # THROUGH the wrap (the lint/runtime twins see the same streams)
    wrapped = [s for s in graph.streams if "+ledger:" in s[3]]
    assert any("schedule." in s[3] for s in wrapped), graph.streams


@pytest.mark.lint
def test_cli_rng_mode_clean():
    """``python -m d4pg_tpu.lint --rng`` is the review artifact for
    determinism PRs; it must exit 0 on the repo and print the stream
    table, the branch sites, and no findings."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", "--rng", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rnggraph:" in proc.stdout
    assert "streams (ctor site -> owner [ctor/seed] draws threads):" \
        in proc.stdout
    assert "branch sites (SeedSequence / spawn):" in proc.stdout
    assert "findings: none" in proc.stdout


def test_rng_cli_mode_fires_on_fixture(tmp_path, capsys):
    """`--rng` exits 1 iff a family fires, 0 on the clean variant. The
    fixture filename carries a scoped stem (chaos) — scope is a path
    property, not a flag."""
    bad = tmp_path / "chaos_bad.py"
    bad.write_text(textwrap.dedent("""
        import numpy as np

        def tick():
            return np.random.randn(4)
        """))
    assert lint_main(["--rng", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "rng-ambient-stream" in out

    good = tmp_path / "chaos_good.py"
    good.write_text(textwrap.dedent("""
        import numpy as np

        def make(seed):
            rng = np.random.default_rng(
                np.random.SeedSequence(seed, spawn_key=(1,)))
            return rng.random()
        """))
    assert lint_main(["--rng", str(good)]) == 0
    out = capsys.readouterr().out
    assert "findings: none" in out
    assert "[default_rng/branched]" in out


def test_json_rng_mode(tmp_path, capsys):
    src = tmp_path / "chaos_mod.py"
    src.write_text(textwrap.dedent("""
        import numpy as np

        def make(seed):
            rng = np.random.default_rng(
                np.random.SeedSequence(seed, spawn_key=(1,)))
            return rng.random()
        """))
    assert lint_main(["--rng", "--json", str(src)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == 1 and doc["mode"] == "rng"
    assert doc["findings"] == [] and doc["errors"] == []
    for key in ("functions", "modules", "scoped", "streams", "branches",
                "handlers"):
        assert key in doc, key
    assert len(doc["streams"]) == 1
    row = doc["streams"][0]
    assert set(row) == {"site", "owner", "ctor", "seed", "draws", "threads"}
    assert row["seed"] == "branched"
    assert len(doc["branches"]) == 1


def test_json_all_mode_carries_rng_section(tmp_path, capsys):
    src = tmp_path / "chaos_mod.py"
    src.write_text(textwrap.dedent("""
        import numpy as np

        def make(seed):
            rng = np.random.default_rng(
                np.random.SeedSequence(seed, spawn_key=(1,)))
            return rng.random()
        """))
    assert lint_main(["--all", "--json", str(src)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "rng" in doc
    assert doc["rng"]["findings"] == [] and doc["rng"]["errors"] == []
    assert doc["rng"]["streams"]


# ------------------------------------ DrawLedger (runtime twin) -----------

def test_draw_ledger_counts_and_reset():
    led = DrawLedger()
    led.count("a")
    led.count("a", 2)
    led.count("b")
    assert led.counts() == {"a": 3, "b": 1}
    led.disarm()
    led.count("a")  # disarmed: no-op
    assert led.counts() == {"a": 3, "b": 1}
    led.reset(armed=True)
    assert led.counts() == {}
    led.count("c")
    assert led.counts() == {"c": 1}


def test_draw_ledger_wrap_is_transparent():
    """The proxy counts draw-method CALLS (the family-24 unit) and
    delegates everything — including the drawn values — unchanged."""
    led = DrawLedger()
    raw = np.random.default_rng(11)
    wrapped = led.wrap("s", np.random.default_rng(11))
    a = wrapped.random(4)
    b = wrapped.integers(0, 10, size=3)
    assert np.array_equal(a, raw.random(4))
    assert np.array_equal(b, raw.integers(0, 10, size=3))
    assert led.counts() == {"s": 2}  # two calls, not seven elements
    # non-draw attributes pass through to the real Generator
    assert wrapped.bit_generator is not None


def test_draw_ledger_digest_is_canonical():
    """Equal counted histories hash equal regardless of arrival order;
    the schedule prefix filters the namespace the A/B drivers pin."""
    one, two = DrawLedger(), DrawLedger()
    one.count("schedule.x")
    one.count("chaos.y", 3)
    two.count("chaos.y", 3)
    two.count("schedule.x")
    assert one.digest() == two.digest()
    assert one.digest(SCHEDULE_PREFIX) == two.digest(SCHEDULE_PREFIX)
    two.count("chaos.y")  # runtime streams differ...
    assert one.digest() != two.digest()
    # ...but the schedule namespace digest is unaffected
    assert one.digest(SCHEDULE_PREFIX) == two.digest(SCHEDULE_PREFIX)
    exp = one.export()
    assert set(exp) == {"streams", "total_draws", "digest",
                        "schedule_digest"}
    assert exp["total_draws"] == 4


def test_component_streams_report_through_global_ledger():
    """TrafficModel construction + the chaos schedules/actor streams
    count into the process ledger when armed, under the documented
    stream names; two identical construction windows export the same
    schedule digest (the equal-seeded-load oracle)."""
    from d4pg_tpu.elastic.traffic import TrafficConfig, TrafficModel
    from d4pg_tpu.fleet.chaos import ChaosConfig, ChaosPolicy

    def window():
        LEDGER.reset(armed=True)
        TrafficModel(TrafficConfig(n_actors=4, seed=3))
        pol = ChaosPolicy(ChaosConfig(
            service_kill_every_s=1.0, service_kill_count=3, seed=3))
        pol.service_kill_schedule(10.0)
        actor = pol.actor_stream(0, "actor-0")
        for _ in range(5):
            actor.next()
        exp = LEDGER.export()
        LEDGER.reset(armed=False)
        return exp

    first, second = window(), window()
    streams = first["streams"]
    assert streams["schedule.traffic.diurnal"] == 1
    assert streams["schedule.traffic.pareto"] == 4  # one per actor lane
    assert streams["schedule.service_kill"] == 3    # one per kill
    assert streams["chaos.actor-0"] == 5            # one call per event
    assert "schedule.traffic.flash" in streams
    assert first["schedule_digest"] == second["schedule_digest"]
    assert first["digest"] == second["digest"]


@pytest.mark.slow
def test_sampler_chaos_arms_pin_schedule_digest():
    """The A/B equal-seeded-load oracle end to end: two sampler-chaos
    arms at one seed — different sample paths, so different runtime
    behaviour — must export the SAME schedule-namespace digest, and
    every run's artifact must carry the draw_ledger block."""
    from d4pg_tpu.fleet.sampler_chaos import (SamplerChaosConfig,
                                              run_sampler_chaos)

    reports = [
        run_sampler_chaos(SamplerChaosConfig(
            sample_path=path, n_actors=2, duration_s=1.5,
            rows_per_sec=30.0, learner_kills=1, seed=9))
        for path in ("dealer", "host")
    ]
    for rep in reports:
        block = rep["draw_ledger"]
        assert set(block) == {"streams", "total_draws", "digest",
                              "schedule_digest"}
        assert block["streams"]["schedule.sampler_kill"] == 1
        assert any(k.startswith("chaos.") for k in block["streams"])
    assert (reports[0]["draw_ledger"]["schedule_digest"]
            == reports[1]["draw_ledger"]["schedule_digest"])
