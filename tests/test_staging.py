"""DeviceStager prefetch semantics (SURVEY.md §7 host<->device overlap):
batches come back in sampling order, host aux (PER indices) rides along
untouched, and invalidate() drops the in-flight batch."""

import numpy as np

from d4pg_tpu.replay.staging import DeviceStager


def test_stager_preserves_order_and_values():
    counter = {"n": 0}

    def sample():
        i = counter["n"]
        counter["n"] += 1
        return np.full((4,), float(i), np.float32)

    st = DeviceStager(sample)
    for expect in range(5):
        got = np.asarray(st.next())
        np.testing.assert_array_equal(got, np.full((4,), float(expect)))
    # one batch is always in flight beyond what was consumed
    assert counter["n"] == 6


def test_stager_aux_rides_on_host():
    counter = {"n": 0}

    def sample():
        i = counter["n"]
        counter["n"] += 1
        payload = {"x": np.full((2,), float(i), np.float32)}
        return payload, ("idx", i)

    st = DeviceStager(sample, with_aux=True)
    p0, aux0 = st.next()
    p1, aux1 = st.next()
    assert aux0 == ("idx", 0) and aux1 == ("idx", 1)
    np.testing.assert_array_equal(np.asarray(p0["x"]), [0.0, 0.0])
    np.testing.assert_array_equal(np.asarray(p1["x"]), [1.0, 1.0])
    # aux stays a host object, payload became a device array
    assert hasattr(p1["x"], "devices")


def test_pipeline_depth_defers_but_never_drops_write_backs():
    """ChunkPipeline keeps up to `depth` chunks in flight: write-backs for
    early chunks are deferred (not yet flushed while the window fills) but
    every chunk's priorities land exactly once by the end of run()."""
    import jax.numpy as jnp

    from d4pg_tpu.learner.pipeline import ChunkPipeline

    n_sampled = {"n": 0}

    def sample():
        i = n_sampled["n"]
        n_sampled["n"] += 1
        return (np.full((2,), float(i), np.float32), None), ("aux", i)

    def update(state, batch):
        return state + 1, {"td_error": jnp.full((2,), float(np.asarray(batch)[0]))}

    flushed = []
    pipe = ChunkPipeline(update, sample,
                         write_back=lambda aux, td: flushed.append(
                             (aux[1], float(td[0]))),
                         use_weights=False, depth=3)
    state, _ = pipe.run(0, 8)
    assert state == 8
    # every chunk flushed exactly once, in order, with its own td
    assert [f[0] for f in flushed] == list(range(8))
    for i, td in flushed:
        assert np.isclose(td, float(i) + 1e-6)


def test_stager_invalidate_drops_inflight():
    counter = {"n": 0}

    def sample():
        i = counter["n"]
        counter["n"] += 1
        return np.array([float(i)], np.float32)

    st = DeviceStager(sample)
    assert float(np.asarray(st.next())[0]) == 0.0  # 1 staged in flight
    st.invalidate()  # drops sample 1
    assert float(np.asarray(st.next())[0]) == 2.0
