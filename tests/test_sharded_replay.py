"""Sharded device-resident replay over the data-parallel mesh
(replay/sharded_per.py + learner/fused.make_sharded_fused_chunk), on the
8-virtual-CPU-device mesh. The host segment trees serve as the oracle
for the per-shard tree state."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.learner import D4PGConfig, init_state
from d4pg_tpu.learner.fused import make_sharded_fused_chunk
from d4pg_tpu.parallel import MeshSpec, make_mesh
from d4pg_tpu.replay.sharded_per import ShardedFusedReplay
from d4pg_tpu.replay.uniform import TransitionBatch


def _mesh(dp=4):
    return make_mesh(MeshSpec(data_parallel=dp),
                     devices=jax.devices()[:dp])


def _batch(rng, n, obs_dim=4, act_dim=2):
    done = np.zeros(n, np.float32)
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, act_dim)).astype(np.float32),
        reward=np.arange(n, dtype=np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=done,
        discount=np.full(n, 0.99, np.float32),
    )


def test_round_robin_insert_balances_shards(rng):
    buf = ShardedFusedReplay(64, 4, 2, _mesh(4), prioritized=True)
    assert buf.n_shards == 4 and buf.cap_shard == 16
    buf.add(_batch(rng, 10))
    buf.add(_batch(rng, 7))
    assert len(buf) == 17
    buf.drain()
    assert buf._size.sum() == 17
    assert buf._size.max() - buf._size.min() <= 1
    # every inserted reward value landed somewhere, exactly once
    rewards = np.sort(np.concatenate([
        np.asarray(buf.storage.reward[s, :buf._size[s]])
        for s in range(4)
    ]))
    np.testing.assert_array_equal(
        rewards, np.sort(np.concatenate([np.arange(10), np.arange(7)])))
    # trees: every live slot carries max_priority**alpha == 1
    for s in range(4):
        sz = int(buf._size[s])
        np.testing.assert_allclose(
            np.asarray(buf.trees.sum_tree[s, 1]), sz, rtol=1e-6)


def test_ring_wrap_per_shard(rng):
    buf = ShardedFusedReplay(16, 4, 2, _mesh(4), prioritized=False)
    for _ in range(3):
        buf.add(_batch(rng, 10))
        buf.drain()
    assert buf._size.sum() == 16  # full, wrapped
    assert all(buf._size == 4)


def test_sharded_fused_chunk_per(rng):
    mesh = _mesh(4)
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(16, 16, 16))
    state = init_state(config, jax.random.key(0))
    buf = ShardedFusedReplay(64, 4, 2, mesh, alpha=0.6)
    buf.add(_batch(rng, 64))
    buf.drain()
    fn = make_sharded_fused_chunk(config, mesh, k=3, batch_size=16,
                                  alpha=0.6, donate=False)
    s1, t1, m1 = fn(state, buf.trees, buf.storage, buf.size)
    assert int(jax.device_get(s1.step)) == 3
    assert m1["critic_loss"].shape == (3,)
    assert m1["td_error"].shape == (3, 16)
    assert np.isfinite(np.asarray(m1["critic_loss"])).all()
    # weights bounded by the global normalizer: max weight <= 1 (+eps)
    # run a fresh chunk (k=1) on untouched trees where all priorities are
    # equal -> all weights must be exactly 1
    fn1 = make_sharded_fused_chunk(config, mesh, k=1, batch_size=16,
                                   alpha=0.6, donate=False)
    _, _, m = fn1(state, buf.trees, buf.storage, buf.size)
    # recompute weights is internal; instead check determinism + tree change
    s2, t2, m2 = fn(state, buf.trees, buf.storage, buf.size)
    np.testing.assert_array_equal(np.asarray(m1["idx"]), np.asarray(m2["idx"]))
    np.testing.assert_array_equal(np.asarray(t1.sum_tree),
                                  np.asarray(t2.sum_tree))
    assert not np.allclose(np.asarray(t1.sum_tree),
                           np.asarray(buf.trees.sum_tree))


def test_sharded_fused_priorities_written_per_shard(rng):
    """k=1: each shard's tree leaves at the sampled local idx must equal
    (|td| + eps) ** alpha — td rows [i*b_local:(i+1)*b_local] belong to
    shard i by the P('data') layout."""
    mesh = _mesh(4)
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(16, 16, 16))
    state = init_state(config, jax.random.key(1))
    buf = ShardedFusedReplay(64, 4, 2, mesh, alpha=0.6)
    buf.add(_batch(rng, 64))
    buf.drain()
    fn = make_sharded_fused_chunk(config, mesh, k=1, batch_size=16,
                                  alpha=0.6, donate=False)
    _, trees, m = fn(state, buf.trees, buf.storage, buf.size)
    idx = np.asarray(m["idx"][0]).reshape(4, 4)   # [shard, b_local]
    td = np.asarray(m["td_error"][0]).reshape(4, 4)
    leaves = np.asarray(trees.sum_tree)[:, buf.cap_shard:]
    expect = (np.abs(td) + 1e-6) ** 0.6
    for s in range(4):
        for j, slot in enumerate(idx[s]):
            cands = expect[s][idx[s] == slot]
            assert np.any(np.isclose(leaves[s, slot], cands, rtol=1e-4))


def test_sharded_equal_priorities_weights_are_one(rng):
    """With every priority equal across all shards the IS weights must be
    exactly 1 regardless of beta — verified through the critic loss being
    identical to a run with beta0=1 (weights can only differ via w)."""
    mesh = _mesh(2)
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(8, 8))
    state = init_state(config, jax.random.key(2))
    buf = ShardedFusedReplay(32, 4, 2, mesh, alpha=0.6)
    buf.add(_batch(rng, 32))
    buf.drain()
    loss = {}
    for b0 in (0.4, 1.0):
        fn = make_sharded_fused_chunk(config, mesh, k=1, batch_size=8,
                                      alpha=0.6, beta0=b0, donate=False)
        _, _, m = fn(state, buf.trees, buf.storage, buf.size)
        loss[b0] = float(np.asarray(m["critic_loss"][0]))
    assert loss[0.4] == pytest.approx(loss[1.0], rel=1e-6)


def test_sharded_fused_uniform_chunk(rng):
    mesh = _mesh(4)
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(8, 8))
    state = init_state(config, jax.random.key(3))
    buf = ShardedFusedReplay(64, 4, 2, mesh, prioritized=False)
    buf.add(_batch(rng, 64))
    buf.drain()
    fn = make_sharded_fused_chunk(config, mesh, k=2, batch_size=16,
                                  prioritized=False, donate=False)
    s1, m = fn(state, buf.storage, buf.size)
    assert int(jax.device_get(s1.step)) == 2
    idx = np.asarray(m["idx"])
    assert idx.min() >= 0 and idx.max() < buf.cap_shard


def test_sharded_drain_overflow_keeps_newest(rng):
    """A staged backlog past total capacity is trimmed to the newest
    `capacity` rows before the shard split (more than cap_shard rows on
    one shard would mean duplicate slots in a single scatter)."""
    buf = ShardedFusedReplay(16, 4, 2, _mesh(4), prioritized=False)
    for lo in (0, 11):
        b = _batch(rng, 11)
        b = TransitionBatch(*[np.asarray(v) for v in b])
        b = b._replace(reward=np.arange(lo, lo + 11, dtype=np.float32))
        buf.add(b)
    assert buf.drain() == 16
    assert buf._size.sum() == 16
    got = np.sort(np.concatenate([
        np.asarray(buf.storage.reward[s, :buf._size[s]]) for s in range(4)]))
    np.testing.assert_array_equal(got, np.arange(6, 22))


def test_sharded_state_dict_roundtrip(rng):
    mesh = _mesh(4)
    src = ShardedFusedReplay(64, 4, 2, mesh, alpha=0.6)
    src.add(_batch(rng, 40))
    src.drain()
    dst = ShardedFusedReplay(64, 4, 2, mesh, alpha=0.6)
    dst.load_state_dict(src.state_dict())
    np.testing.assert_array_equal(dst._size, src._size)
    np.testing.assert_array_equal(dst._head, src._head)
    assert dst._rr == src._rr
    np.testing.assert_allclose(np.asarray(dst.trees.sum_tree),
                               np.asarray(src.trees.sum_tree))
    np.testing.assert_array_equal(np.asarray(dst.storage.reward),
                                  np.asarray(src.storage.reward))


def test_sharded_checkpoint_rejected_by_flat_buffers(rng):
    """A sharded replay checkpoint restored into a non-sharded buffer must
    raise, not silently resume with an empty ring."""
    from d4pg_tpu.replay import PrioritizedReplayBuffer
    from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

    src = ShardedFusedReplay(64, 4, 2, _mesh(4), alpha=0.6)
    src.add(_batch(rng, 20))
    src.drain()
    d = src.state_dict()
    with pytest.raises(ValueError, match="sharded"):
        PrioritizedReplayBuffer(64, 4, 2).load_state_dict(d)
    with pytest.raises(ValueError, match="sharded"):
        FusedDeviceReplay(64, 4, 2).load_state_dict(d)
    # and a different data-parallel degree is rejected too
    with pytest.raises(ValueError, match="data-parallel"):
        ShardedFusedReplay(64, 4, 2, _mesh(2)).load_state_dict(d)


def test_train_sharded_fused_end_to_end(tmp_path):
    """train() with --data_parallel 4 + device replay: the fused data
    plane lives on the mesh (no more host-tree fallback for multi-chip)."""
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=12,
        eval_trials=1, batch_size=16, memory_size=2000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, replay_storage="device", fused_replay="on",
        data_parallel=4, updates_per_dispatch=8,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
