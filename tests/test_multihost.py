"""Simulated multi-host: two local processes form one jax.distributed mesh
and run the sharded D4PG update (SURVEY.md §4; VERDICT r1 #8). Spawned as
real subprocesses — jax.distributed state is process-global and must not
contaminate the test process."""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_processes_form_one_mesh():
    port = _free_port()
    env = dict(os.environ)
    env.update({
        # stripped axon plugin + explicit CPU: robust even when the TPU
        # tunnel is wedged (see .claude/skills/verify/SKILL.md)
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    })
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "d4pg_tpu.parallel.multihost_check",
             "--coordinator", f"127.0.0.1:{port}",
             "--num_processes", "2", "--process_id", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
        assert p.returncode == 0, out[-2000:]
    oks = [line for out in outs for line in out.splitlines()
           if line.startswith("multihost_check OK")]
    assert len(oks) == 2
    assert "mesh 8 devices" in oks[0]
    # replicas agree: both processes report identical losses
    assert oks[0].split("losses")[1] == oks[1].split("losses")[1]
