"""Simulated multi-host: two local processes form one jax.distributed mesh
and run the sharded D4PG update (SURVEY.md §4; VERDICT r1 #8). Spawned as
real subprocesses — jax.distributed state is process-global and must not
contaminate the test process.

Backend support is PROBED, not assumed (mirroring test_native.py's
loader-skip pattern): some jaxlib builds cannot run multiprocess
computations on the CPU backend at all ("Multiprocess computations
aren't implemented on the CPU backend" out of every collective), which
previously failed all of this module identically on such containers. A
tiny two-process ``jax.distributed`` barrier runs once per session; when
it dies, every test here SKIPS with the probe's error as the reason.
The probe is lazy (module-scoped fixture), so merely collecting this
``slow``-marked module costs nothing in a ``-m "not slow"`` tier-1 run.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_PROBE_SRC = """
import jax
jax.config.update("jax_platforms", "cpu")
import sys
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("probe")
print("MULTIHOST_PROBE_OK")
"""


def _probe_multiprocess_backend() -> tuple[bool, str]:
    """Can this jax/jaxlib actually run a two-process CPU collective?"""
    port = _free_port()
    env = _mh_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC,
             f"127.0.0.1:{port}", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "probe timeout"
        outs.append(out)
    if all(p.returncode == 0 for p in procs) and all(
            "MULTIHOST_PROBE_OK" in out for out in outs):
        return True, ""
    # surface the terminal error line as the skip reason
    reason = "multiprocess jax probe failed"
    for out in outs:
        for line in reversed(out.splitlines()):
            if "Error" in line or "error" in line:
                reason = line.strip()[:200]
                break
        else:
            continue
        break
    return False, reason


@pytest.fixture(scope="module", autouse=True)
def _require_multiprocess_backend():
    ok, reason = _probe_multiprocess_backend()
    if not ok:
        pytest.skip("jax.distributed cannot run two CPU processes on "
                    f"this build: {reason}")


def _mh_env() -> dict:
    env = dict(os.environ)
    env.update({
        # stripped axon plugin + explicit CPU: robust even when the TPU
        # tunnel is wedged (see .claude/skills/verify/SKILL.md)
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    })
    return env


def test_two_process_full_train(tmp_path):
    """The real train() CLI across two processes: PER chunk pipeline
    (global [K, B] staging + local td write-back), the single-dispatch
    remainder, per-cycle checkpointing (process 0 only owns io/ckpt/eval —
    process 1 must not crash on the absent manager)."""
    port = _free_port()
    env = _mh_env()
    args = [
        "--env", "point", "--max_steps", "20", "--num_envs", "2",
        "--warmup", "100", "--n_eps", "1", "--n_cycles", "2",
        "--episodes_per_cycle", "1", "--train_steps_per_cycle", "18",
        "--updates_per_dispatch", "8", "--eval_trials", "1",
        "--bsize", "16", "--rmsize", "2000", "--n_atoms", "11",
        "--v_min", "-5.0", "--v_max", "0.0",
        "--log_dir", str(tmp_path),
        "--coordinator", f"127.0.0.1:{port}", "--num_processes", "2",
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "d4pg_tpu.train", *args,
             "--process_id", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
        assert p.returncode == 0, out[-3000:]
    assert all("final:" in out for out in outs)
    # eval/io belong to process 0 alone
    assert "avg_test_reward" in outs[0]
    assert "avg_test_reward" not in outs[1]


def test_two_processes_form_one_mesh():
    port = _free_port()
    env = _mh_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "d4pg_tpu.parallel.multihost_check",
             "--coordinator", f"127.0.0.1:{port}",
             "--num_processes", "2", "--process_id", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
        assert p.returncode == 0, out[-2000:]
    oks = [line for out in outs for line in out.splitlines()
           if line.startswith("multihost_check OK")]
    assert len(oks) == 2
    assert "mesh 8 devices" in oks[0]
    # replicas agree: both processes report identical losses
    assert oks[0].split("losses")[1] == oks[1].split("losses")[1]


def test_two_processes_fused_replay_plane():
    """VERDICT r3 #1: the fused sharded replay data plane on the
    multi-host runtime — each host drains its rows into its own shard-set
    (collective insert), the fused chunk runs SPMD over the global mesh,
    and the per-host checkpoint payload roundtrips. Replica losses must
    agree bit-for-bit across processes."""
    port = _free_port()
    env = _mh_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "d4pg_tpu.parallel.multihost_check",
             "--coordinator", f"127.0.0.1:{port}",
             "--num_processes", "2", "--process_id", str(i), "--fused", "1"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
        assert p.returncode == 0, out[-2000:]
    oks = [line for out in outs for line in out.splitlines()
           if line.startswith("multihost_check OK")]
    assert len(oks) == 2
    assert oks[0].split("losses")[1] == oks[1].split("losses")[1]


def test_two_process_fused_full_train_and_resume(tmp_path):
    """The real train() CLI with --fused_replay on across two processes
    (VERDICT r3 #1's 'production configuration'): device-sharded ring +
    trees over the global mesh, collective drains at chunk boundaries,
    per-cycle checkpointing with per-host replay sidecars, then a resume
    where BOTH hosts restore their own shard-set."""
    env = _mh_env()
    base = [
        "--env", "point", "--max_steps", "20", "--num_envs", "2",
        "--warmup", "100", "--n_eps", "1", "--n_cycles", "2",
        "--episodes_per_cycle", "1", "--train_steps_per_cycle", "18",
        "--updates_per_dispatch", "8", "--eval_trials", "1",
        "--bsize", "16", "--rmsize", "2000", "--n_atoms", "11",
        "--v_min", "-5.0", "--v_max", "0.0",
        "--replay_storage", "device", "--fused_replay", "on",
        "--checkpoint_replay", "1", "--checkpoint_replay_every", "1",
        "--log_dir", str(tmp_path), "--num_processes", "2",
    ]

    def launch(extra_args):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "d4pg_tpu.train", *base, *extra_args,
                 "--coordinator", f"127.0.0.1:{port}",
                 "--process_id", str(i)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            for i in range(2)
        ]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
            assert p.returncode == 0, out[-3000:]
        return outs

    outs = launch([])
    assert all("final:" in out for out in outs)
    # the two replicas ended on the SAME loss (losses are printed in the
    # final dict; replica divergence would show up here)
    finals = [out.rsplit("final:", 1)[1].split("critic_loss': ")[1]
                 .split(",")[0] for out in outs]
    assert finals[0] == finals[1], finals
    # EVERY host wrote its replay shard sidecar, process 0 included
    run_dirs = [d for d in os.listdir(tmp_path) if d.startswith("exp_")]
    assert len(run_dirs) == 1
    assert os.path.exists(os.path.join(tmp_path, run_dirs[0], "replay_p0.pkl"))
    assert os.path.exists(os.path.join(tmp_path, run_dirs[0], "replay_p1.pkl"))

    outs = launch(["--resume", "1"])
    import re

    for i, out in enumerate(outs):
        assert f"[p{i}] resumed from step 36" in out, out[-3000:]
    rows = [int(re.search(r"(\d+) replay rows", out).group(1))
            for out in outs]
    assert all(r > 0 for r in rows), rows


def test_two_process_resume_with_normalize(tmp_path):
    """VERDICT r2 #6: the multi-host runtime must support --resume and
    --normalize_obs. Run 1 trains with synced observation normalization
    and per-cycle checkpoints (replay snapshots every save: process 0's in
    the Orbax extra, process 1's as a sidecar file). Run 2 resumes: BOTH
    processes must restore the broadcast state, their own replay shard,
    and the shared normalizer statistics."""
    env = _mh_env()
    base = [
        "--env", "point", "--max_steps", "20", "--num_envs", "2",
        "--warmup", "100", "--n_eps", "1", "--n_cycles", "2",
        "--episodes_per_cycle", "1", "--train_steps_per_cycle", "8",
        "--updates_per_dispatch", "4", "--eval_trials", "1",
        "--bsize", "16", "--rmsize", "2000", "--n_atoms", "11",
        "--v_min", "-5.0", "--v_max", "0.0",
        "--normalize_obs", "1", "--checkpoint_replay", "1",
        "--checkpoint_replay_every", "1",
        "--log_dir", str(tmp_path), "--num_processes", "2",
    ]

    def launch(extra_args):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "d4pg_tpu.train", *base, *extra_args,
                 "--coordinator", f"127.0.0.1:{port}",
                 "--process_id", str(i)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            for i in range(2)
        ]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
            assert p.returncode == 0, out[-3000:]
        return outs

    launch([])
    # both hosts wrote their replay shard (p0 via Orbax extra, p1 sidecar)
    run_dirs = [d for d in os.listdir(tmp_path) if d.startswith("exp_")]
    assert len(run_dirs) == 1
    assert os.path.exists(os.path.join(tmp_path, run_dirs[0], "replay_p1.pkl"))

    outs = launch(["--resume", "1"])
    for i, out in enumerate(outs):
        assert f"[p{i}] resumed from step 16" in out, out[-3000:]
    # resumed replay shards were non-empty on both hosts
    import re

    rows = [int(re.search(r"(\d+) replay rows", out).group(1)) for out in outs]
    assert all(r > 0 for r in rows), rows
