"""Sharding/collective static analysis (lint/meshgraph.py, families
19-21) + the ReshardSentinel runtime twin.

Fixture halves drive each family on a known-bad snippet and its
known-good variant (parsed, never executed); the package halves gate the
real tree: the mesh graph over ``d4pg_tpu/`` must be clean, every
collective bound, the ``--mesh``/``--all`` CLI artifacts must exit 0,
and the axis/factory mirrors must equal what ``parallel/mesh.py`` and
``parallel/partition.py`` actually declare. The runtime half pins the
fused learner path to ZERO resharding collectives in its compiled HLO.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

import d4pg_tpu
from d4pg_tpu.lint import lint_source
from d4pg_tpu.lint.__main__ import main as lint_main

pytestmark = pytest.mark.meshlint

PACKAGE_DIR = os.path.dirname(os.path.abspath(d4pg_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)


def findings(src, rule):
    res = lint_source(textwrap.dedent(src), "fixture.py")
    assert not res.errors, res.errors
    return [f for f in res.findings if f.rule == rule]


# ------------------------------------ R19 collective-axis-unbound ---------

def test_unbound_collective_fires():
    out = findings("""
        import jax

        DATA_AXIS = "data"

        def merge(x):
            return jax.lax.psum(x, DATA_AXIS)
        """, "collective-axis-unbound")
    assert len(out) == 1
    assert "not reachable from any shard_map" in out[0].message


def test_bound_collective_clean():
    out = findings("""
        import jax
        from jax.experimental.shard_map import shard_map

        DATA_AXIS = "data"

        def make(mesh, specs):
            def body(x):
                return jax.lax.psum(x, DATA_AXIS)
            return shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs)
        """, "collective-axis-unbound")
    assert out == []


def test_hand_spelled_axis_fires_even_when_bound():
    out = findings("""
        import jax
        from jax.experimental.shard_map import shard_map

        def make(mesh, specs):
            def body(x):
                return jax.lax.psum(x, "data")
            return shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs)
        """, "collective-axis-unbound")
    assert len(out) == 1
    assert "hand-spelled" in out[0].message
    assert "DATA_AXIS" in out[0].message


def test_undeclared_axis_fires():
    out = findings("""
        import jax
        from jax.experimental.shard_map import shard_map

        def make(mesh, specs):
            def body(x):
                return jax.lax.pmean(x, "batch")
            return shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs)
        """, "collective-axis-unbound")
    assert any("not a declared mesh axis" in f.message for f in out)


def test_axis_bound_by_declaration_satisfies():
    """A helper outside the shard_map lexically may declare its binding
    caller; the declaration is audited — the named frame must itself be
    under a shard_map axis binding."""
    out = findings("""
        import jax
        from jax.experimental.shard_map import shard_map

        DATA_AXIS = "data"

        def make(mesh, specs):
            def body(x):
                return x + 1
            return shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs)

        def helper(x):  # jaxlint: axis-bound-by=make.body
            return jax.lax.psum(x, DATA_AXIS)
        """, "collective-axis-unbound")
    assert out == []


def test_axis_bound_by_weak_binder_fires():
    out = findings("""
        import jax

        DATA_AXIS = "data"

        def plain(x):
            return x

        def helper(x):  # jaxlint: axis-bound-by=plain
            return jax.lax.psum(x, DATA_AXIS)
        """, "collective-axis-unbound")
    assert len(out) == 1
    assert "not itself under any shard_map" in out[0].message


def test_axis_bound_by_unresolvable_binder_fires():
    out = findings("""
        import jax

        DATA_AXIS = "data"

        def helper(x):  # jaxlint: axis-bound-by=no_such_frame
            return jax.lax.psum(x, DATA_AXIS)
        """, "collective-axis-unbound")
    assert len(out) == 1
    assert "unauditable" in out[0].message


# ------------------------------------ R20 sharding-spec-drift -------------

def test_spec_drift_fires_through_alias():
    out = findings("""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        def make(mesh):
            spec = NamedSharding(mesh, PartitionSpec("data"))
            return jax.jit(lambda x: x, in_shardings=spec)
        """, "sharding-spec-drift")
    assert len(out) == 1
    assert "raw NamedSharding" in out[0].message


def test_spec_clean_through_factory_helper():
    out = findings("""
        import jax
        from d4pg_tpu.parallel import partition

        def _spec(mesh):
            return partition.batch_sharding(mesh)

        def make(mesh):
            return jax.jit(lambda x: x, out_shardings=_spec(mesh))
        """, "sharding-spec-drift")
    assert out == []


def test_implicit_reshard_fires_on_replacement():
    out = findings("""
        import jax
        from d4pg_tpu.parallel import partition

        def move(x, mesh):
            y = jax.device_put(x, partition.batch_sharding(mesh))
            z = jax.device_put(y, partition.replicated(mesh))
            return z
        """, "sharding-spec-drift")
    assert len(out) == 1
    assert "implicit reshard" in out[0].message


def test_consistent_placement_clean():
    out = findings("""
        import jax
        from d4pg_tpu.parallel import partition

        def move(x, w, mesh):
            y = jax.device_put(x, partition.batch_sharding(mesh))
            z = jax.device_put(w, partition.replicated(mesh))
            return y, z
        """, "sharding-spec-drift")
    assert out == []


# ------------------------------------ R21 donation-alias ------------------

def test_donation_alias_fires_on_duplicate_argument():
    out = findings("""
        import jax

        step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

        def run(x):
            return step(x, x)
        """, "donation-alias")
    assert len(out) == 1
    assert "aliases argument" in out[0].message


def test_donation_captured_reference_fires():
    out = findings("""
        import jax

        step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

        class Holder:
            def run(self):
                out = step(self._state, self._aux)
                return out
        """, "donation-alias")
    assert len(out) == 1
    assert "live captured reference" in out[0].message


def test_donation_clean_on_rebind_and_copy():
    """Rebinding the donated attribute from the result — the replica
    deep-copy fix shape — and donating a fresh copy are both clean."""
    out = findings("""
        import jax

        step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

        class Holder:
            def run(self):
                self._state = step(self._state, self._aux)

        def run_copy(x, aux):
            return step(jax.tree.map(lambda a: a.copy(), x), aux)
        """, "donation-alias")
    assert out == []


def test_donation_clean_on_handoff_to_owner():
    """Donating an owned buffer then swapping the result back through
    the owner (the fused_buffer commit shape) is the sanctioned
    double-buffer pattern."""
    out = findings("""
        import jax

        step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

        class Holder:
            def run(self):
                out = step(self._store.arrays, self._aux)
                self._store.swap_arrays(out)
        """, "donation-alias")
    assert out == []


def test_donation_intersection_over_branch_factories():
    """A handle resolving to several jit bindings donates only what EVERY
    binding donates — the second argument of the (0, 1)-donating branch
    must NOT be treated as donated at a shared call site."""
    out = findings("""
        import jax

        def _make(fast):
            if fast:
                return jax.jit(lambda a, b: a + b, donate_argnums=(0, 1))
            return jax.jit(lambda a, b: a + b, donate_argnums=(0,))

        class Holder:
            def run(self):
                self._state = _make(True)(self._state, self._aux)
        """, "donation-alias")
    assert out == []


# ------------------------------------ package gates -----------------------

@pytest.mark.lint
def test_mesh_graph_clean_over_package():
    """Tier-1 gate for the sharding surface: the whole-program mesh graph
    over ``d4pg_tpu/`` must bind every collective, resolve every sharding
    consumer without drift, show every donation rebound or handed back,
    and carry zero findings."""
    from d4pg_tpu.lint.engine import build_mesh_graph
    from d4pg_tpu.lint.meshgraph import format_meshgraph

    graph, errors = build_mesh_graph([PACKAGE_DIR])
    assert not errors, errors
    assert graph.findings == [], format_meshgraph(graph)
    assert graph.shard_maps, "no shard_map sites discovered — walker rot?"
    assert graph.collectives, "no collective uses discovered — walker rot?"
    for site, op, axis, witness, status in graph.collectives:
        assert status == "bound", (site, op, axis, witness, status)
        assert witness.startswith("shard_map:"), (site, witness)
    for site, kind, resolution, status in graph.shardings:
        assert status in ("factory", "tree", "param", "opaque"), (
            site, kind, resolution, status)
    for site, callee, donated, status in graph.donations:
        assert status in ("ok", "handoff"), (site, callee, donated, status)


@pytest.mark.lint
def test_axis_mirror_matches_declared_mesh():
    """The lint package is stdlib-only, so ``meshgraph._DECLARED_AXES``
    mirrors ``parallel/mesh.py`` instead of importing it. This equality
    pin is what makes the mirror safe: any axis added, renamed or
    removed there fails here with the exact constant named."""
    from d4pg_tpu.lint.meshgraph import _DECLARED_AXES
    from d4pg_tpu.parallel import mesh

    declared = {name: value for name, value in vars(mesh).items()
                if name.endswith("_AXIS") and isinstance(value, str)}
    assert _DECLARED_AXES == declared


@pytest.mark.lint
def test_factory_mirror_matches_partition_surface():
    """Every name family 20 accepts as a sanctioned spec source must be
    a real exported callable of ``parallel/partition.py`` — a renamed
    factory would otherwise silently demote clean sites to drift."""
    from d4pg_tpu.lint.meshgraph import _FACTORIES
    from d4pg_tpu.parallel import partition

    assert _FACTORIES <= set(partition.__all__), (
        _FACTORIES - set(partition.__all__))
    for name in _FACTORIES:
        assert callable(getattr(partition, name)), name


@pytest.mark.lint
def test_cli_mesh_mode_clean():
    """``python -m d4pg_tpu.lint --mesh`` is the review artifact for
    sharding PRs; it must exit 0 on the repo, print the axis mirror and
    the binding tables, and report no findings."""
    proc = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.lint", "--mesh", PACKAGE_DIR],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "findings: none" in proc.stdout
    assert "declared axes (parallel/mesh.py mirror):" in proc.stdout
    for const in ("DATA_AXIS", "MODEL_AXIS", "REPLICA_AXIS"):
        assert const in proc.stdout, proc.stdout
    assert "shard_map sites" in proc.stdout
    assert "[bound]" in proc.stdout


def test_mesh_cli_mode_fires_on_fixture(tmp_path, capsys):
    """`--mesh` exits 1 iff a family fires, 0 on the clean variant."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        DATA_AXIS = "data"

        def merge(x):
            return jax.lax.psum(x, DATA_AXIS)
        """))
    assert lint_main(["--mesh", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "collectives" in out and "[unbound]" in out

    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent("""
        import jax
        from jax.experimental.shard_map import shard_map

        DATA_AXIS = "data"

        def make(mesh, specs):
            def body(x):
                return jax.lax.psum(x, DATA_AXIS)
            return shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs)
        """))
    assert lint_main(["--mesh", str(good)]) == 0
    out = capsys.readouterr().out
    assert "[bound]" in out and "findings: none" in out


def test_json_mesh_mode(tmp_path, capsys):
    src = tmp_path / "mesh.py"
    src.write_text(textwrap.dedent("""
        import jax

        DATA_AXIS = "data"

        def merge(x):
            return jax.lax.psum(x, DATA_AXIS)
        """))
    rc = lint_main(["--mesh", "--json", str(src)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["schema"] == 1 and doc["mode"] == "mesh"
    assert {"axes", "shard_maps", "collectives", "shardings",
            "donations", "handlers"} <= set(doc)
    assert doc["axes"]["DATA_AXIS"] == "data"
    assert doc["collectives"][0]["status"] == "unbound"
    assert any(f["rule"] == "collective-axis-unbound"
               for f in doc["findings"])


def test_json_all_mode_merges_every_section(tmp_path, capsys):
    """``--all --json`` emits ONE merged document: the syntactic findings
    (which already include every program family) plus all four graph
    artifacts; exit 1 iff anything fires."""
    src = tmp_path / "prog.py"
    src.write_text(textwrap.dedent("""
        import jax

        DATA_AXIS = "data"

        def merge(x):
            return jax.lax.psum(x, DATA_AXIS)
        """))
    rc = lint_main(["--all", "--json", str(src)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["schema"] == 1 and doc["mode"] == "all"
    assert any(f["rule"] == "collective-axis-unbound"
               for f in doc["findings"])
    for section in ("locks", "wire", "fail", "mesh"):
        assert section in doc, sorted(doc)
    # the mesh section re-states its own family's findings
    assert any(f["rule"] == "collective-axis-unbound"
               for f in doc["mesh"]["findings"])
    assert doc["locks"]["cycles"] == []

    src.write_text("x = 1\n")
    assert lint_main(["--all", "--json", str(src)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == [] and doc["mesh"]["findings"] == []


# ------------------------------------ ReshardSentinel (runtime twin) ------

def test_reshard_sentinel_counts_reshard_ops_only():
    from d4pg_tpu.io.profiling import ReshardError, ReshardSentinel
    from d4pg_tpu.obs.registry import REGISTRY

    before = REGISTRY.counter("profiling.reshards").value
    hlo = "\n".join([
        "%r0 = all-reduce(%g)",         # expected: gradient reduction
        "%r1 = all-gather(%w)",         # expected: merge broadcast
        "%r2 = all-to-all(%t)",         # reshard: layout move
        "%r3 = collective-permute(%t)",  # reshard: layout move
        "%r4 = all-to-all(%u)",
    ])
    sentinel = ReshardSentinel()
    assert sentinel.inspect_text(hlo) == 3
    assert sentinel.steady_state_reshards == 3
    assert sentinel.ops == {"all-to-all": 2, "collective-permute": 1}
    # published into the unified ledger, same as the other sentinels
    assert REGISTRY.counter("profiling.reshards").value == before + 3
    with pytest.raises(ReshardError, match="all-to-all x2"):
        sentinel.assert_clean("fixture path")


def test_reshard_sentinel_clean_and_publishes_counter():
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.io.profiling import ReshardSentinel
    from d4pg_tpu.obs.registry import REGISTRY

    before = REGISTRY.counter("profiling.reshards").value
    f = jax.jit(lambda x: (x * 2.0).sum())
    sentinel = ReshardSentinel()
    assert sentinel.inspect(f, jnp.ones(16)) == 0
    sentinel.assert_clean()
    assert REGISTRY.counter("profiling.reshards").value == before


def test_fused_learner_path_has_zero_reshards(rng):
    """The headline invariant bench.py asserts, pinned in-tree: the fused
    chunk dispatch must compile to zero resharding collectives — the
    runtime proof that no tree crosses layouts mid-program (family 20's
    dynamic twin)."""
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.io.profiling import ReshardSentinel
    from d4pg_tpu.learner import D4PGConfig, init_state
    from d4pg_tpu.learner.fused import make_fused_chunk
    from d4pg_tpu.replay import device_per as dper
    from d4pg_tpu.replay.uniform import TransitionBatch

    cap = 64
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-10, v_max=10,
                        n_atoms=11, hidden=(16, 16, 16))
    state = init_state(config, jax.random.key(0))
    storage = TransitionBatch(
        obs=jnp.asarray(rng.standard_normal((cap, 4)), jnp.float32),
        action=jnp.asarray(rng.uniform(-1, 1, (cap, 2)), jnp.float32),
        reward=jnp.asarray(rng.standard_normal(cap), jnp.float32),
        next_obs=jnp.asarray(rng.standard_normal((cap, 4)), jnp.float32),
        done=jnp.zeros(cap, jnp.float32),
        discount=jnp.full(cap, 0.99, jnp.float32),
    )
    trees = dper.insert(dper.init(cap), jnp.arange(cap), 0.6)
    fn = make_fused_chunk(config, k=2, batch_size=8, prioritized=True,
                          alpha=0.6, donate=False)
    sentinel = ReshardSentinel()
    sentinel.inspect(fn, state, trees, storage, cap)
    sentinel.assert_clean("fused learner path")
    assert sentinel.steady_state_reshards == 0
